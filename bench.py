"""Benchmark: DSGD training throughput on one chip (+ ALS, RMSE context).

Primary metric: ratings/sec/chip on a synthetic ML-25M-shaped DSGD workload
(BASELINE.md north star). The baseline is the reference's own inner-loop
style — a sequential per-rating NumPy SGD loop, the direct analogue of
DSGDforMF.scala:398-417 / netlib ddot — measured on the same host.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Extra context (ALS rows/s, RMSE, wall) rides in an "extra" sub-object and
on stderr; a hard failure still prints the JSON line with an "error" field.

Structure (round-1 lesson, VERDICT.md: one backend failure must not cost the
round its perf evidence): the parent process never imports jax. It runs the
real benchmark in a child subprocess, retries transient TPU-backend failures
with backoff, and falls back to a reduced CPU run if the chip stays
unavailable — so a JSON line is ALWAYS emitted.

Env knobs: BENCH_NNZ, BENCH_RANK, BENCH_ITERS, BENCH_USERS, BENCH_ITEMS,
BENCH_MB (minibatch), BENCH_BLOCKS, BENCH_TIMEOUT (per-attempt seconds).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


# --------------------------------------------------------------------------
# Child: the actual measurement (runs in a subprocess; may die on backend
# errors — the parent handles that).
# --------------------------------------------------------------------------

def _numpy_sequential_baseline(ratings, rank, sample=150_000, lr=0.01,
                               lam=0.1, seed=0):
    """Reference-style sequential per-rating SGD (the Flink/Spark inner loop,
    DSGDforMF.scala:398-417) in NumPy — ratings/sec on host CPU."""
    ru, ri, rv, _ = ratings.to_numpy()
    n = min(sample, len(ru))
    rng = np.random.default_rng(seed)
    nu, ni = int(ru.max()) + 1, int(ri.max()) + 1
    U = rng.normal(0, 0.1, (nu, rank))
    V = rng.normal(0, 0.1, (ni, rank))
    t0 = time.perf_counter()
    for j in range(n):
        u, i, r = ru[j], ri[j], rv[j]
        pu, qv = U[u], V[i]
        e = r - pu @ qv
        U[u] = pu - lr * (lam * pu - e * qv)
        V[i] = qv - lr * (lam * qv - e * pu)
    dt = time.perf_counter() - t0
    return n / dt


def run_child() -> None:
    nnz = int(os.environ.get("BENCH_NNZ", 2_000_000))
    rank = int(os.environ.get("BENCH_RANK", 64))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    num_users = int(os.environ.get("BENCH_USERS", 100_000))
    num_items = int(os.environ.get("BENCH_ITEMS", 20_000))
    mb = int(os.environ.get("BENCH_MB", 8192))
    blocks = int(os.environ.get("BENCH_BLOCKS", 4))

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # Env JAX_PLATFORMS alone is not enough where a site hook pins the
        # jax_platforms config to the accelerator (utils/platform.py).
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()

    import jax

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.als import ALS, ALSConfig
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    device = jax.devices()[0]

    gen = SyntheticMFGenerator(num_users=num_users, num_items=num_items,
                               rank=min(rank, 32), noise=0.1, seed=0)
    ratings = gen.generate(nnz)
    holdout = gen.generate(100_000)

    cfg = DSGDConfig(
        num_factors=rank, lambda_=0.05, iterations=iters,
        learning_rate=0.05, lr_schedule="constant", seed=0,
        minibatch_size=mb, init_scale=0.1,
    )

    # Warm-up: compile (and one full run, first compile is slow).
    warm_cfg = DSGDConfig(
        num_factors=rank, lambda_=0.05, iterations=1,
        learning_rate=0.05, lr_schedule="constant", seed=0,
        minibatch_size=mb, init_scale=0.1,
    )
    DSGD(warm_cfg).fit(ratings, num_blocks=blocks).U.block_until_ready()

    solver = DSGD(cfg)
    t0 = time.perf_counter()
    model = solver.fit(ratings, num_blocks=blocks)
    model.U.block_until_ready()
    dsgd_wall = time.perf_counter() - t0
    # NOTE: wall includes the host blocking pass (fair: the reference's
    # supersteps include their shuffles).
    throughput = nnz * iters / dsgd_wall
    rmse = model.rmse(holdout)

    baseline = _numpy_sequential_baseline(ratings, rank)

    # ALS: the MXU-heavy path — rows solved (normal-equation Cholesky) per
    # second, ≙ the reference's MLlib ALS retrain branch
    # (OnlineSpark.scala:125-131).
    als_nnz = min(nnz, 1_000_000)
    als_cfg = ALSConfig(num_factors=rank, lambda_=0.1, iterations=2,
                        seed=0, chunk_size=65536)
    als_ratings = ratings if als_nnz == nnz else gen.generate(als_nnz)
    als = ALS(als_cfg)
    als.fit(als_ratings).U.block_until_ready()  # compile warm-up
    t0 = time.perf_counter()
    als_model = ALS(als_cfg).fit(als_ratings)
    als_model.U.block_until_ready()
    als_wall = time.perf_counter() - t0
    als_rows = (als_model.U.shape[0] + als_model.V.shape[0]) * als_cfg.iterations
    als_rows_per_s = als_rows / als_wall

    result = {
        "metric": f"ratings/sec/chip (synthetic DSGD rank={rank}, "
                  f"{nnz / 1e6:g}M ratings, {blocks}x{blocks} strata)",
        "value": round(throughput, 1),
        "unit": "ratings/s",
        "vs_baseline": round(throughput / baseline, 2),
        "extra": {
            "device": str(device),
            "dsgd_wall_s": round(dsgd_wall, 2),
            "dsgd_rmse_holdout": round(float(rmse), 4),
            "numpy_seq_baseline_ratings_per_s": round(baseline, 1),
            "als_rows_solved_per_s": round(als_rows_per_s, 1),
            "als_wall_s": round(als_wall, 2),
            "als_nnz": als_nnz,
        },
    }
    print(json.dumps(result))
    print(
        f"# wall={dsgd_wall:.2f}s iters={iters} rmse={rmse:.4f} "
        f"numpy_baseline={baseline:.0f} r/s als={als_rows_per_s:.0f} rows/s "
        f"device={device}",
        file=sys.stderr,
    )


# --------------------------------------------------------------------------
# Parent: retry orchestration. Never imports jax itself.
# --------------------------------------------------------------------------

def _attempt(env_overrides: dict[str, str], timeout: float):
    """Run one child attempt; return (json_dict | None, tail_of_output)."""
    env = dict(os.environ)
    env.update(env_overrides)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"")[-2000:] if isinstance(e.stderr, bytes)
                else (e.stderr or "")[-2000:])
        return None, f"timeout after {timeout}s; stderr tail: {tail}"
    out_lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode == 0 and out_lines:
        try:
            parsed = json.loads(out_lines[-1])
            if "value" in parsed:
                return parsed, proc.stderr[-1000:]
        except json.JSONDecodeError:
            pass
    tail = (proc.stderr or proc.stdout)[-2000:]
    return None, f"rc={proc.returncode}; tail: {tail}"


def _looks_transient(tail: str) -> bool:
    """Backend/availability failures are worth a retry; a deterministic
    crash (ImportError, assertion) is not — retrying it only delays the
    CPU fallback and misattributes the root cause."""
    needles = ("timeout", "UNAVAILABLE", "backend", "Backend", "TPU",
               "axon", "pjrt", "PJRT", "DEADLINE", "RESOURCE_EXHAUSTED")
    return any(n in tail for n in needles)


def main() -> None:
    per_attempt = float(os.environ.get("BENCH_TIMEOUT", 1500))
    errors: list[str] = []

    # Attempt on whatever backend the environment provides (TPU when
    # healthy); retry once with backoff only if the failure looks like a
    # transient backend problem — round-1's failure mode was a transient
    # "TPU backend setup/compile error (Unavailable)".
    result, tail = _attempt({}, per_attempt)
    if result is not None:
        print(json.dumps(result))
        return
    errors.append(f"attempt 1: {tail}")
    print(f"# bench attempt 1 failed: {tail[-300:]}", file=sys.stderr)
    if _looks_transient(tail):
        time.sleep(15)
        result, tail = _attempt({}, per_attempt)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt 2: {tail}")
        print(f"# bench attempt 2 failed: {tail[-300:]}", file=sys.stderr)

    # CPU fallback on a reduced workload — a real (if slower) number beats
    # no number; the error field records the actual per-attempt failures
    # (which may or may not be the accelerator's fault).
    cpu_env = {
        "JAX_PLATFORMS": "cpu",
        "BENCH_FORCE_CPU": "1",
        "BENCH_NNZ": os.environ.get("BENCH_NNZ_CPU", "400000"),
        "BENCH_ITERS": "2",
        "BENCH_USERS": "40000",
        "BENCH_ITEMS": "10000",
    }
    result, tail = _attempt(cpu_env, per_attempt)
    if result is not None:
        result["error"] = (
            "default-backend attempts failed; value is a reduced "
            "CPU-fallback run. " + " | ".join(e[:300] for e in errors)
        )
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: {tail}")

    # Total failure: still emit the one-line JSON contract.
    print(json.dumps({
        "metric": "ratings/sec/chip (synthetic DSGD)",
        "value": 0.0,
        "unit": "ratings/s",
        "vs_baseline": 0.0,
        "error": " | ".join(e[:500] for e in errors),
    }))


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child()
    else:
        main()
