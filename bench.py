"""Benchmark: the BASELINE.md north-star configs on one chip.

Headline metric: ratings/sec/chip for DSGD on the ML-25M-shaped skewed
workload (162K users x 59K items, ~23.7M train ratings) at rank 128, with
wall-clock to a pre-registered RMSE target and achieved-bandwidth/MFU
accounting. Extra lines: bucketed ALS rows-solved/s at rank 64 (the
round-2 comparison), 128 (+implicit) and 256,
sustained online-stream ratings/s at rank 128, and PS-mode throughput.

The baseline for ``vs_baseline`` is the reference's own inner-loop style —
a sequential per-rating NumPy SGD loop, the direct analogue of
DSGDforMF.scala:398-417 (netlib ddot per rating) — measured on this host.

Contract: the LAST stdout line is the result JSON
{"metric", "value", "unit", "vs_baseline", ...}. (The child also prints
the headline line EARLY — before the extra benchmark lines run — so a
timeout mid-extras can be salvaged by the parent; consumers must parse
the last line, as the driver does.) Context rides in "extra" and on
stderr; a hard failure still prints the JSON line with an "error" field.

Structure (round-1 lesson: one backend failure must not cost the round its
perf evidence): the parent process never imports jax. It runs the real
benchmark in a child subprocess, retries transient TPU-backend failures
with backoff, and falls back to a reduced CPU run if the chip stays
unavailable — a JSON line is ALWAYS emitted.

Round-3 lesson (measured, not assumed): the bench device may sit behind a
narrow host link (the tunneled chip moves ~MB/s, not PCIe GB/s), and a
multi-hundred-MB ``device_put`` can wedge the link for good. So the DSGD
workload is generated AND blocked on device (``data.device_blocking``) —
kilobytes cross the link instead of ~600 MB — the bench probes the link
bandwidth first (``h2d_mbps``), and the extra lines auto-skip when the
link is too slow to carry their inputs inside the attempt window.

Env knobs: BENCH_NNZ, BENCH_RANK, BENCH_ITERS (max sweeps), BENCH_MB,
BENCH_BLOCKS, BENCH_RMSE_TARGET, BENCH_TIMEOUT (per-attempt seconds),
BENCH_DATA (=path to a real ratings file/dir — ML-25M ratings.csv or
ML-100K u.data; parse → compact → block → train with the real-data
RMSE-0.85 target; BENCH_NNZ becomes a seeded subsample cap),
BENCH_SKIP_EXTRAS (=1 → DSGD line only), BENCH_MIN_MBPS (extras gate),
BENCH_HOST_PIPELINE (=1 → round-2 host-side gen+blocking path),
BENCH_SORT (intra-minibatch locality ordering, BOTH pipelines; default
"item" — measured +19% per sweep at identical RMSE, docs/PERF.md
"Sort lever"; set =none to reproduce earlier unsorted runs, =user for
the other side),
BENCH_AUTOTUNE (=1 → A/B the kernel minibatch vs its 2× on one timed
sweep each, same blocked layout, before the timed run; OFF by default
because sweep time is only half the story — at full scale mb 65536
measured faster per sweep but MISSED the RMSE target in 10 sweeps, see
docs/PERF.md), BENCH_EXTRAS_DEADLINE (seconds of child elapsed after
which extras are skipped; defaults to BENCH_TIMEOUT/2 under the parent,
unlimited for a standalone child — and the headline JSON prints BEFORE
extras either way, so an extras overrun can never cost the measurement).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

# v5e (TPU v5 lite) single-chip peaks for the roofline accounting.
# MIRRORED in large_scale_recommendation_tpu/obs/introspect.py (the live
# /rooflinez denominators) — this module cannot import the package at
# module scope (backend-init ordering), so a change here changes there.
HBM_PEAK_GBS = 819.0
BF16_PEAK_TFLOPS = 197.0
FP32_PEAK_TFLOPS = 49.0


def _numpy_sequential_baseline(ru, ri, rv, rank, sample=150_000, lr=0.01,
                               lam=0.1, seed=0):
    """Reference-style sequential per-rating SGD (the Flink/Spark inner
    loop, DSGDforMF.scala:398-417) in NumPy — ratings/sec on host CPU."""
    n = min(sample, len(ru))
    rng = np.random.default_rng(seed)
    nu, ni = int(ru.max()) + 1, int(ri.max()) + 1
    U = rng.normal(0, 0.1, (nu, rank))
    V = rng.normal(0, 0.1, (ni, rank))
    t0 = time.perf_counter()
    for j in range(n):
        u, i, r = ru[j], ri[j], rv[j]
        pu, qv = U[u], V[i]
        e = r - pu @ qv
        U[u] = pu - lr * (lam * pu - e * qv)
        V[i] = qv - lr * (lam * qv - e * pu)
    dt = time.perf_counter() - t0
    return n / dt


def run_child() -> None:
    child_t0 = time.perf_counter()
    nnz = int(os.environ.get("BENCH_NNZ", 25_000_095))
    rank = int(os.environ.get("BENCH_RANK", 128))
    max_iters = int(os.environ.get("BENCH_ITERS", 12))
    mb = int(os.environ.get("BENCH_MB", 32768))
    blocks = int(os.environ.get("BENCH_BLOCKS", 8))
    # Pre-registered target for the ML-25M-shaped stand-in: planted rank-16
    # structure, noise 0.1 (rating std ≈ 0.27, noise floor 0.1) → holdout
    # RMSE 0.155 means the model has recovered essentially all learnable
    # structure (the analogue of "RMSE 0.85 on real ML-25M", whose absolute
    # value is a property of the real data). Noise 0.1, not the
    # synthetic_like default 0.3: at 0.3 the SNR is < 1 and NO solver beats
    # predict-zero — measured, not assumed (ALS plateaus at the data std).
    # BENCH_DATA=/path/to/ratings.csv (or a directory holding one): train
    # on REAL data through the same timed loop — parse → compact → block →
    # train. The RMSE target flips to the BASELINE.md real-ML-25M contract
    # (0.85) unless overridden; the vocab knobs are ignored (the file is
    # the workload) and BENCH_NNZ becomes a seeded subsample cap.
    bench_data = os.environ.get("BENCH_DATA")
    rmse_target = float(os.environ.get(
        "BENCH_RMSE_TARGET", "0.85" if bench_data else "0.155"))
    skip_extras = os.environ.get("BENCH_SKIP_EXTRAS") == "1"
    # Vocab overrides: reduced runs MUST shrink the user/item space with
    # nnz — below ~100 obs/row the planted structure is unrecoverable by
    # any solver (docs/PERF.md) and the RMSE curve carries no information.
    from large_scale_recommendation_tpu.data.movielens import (
        vocab_overrides_from_env,
    )

    num_users, num_items = vocab_overrides_from_env()
    # effective vocab for labels: ml-25m shape with any overrides applied
    eff_users = num_users if num_users is not None else 162_541
    eff_items = num_items if num_items is not None else 59_047

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()

    import jax

    # Persistent compile cache: ~90% of the r5 blocking wall (153 s) was
    # remote-helper compiles, all cacheable across processes (measured).
    # BENCH_COMPILE_CACHE=0 opts out for cold-compile measurements.
    cache_state = "off"
    if os.environ.get("BENCH_COMPILE_CACHE", "1") == "1":
        from large_scale_recommendation_tpu.utils.platform import (
            enable_compilation_cache,
        )

        cdir = enable_compilation_cache()
        try:
            cache_state = "warm" if os.listdir(cdir) else "cold"
        except OSError:
            cache_state = "cold"
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.obs.introspect import Introspector
    from large_scale_recommendation_tpu.ops import sgd as sgd_ops

    # XLA introspection for the whole bench run (registry stays null —
    # the introspector keeps its own records): every compile's wall is
    # measured at the funnel, so the compile_count / xla_compile_wall_s
    # extras below see EVERYTHING — warm-ups, bucket families, probes —
    # not just the hand-bracketed headline warm-up (ISSUE 9: compile
    # regressions were invisible to the regress gate before this)
    introspector = Introspector()
    introspector.install()

    device = jax.devices()[0]
    extra: dict = {"device": str(device), "nnz": nnz, "rank": rank,
                   "blocks": blocks, "minibatch": mb,
                   "rmse_target": rmse_target,
                   "compile_cache": cache_state}

    # ---- link probe: host→device bandwidth -------------------------------
    # The chip may sit behind a narrow tunnel; everything below budgets its
    # transfers against this number (and the extras gate on it).
    probe = np.ones(1 << 22, np.float32)  # 16 MB
    jax.device_put(probe[:1024], device).block_until_ready()  # wake the link
    t0 = time.perf_counter()
    jax.device_put(probe, device).block_until_ready()
    h2d_mbps = (probe.nbytes / (1 << 20)) / max(time.perf_counter() - t0,
                                                1e-9)
    extra["h2d_mbps"] = round(h2d_mbps, 1)

    # λ=0.1 with the λ/ω rule ≈ an lr·λ total shrink per sweep — scaled to
    # the stand-in's signal magnitude (λ=1 over-regularizes it to the
    # predict-zero plateau; grid-searched on CPU before pinning). The
    # warm_boost schedule (lr 0.75 for 2 sweeps, then 0.3) cuts the
    # bilinear-bootstrap plateau: target at sweep 3 vs 8, lower floor —
    # measured at full scale, docs/PERF.md.
    cfg = DSGDConfig(num_factors=rank, lambda_=0.1, iterations=1,
                     learning_rate=0.3, lr_schedule="warm_boost", seed=0,
                     minibatch_size=mb, init_scale=0.08,
                     collision_mode="mean")
    solver = DSGD(cfg)

    # BENCH_SORT=user|item|none — intra-minibatch locality ordering, BOTH
    # pipelines (pure gather/scatter-locality lever, math unchanged).
    # Default "item": measured at full scale — 19% faster per sweep than
    # unsorted at IDENTICAL rmse trajectory (docs/PERF.md "Sort lever");
    # index clustering helps the TPU gather more than the CPU one (~3x
    # clustering effect, "Kernel facts").
    sort = os.environ.get("BENCH_SORT", "item")
    sort = None if sort in ("", "none", "0") else sort
    if sort:
        extra["minibatch_sort"] = sort

    if os.environ.get("BENCH_HOST_PIPELINE") == "1" and not bench_data:
        # round-2 style: host generation + host/native blocking + bulk
        # device_put (~600 MB at the default config — needs a wide link)
        from large_scale_recommendation_tpu.data import blocking
        from large_scale_recommendation_tpu.data.movielens import (
            synthetic_like,
        )

        extra["pipeline"] = "host"
        t0 = time.perf_counter()
        train, holdout = synthetic_like("ml-25m", nnz=nnz, rank=16,
                                        noise=0.1, seed=0, skew_lam=2.0,
                                        num_users=num_users,
                                        num_items=num_items)
        extra["gen_wall_s"] = round(time.perf_counter() - t0, 1)
        ru, ri, rv, _ = train.to_numpy()
        base_sample = (ru, ri, rv)
        train_nnz = len(ru)

        t0 = time.perf_counter()
        problem = blocking.block_problem(train, num_blocks=blocks, seed=0,
                                         minibatch_multiple=mb,
                                         minibatch_sort=sort)
        icu, icv = blocking.minibatch_inv_counts(problem.ratings, mb)
        extra["blocking_wall_s"] = round(time.perf_counter() - t0, 1)
        extra["max_pad_ratio"] = round(problem.ratings.max_pad_ratio, 3)

        t0 = time.perf_counter()
        U, V = solver._init_factors(problem)
        args = (
            jnp.asarray(problem.ratings.u_rows, jnp.int32),
            jnp.asarray(problem.ratings.i_rows, jnp.int32),
            jnp.asarray(problem.ratings.values, jnp.float32),
            jnp.asarray(problem.ratings.weights, jnp.float32),
            jnp.asarray(problem.users.omega),
            jnp.asarray(problem.items.omega),
            jnp.asarray(icu),
            jnp.asarray(icv),
        )
        hu, hi, hv, _ = holdout.to_numpy()
        hur, hum = problem.users.rows_for(hu)
        hir, him = problem.items.rows_for(hi)
        hmask = jnp.asarray(hum * him)
        hur_d, hir_d = jnp.asarray(hur), jnp.asarray(hir)
        hv_d = jnp.asarray(hv)
        jax.block_until_ready(args)
        extra["device_put_wall_s"] = round(time.perf_counter() - t0, 1)
    else:
        # device pipeline (default): generation + blocking on chip, only
        # scalars and a 256-byte size vector cross the link
        from large_scale_recommendation_tpu.data.device_blocking import (
            device_block_problem,
            init_factors_device,
            synthetic_like_device,
        )

        extra["pipeline"] = "device"
        t0 = time.perf_counter()
        if bench_data:
            # real data: parse → compact on host (the file lives there),
            # then ship the dense COO (~12 B/rating — ML-25M ≈ 300 MB;
            # the h2d probe above says what the link can take) and block
            # on device like every other run
            from large_scale_recommendation_tpu.data.movielens import (
                compact_ratings,
                load_ratings_file,
            )

            cu_, ci_, cv_, nu, ni = compact_ratings(
                load_ratings_file(bench_data))
            cap_env = os.environ.get("BENCH_NNZ")
            if cap_env and int(cap_env) < len(cu_):
                # honor an explicit size cap (the parent's CPU fallback
                # shrinks every workload) with a seeded subsample that
                # keeps the real distribution
                keep = np.random.default_rng(1).choice(
                    len(cu_), int(cap_env), replace=False)
                cu_, ci_, cv_ = cu_[keep], ci_[keep], cv_[keep]
                extra["data_subsampled_to"] = int(cap_env)
            nnz = len(cu_)
            extra["nnz"] = nnz
            extra["data_file"] = bench_data
            extra["data_vocab"] = [nu, ni]
            eff_users, eff_items = nu, ni
            rng = np.random.default_rng(0)
            test_mask = np.zeros(nnz, bool)
            test_mask[rng.choice(nnz, max(1, int(nnz * 0.05)),
                                 replace=False)] = True
            # center by the TRAIN mean: raw star ratings sit at ~3.5 and
            # the plain bilinear model (no bias terms) must otherwise
            # spend its first sweeps learning the offset — with the bench
            # step sizes it diverges instead. Predictions are implicitly
            # mean + u·v, so holdout values are centered identically and
            # the reported RMSE is unchanged by the shift.
            mu = float(cv_[~test_mask].mean())
            extra["data_mean"] = round(mu, 4)
            du = jnp.asarray(cu_[~test_mask])
            di = jnp.asarray(ci_[~test_mask])
            dr = jnp.asarray(cv_[~test_mask] - mu)
            dhu = jnp.asarray(cu_[test_mask])
            dhi = jnp.asarray(ci_[test_mask])
            dhv = jnp.asarray(cv_[test_mask] - mu)
        else:
            (du, di, dr), (dhu, dhi, dhv), (nu, ni) = synthetic_like_device(
                "ml-25m", nnz=nnz, rank=16, noise=0.1, seed=0, skew_lam=2.0,
                num_users=num_users, num_items=num_items)
        jax.block_until_ready(dr)
        extra["gen_wall_s"] = round(time.perf_counter() - t0, 1)
        train_nnz = int(du.shape[0])

        # BENCH_AUTOTUNE=1 (opt-in): A/B the kernel minibatch against its
        # 2× AND half candidates on a single timed sweep each from the
        # SAME blocked layout (pad to the largest candidate; all divide
        # it). The half candidate earned its slot on chip (r5): the
        # amortized probe measured mb 1024 at 17.9M r/s vs 12.3M at
        # mb 2048 (rank 128). Off by default: the probe sees throughput
        # only, and mb 65536 measured faster per sweep yet missed the
        # full-scale RMSE target (docs/PERF.md) — the validated default
        # 32768 stays unless explicitly overridden.
        autotune = os.environ.get("BENCH_AUTOTUNE", "0") == "1"
        mb_cands = (sorted({max(mb // 2, 1), mb, mb * 2}) if autotune
                    else [mb])
        t0 = time.perf_counter()
        p = device_block_problem(du, di, dr, nu, ni, num_blocks=blocks,
                                 minibatch_multiple=max(mb_cands), seed=0,
                                 minibatch_sort=sort)
        jax.block_until_ready(p.su)
        extra["blocking_wall_s"] = round(time.perf_counter() - t0, 1)
        extra["max_pad_ratio"] = round(p.max_pad_ratio, 3)

        U, V = init_factors_device(p, rank, scale=cfg.init_scale)
        inv_by_mb = {max(mb_cands): (p.icu, p.icv)}
        for c in mb_cands:
            if c not in inv_by_mb:
                from large_scale_recommendation_tpu.data.device_blocking \
                    import recompute_inv_counts

                inv_by_mb[c] = recompute_inv_counts(p, c)
        base_args = (p.su, p.si, p.sv, p.sw, p.omega_u, p.omega_v)
        if len(mb_cands) > 1:
            tune: dict = {}
            for c in mb_cands:
                cargs = base_args + inv_by_mb[c]
                ck = dict(updater=solver.updater, minibatch=c,
                          num_blocks=blocks, iterations=1,
                          collision="mean")
                Uw, Vw = sgd_ops.dsgd_train(U, V, *cargs, **ck, t0=0)
                jax.block_until_ready((Uw, Vw))  # compile warm-up
                t0 = time.perf_counter()
                Uw, Vw = sgd_ops.dsgd_train(U, V, *cargs, **ck, t0=0)
                jax.block_until_ready((Uw, Vw))
                tune[str(c)] = round(time.perf_counter() - t0, 3)
            del Uw, Vw
            mb = int(min(tune, key=tune.get))
            extra["autotune_sweep_s"] = tune
            extra["minibatch"] = mb
        args = base_args + inv_by_mb[mb]
        hur_d, hir_d, hmask = p.holdout_rows(dhu, dhi)
        hv_d = dhv
        # small device→host sample for the sequential-NumPy baseline
        s = min(150_000, int(du.shape[0]))
        base_sample = (np.asarray(du[:s]), np.asarray(di[:s]),
                       np.asarray(dr[:s]))
    n_eval = float(np.asarray(hmask).sum())

    def rmse(U, V):
        sse = sgd_ops.sse_rows(U, V, hur_d, hir_d, hv_d, hmask)
        return float(np.sqrt(float(sse) / n_eval))

    # BENCH_KERNEL=pallas routes the headline through the VMEM-staged
    # Pallas kernel via the model layer's own routing (DSGDConfig.kernel →
    # DSGD._train_fn — the surface users flip). Opt-in: the wrapper
    # enforces the Pallas VMEM/SMEM geometry (rank 128 needs
    # BENCH_BLOCKS=16 and mb ≤ 4096) and raises loudly on violation.
    # The minibatch autotune above stays an XLA-kernel A/B by design.
    bench_kernel = os.environ.get("BENCH_KERNEL", "xla")
    extra["kernel"] = bench_kernel
    # BENCH_FACTOR_DTYPE=bfloat16 stores the factor tables at half width
    # (DSGDConfig.factor_dtype — f32 accumulation either way); the
    # roofline below prices the halved factor traffic automatically
    bench_fdtype = os.environ.get("BENCH_FACTOR_DTYPE", "float32")
    extra["factor_dtype"] = bench_fdtype
    solver.config = dataclasses.replace(cfg, kernel=bench_kernel,
                                        minibatch_size=mb,
                                        factor_dtype=bench_fdtype)
    U = U.astype(jnp.dtype(bench_fdtype))
    V = V.astype(jnp.dtype(bench_fdtype))
    sweep_fn = solver._train_fn(args)

    def one_sweep(U, V, t):
        return sweep_fn(U, V, iterations=1, t0=t, k=blocks)

    # warm-up: compile the per-sweep kernel
    t0 = time.perf_counter()
    Uw, Vw = one_sweep(U, V, 0)
    jax.block_until_ready((Uw, Vw))
    extra["compile_wall_s"] = round(time.perf_counter() - t0, 1)

    # optional profiler capture of ONE sweep (BENCH_PROFILE=dir):
    # tensorboard-format XLA timeline via utils.metrics.profile
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        from large_scale_recommendation_tpu.utils.metrics import profile

        with profile(profile_dir):
            Uw, Vw = one_sweep(U, V, 0)
            jax.block_until_ready((Uw, Vw))
        extra["profile_trace_dir"] = profile_dir
    del Uw, Vw

    # ---- timed training: sweep-by-sweep until the RMSE target ------------
    train_wall = 0.0
    time_to_target = None
    sweeps_to_target = None
    rmse_now = rmse(U, V)
    curve = [round(rmse_now, 4)]
    for it in range(max_iters):
        t0 = time.perf_counter()
        U, V = one_sweep(U, V, it)
        jax.block_until_ready((U, V))
        train_wall += time.perf_counter() - t0
        rmse_now = rmse(U, V)
        curve.append(round(rmse_now, 4))
        if time_to_target is None and rmse_now <= rmse_target:
            time_to_target = train_wall
            sweeps_to_target = it + 1
            break
    sweeps = sweeps_to_target or max_iters
    # normalize to the ratings actually visited per sweep (the 95% train
    # split), not the total generated nnz — ADVICE r3
    throughput = train_nnz * sweeps / train_wall
    extra["train_nnz"] = train_nnz

    # roofline accounting, PER KERNEL (ops.sgd.dsgd_bytes_per_sweep — the
    # one shared traffic model): the xla gather path pays ~4 row-latency
    # transactions per rating; the pallas path streams each factor row
    # through VMEM once per stratum (contiguous) plus the COO streams.
    # bf16 factor storage halves the factor term on both.
    # model_size=1: the headline bench is a single-chip run — factor rows
    # are full-rank and no 'model'-axis collective traffic exists (the
    # rank-sharded terms are priced in scripts/pod_dryrun.py's 2-D pass)
    bytes_per_sweep = sgd_ops.dsgd_bytes_per_sweep(
        train_nnz, rank, kernel=bench_kernel, num_blocks=blocks,
        rows_u=int(U.shape[0]), rows_v=int(V.shape[0]),
        factor_bytes=jnp.dtype(bench_fdtype).itemsize, model_size=1)
    # FLOP model via the shared hand model (ops.sgd.dsgd_flops_per_sweep
    # — the same one the /rooflinez cross-check column prices against)
    flops_per_rating = sgd_ops.dsgd_flops_per_sweep(1, rank)
    eff_gbs = bytes_per_sweep * sweeps / train_wall / 1e9
    eff_tflops = throughput * flops_per_rating / 1e12
    # end-to-end including ALL setup (gen + blocking + placement + compile)
    # — the basis round 2's headline was measured on (its 2.06M r/s was
    # ~80% setup; the device pipeline moved that work on chip)
    setup = (extra.get("gen_wall_s", 0) + extra.get("blocking_wall_s", 0)
             + extra.get("device_put_wall_s", 0)
             + extra.get("compile_wall_s", 0))
    extra["e2e_ratings_per_s_incl_setup"] = round(
        train_nnz * sweeps / (train_wall + setup), 1)
    extra.update({
        "dsgd_train_wall_s": round(train_wall, 2),
        "dsgd_sweeps": sweeps,
        "rmse_curve": curve,
        "rmse_final": round(rmse_now, 4),
        "time_to_rmse_target_s": (None if time_to_target is None
                                  else round(time_to_target, 2)),
        "sweeps_to_target": sweeps_to_target,
        "effective_hbm_gbs": round(eff_gbs, 1),
        "pct_of_hbm_peak": round(100 * eff_gbs / HBM_PEAK_GBS, 2),
        "effective_tflops": round(eff_tflops, 3),
        "pct_of_fp32_peak": round(100 * eff_tflops / FP32_PEAK_TFLOPS, 3),
    })

    baseline = _numpy_sequential_baseline(*base_sample, rank)
    extra["numpy_seq_baseline_ratings_per_s"] = round(baseline, 1)

    if bench_data:
        shape_lbl = (f"real data {os.path.basename(bench_data.rstrip('/'))}"
                     f" {eff_users}x{eff_items}")
    else:
        shape_lbl = ("ML-25M-shaped skewed" if num_users is None
                     and num_items is None else
                     f"{eff_users}x{eff_items} skewed (reduced vocab)")

    def result_line() -> dict:
        return {
            "metric": (f"ratings/sec/chip (DSGD, {shape_lbl}, "
                       f"rank={rank}, {nnz/1e6:.1f}M ratings, "
                       f"{blocks}x{blocks} strata)"),
            "value": round(throughput, 1),
            "unit": "ratings/s",
            "vs_baseline": round(throughput / baseline, 2),
            "extra": extra,
        }

    # The headline line prints BEFORE extras: if the extras overrun the
    # parent's window and the child is killed, the parent salvages the last
    # complete line — an extras overrun can never forfeit the computed
    # DSGD measurement. A second, final line (with extras merged) replaces
    # it when everything completes (the parent parses the LAST line).
    print(json.dumps(result_line()), flush=True)

    # extras only if the headline left enough window; the deadline applies
    # when a parent window exists (parent sets BENCH_PARENT=1) or when
    # explicitly configured — a standalone child run has no clock to beat
    elapsed = time.perf_counter() - child_t0
    explicit = ("BENCH_EXTRAS_DEADLINE" in os.environ
                or "BENCH_TIMEOUT" in os.environ
                or os.environ.get("BENCH_PARENT") == "1")
    extras_deadline = (float(os.environ.get(
        "BENCH_EXTRAS_DEADLINE",
        float(os.environ.get("BENCH_TIMEOUT", 2400)) / 2))
        if explicit else float("inf"))
    if not skip_extras:
        if elapsed < extras_deadline:
            _extra_lines(extra, rank, jax, h2d_mbps,
                         num_users=num_users, num_items=num_items,
                         model_factors=(U, V))
        else:
            extra["extras_skipped"] = (
                f"headline took {elapsed:.0f}s ≥ extras deadline "
                f"{extras_deadline:.0f}s (BENCH_EXTRAS_DEADLINE)")

    # compile accounting from the introspection hook, LAST so the probes
    # and serving extras above are counted too: compile_count is every
    # XLA compile the whole run paid, xla_compile_wall_s their summed
    # funnel wall (the hand-bracketed compile_wall_s above stays the
    # headline-kernel warm-up). Both gate in bench_regress's default
    # watch set, lower-is-better.
    extra["compile_count"] = introspector.compile_count
    extra["xla_compile_wall_s"] = round(introspector.compile_wall_s, 2)
    introspector.uninstall()

    # the stderr extras echo goes FIRST, then the final stdout line: a
    # wrapper capturing the child with 2>&1 sees the JSON summary as the
    # genuinely last line (round-5 driver recorded `parsed: null` when a
    # late stderr write landed after the summary in the merged stream)
    print(f"# {json.dumps(extra)}", file=sys.stderr)
    _emit_final(result_line())  # final line wins


def _extra_lines(extra: dict, rank: int, jax, h2d_mbps: float,
                 num_users: int | None = None,
                 num_items: int | None = None,
                 model_factors=None) -> None:
    """ALS (rank 128 + 256 + implicit), online-stream, and PS-mode lines.

    The ALS inputs are generated AND plan-built on device
    (``device_prepare_side``) — no link traffic at all; the online and
    PS lines stream real host data by design, so they gate on the
    measured link bandwidth."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.core.initializers import (
        PseudoRandomFactorInitializer,
    )
    from large_scale_recommendation_tpu.data.device_blocking import (
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.ops import als as als_ops

    # ---- Pallas gather-ceiling experiment (VERDICT r3 #2) ----------------
    # One realistic block visit: XLA kernel vs the VMEM-staged Pallas
    # kernel (both gather variants). Runs whenever a real TPU is the bench
    # device so the experiment is recorded even if the only live tunnel
    # window of the round is the driver's own bench run. A Mosaic lowering
    # failure is recorded verbatim — a measured negative beats an argued
    # one. Zero link traffic (all inputs generated on device host-side
    # small, tables on chip).
    if (os.environ.get("BENCH_PALLAS", "1") == "1"
            and jax.devices()[0].platform == "tpu"):
        from large_scale_recommendation_tpu.ops.pallas_sgd import (
            probe_variants,
        )

        try:
            # rank capped at 128: the VMEM budget (slices + 4 [mb, rank]
            # tiles) is sized for the k=32 ML-25M shape at rank ≤ 128
            # sweeps=16 amortizes the tunneled dispatch RTT (~30-70 ms per
            # call — at sweeps=1 the probe measures the link, not the
            # kernel: rank-64 XLA read 2.8M r/s unamortized vs 18.7M
            # amortized, measured r5)
            pr = min(rank, 128)
            # pallas_take is excluded from RUNTIME probes: its Mosaic
            # rejection is already recorded chip-free (MOSAIC_AOT.json —
            # multi-vreg gather / VMEM budget), and attempting the
            # runtime compile CRASHES the remote compile helper
            # (subprocess exit 1, measured r5), destabilizing the very
            # tunnel the rest of the harvest depends on.
            pvar = ("xla", "pallas_loop")
            # ONE geometry definition (the ML-25M k=32 block visit —
            # also probe_variants' defaults, passed explicitly so the
            # GB/s pricing below can never drift from what actually ran)
            p_rpb_u, p_rpb_v, e_probe, p_mb = 5080, 1848, 24576, 2048
            pv = probe_variants(rank=pr, mb=p_mb, rpb_u=p_rpb_u,
                                rpb_v=p_rpb_v, nnz=e_probe, reps=3,
                                sweeps=16, variants=pvar)
            # per-kernel achieved bandwidth (the gated ISSUE-6 metric),
            # priced by the per-kernel traffic model — xla pays the
            # 4-row-transaction gather, pallas streams the slice pair
            # through VMEM once (contiguous)
            from large_scale_recommendation_tpu.ops import sgd as sgd_ops

            def probe_hbm_gbs(label, ratings_per_s):
                kern = "pallas" if label.startswith("pallas") else "xla"
                bpv = sgd_ops.dsgd_bytes_per_sweep(
                    e_probe, pr, kernel=kern, num_blocks=1,
                    rows_u=p_rpb_u, rows_v=p_rpb_v, factor_bytes=4,
                    model_size=1)
                return round(ratings_per_s / e_probe * bpv / 1e9, 1)

            for label, val in pv.items():
                extra[f"kernel_{label}_ratings_per_s"] = val
                if not isinstance(val, str):
                    extra[f"kernel_{label}_effective_hbm_gbs"] = (
                        probe_hbm_gbs(label, val))
            ploop = extra.get("kernel_pallas_loop_effective_hbm_gbs")
            if ploop is not None:
                # the ISSUE-6 steady-state target, asserted only where a
                # real memory system exists (this block is TPU-gated)
                extra["pallas_hbm_target_met"] = bool(
                    ploop >= 0.10 * HBM_PEAK_GBS)
                if not extra["pallas_hbm_target_met"]:
                    print(f"# WARNING: pallas_loop achieved {ploop} GB/s "
                          f"< 10% of HBM peak ({HBM_PEAK_GBS} GB/s)",
                          file=sys.stderr)
            extra["kernel_pallas_take_ratings_per_s"] = (
                "SKIPPED: Mosaic-rejected at every realistic shape "
                "(docs/MOSAIC_AOT.json); runtime attempt crashes the "
                "remote compile helper")
            pv_sorted = probe_variants(rank=pr, mb=p_mb, rpb_u=p_rpb_u,
                                       rpb_v=p_rpb_v, nnz=e_probe,
                                       reps=3, sweeps=16, sort=True,
                                       variants=pvar)
            for label, val in pv_sorted.items():
                extra[f"kernel_{label}_sorted_ratings_per_s"] = val
            if pr != 64:
                # apples-to-apples vs the historical 13.6M r/s figure
                # (rank 64, round-2 TPU measurement — itself
                # dispatch-bound; the amortized number is the real one)
                for label, val in probe_variants(
                        rank=64, mb=p_mb, rpb_u=p_rpb_u, rpb_v=p_rpb_v,
                        nnz=e_probe, reps=3, sweeps=16,
                        variants=pvar).items():
                    extra[f"kernel64_{label}_ratings_per_s"] = val
        except Exception as ex:  # never let the experiment kill the extras
            extra["kernel_probe_error"] = f"{type(ex).__name__}: {ex}"

    # ---- top-K serving throughput (the MXU-shaped consumer surface) ------
    # recommend's scoring is [chunk, n_item_rows] dense matmuls at the
    # model rank — unlike the latency-bound DSGD gather loop, this is
    # the workload a TensorCore is FOR, so the serving line is where MFU
    # belongs on this framework. Pure compute measurement: row-space,
    # no exclusion lists (their construction is host metadata work, and
    # shipping 23.7M train pairs back over a narrow link to build them
    # would measure the link); only the tiny row-index chunks cross.
    if model_factors is not None:
        try:
            from large_scale_recommendation_tpu.utils.metrics import (
                top_k_recommend,
            )

            Um, Vm = model_factors  # the headline's trained tables
            serve_users = int(os.environ.get("BENCH_SERVE_USERS", 16384))
            srows = np.arange(serve_users, dtype=np.int32) % int(Um.shape[0])
            top_k_recommend(Um, Vm, srows[:2048], k=10, chunk=2048)  # warm
            t0 = time.perf_counter()
            top_k_recommend(Um, Vm, srows, k=10, chunk=2048)
            wall = time.perf_counter() - t0  # numpy outputs → synced
            extra["serving_users_per_s"] = round(serve_users / wall, 1)
            sflops = 2.0 * serve_users * int(Vm.shape[0]) * rank
            extra["serving_tflops"] = round(sflops / wall / 1e12, 3)
            extra["serving_pct_of_fp32_peak"] = round(
                100.0 * sflops / wall / 1e12 / FP32_PEAK_TFLOPS, 2)
        except Exception as ex:
            extra["serving_error"] = f"{type(ex).__name__}: {ex}"

    # ---- sustained serving: the engine vs the per-call path --------------
    # The request-stream twin of the line above: many small mixed-size
    # recommend requests through serving.engine's micro-batcher vs one
    # mesh_top_k_recommend call per request over the same prebuilt
    # catalog (scripts/serving_bench.py is the standalone CPU form). The
    # engine's whole claim — sustained users/s, O(#buckets) compiles —
    # is measured here on the bench device.
    if (model_factors is not None
            and os.environ.get("BENCH_SERVE_ENGINE", "1") == "1"):
        try:
            repo = os.path.dirname(os.path.abspath(__file__))
            if repo not in sys.path:  # scripts/ is a namespace package
                sys.path.insert(0, repo)
            from scripts.serving_bench import run as serving_engine_run

            # capped shape: the engine bench measures serving MACHINERY
            # (dispatch, bucketing, recompiles), and it builds its own
            # tables — uncapped it would allocate a second headline-size
            # model (plus catalog + bf16 copies) next to the resident one
            sr = serving_engine_run(
                num_users=min(int(model_factors[0].shape[0]), 100_000),
                num_items=min(int(model_factors[1].shape[0]), 65_536),
                rank=rank,
                n_requests=int(os.environ.get("BENCH_SERVE_REQUESTS", 256)),
                req_max=int(os.environ.get("BENCH_SERVE_REQ_MAX", 64)),
                n_dev=1)
            se = sr["extra"]
            extra["serving_engine_users_per_s"] = se["engine_users_per_s"]
            extra["serving_engine_bf16_users_per_s"] = (
                se["engine_bf16_users_per_s"])
            extra["serving_percall_users_per_s"] = se["percall_users_per_s"]
            extra["serving_engine_vs_percall"] = sr["vs_baseline"]
            extra["serving_engine_executable_variants"] = (
                se["engine_executable_variants"])
            # instrumentation-overhead pin (obs/): the same engine loop
            # with the metrics registry + tracer live vs disabled — the
            # ≤3% acceptance bound rides in the bench evidence, not as a
            # tier-1 wall-clock gate (shared-runner noise policy, see
            # test_bench_contract.py)
            if "obs_overhead_pct" in se:
                extra["obs_overhead_pct"] = se["obs_overhead_pct"]
                extra["obs_overhead_enabled_users_per_s"] = (
                    se["engine_obs_users_per_s"])
                extra["obs_overhead_disabled_users_per_s"] = (
                    se["engine_warm_users_per_s"])
        except Exception as ex:
            extra["serving_engine_error"] = f"{type(ex).__name__}: {ex}"

    # ---- ALS: bucketed-matmul normal equations, all on device ------------
    als_nnz = int(os.environ.get("BENCH_ALS_NNZ", 2_000_000))
    # vocab overrides flow through (the fallback runs THESE extras at its
    # reduced shape — full 162K×59K plans would solve mostly-empty normal
    # equations on CPU and burn the attempt window)
    (au, ai, ar), (ahu, ahi, _ahr), (anu, ani) = synthetic_like_device(
        "ml-25m", nnz=int(als_nnz / 0.95) + 1, rank=16, noise=0.1, seed=1,
        skew_lam=2.0, num_users=num_users, num_items=num_items)
    t0 = time.perf_counter()
    # one prepared set per orientation serves both ranks (chunk geometry
    # sized for the larger) — built on chip, ≤33-int readback each
    prep_u = als_ops.device_prepare_side(au, ai, ar, anu,
                                         rank_for_chunking=256)
    prep_v = als_ops.device_prepare_side(ai, au, ar, ani,
                                         rank_for_chunking=256)
    jax.block_until_ready((prep_u, prep_v))
    extra["als_plan_wall_s"] = round(time.perf_counter() - t0, 2)
    # rank 64 first: the apples-to-apples line against round 2's
    # 60.8K rows/s (same rank, scatter-formulation) — then the target
    # ranks, first-entry-wins on duplicates (BENCH_RANK may be 64 or 256)
    als_max_rank = int(os.environ.get("BENCH_ALS_MAX_RANK", 256))
    rank_iters: list = []
    for rr, it in ((64, 2), (rank, 2), (256, 1)):
        if rr <= als_max_rank and all(rr != seen for seen, _ in rank_iters):
            rank_iters.append((rr, it))
    for als_rank, iters in rank_iters:
        # λ scaled to the stand-in's signal magnitude (see run_child note);
        # "direct" mode ≙ MLlib ALS.train's regParam semantics
        init = PseudoRandomFactorInitializer(als_rank, scale=0.1)
        V = init(np.arange(ani, dtype=np.int32))

        def rounds(V, n):
            return als_ops.als_rounds(V, prep_u, prep_v, anu, ani, 0.01, n)

        jax.block_until_ready(rounds(V, 1))  # compile warm-up, BOTH sides
        t0 = time.perf_counter()
        U, V = rounds(V, iters)
        jax.block_until_ready((U, V))  # the item solve is counted in rows
        wall = time.perf_counter() - t0
        rows = (anu + ani) * iters
        extra[f"als_rank{als_rank}_rows_per_s"] = round(rows / wall, 1)
        extra[f"als_rank{als_rank}_wall_s"] = round(wall, 2)

        if als_rank == rank:
            # iALS (≙ ALS.trainImplicit; the BASELINE Criteo-implicit
            # config): reuse the SAME device-resident buckets — the
            # implicit gram/b weights are jitted transforms of the explicit
            # ones (wi' = α·v, va' = w + α·v), zero extra link traffic —
            # plus one full-table VᵀV matmul per half-step.
            iprep_u = als_ops.implicit_prepared(prep_u, 1.0)
            iprep_v = als_ops.implicit_prepared(prep_v, 1.0)

            def irounds(V, n):
                return als_ops.als_rounds(V, iprep_u, iprep_v, anu, ani,
                                          0.01, n, implicit=True)

            jax.block_until_ready(irounds(V, 1))
            t0 = time.perf_counter()
            iU, iV = irounds(V, iters)
            jax.block_until_ready((iU, iV))
            wall = time.perf_counter() - t0
            extra[f"als_rank{als_rank}_implicit_rows_per_s"] = round(
                (anu + ani) * iters / wall, 1)
            # ranking quality of the implicit fit (VERDICT r4 #8,
            # re-protocoled in ISSUE 10): held-out positives ranked
            # against SAMPLED negatives with train-seen items masked
            # out of the pool — obs.quality.sampled_ranking_metrics,
            # the ONE shared metric kernel with the online evaluator
            # (its floor/ceiling are planted-structure-pinned in
            # tests/test_obs_quality.py). The old full-unmasked-catalog
            # protocol sat at the random floor (~k/n_items ≈ 0.0002 on
            # this 59K catalog) for any merely-WEAK model — numerically
            # indistinguishable from a broken eval, which is how
            # ndcg=0.003 shipped for five rounds. The sampled protocol
            # has a KNOWN floor: a random model ranks uniformly among
            # num_negatives+1 candidates, HR10 ≈ 10/101 ≈ 0.099 — so
            # the emitted floor key prices the margin explicitly and
            # bench_regress --family quality gates the trajectory.
            from large_scale_recommendation_tpu.obs.quality import (
                catalog_coverage,
                sampled_ranking_metrics,
            )

            impl_negatives = 100
            ns = min(20_000, int(ahu.shape[0]))
            rq = sampled_ranking_metrics(
                iU, iV, np.asarray(ahu[:ns]), np.asarray(ahi[:ns]),
                k=10, num_negatives=impl_negatives,
                train_u=np.asarray(au), train_i=np.asarray(ai), seed=7)
            extra["als_implicit_ndcg"] = round(rq["ndcg"], 4)
            extra["als_implicit_hr10"] = round(rq["hr"], 4)
            extra["als_implicit_hr10_floor"] = round(
                10.0 / (impl_negatives + 1), 4)
            extra["als_implicit_valid_negatives"] = round(
                rq["valid_negatives"], 1)
            # aggregate diversity of what would actually be served:
            # fraction of the catalog surfaced across sampled users'
            # top-10 lists (a head-only model ranks fine and covers
            # nothing — the failure HR/NDCG can't see). Seeded RANDOM
            # user sample — np.unique is sorted, so a [:2048] prefix
            # would always measure the lowest-id users and bias the
            # gated number wherever id order correlates with anything
            cov_users = np.unique(np.asarray(ahu[:ns]))
            if len(cov_users) > 2048:
                cov_users = np.random.default_rng(7).choice(
                    cov_users, 2048, replace=False)
            extra["als_implicit_coverage"] = round(catalog_coverage(
                iU, iV, cov_users, k=10, train_u=np.asarray(au),
                train_i=np.asarray(ai)), 4)
            del iU, iV
            del iprep_u, iprep_v  # free before the HBM-hungry rank-256 pass
        del U, V
    del prep_u, prep_v
    extra["als_nnz"] = als_nnz

    # ---- ALS accuracy AT SCALE: rank 32, time-to-RMSE --------------------
    # The well-posed exact-solve regime (rank 128 at ~146 obs/row is
    # ill-posed — measured, docs/PERF.md); this is the measured form of the
    # MLlib retrain branch the reference trusts (OnlineSpark.scala:125-131),
    # on the SAME workload family as the DSGD headline so the two
    # time-to-target numbers are comparable. All inputs generated and
    # plan-built on device.
    if (os.environ.get("BENCH_ALS_CONV", "1") == "1"
            and int(os.environ.get("BENCH_ALS_CONV_ROUNDS", 7)) >= 1):
        conv_nnz = int(os.environ.get("BENCH_ALS_CONV_NNZ", 25_000_095))
        conv_rank = int(os.environ.get("BENCH_ALS_CONV_RANK", 32))
        conv_target = float(os.environ.get("BENCH_ALS_CONV_TARGET", 0.155))
        conv_rounds = int(os.environ.get("BENCH_ALS_CONV_ROUNDS", 7))
        nu_o, ni_o = num_users, num_items
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.ops import sgd as sgd_ops

        (cu, ci, cr), (chu, chi, chv), (cnu, cni) = synthetic_like_device(
            "ml-25m", nnz=conv_nnz, rank=16, noise=0.1, seed=4,
            skew_lam=2.0, num_users=nu_o, num_items=ni_o)
        t0 = time.perf_counter()
        cprep_u = als_ops.device_prepare_side(cu, ci, cr, cnu,
                                              rank_for_chunking=conv_rank)
        cprep_v = als_ops.device_prepare_side(ci, cu, cr, cni,
                                              rank_for_chunking=conv_rank)
        jax.block_until_ready((cprep_u, cprep_v))
        extra["als_conv_plan_wall_s"] = round(time.perf_counter() - t0, 2)
        cinit = PseudoRandomFactorInitializer(conv_rank, scale=0.1)
        Vc = cinit(np.arange(cni, dtype=np.int32))
        ones = jnp.ones(chu.shape[0], jnp.float32)

        def conv_rmse(U, V):
            sse = sgd_ops.sse_rows(U, V, chu, chi, chv, ones)
            return float(np.sqrt(float(sse) / chu.shape[0]))

        # warm-up compile on a single round (not timed)
        jax.block_until_ready(
            als_ops.als_rounds(Vc, cprep_u, cprep_v, cnu, cni, 0.01, 1))
        curve = []
        conv_wall = 0.0
        conv_time_to = None
        for rd in range(conv_rounds):
            t0 = time.perf_counter()
            Uc, Vc = als_ops.als_rounds(Vc, cprep_u, cprep_v, cnu, cni,
                                        0.01, 1)
            jax.block_until_ready((Uc, Vc))
            conv_wall += time.perf_counter() - t0
            r_now = conv_rmse(Uc, Vc)
            curve.append(round(r_now, 4))
            if conv_time_to is None and r_now <= conv_target:
                conv_time_to = conv_wall
                break
        extra[f"als_rank{conv_rank}_rmse_curve"] = curve
        extra[f"als_rank{conv_rank}_time_to_rmse_s"] = (
            None if conv_time_to is None else round(conv_time_to, 2))
        extra["als_conv_nnz"] = conv_nnz
        del cprep_u, cprep_v

    # ---- link-bound lines: online stream + PS mode -----------------------
    min_mbps = float(os.environ.get("BENCH_MIN_MBPS", "2"))
    if h2d_mbps < min_mbps:
        extra["extras_skipped"] = (
            f"online/PS lines skipped: h2d {h2d_mbps:.1f} MB/s < "
            f"{min_mbps} MB/s — their host-streamed inputs would not fit "
            "through the link in the attempt window")
        return

    # ---- online stream: Netflix-shaped micro-batches ---------------------
    # Ingest mode (emit_updates=False): the sustained-throughput number.
    # Each micro-batch ships ~16 B/rating down; nothing comes back until
    # the model is polled. A separate short updates-emitting segment
    # measures the reference-parity contract (per-batch updates-only pull).
    on_batches = int(os.environ.get("BENCH_ONLINE_BATCHES", 10))
    on_bs = int(os.environ.get("BENCH_ONLINE_BATCH", 100_000))
    ngen = SyntheticMFGenerator(num_users=480_189, num_items=17_770, rank=16,
                                noise=0.1, seed=2, skew_lam=2.0)
    batches = [ngen.generate(on_bs) for _ in range(on_batches)]
    om = OnlineMF(OnlineMFConfig(num_factors=rank, learning_rate=0.05,
                                 minibatch_size=16384, init_capacity=1 << 19))
    om.partial_fit(batches[0], emit_updates=False)  # warm-up (compile+grow)
    # per-micro-batch latency: each batch is synced before the next — the
    # streaming contract (a dstream fold applies batch t before t+1), and
    # the only definition under which p50/p99 mean anything
    lat = []
    t0 = time.perf_counter()
    for b in batches[1:]:
        t1 = time.perf_counter()
        om.partial_fit(b, emit_updates=False)
        jax.block_until_ready(om.users.array)
        lat.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    if lat:  # BENCH_ONLINE_BATCHES=1 → only the warm-up batch ran
        extra["online_ratings_per_s"] = round(
            on_bs * (on_batches - 1) / wall, 1)
        extra["online_wall_s"] = round(wall, 2)
        extra["online_batch_ms_p50"] = round(
            float(np.percentile(lat, 50)) * 1e3, 1)
        extra["online_batch_ms_p99"] = round(
            float(np.percentile(lat, 99)) * 1e3, 1)
        extra["online_batch_ms_max"] = round(max(lat) * 1e3, 1)
        # steady-state line (second half of the stream): the first batches
        # carry the one-time jit tail of the shrinking fresh-id sizes, a
        # cold-start cost a long-lived stream pays once
        half = lat[len(lat) // 2:]
        extra["online_ratings_per_s_steady"] = round(
            on_bs * len(half) / sum(half), 1)
        # warm-only latency percentiles (VERDICT r4 weak #5): the overall
        # p99 over this few batches is just the max — i.e. the cold jit
        # tail. A streaming SLA quotes the warm numbers; if a tail
        # survives HERE, it is a real stall worth a profile.
        extra["online_batch_ms_p50_warm"] = round(
            float(np.percentile(half, 50)) * 1e3, 1)
        extra["online_batch_ms_p99_warm"] = round(
            float(np.percentile(half, 99)) * 1e3, 1)
        extra["online_batch_ms_max_warm"] = round(max(half) * 1e3, 1)
    up_bs = min(20_000, on_bs)
    up_batches = [ngen.generate(up_bs) for _ in range(2)]
    om.partial_fit(up_batches[0])  # warm the updates-emitting path
    t0 = time.perf_counter()
    ups = om.partial_fit(up_batches[1])
    n_up = len(ups.user_arrays[0]) + len(ups.item_arrays[0])
    wall = time.perf_counter() - t0
    extra["online_updates_ratings_per_s"] = round(up_bs / wall, 1)
    extra["online_updates_rows_emitted"] = n_up

    # ---- durable streaming ingest: log→queue→online_train ----------------
    # The streams/ runtime's number: the SAME online micro-batch stream as
    # above, but through the durable path (event-log appends, offset-
    # stamped tail reads, bounded queue, per-batch WAL-offset checkpoints
    # — scripts/streams_bench.py is the standalone form). vs_bare is the
    # throughput retention of durability; lag 0 at exit means the driver
    # kept up with the log end-to-end.
    if os.environ.get("BENCH_STREAMS", "1") == "1":
        try:
            repo = os.path.dirname(os.path.abspath(__file__))
            if repo not in sys.path:  # scripts/ is a namespace package
                sys.path.insert(0, repo)
            from scripts.streams_bench import run as streams_bench_run

            st = streams_bench_run(
                num_users=20_000, num_items=5_000, rank=rank,
                n_batches=int(os.environ.get("BENCH_STREAMS_BATCHES", 8)),
                batch_records=int(os.environ.get("BENCH_STREAMS_BATCH",
                                                 50_000)))
            se = st["extra"]
            extra["streams_ingest_ratings_per_s"] = (
                se["ingest_ratings_per_s"])
            extra["streams_ingest_vs_bare"] = st["vs_baseline"]
            extra["streams_log_append_ratings_per_s"] = (
                se["log_append_ratings_per_s"])
            extra["streams_ingest_lag_records"] = se["ingest_lag_records"]
            extra["streams_ingest_checkpoints"] = (
                se["checkpoints_written"])
        except Exception as ex:
            extra["streams_ingest_error"] = f"{type(ex).__name__}: {ex}"

    # ---- PS-mode offline throughput --------------------------------------
    from large_scale_recommendation_tpu.ps.mf import (
        PSOfflineMF,
        PSOfflineMFConfig,
    )

    ps_nnz = int(os.environ.get("BENCH_PS_NNZ", 200_000))
    pgen = SyntheticMFGenerator(num_users=10_000, num_items=2_500, rank=16,
                                noise=0.1, seed=3, skew_lam=2.0)
    ps_ratings = pgen.generate(ps_nnz)
    # chunk_size 2048 (was 512): each pull chunk costs a round-trip
    # through the PS queues and, on the tunneled bench device, the
    # ~30-70 ms link — the same RTT-amortization lever as the adaptive
    # line (on-chip r5 the 512 config measured 21.6K r/s, RTT-shaped)
    # BENCH_PS_CHUNK: 2048 is the measured CPU optimum (coarser chunks
    # lose worker-pipeline overlap — docs/PERF.md "PS pull-chunk
    # granularity"); on a tunneled chip the RTT term may favor larger,
    # a one-env-var experiment for the next live window.
    ps_cfg = PSOfflineMFConfig(num_factors=rank, iterations=2,
                               learning_rate=0.05, lr_schedule="inverse_sqrt",
                               worker_parallelism=4, ps_parallelism=4,
                               pull_limit=4,
                               chunk_size=int(os.environ.get(
                                   "BENCH_PS_CHUNK", 2048)),
                               minibatch_size=4096)
    # warm-up on a small run: the PS line measures the threads+queues
    # protocol + jitted chunk kernels, not one-time XLA compiles (every
    # other line here warms its kernels the same way)
    PSOfflineMF(ps_cfg).offline(pgen.generate(max(ps_nnz // 10, 5_000)))
    t0 = time.perf_counter()
    PSOfflineMF(ps_cfg).offline(ps_ratings)
    wall = time.perf_counter() - t0
    extra["ps_ratings_per_s"] = round(ps_nnz * ps_cfg.iterations / wall, 1)
    extra["ps_wall_s"] = round(wall, 2)

    # ---- PS online+batch combo (the reference's most intricate mode,
    # PSOfflineOnlineMF.scala) — online stream with ONE mid-stream batch
    # retrain trigger; events/s counts each rating exactly once ----------
    from large_scale_recommendation_tpu.ps import (
        BATCH_TRIGGER,
        PSOnlineBatchConfig,
        PSOnlineBatchMF,
    )

    ad_nnz = int(os.environ.get("BENCH_PS_ADAPTIVE_NNZ", 50_000))
    aru, ari, arv, _ = pgen.generate(ad_nnz).to_numpy()
    events: list = list(zip(aru[: ad_nnz // 2].tolist(),
                            ari[: ad_nnz // 2].tolist(),
                            arv[: ad_nnz // 2].tolist()))
    events.append(BATCH_TRIGGER)
    events.extend(zip(aru[ad_nnz // 2:].tolist(),
                      ari[ad_nnz // 2:].tolist(),
                      arv[ad_nnz // 2:].tolist()))
    # online_chunk_size is the RTT-amortization knob: every drained chunk
    # costs one pull round-trip, and on the tunneled bench device a
    # round-trip is ~30-70 ms — at the 512 default the line measures the
    # link (~10K ev/s ceiling; observed 5.3K on-chip r5). 4096 keeps the
    # same vectorized-update math (a real deployment tunes this to its
    # link, exactly like the reference's pullLimit window).
    # chunk_size is the BATCH-REPLAY pull granularity (chunks of unique
    # ITEMS, ps/adaptive.py) — the same RTT-amortization lever as
    # online_chunk_size. 4096 measured +36% on CPU (21.0K -> 28.5K ev/s
    # at this config) and cuts the tunneled replay pulls to one per
    # item-vocab sweep (~5x fewer round-trips at this vocab).
    ad_cfg = PSOnlineBatchConfig(
        num_factors=rank, iterations=2, learning_rate=0.05,
        lr_schedule="inverse_sqrt", worker_parallelism=4,
        ps_parallelism=4,
        chunk_size=int(os.environ.get("BENCH_AD_CHUNK", 4096)),
        minibatch_size=4096, online_chunk_size=4096)
    # warm-up (same policy as every line here): the SAME stream, so the
    # pow2 shape buckets of the chunked online path and the batch-replay
    # tables (history-sized — a smaller warm stream lands in different
    # buckets and the measured run would re-pay ~1s of XLA compiles)
    PSOnlineBatchMF(ad_cfg).run(events)
    t0 = time.perf_counter()
    PSOnlineBatchMF(ad_cfg).run(events)
    wall = time.perf_counter() - t0
    extra["ps_adaptive_ratings_per_s"] = round(ad_nnz / wall, 1)
    extra["ps_adaptive_wall_s"] = round(wall, 2)


# --------------------------------------------------------------------------
# Final-line emit: the machine-readable contract
# --------------------------------------------------------------------------

def _emit_final(result: dict) -> None:
    """Print the one-line JSON summary as the LAST line of output.

    The round driver parses the last stdout line; some wrappers merge
    stderr into stdout (2>&1), where an unflushed stderr comment can
    land AFTER the summary and turn it into `parsed: null`. Flushing
    stderr first and the summary last pins the ordering in the merged
    stream for both the success and CPU-fallback paths."""
    sys.stderr.flush()
    print(json.dumps(result), flush=True)


def _failure_result(errors: list[str]) -> dict:
    """The total-failure form of the one-line contract: value 0, every
    attempt's error recorded, the committed on-chip artifact referenced
    so the round still points at real evidence."""
    return {
        "metric": "ratings/sec/chip (DSGD, ML-25M-shaped)",
        "value": 0.0,
        "unit": "ratings/s",
        "vs_baseline": 0.0,
        "error": " | ".join(e[:500] for e in errors),
        "extra": {"on_chip_artifact": ON_CHIP_ARTIFACT},
    }


# --------------------------------------------------------------------------
# Parent: retry orchestration. Never imports jax itself.
# --------------------------------------------------------------------------

def _attempt(env_overrides: dict[str, str], timeout: float):
    """Run one child attempt.

    Returns ``(json_dict | None, tail_of_output, hung)`` — ``hung`` is the
    structured signal that the child consumed its whole window (wedged
    backend), distinct from a quick failure worth retrying."""
    env = dict(os.environ)
    env["BENCH_PARENT"] = "1"  # the child's extras deadline keys off this
    env.update(env_overrides)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        # The child prints its headline line BEFORE the extras run, so a
        # kill mid-extras still leaves a complete measurement to salvage.
        out = (e.stdout.decode(errors="replace")
               if isinstance(e.stdout, bytes) else (e.stdout or ""))
        for ln in reversed([x for x in out.splitlines() if x.strip()]):
            try:
                parsed = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if "value" in parsed:
                parsed.setdefault("extra", {})["extras_truncated"] = (
                    f"child killed at {timeout}s during extras; headline "
                    "measurement completed")
                return parsed, "salvaged headline from timed-out child", False
        tail = ((e.stderr or b"")[-2000:] if isinstance(e.stderr, bytes)
                else (e.stderr or "")[-2000:])
        return None, f"timeout after {timeout}s; stderr tail: {tail}", True
    out_lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode == 0 and out_lines:
        try:
            parsed = json.loads(out_lines[-1])
            if "value" in parsed:
                return parsed, proc.stderr[-1000:], False
        except json.JSONDecodeError:
            pass
    tail = (proc.stderr or proc.stdout)[-2000:]
    return None, f"rc={proc.returncode}; tail: {tail}", False


def _looks_transient(tail: str) -> bool:
    """Backend/availability failures are worth a retry; a deterministic
    crash (ImportError, assertion) is not — retrying it only delays the
    CPU fallback and misattributes the root cause."""
    needles = ("timeout", "UNAVAILABLE", "backend", "Backend", "TPU",
               "axon", "pjrt", "PJRT", "DEADLINE", "RESOURCE_EXHAUSTED")
    return any(n in tail for n in needles)


def _device_preprobe(timeout: float) -> tuple[bool, str]:
    """Cheap child that only lists devices on the default backend.

    A dead/wedged TPU tunnel makes ``jax.devices()`` hang FOREVER (observed:
    the relay process dies and never recovers within a session). Without
    this probe the first real attempt burns its whole BENCH_TIMEOUT window
    discovering that; with it, a dead backend costs ~3 minutes before the
    CPU fallback.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0])"],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return False, f"device probe hung for {timeout}s (dead tunnel?)"
    if proc.returncode != 0:
        return False, f"device probe rc={proc.returncode}: {proc.stderr[-500:]}"
    return True, proc.stdout.strip()


def main() -> None:
    per_attempt = float(os.environ.get("BENCH_TIMEOUT", 2400))
    errors: list[str] = []

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # operator explicitly wants CPU: the child will force_cpu() and
        # never touch the default backend — probing it would only hang on
        # a dead tunnel and then clobber the configured workload with the
        # reduced fallback
        result, tail, _ = _attempt({}, per_attempt)
        if result is not None:
            _emit_final(result)
            return
        errors.append(f"forced-cpu attempt: {tail}")
        _cpu_fallback(per_attempt, errors)
        return

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 180))
    ok, probe_msg = _device_preprobe(probe_timeout)
    if not ok and "hung" in probe_msg:
        # Only a HANG forfeits the TPU attempts (a dead tunnel never heals
        # within a session — observed). A fast non-zero probe exit may be a
        # transient init failure: fall through to the normal attempt+retry
        # path, which handles exactly that.
        print(f"# device pre-probe failed: {probe_msg}", file=sys.stderr)
        errors.append(f"pre-probe: {probe_msg}")
        _cpu_fallback(per_attempt, errors)
        return
    print(f"# device pre-probe: {probe_msg}", file=sys.stderr)

    result, tail, hung = _attempt({}, per_attempt)
    if result is not None:
        _emit_final(result)
        return
    errors.append(f"attempt 1: {tail}")
    print(f"# bench attempt 1 failed: {tail[-300:]}", file=sys.stderr)
    # A full-window hang (wedged TPU tunnel — observed to persist for
    # hours) will not heal in 15 s; burning a second full window just
    # delays the CPU fallback. Retry only quick transient FAILURES.
    if _looks_transient(tail) and not hung:
        time.sleep(15)
        # Re-probe before burning the retry window: a helper/tunnel that
        # died MID-attempt (observed r5: remote_compile "Connection
        # refused", then the retry hung its entire window) makes the
        # device probe hang too — skip straight to the fallback.
        ok2, probe2 = _device_preprobe(probe_timeout)
        if not ok2 and "hung" in probe2:
            # same policy as the first probe: only a HANG forfeits — a
            # fast non-zero exit may be the same transient the retry
            # exists to absorb
            print(f"# retry pre-probe failed: {probe2}", file=sys.stderr)
            errors.append(f"retry pre-probe: {probe2}")
            _cpu_fallback(per_attempt, errors)
            return
        result, tail, _ = _attempt({}, per_attempt)
        if result is not None:
            _emit_final(result)
            return
        errors.append(f"attempt 2: {tail}")
        print(f"# bench attempt 2 failed: {tail[-300:]}", file=sys.stderr)

    _cpu_fallback(per_attempt, errors)


# Reduced fallback config in the RECOVERABLE regime: the vocab shrinks
# WITH nnz so obs/row stays ≥ ~100 (docs/PERF.md) — 950K train ratings
# over 8192 users (~116/user) × 3072 items (~309/item). The r3 fallback
# ran 1M nnz over the full 162K×59K vocab (~6 obs/user): below the bound,
# its RMSE curve ROSE and time-to-target was null — throughput with zero
# convergence information. The 0.135 target is pre-registered from a
# measured CPU run of exactly this config (descending curve 0.272 → 0.134,
# target hit at sweep 12 of 20). Module-level so
# tests/test_bench_contract.py pins the regime against config drift.
# one copy of the evidence pointer both fallback JSON paths emit — the
# most recent committed on-chip measurement (update alongside the artifact)
ON_CHIP_ARTIFACT = (
    "docs/BENCH_TPU_r5_full.json — full driver-grade bench measured on "
    "TPU v5 lite in round 5 (17.60M ratings/s headline); "
    "docs/BENCH_TPU_r5_manual.json is the independent second window")

CPU_FALLBACK_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_FORCE_CPU": "1",
    "BENCH_NNZ": "1000000",
    "BENCH_USERS": "8192",
    "BENCH_ITEMS": "3072",
    "BENCH_RANK": "32",
    "BENCH_ITERS": "20",
    "BENCH_MB": "8192",
    "BENCH_BLOCKS": "4",
    "BENCH_RMSE_TARGET": "0.135",
    # extras RUN on the fallback (labeled CPU by the device field) at
    # reduced sizes, so the online/PS/ALS lines are recorded even when the
    # chip is unreachable — r3 lost them entirely to the skip
    "BENCH_ALS_NNZ": "500000",
    "BENCH_ALS_MAX_RANK": "64",
    "BENCH_ALS_CONV_NNZ": "1000000",
    "BENCH_ALS_CONV_TARGET": "0.135",
    "BENCH_ALS_CONV_ROUNDS": "7",
    "BENCH_ONLINE_BATCHES": "8",
    "BENCH_ONLINE_BATCH": "50000",
    # full-size PS line: the ingest-path fixes made it cheap enough that
    # the reduced 100K run's thread-setup overhead dominated the number
    "BENCH_PS_NNZ": "200000",
}


def _cpu_fallback(per_attempt: float, errors: list[str]) -> None:
    """CPU fallback on a reduced workload — a real (if slower) number beats
    no number; the error field records the per-attempt failures."""
    cpu_env = dict(CPU_FALLBACK_ENV)
    if os.environ.get("BENCH_DATA"):
        # real-data run: the synthetic-calibrated target (0.135) is
        # meaningless against a real file — drop it so the child keeps
        # the real-data 0.85 target; the nnz cap stays (a seeded
        # subsample). The subsample thins obs/row, so the target may
        # legitimately be unreachable in the fallback; the RMSE curve
        # still carries the information. BENCH_USERS/BENCH_ITEMS stay
        # too: the real-data headline ignores them, but the SYNTHETIC
        # extras read them, and without the shrink those lines would
        # build 162K×59K plans on CPU and burn the attempt window.
        cpu_env.pop("BENCH_RMSE_TARGET", None)
    nnz_cpu = os.environ.get("BENCH_NNZ_CPU")
    if nnz_cpu:
        # scale the vocab WITH the nnz override so obs/row (and thus the
        # pre-registered target's reachability) is preserved — otherwise
        # the override silently re-enters the unrecoverable regime
        scale = int(nnz_cpu) / int(cpu_env["BENCH_NNZ"])
        cpu_env["BENCH_NNZ"] = nnz_cpu
        cpu_env["BENCH_USERS"] = str(
            max(256, int(int(cpu_env["BENCH_USERS"]) * scale)))
        cpu_env["BENCH_ITEMS"] = str(
            max(128, int(int(cpu_env["BENCH_ITEMS"]) * scale)))
    result, tail, _ = _attempt(cpu_env, per_attempt)
    if result is not None:
        result["error"] = (
            "default-backend attempts failed; value is a reduced "
            "CPU-fallback run. " + " | ".join(e[:300] for e in errors)
        )
        # the on-chip evidence exists even when THIS run can't reach the
        # chip: point consumers at the committed artifact
        result.setdefault("extra", {})["on_chip_artifact"] = ON_CHIP_ARTIFACT
        _emit_final(result)
        return
    errors.append(f"cpu fallback: {tail}")

    # Total failure: still emit the one-line JSON contract.
    _emit_final(_failure_result(errors))


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child()
    else:
        main()
