"""Benchmark: DSGD training throughput on one chip.

Metric: ratings/sec/chip on a synthetic ML-25M-shaped DSGD workload
(BASELINE.md north star: ratings/sec/chip; the reference publishes no
numbers, so the baseline is the reference's own inner-loop style — a
sequential per-rating NumPy SGD loop, the direct analogue of
DSGDforMF.scala:398-417 / netlib ddot — measured here on the same host).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: BENCH_NNZ, BENCH_RANK, BENCH_ITERS, BENCH_USERS, BENCH_ITEMS,
BENCH_MB (minibatch), BENCH_BLOCKS.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _numpy_sequential_baseline(ratings, rank, sample=150_000, lr=0.01,
                               lam=0.1, seed=0):
    """Reference-style sequential per-rating SGD (the Flink/Spark inner loop,
    DSGDforMF.scala:398-417) in NumPy — ratings/sec on host CPU."""
    ru, ri, rv, _ = ratings.to_numpy()
    n = min(sample, len(ru))
    rng = np.random.default_rng(seed)
    nu, ni = int(ru.max()) + 1, int(ri.max()) + 1
    U = rng.normal(0, 0.1, (nu, rank))
    V = rng.normal(0, 0.1, (ni, rank))
    t0 = time.perf_counter()
    for j in range(n):
        u, i, r = ru[j], ri[j], rv[j]
        pu, qv = U[u], V[i]
        e = r - pu @ qv
        U[u] = pu - lr * (lam * pu - e * qv)
        V[i] = qv - lr * (lam * qv - e * pu)
    dt = time.perf_counter() - t0
    return n / dt


def main():
    nnz = int(os.environ.get("BENCH_NNZ", 2_000_000))
    rank = int(os.environ.get("BENCH_RANK", 64))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    num_users = int(os.environ.get("BENCH_USERS", 100_000))
    num_items = int(os.environ.get("BENCH_ITEMS", 20_000))
    mb = int(os.environ.get("BENCH_MB", 8192))
    blocks = int(os.environ.get("BENCH_BLOCKS", 4))

    import jax

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=num_users, num_items=num_items,
                               rank=min(rank, 32), noise=0.1, seed=0)
    ratings = gen.generate(nnz)

    cfg = DSGDConfig(
        num_factors=rank, lambda_=0.05, iterations=iters,
        learning_rate=0.05, lr_schedule="constant", seed=0,
        minibatch_size=mb, init_scale=0.1,
    )

    # Warm-up: compile (and one full run, first compile is slow).
    warm_cfg = DSGDConfig(
        num_factors=rank, lambda_=0.05, iterations=1,
        learning_rate=0.05, lr_schedule="constant", seed=0,
        minibatch_size=mb, init_scale=0.1,
    )
    DSGD(warm_cfg).fit(ratings, num_blocks=blocks).U.block_until_ready()

    solver = DSGD(cfg)
    t0 = time.perf_counter()
    model = solver.fit(ratings, num_blocks=blocks)
    model.U.block_until_ready()
    dt = time.perf_counter() - t0
    # NOTE: dt includes the host blocking pass (fair: the reference's
    # supersteps include their shuffles).
    throughput = nnz * iters / dt

    baseline = _numpy_sequential_baseline(ratings, rank)

    rmse = model.rmse(gen.generate(100_000))
    result = {
        "metric": f"ratings/sec/chip (synthetic DSGD rank={rank}, "
                  f"{nnz // 1_000_000}M ratings, {blocks}x{blocks} strata)",
        "value": round(throughput, 1),
        "unit": "ratings/s",
        "vs_baseline": round(throughput / baseline, 2),
    }
    print(json.dumps(result))
    # Extra context on stderr (not part of the one-line contract)
    import sys
    print(
        f"# wall={dt:.2f}s iters={iters} rmse={rmse:.4f} "
        f"numpy_baseline={baseline:.0f} r/s device={jax.devices()[0]}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
