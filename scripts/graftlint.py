#!/usr/bin/env python
"""graftlint runner: check the codebase's sharding/concurrency/
zero-cost-observability invariants (tools/graftlint/) and emit a human
table (stderr) plus ONE machine-readable JSON line as the LAST stdout
line — the established ``_emit_final`` contract every bench harness in
this repo follows (stderr flushed first, so a 2>&1-merged wrapper
always parses the final line).

Usage:

    python scripts/graftlint.py                      # report, exit 0
    python scripts/graftlint.py --strict             # CI gate
    python scripts/graftlint.py --rules obs-gate,lock-gap path/ ...
    python scripts/graftlint.py --disable host-sync
    python scripts/graftlint.py --write-baseline     # grandfather now
    python scripts/graftlint.py --json out.json      # full doc to file

Exit codes: 0 clean (or non-strict report); 1 unsuppressed findings
under --strict, reason-less baseline entries under --strict, or
parse/usage errors.

Pure-stdlib AST analysis — no jax import, safe to run anywhere,
sub-second on the whole package.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import ALL_CHECKERS, run_lint  # noqa: E402
from tools.graftlint.core import DEFAULT_BASELINE, write_baseline  # noqa: E402


def _emit_final(result: dict) -> None:
    """stderr first, the JSON line last — pinned for merged streams."""
    sys.stderr.flush()
    print(json.dumps(result), flush=True)


def render_table(res) -> str:
    """Human-readable findings + summary table."""
    lines = []
    if res.findings:
        w = max(len(f.rule) for f in res.findings)
        for f in res.findings:
            lines.append(f"{f.rule:<{w}}  {f.path}:{f.line}  "
                         f"[{f.symbol}]")
            lines.append(f"{'':<{w}}  {f.message}")
            if f.line_text.strip():
                lines.append(f"{'':<{w}}  > {f.line_text.strip()}")
    lines.append("")
    lines.append(f"{'rule':<18} {'findings':>8} {'baselined':>9} "
                 f"{'suppressed':>10}")
    per = res.per_rule()
    base_per: dict[str, int] = {}
    for f in res.baselined:
        base_per[f.rule] = base_per.get(f.rule, 0) + 1
    sup_per: dict[str, int] = {}
    for f in res.suppressed:
        sup_per[f.rule] = sup_per.get(f.rule, 0) + 1
    for rule in res.rules_run:
        lines.append(f"{rule:<18} {per.get(rule, 0):>8} "
                     f"{base_per.get(rule, 0):>9} "
                     f"{sup_per.get(rule, 0):>10}")
    lines.append(f"{'TOTAL':<18} {len(res.findings):>8} "
                 f"{len(res.baselined):>9} {len(res.suppressed):>10}"
                 f"    ({res.files_scanned} files)")
    for e in res.baseline_errors:
        lines.append(f"baseline ERROR: {e}")
    for e in res.baseline_stale:
        lines.append(f"baseline stale (fixed? remove the entry): "
                     f"{e.get('rule')}:{e.get('path')}:{e.get('symbol')}")
    for e in res.parse_errors:
        lines.append(f"parse ERROR: {e}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the production "
                        "package)")
    p.add_argument("--rules", help="comma-separated rule subset "
                   f"(have: {','.join(sorted(ALL_CHECKERS))})")
    p.add_argument("--disable", default="",
                   help="comma-separated rules to skip")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (empty string disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather current unsuppressed findings "
                        "(each entry then needs a reason filled in)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any unsuppressed finding or "
                        "reason-less baseline entry")
    p.add_argument("--json", dest="json_out",
                   help="write the full machine-readable doc here too")
    args = p.parse_args(argv)

    rules = args.rules.split(",") if args.rules else None
    disable = [r for r in args.disable.split(",") if r]
    try:
        res = run_lint(paths=args.paths or None, rules=rules,
                       disable=disable,
                       baseline_path=args.baseline or None)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        _emit_final({"metric": "graftlint unsuppressed findings",
                     "value": -1, "unit": "findings", "vs_baseline": 0,
                     "error": str(e), "extra": {}})
        return 1

    if args.write_baseline:
        if not args.baseline:
            # --baseline '' means "no baseline in play" — silently
            # falling back to rewriting the committed default would
            # touch the exact file the user opted out of
            print("graftlint: --write-baseline needs a --baseline "
                  "path (got an explicitly disabled baseline)",
                  file=sys.stderr)
            _emit_final({"metric": "graftlint unsuppressed findings",
                         "value": -1, "unit": "findings",
                         "vs_baseline": 0,
                         "error": "--write-baseline with disabled "
                                  "baseline", "extra": {}})
            return 1
        path = args.baseline
        if not os.path.isabs(path):
            path = os.path.join(REPO, path)
        write_baseline(path, res.findings + res.baselined,
                       rules_run=res.rules_run,
                       scanned_paths=res.scanned_paths)
        print(f"baseline written: {path} "
              f"({len(res.findings) + len(res.baselined)} entries — "
              f"fill in every reason)", file=sys.stderr)

    print(render_table(res), file=sys.stderr)

    doc = res.to_dict()
    strict_ok = (not res.findings and not res.baseline_errors
                 and not res.parse_errors)
    final = {
        "metric": "graftlint unsuppressed findings",
        "value": len(res.findings),
        "unit": "findings",
        "vs_baseline": len(res.baselined),
        "extra": doc | {"strict": bool(args.strict),
                        "strict_ok": strict_ok},
    }
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(final, fh, indent=2)
    _emit_final(final)
    if res.parse_errors:
        return 1  # a typo'd path / unparseable file is never a clean
        # run, strict or not (the docstring's exit-code contract)
    if args.strict and not strict_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
