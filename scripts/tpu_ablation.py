"""Kernel-lever ablation harness: one command, one table.

Runs the DSGD kernel levers documented in docs/PERF.md (minibatch size,
intra-minibatch locality sort, collision mode, precomputed scales) on the
CURRENT default device over the device-pipeline workload, and prints
per-sweep wall + convergence after N sweeps for each combination — the
tool for turning PERF.md's "levers" section into measured numbers on real
hardware (CPU runs give relative-convergence signal only).

Usage:
    python scripts/tpu_ablation.py                 # default grid
    ABL_NNZ=4000000 ABL_SWEEPS=3 python scripts/tpu_ablation.py
    ABL_CPU=1 python scripts/tpu_ablation.py       # force the CPU backend

Output: one row per combination —
    mb=32768 sort=none  collision=mean  sweep_s=...  rmse@N=...
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("ABL_CPU") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()

    import numpy as np
    import jax

    from large_scale_recommendation_tpu.core.updaters import (
        RegularizedSGDUpdater,
        warm_boost_lr,
    )
    from large_scale_recommendation_tpu.data.device_blocking import (
        device_block_problem,
        init_factors_device,
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.ops import sgd as sgd_ops

    nnz = int(os.environ.get("ABL_NNZ", 25_000_095))
    rank = int(os.environ.get("ABL_RANK", 128))
    k = int(os.environ.get("ABL_BLOCKS", 8))
    sweeps = int(os.environ.get("ABL_SWEEPS", 3))
    mbs = [int(x) for x in os.environ.get("ABL_MBS", "16384,32768").split(",")]
    sorts = os.environ.get("ABL_SORTS", "none,item").split(",")

    print(f"# device={jax.devices()[0]} nnz={nnz} rank={rank} k={k} "
          f"sweeps={sweeps}", flush=True)
    (u, i, r), (hu, hi, hr), (nu, ni) = synthetic_like_device(
        "ml-25m", nnz=nnz, rank=16, noise=0.1, seed=0, skew_lam=2.0)
    train_nnz = int(u.shape[0])  # 95% split — ratings visited per sweep
    upd = RegularizedSGDUpdater(0.3, 0.1, warm_boost_lr())

    for mb in mbs:
        for sort in sorts:
            sort_arg = None if sort in ("none", "") else sort
            p = device_block_problem(u, i, r, nu, ni, num_blocks=k,
                                     minibatch_multiple=mb, seed=0,
                                     minibatch_sort=sort_arg)
            hur, hir, hmask = p.holdout_rows(hu, hi)
            n_eval = float(np.asarray(hmask).sum())
            U, V = init_factors_device(p, rank, scale=0.08)
            kw = dict(updater=upd, minibatch=mb, num_blocks=k,
                      iterations=1, collision="mean")
            args = (p.su, p.si, p.sv, p.sw, p.omega_u, p.omega_v,
                    p.icu, p.icv)
            Uw, Vw = sgd_ops.dsgd_train(U, V, *args, **kw, t0=0)
            jax.block_until_ready((Uw, Vw))  # compile warm-up
            del Uw, Vw
            walls = []
            for t in range(sweeps):
                t0 = time.perf_counter()
                U, V = sgd_ops.dsgd_train(U, V, *args, **kw, t0=t)
                jax.block_until_ready((U, V))
                walls.append(time.perf_counter() - t0)
            sse = sgd_ops.sse_rows(U, V, hur, hir, hr, hmask)
            rmse = float(np.sqrt(float(sse) / n_eval))
            rate = train_nnz / (sum(walls) / len(walls))
            print(f"mb={mb:6d} sort={sort:5s} "
                  f"sweep_s={sum(walls)/len(walls):7.3f} "
                  f"ratings_per_s={rate:12.0f} "
                  f"rmse@{sweeps}={rmse:.4f}", flush=True)


if __name__ == "__main__":
    main()
