"""A/B probe for the round-5 ALS gather levers on the CURRENT device.

Mirrors bench.py's ALS line exactly (same workload, plans, warm-up and
timing protocol) and measures, at each probed rank:

  f32       — the production path (partner-lexsorted plans as of r5)
  bf16      — ALSConfig(gram_dtype="bf16"): half-width fixed-side gather
              + native-MXU bf16 einsum inputs, f32 accumulation/solve

The pre-lever baseline is the in-bench line recorded by the LAST run of
the old code on the same chip (BENCH JSON `als_rank128_rows_per_s`) —
compare against that for the partner-sort effect, and f32-vs-bf16 here
for the dtype effect. Prints one JSON line.

Usage: python scripts/als_probe.py  [ALS_PROBE_RANKS=64,128,256]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("PROBE_CPU") == "1":
        # config-level CPU pin — env vars alone lose to the axon site hook
        # and wedge on a dead tunnel (utils/platform.py)
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()
    import jax
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.core.initializers import (
        PseudoRandomFactorInitializer,
    )
    from large_scale_recommendation_tpu.data.device_blocking import (
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.ops import als as als_ops
    from large_scale_recommendation_tpu.utils.platform import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    dev = jax.devices()[0]
    out: dict = {"device": str(dev.device_kind) + str(dev.id)}

    from large_scale_recommendation_tpu.data.movielens import (
        vocab_overrides_from_env,
    )

    als_nnz = int(os.environ.get("BENCH_ALS_NNZ", 2_000_000))
    num_users, num_items = vocab_overrides_from_env()
    (au, ai, ar), _, (anu, ani) = synthetic_like_device(
        "ml-25m", nnz=int(als_nnz / 0.95) + 1, rank=16, noise=0.1, seed=1,
        skew_lam=2.0, num_users=num_users, num_items=num_items)
    t0 = time.perf_counter()
    prep_u = als_ops.device_prepare_side(au, ai, ar, anu,
                                         rank_for_chunking=256)
    prep_v = als_ops.device_prepare_side(ai, au, ar, ani,
                                         rank_for_chunking=256)
    jax.block_until_ready((prep_u, prep_v))
    out["plan_wall_s"] = round(time.perf_counter() - t0, 2)

    ranks = [int(r) for r in os.environ.get(
        "ALS_PROBE_RANKS", "64,128,256").split(",")]
    for rank in ranks:
        iters = 1 if rank >= 256 else 2
        init = PseudoRandomFactorInitializer(rank, scale=0.1)
        V0 = init(np.arange(ani, dtype=np.int32))
        for label, dt in (("f32", None), ("bf16", jnp.bfloat16)):
            def rounds(V, n):
                return als_ops.als_rounds(V, prep_u, prep_v, anu, ani,
                                          0.01, n, gram_dtype=dt)

            jax.block_until_ready(rounds(V0, 1))  # warm-up both sides
            t0 = time.perf_counter()
            U, V = rounds(V0, iters)
            jax.block_until_ready((U, V))
            wall = time.perf_counter() - t0
            out[f"als_rank{rank}_{label}_rows_per_s"] = round(
                (anu + ani) * iters / wall, 1)
        # quality guard at the FIRST probed rank only: one extra round per
        # mode suffices (tests/test_als.py pins f32/bf16 parity across the
        # surface) and chip-window seconds are the binding resource
        if rank == ranks[0]:
            U32, _ = als_ops.als_rounds(V0, prep_u, prep_v, anu, ani,
                                        0.01, 1)
            U16, _ = als_ops.als_rounds(V0, prep_u, prep_v, anu, ani,
                                        0.01, 1, gram_dtype=jnp.bfloat16)
            num = float(jnp.abs(U16 - U32).max())
            den = float(jnp.abs(U32).max())
            out[f"als_rank{rank}_bf16_rel_err"] = round(
                num / max(den, 1e-9), 5)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
