"""Pallas vs XLA DSGD kernel: the gather-ceiling experiment, measured.

Round-3 verdict: the claim "a Pallas kernel has no physics headroom" was
argued from an XLA gather microbenchmark, not from a pipelined kernel —
and the host CPU within 2x of the TPU kernel says headroom exists. This
script MEASURES the question on the current device:

  xla    — ops.sgd.sgd_block_sweep (the production kernel) on one
           realistic (stratum, block) visit;
  take   — ops.pallas_sgd.pallas_block_sweep, VMEM-staged factor slices,
           vectorized jnp.take gather (Mosaic dynamic-gather);
  loop   — same staging, per-entry fori_loop gather (guaranteed lowering).

The Pallas kernels stage the block's CONTIGUOUS factor-row ranges in VMEM
(one big DMA each way) and do all row access VMEM-side — the structural
lever the XLA gather cannot express (its every row access is an HBM
latency round trip, measured ~0.6% of HBM peak, docs/PERF.md).

A Mosaic lowering failure is itself a result: it prints as
``variant=... FAILED <error>`` — record it, don't hide it.

Usage:
    python scripts/pallas_probe.py                    # current device
    PROBE_RANK=64 PROBE_MB=1024 python scripts/pallas_probe.py
    PROBE_CPU=1 python scripts/pallas_probe.py        # interpret fallback

Defaults model one ML-25M block visit at k=32 (rpb_u 5080, rpb_v 1848,
~24K ratings) — the production operating point since the k=16 visit
OOM'd under this jax's 2× stream buffering (docs/MOSAIC_AOT.json);
VMEM-sized for v5e at rank 128.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("PROBE_CPU") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    rank = int(os.environ.get("PROBE_RANK", 128))
    mb = int(os.environ.get("PROBE_MB", 2048))
    rpb_u = int(os.environ.get("PROBE_RPB_U", 5080))
    rpb_v = int(os.environ.get("PROBE_RPB_V", 1848))
    e = int(os.environ.get("PROBE_NNZ", 24576))
    e -= e % mb
    reps = int(os.environ.get("PROBE_REPS", 5))
    lr, lam = 0.1, 0.1

    print(f"# device={dev} rank={rank} mb={mb} rpb_u={rpb_u} "
          f"rpb_v={rpb_v} nnz={e}", flush=True)

    from large_scale_recommendation_tpu.ops import sgd as sgd_ops
    from large_scale_recommendation_tpu.ops.pallas_sgd import probe_variants

    res = probe_variants(rank=rank, mb=mb, rpb_u=rpb_u, rpb_v=rpb_v,
                         nnz=e, reps=reps,
                         sort=os.environ.get("PROBE_SORT") == "1",
                         interpret=not on_tpu)
    summary = {
        "device": str(dev), "tpu": on_tpu, "rank": rank, "mb": mb,
        "rpb_u": rpb_u, "rpb_v": rpb_v, "nnz": e, "reps": reps,
    }
    for label, val in res.items():
        if isinstance(val, str):
            print(f"{label:12s} {val}", flush=True)
            summary[label] = val
        else:
            kern = "pallas" if label.startswith("pallas") else "xla"
            bpv = sgd_ops.dsgd_bytes_per_sweep(
                e, rank, kernel=kern, num_blocks=1,
                rows_u=rpb_u, rows_v=rpb_v)
            gbs = round(val / e * bpv / 1e9, 1)
            print(f"{label:12s} ratings_per_s={val:14.0f} "
                  f"effective_hbm_gbs={gbs:8.1f}", flush=True)
            summary[f"{label}_ratings_per_s"] = val
            summary[f"{label}_effective_hbm_gbs"] = gbs

    # machine-readable contract (same as bench.py::_emit_final): flush
    # stderr FIRST so a 2>&1-merging wrapper still sees the JSON summary
    # as the genuinely last line, diffable across rounds like BENCH
    # artifacts
    sys.stderr.flush()
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
