"""Render a metrics snapshot as a human-readable table.

One command to see serving p99, ingest lag, and train step time side by
side::

    python scripts/obs_report.py metrics.jsonl       # last snapshot line
    python scripts/obs_report.py snapshot.json       # single snapshot
    python scripts/obs_report.py metrics.jsonl --name serving_flush_s

Input is either a single-snapshot JSON file or a JSONL metrics log
(``MetricsRegistry.append_jsonl``); for JSONL the LAST line is rendered
(``--line N`` picks another, 0-based). ``--name SUBSTR`` filters rows.

The same renderer is importable (``render_snapshot``) — the demo and
tests drive it in-process.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_snapshot(path: str, line: int | None = None) -> dict:
    """Load a snapshot from a JSON file or a JSONL log (last line, or
    ``line`` 0-based)."""
    with open(path) as f:
        text = f.read()
    if line is None:
        # whole-file parse first: a single snapshot may be
        # pretty-printed (multi-line), which is NOT line-per-record JSONL
        try:
            doc = json.loads(text)
            if isinstance(doc, dict):
                return doc
        except json.JSONDecodeError:
            pass
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path} is empty")
    return json.loads(lines[-1 if line is None else line])


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        if abs(v) >= 1:
            return f"{v:.3g}"
        return f"{v:.3g}"
    return str(v)


def _label_str(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def render_snapshot(snap: dict, name_filter: str | None = None) -> str:
    """The table: counters/gauges first (name, labels, value), then
    histograms (count, mean, p50/p90/p99, max)."""
    metrics = snap.get("metrics", [])
    if name_filter:
        metrics = [m for m in metrics if name_filter in m["name"]]
    scalars = [m for m in metrics if m["type"] in ("counter", "gauge")]
    hists = [m for m in metrics if m["type"] == "histogram"]
    out: list[str] = []

    if scalars:
        rows = [(m["name"], _label_str(m["labels"]), _fmt(m["value"]),
                 m["type"]) for m in scalars]
        w0 = max(len("metric"), *(len(r[0]) for r in rows))
        w1 = max(len("labels"), *(len(r[1]) for r in rows))
        w2 = max(len("value"), *(len(r[2]) for r in rows))
        out.append(f"{'metric':<{w0}}  {'labels':<{w1}}  "
                   f"{'value':>{w2}}  type")
        out.append("-" * (w0 + w1 + w2 + 12))
        for r in rows:
            out.append(f"{r[0]:<{w0}}  {r[1]:<{w1}}  {r[2]:>{w2}}  {r[3]}")
        out.append("")

    if hists:
        cols = ("count", "mean", "p50", "p90", "p99", "max")
        rows = [(m["name"], _label_str(m["labels"]),
                 *(_fmt(m.get(c)) for c in cols)) for m in hists]
        w0 = max(len("histogram"), *(len(r[0]) for r in rows))
        w1 = max(len("labels"), *(len(r[1]) for r in rows))
        ws = [max(len(c), *(len(r[2 + j]) for r in rows))
              for j, c in enumerate(cols)]
        head = f"{'histogram':<{w0}}  {'labels':<{w1}}"
        for j, c in enumerate(cols):
            head += f"  {c:>{ws[j]}}"
        out.append(head)
        out.append("-" * len(head))
        for r in rows:
            line = f"{r[0]:<{w0}}  {r[1]:<{w1}}"
            for j in range(len(cols)):
                line += f"  {r[2 + j]:>{ws[j]}}"
            out.append(line)
        out.append("")

    if not out:
        return "(no metrics)"
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="snapshot JSON or metrics JSONL file")
    ap.add_argument("--line", type=int, default=None,
                    help="0-based JSONL line (default: last)")
    ap.add_argument("--name", default=None,
                    help="only metrics whose name contains this")
    args = ap.parse_args(argv)
    snap = load_snapshot(args.path, args.line)
    print(render_snapshot(snap, args.name))
    return 0


if __name__ == "__main__":
    sys.exit(main())
