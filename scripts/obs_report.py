"""Render a metrics snapshot as a human-readable table.

One command to see serving p99, ingest lag, and train step time side by
side::

    python scripts/obs_report.py metrics.jsonl       # last snapshot line
    python scripts/obs_report.py snapshot.json       # single snapshot
    python scripts/obs_report.py metrics.jsonl --name serving_flush_s
    python scripts/obs_report.py http://127.0.0.1:8080/varz --watch 2
    python scripts/obs_report.py --bundle postmortem/bundle_watchdog_trip_000
    python scripts/obs_report.py --roofline http://127.0.0.1:8080/rooflinez
    python scripts/obs_report.py --roofline roofline.json
    python scripts/obs_report.py --lineage http://127.0.0.1:8080/lineagez
    python scripts/obs_report.py --quality http://127.0.0.1:8080/seriesz
    python scripts/obs_report.py --critical-path \
        http://127.0.0.1:8080/criticalpathz

``--bundle <dir>`` renders a postmortem bundle (``obs.recorder``):
validates it first (``validate_bundle`` — a torn bundle is an error,
not a pretty table), then prints the trigger/detail, the health report,
the event tail, and a per-series summary of the recorded lead-up.

``--roofline <src>`` renders the live per-kernel roofline table
(``obs.introspect``): one row per compile key with XLA's cost-analysis
FLOPs/bytes-accessed, the measured execute wall, achieved GB/s and
TFLOP/s, pct-of-HBM/FP32-peak, and the XLA-vs-hand-model bytes
cross-check. ``src`` is a ``/rooflinez`` URL on a live server or a
dumped roofline JSON file (``examples/obs_demo.py`` writes one).

``--lineage <src>`` renders catalog lineage (``obs.lineage``): the
freshness summary (servable watermark vs latest ingest — the staleness
SLO's inputs) and one row per swap's provenance record. ``src`` is a
``/lineagez`` URL, a dumped lineage JSON, or a bundle ``lineage.json``.

``--quality <src>`` renders the model-quality plane: the lead-up of
every ``eval_*`` / ``dataq_*`` / ``lineage_*`` flight-recorder series
from a ``/seriesz`` URL or dumped series JSON (``examples/obs_demo.py``
writes one), or the frozen instrument values from a bundle
``lineage.json``.

``--critical-path <src>`` renders the ingest→servable critical path
(``obs.disttrace.CriticalPathAnalyzer``): the per-stage attribution
summary (queue wait / train apply / swap lag / flush wait) and the
newest completed samples. ``src`` is a ``/criticalpathz`` URL or a
dumped snapshot JSON.

``--transfers <src>`` renders the device↔host transfer plane
(``obs.transfers.TransferLedger``): the per-site ledger (bytes and
counts per direction, blocked wait, derived effective GB/s), the
implicit-transfer attribution, and the retrace ring with its
human-readable signature diffs. ``src`` is a ``/transferz`` URL, a
dumped snapshot JSON (the CI steady-state gate writes one), a bundle
``transfers.json``, or a fleet ``/transferz`` pod aggregate.

``--budget <src>`` renders the rollout plane
(``obs.budget.RolloutBudget``): service-level multi-window burn
rates, the per-catalog-version cohort attribution table (served /
shed / attainment / fast burn / remaining budget per version), and
the canary verdict tail with any un-acted-on ROLLBACKs. ``src`` is a
``/budgetz`` URL, a dumped snapshot JSON, a bundle ``budget.json``,
or a fleet ``/budgetz`` pod aggregate.

``--contention <src>`` renders the concurrency & saturation plane
(``obs.contention.SaturationAnalyzer``): the Amdahl window summary
(consumers, efficiency, Karp–Flatt serial fraction, projected speedup
at 2N), the contended-lock table, and per-partition busy/blocked
shares joined with their ``streams_*`` gauges. ``src`` is a
``/contentionz`` URL, a dumped snapshot JSON (the streams_bench
sustained pass writes one), a bundle ``contention.json``, or a fleet
``/contentionz`` pod aggregate.

Input is a single-snapshot JSON file, a JSONL metrics log
(``MetricsRegistry.append_jsonl``), or — live mode — an HTTP URL to a
running ``obs.server.ObsServer``'s ``/varz`` route. For JSONL the LAST
line is rendered (``--line N`` picks another, 0-based). ``--name
SUBSTR`` filters rows.

``--watch N`` polls the source every N seconds and renders *deltas and
rates* between consecutive snapshots — counters show Δ and Δ/s,
histograms show new observations per second next to their current
p50/p99 — so the live endpoint is usable from a terminal without a
Prometheus stack. ``--count M`` bounds the number of polls (default:
until interrupted).

The renderers are importable (``render_snapshot``, ``render_deltas``,
``fetch_snapshot``) — the demo and tests drive them in-process.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def load_snapshot(path: str, line: int | None = None) -> dict:
    """Load a snapshot from a JSON file or a JSONL log (last line, or
    ``line`` 0-based)."""
    with open(path) as f:
        text = f.read()
    if line is None:
        # whole-file parse first: a single snapshot may be
        # pretty-printed (multi-line), which is NOT line-per-record JSONL
        try:
            doc = json.loads(text)
            if isinstance(doc, dict):
                return doc
        except json.JSONDecodeError:
            pass
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path} is empty")
    return json.loads(lines[-1 if line is None else line])


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        if abs(v) >= 1:
            return f"{v:.3g}"
        return f"{v:.3g}"
    return str(v)


def _label_str(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def render_snapshot(snap: dict, name_filter: str | None = None) -> str:
    """The table: counters/gauges first (name, labels, value), then
    histograms (count, mean, p50/p90/p99, max)."""
    metrics = snap.get("metrics", [])
    if name_filter:
        metrics = [m for m in metrics if name_filter in m["name"]]
    scalars = [m for m in metrics if m["type"] in ("counter", "gauge")]
    hists = [m for m in metrics if m["type"] == "histogram"]
    out: list[str] = []

    if scalars:
        rows = [(m["name"], _label_str(m["labels"]), _fmt(m["value"]),
                 m["type"]) for m in scalars]
        w0 = max(len("metric"), *(len(r[0]) for r in rows))
        w1 = max(len("labels"), *(len(r[1]) for r in rows))
        w2 = max(len("value"), *(len(r[2]) for r in rows))
        out.append(f"{'metric':<{w0}}  {'labels':<{w1}}  "
                   f"{'value':>{w2}}  type")
        out.append("-" * (w0 + w1 + w2 + 12))
        for r in rows:
            out.append(f"{r[0]:<{w0}}  {r[1]:<{w1}}  {r[2]:>{w2}}  {r[3]}")
        out.append("")

    if hists:
        cols = ("count", "mean", "p50", "p90", "p99", "max")
        rows = [(m["name"], _label_str(m["labels"]),
                 *(_fmt(m.get(c)) for c in cols)) for m in hists]
        w0 = max(len("histogram"), *(len(r[0]) for r in rows))
        w1 = max(len("labels"), *(len(r[1]) for r in rows))
        ws = [max(len(c), *(len(r[2 + j]) for r in rows))
              for j, c in enumerate(cols)]
        head = f"{'histogram':<{w0}}  {'labels':<{w1}}"
        for j, c in enumerate(cols):
            head += f"  {c:>{ws[j]}}"
        out.append(head)
        out.append("-" * len(head))
        for r in rows:
            line = f"{r[0]:<{w0}}  {r[1]:<{w1}}"
            for j in range(len(cols)):
                line += f"  {r[2 + j]:>{ws[j]}}"
            out.append(line)
        out.append("")

    if not out:
        return "(no metrics)"
    return "\n".join(out)


def fetch_snapshot(src: str, line: int | None = None,
                   timeout: float = 5.0) -> dict:
    """One snapshot from a file path or a live ``/varz`` URL."""
    if src.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(src, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    return load_snapshot(src, line)


def _index(snap: dict) -> dict:
    return {(m["name"], tuple(sorted(m["labels"].items()))): m
            for m in snap.get("metrics", [])}


def snapshot_deltas(prev: dict, cur: dict, dt: float) -> list[dict]:
    """Per-instrument deltas between two snapshots: counters get
    ``delta``/``rate`` (per second), histograms get observation-count
    deltas alongside their current quantiles, gauges get their current
    value plus the change since the last snapshot (``delta``, no rate —
    a gauge delta is rarely a rate, but it decides whether the row is
    "active" in watch mode: a moving lag gauge must show up). New
    instruments count from zero. ``dt`` ≤ 0 suppresses rates."""
    before = _index(prev)
    rows = []
    for key, m in _index(cur).items():
        p = before.get(key)
        row = {"name": m["name"], "labels": m["labels"], "type": m["type"]}
        if m["type"] in ("counter", "gauge"):
            row["value"] = m["value"]
            delta = m["value"] - (p["value"] if p else 0.0)
            row["delta"] = delta
            if m["type"] == "counter":
                row["rate"] = delta / dt if dt > 0 else None
        else:  # histogram
            delta = m["count"] - (p["count"] if p else 0)
            row["value"] = m["count"]
            row["delta"] = delta
            row["rate"] = delta / dt if dt > 0 else None
            row["p50"] = m.get("p50")
            row["p99"] = m.get("p99")
        rows.append(row)
    rows.sort(key=lambda r: (r["name"], sorted(r["labels"].items())))
    return rows


def format_table(header: tuple, rows: list) -> list[str]:
    """Fixed-width left-aligned table lines (header, dashed rule, one
    line per row of pre-formatted strings) — ONE copy of the layout
    logic, shared with ``scripts/bench_regress.py``'s report table."""
    widths = [max(len(header[i]), *(len(r[i]) for r in rows)) if rows
              else len(header[i]) for i in range(len(header))]
    lines = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(header))]
    lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for r in rows:
        lines.append("  ".join(r[i].ljust(widths[i])
                               for i in range(len(header))))
    return lines


def render_deltas(prev: dict, cur: dict, dt: float,
                  name_filter: str | None = None,
                  active_only: bool = False) -> str:
    """Delta/rate table between two snapshots. ``active_only`` drops
    rows whose counters/gauges/histograms saw nothing this interval."""
    rows = snapshot_deltas(prev, cur, dt)
    if name_filter:
        rows = [r for r in rows if name_filter in r["name"]]
    if active_only:
        rows = [r for r in rows if r.get("delta")]
    if not rows:
        return "(no activity)" if active_only else "(no metrics)"
    cells = [(r["name"], _label_str(r["labels"]), r["type"],
              _fmt(r["value"]), _fmt(r.get("delta")),
              _fmt(r.get("rate")), _fmt(r.get("p50")), _fmt(r.get("p99")))
             for r in rows]
    header = ("metric", "labels", "type", "value", "Δ", "Δ/s", "p50", "p99")
    return "\n".join(format_table(header, cells))


def watch(src: str, interval_s: float, count: int | None = None,
          name_filter: str | None = None, out=sys.stdout) -> int:
    """Poll ``src`` every ``interval_s`` and render deltas/rates. The
    first poll prints the full snapshot (nothing to diff yet)."""
    prev = fetch_snapshot(src)
    print(f"# {src} — snapshot at {time.strftime('%H:%M:%S')}", file=out)
    print(render_snapshot(prev, name_filter), file=out)
    polls = 0
    while count is None or polls < count:
        time.sleep(interval_s)
        cur = fetch_snapshot(src)
        dt = cur.get("time", 0.0) - prev.get("time", 0.0)
        if dt <= 0:
            dt = interval_s
        print(f"\n# Δ over {dt:.1f}s at {time.strftime('%H:%M:%S')}",
              file=out)
        print(render_deltas(prev, cur, dt, name_filter, active_only=True),
              file=out)
        prev = cur
        polls += 1
    return 0


def render_bundle(directory: str, name_filter: str | None = None,
                  event_tail: int = 20) -> str:
    """Validate + render one postmortem bundle directory."""
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from large_scale_recommendation_tpu.obs.recorder import load_bundle

    docs = load_bundle(directory)  # validates; raises on a torn bundle
    manifest = docs["manifest"]

    out = [f"# postmortem bundle {directory}",
           f"trigger   : {manifest['trigger']}",
           f"created   : "
           f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(manifest['created']))}",
           f"detail    : {json.dumps(manifest['detail'])}",
           f"contents  : {manifest['counts']['series']} series, "
           f"{manifest['counts']['events']} events, "
           f"{manifest['counts']['spans']} spans", ""]

    health = docs["health"]
    out.append(f"health    : {health.get('status', 'unknown')}")
    for name, res in sorted(health.get("checks", {}).items()):
        if res.get("status") != "ok":
            out.append(f"  {name}: {res['status']} "
                       f"{json.dumps(res.get('detail', {}))[:120]}")
    out.append("")

    events = docs["events"]
    if events:
        out.append(f"event tail (last {min(event_tail, len(events))} "
                   f"of {len(events)}):")
        rows = [(time.strftime("%H:%M:%S", time.localtime(e["time"])),
                 e["severity"], e["kind"],
                 "-" if e.get("span_id") is None else str(e["span_id"]),
                 json.dumps(e.get("detail", {}))[:60])
                for e in events[-event_tail:]]
        out.extend(format_table(("time", "sev", "kind", "span", "detail"),
                                rows))
        out.append("")

    series = docs["series"].get("series", {})
    keys = sorted(k for k in series
                  if name_filter is None or name_filter in k)
    if keys:
        out.append(f"series lead-up ({len(keys)} of {len(series)}):")
        rows = []
        for key in keys:
            vals = [v for _, v in series[key]["points"]] or [None]
            numeric = [v for v in vals if isinstance(v, (int, float))]
            rows.append((key, str(len(series[key]["points"])),
                         _fmt(vals[0]),
                         _fmt(min(numeric) if numeric else None),
                         _fmt(max(numeric) if numeric else None),
                         _fmt(vals[-1])))
        out.extend(format_table(
            ("series", "n", "first", "min", "max", "last"), rows))
        out.append("")
    out.append("(full registry snapshot: metrics.json; span tail: "
               "trace.json — Perfetto-loadable)")
    return "\n".join(out)


def render_roofline(doc: dict, name_filter: str | None = None) -> str:
    """Render one roofline document (``/rooflinez`` body or
    ``Introspector.roofline()``): header with compile totals + chip
    peaks, then one row per compile key. Wall-less rows (key compiled
    but never executed a steady-state span) render with ``-`` in the
    measured columns rather than being dropped — a compiled-but-unused
    kernel is information."""
    rows = doc.get("rows", [])
    if name_filter:
        rows = [r for r in rows if name_filter in r["key"]]
    out = [
        "# per-kernel roofline "
        f"(HBM peak {_fmt(doc.get('hbm_peak_gbs'))} GB/s, "
        f"fp32 peak {_fmt(doc.get('fp32_peak_tflops'))} TFLOP/s)",
        f"compiles: {doc.get('compile_count', '-')} totalling "
        f"{_fmt(doc.get('compile_wall_s'))}s"
        + (f"; note: {doc['note']}" if doc.get("note") else ""),
        "",
    ]
    if not rows:
        out.append("(no compile records)")
        return "\n".join(out)

    def num(v, scale=1.0):
        return "-" if v is None else _fmt(v * scale)

    cells = [(r["key"][:64], str(r["compiles"]),
              num(r.get("compile_wall_s")),
              num(r.get("xla_flops"), 1e-9),
              num(r.get("xla_bytes_accessed"), 1e-6),
              str(r.get("execute_count", 0)),
              num(r.get("wall_per_exec_s"), 1e3),
              num(r.get("achieved_gbs")),
              num(r.get("pct_of_hbm_peak")),
              num(r.get("pct_of_fp32_peak")),
              num(r.get("xla_vs_model_bytes")))
             for r in sorted(rows,
                             key=lambda r: -(r.get("xla_bytes_accessed")
                                             or 0))]
    header = ("compile key", "comp", "comp_s", "GFLOP", "MB_acc", "execs",
              "ms/exec", "GB/s", "%HBM", "%FP32", "xla/model")
    out.extend(format_table(header, cells))
    return "\n".join(out)


def render_lineage(doc: dict, tail: int = 30) -> str:
    """Render catalog lineage (``/lineagez`` body, a dumped lineage
    JSON, or a bundle's ``lineage.json``): the freshness summary the
    staleness SLO verdicts on, then one row per provenance record —
    version, source, WAL watermark, train step, retrain id, age."""
    if "lineage" in doc and isinstance(doc["lineage"], dict):
        doc = doc["lineage"]  # a bundle lineage.json wraps the snapshot
    records = doc.get("records", [])
    fresh = doc.get("freshness", {}) or {}
    now = doc.get("time", time.time())
    out = [
        "# catalog lineage "
        f"({doc.get('swaps', '-')} swaps, {len(records)} records"
        + (f", {doc['evicted']} evicted" if doc.get("evicted") else "")
        + ")"
        + (f"; note: {doc['note']}" if doc.get("note") else ""),
        f"servable watermark: {_fmt(fresh.get('servable_watermark'))} "
        f"(swap age {_fmt(fresh.get('servable_swap_age_s'))}s); "
        f"latest ingest offset: "
        f"{_fmt(fresh.get('latest_ingest_offset'))}; "
        + ("INGEST AHEAD — oldest unservable record waited "
           f"{_fmt(fresh.get('unservable_age_s'))}s"
           if fresh.get("ingest_ahead") else "servable covers ingest"),
        "",
    ]
    if not records:
        out.append("(no provenance records)")
        return "\n".join(out)
    rows = [(str(r.get("catalog_version")), str(r.get("source") or "-"),
             _fmt(r.get("wal_offset_watermark")),
             _fmt(r.get("train_step")), _fmt(r.get("retrain_id")),
             _fmt(round(now - r["wall_time"], 1))
             if r.get("wall_time") else "-")
            for r in records[-tail:]]
    out.extend(format_table(("version", "source", "wal_watermark",
                             "step", "retrain", "age_s"), rows))
    return "\n".join(out)


def render_critical_path(doc: dict, tail: int = 20) -> str:
    """Render the ingest→servable critical path (a ``/criticalpathz``
    body or dumped analyzer snapshot): the per-stage attribution
    summary, then the newest completed samples — one row per sampled
    record with its stage decomposition and total."""
    stages = doc.get("stages", {})
    samples = doc.get("samples", [])
    out = [
        "# ingest→servable critical path "
        f"({doc.get('samples_total', '-')} samples)"
        + (f"; note: {doc['note']}" if doc.get("note") else ""),
        "",
    ]
    stage_rows = [(name, str(st.get("count", 0)), _fmt(st.get("mean_s")),
                   _fmt(st.get("last_s")), _fmt(st.get("max_s")))
                  for name, st in stages.items()]
    if stage_rows:
        out.extend(format_table(("stage", "n", "mean_s", "last_s",
                                 "max_s"), stage_rows))
        out.append("")
    if not samples:
        out.append("(no completed samples — arm obs.enable_disttrace() "
                   "before building the log/driver/engine)")
        return "\n".join(out)
    rows = [(str(s.get("catalog_version")), str(s.get("partition")),
             str(s.get("offset")), _fmt(s.get("queue_wait_s")),
             _fmt(s.get("train_apply_s")), _fmt(s.get("swap_lag_s")),
             _fmt(s.get("flush_wait_s")), _fmt(s.get("total_s")))
            for s in samples[-tail:]]
    out.extend(format_table(("version", "part", "offset", "queue_s",
                             "train_s", "swap_s", "flush_s", "total_s"),
                            rows))
    return "\n".join(out)


def render_contention(doc: dict, tail: int = 20) -> str:
    """Render a ``/contentionz`` body (or dumped snapshot / bundle
    ``contention.json`` / fleet pod aggregate): the Amdahl window
    summary, the contended-lock table (wait/hold/acquisition columns),
    and — per-process docs — one row per consumer partition with its
    busy/blocked split and ``streams_*`` joins."""
    window = doc.get("window") or {}
    head = ["# concurrency & saturation"]
    if doc.get("note"):
        head[0] += f" — note: {doc['note']}"
    summary = (f"consumers: {_fmt(doc.get('consumers'))}; "
               f"window: {_fmt(window.get('wall_s'))}s wall, "
               f"{_fmt(doc.get('capacity_s'))}s capacity; "
               f"busy {_fmt(doc.get('busy_s'))}s / blocked "
               f"{_fmt(doc.get('blocked_s'))}s")
    head.append(summary)
    head.append(
        f"efficiency: {_fmt(doc.get('efficiency'))}; serial fraction "
        f"(Karp–Flatt): {_fmt(doc.get('serial_fraction'))}; projected "
        f"speedup at 2N: {_fmt(doc.get('projected_speedup_at_2n'))}; "
        f"Amdahl limit: {_fmt(doc.get('amdahl_limit'))}"
        + (f" (cpu: {doc['cpu_source']})" if doc.get("cpu_source")
           else ""))
    head.append(f"lock wait total: "
                f"{_fmt(doc.get('lock_wait_s_total'))}s")
    out = head + [""]
    locks = doc.get("locks", [])
    if locks:
        rows = [(r["lock"], str(r.get("kind") or "-"),
                 _fmt(r.get("acquisitions")), _fmt(r.get("contended")),
                 _fmt(r.get("cv_waits")), _fmt(r.get("wait_s")),
                 _fmt(r.get("hold_s")),
                 _fmt(r.get("wait_frac_of_capacity")))
                for r in locks[:tail]]
        out.extend(format_table(("lock", "kind", "acq", "contended",
                                 "cv_waits", "wait_s", "hold_s",
                                 "wait/cap"), rows))
        out.append("")
    else:
        out.append("(no lock activity in window — arm "
                   "obs.enable_contention() before building the "
                   "models/drivers/engines)")
    partitions = doc.get("partitions") or {}
    if partitions:
        rows = [(p, str(row.get("thread") or "-"),
                 _fmt(row.get("busy_s")), _fmt(row.get("blocked_s")),
                 _fmt(row.get("blocked_frac")),
                 _fmt(row.get("records_total")),
                 _fmt(row.get("lag_records")),
                 _fmt(row.get("queue_depth")))
                for p, row in sorted(partitions.items())]
        out.extend(format_table(("part", "thread", "busy_s",
                                 "blocked_s", "blocked%", "records",
                                 "lag", "queue"), rows))
        out.append("")
    targets = doc.get("targets")
    if targets:  # a fleet pod aggregate: per-host summaries ride along
        rows = [(str(t.get("host")), _fmt(t.get("consumers")),
                 _fmt(t.get("wall_s")), _fmt(t.get("efficiency")),
                 _fmt(t.get("serial_fraction")),
                 _fmt(t.get("lock_wait_s_total")),
                 str(t.get("note") or "-"))
                for t in targets]
        out.extend(format_table(("host", "consumers", "wall_s", "eff",
                                 "serial", "lock_wait_s", "note"), rows))
        out.append("")
    return "\n".join(out).rstrip()


def render_transfers(doc: dict, tail: int = 12) -> str:
    """Render a ``/transferz`` body (or dumped snapshot / bundle
    ``transfers.json`` / fleet pod aggregate): the per-site transfer
    ledger (bytes/counts/wait per direction + derived effective GB/s),
    the implicit-transfer attribution, and the retrace ring with its
    signature diffs."""
    head = ["# device↔host transfers & retraces"]
    if doc.get("note"):
        head[0] += f" — note: {doc['note']}"
    if doc.get("guard_mode"):
        head.append(f"guard mode: {doc['guard_mode']}")
    steady = doc.get("steady") or {}
    if steady:
        head.append(
            f"steady state: "
            f"{'marked' if steady.get('marked') else 'warmup (unmarked)'}"
            f"; retraces {_fmt(steady.get('retraces'))}, implicit "
            f"transfers {_fmt(steady.get('implicit_transfers'))}")
    out = head + [""]
    sites = doc.get("sites") or {}
    if sites:
        rows = [(name,
                 _fmt(s.get("h2d_bytes")), _fmt(s.get("h2d_count")),
                 _fmt(s.get("d2h_bytes")), _fmt(s.get("d2h_count")),
                 _fmt(s.get("wait_s")), _fmt(s.get("effective_gbs")),
                 _fmt(s.get("hosts")) if "hosts" in s else "-")
                for name, s in sorted(
                    sites.items(),
                    key=lambda kv: -((kv[1].get("h2d_bytes") or 0)
                                     + (kv[1].get("d2h_bytes") or 0)))]
        out.extend(format_table(("site", "h2d_B", "h2d_n", "d2h_B",
                                 "d2h_n", "wait_s", "GB/s", "hosts"),
                                rows))
        out.append("")
    else:
        out.append("(no transfers recorded — arm "
                   "obs.enable_transfers() before building the "
                   "stores/drivers/engines)")
        out.append("")
    imp = doc.get("implicit_by_site") or {}
    out.append(f"implicit transfers: "
               f"{_fmt(doc.get('implicit_transfers_total'))}"
               + (" — " + ", ".join(f"{k}={v}"
                                    for k, v in sorted(imp.items()))
                  if imp else ""))
    retr = doc.get("retraces") or {}
    by_fn = retr.get("by_fn") or {}
    out.append(f"retraces: {_fmt(retr.get('total', doc.get('retrace_total')))}"
               + (" — " + ", ".join(f"{k}={v}"
                                    for k, v in sorted(by_fn.items()))
                  if by_fn else ""))
    ring = retr.get("ring") or []
    if ring:
        out.append("")
        rows = [(time.strftime("%H:%M:%S", time.localtime(r["time"])),
                 r["fn"], str(r["traces"]), str(r["new"]),
                 "; ".join(r.get("diff", []))[:80])
                for r in ring[-tail:]]
        out.extend(format_table(("time", "fn", "traces", "new",
                                 "signature diff"), rows))
    targets = doc.get("targets")
    if targets:  # a fleet pod aggregate: per-host summaries ride along
        out.append("")
        rows = [(str(t.get("host")), str(t.get("guard_mode") or "-"),
                 _fmt(t.get("implicit_transfers_total")),
                 _fmt(t.get("retrace_total")),
                 str(t.get("note") or "-"))
                for t in targets]
        out.extend(format_table(("host", "guard", "implicit", "retraces",
                                 "note"), rows))
    return "\n".join(out).rstrip()


def render_budget(doc: dict, tail: int = 12) -> str:
    """Render a ``/budgetz`` body (or dumped snapshot / bundle
    ``budget.json`` / fleet pod aggregate): service-level multi-window
    burn rates, the per-catalog-version cohort attribution table, and
    the canary verdict tail with any un-acted-on ROLLBACKs."""
    head = ["# rollout error budget & canary verdicts"]
    if doc.get("note"):
        head[0] += f" — note: {doc['note']}"
    if doc.get("objective") is not None:
        slo_bits = [f"objective {_fmt(doc['objective'])}"]
        if doc.get("target_s") is not None:
            slo_bits.insert(0, f"target {_fmt(doc['target_s'] * 1e3)} ms")
        head.append("slo: " + ", ".join(slo_bits))
    burns = doc.get("burn_rates") or {}
    if burns:
        head.append("burn rates: " + ", ".join(
            f"{w}={_fmt(b)}" for w, b in sorted(burns.items())))
    out = head + [""]

    cohorts = doc.get("cohorts")
    # A local snapshot keys cohorts by version string; a fleet pod
    # aggregate ships a pre-merged, version-sorted row list.
    if isinstance(cohorts, dict):
        rows_in = [dict(row, version=v) for v, row in sorted(
            cohorts.items(), key=lambda kv: int(kv[0]))]
    else:
        rows_in = list(cohorts or [])
    if rows_in:
        rows = [(str(r.get("version")), _fmt(r.get("served")),
                 _fmt(r.get("shed")), _fmt(r.get("shed_frac")),
                 _fmt(r.get("attainment")),
                 _fmt(r.get("burn_rate_fast",
                            r.get("burn_rate_fast_max"))),
                 _fmt(r.get("p99_ms", r.get("p99_ms_max"))),
                 _fmt(r.get("error_budget_remaining",
                            r.get("error_budget_remaining_min"))),
                 _fmt(r.get("hosts")) if "hosts" in r else "-")
                for r in rows_in]
        out.extend(format_table(("version", "served", "shed", "shed%",
                                 "attain", "burn_fast", "p99_ms",
                                 "budget", "hosts"), rows))
        out.append("")
    else:
        out.append("(no cohorts recorded — arm obs.enable_budget() "
                   "before constructing the serving engines)")
        out.append("")

    verdicts = doc.get("verdicts") or {}
    pending = (verdicts.get("pending_rollbacks")
               or doc.get("pending_rollbacks") or {})
    if pending:
        for version, rec in sorted(pending.items()):
            if isinstance(rec, list):  # fleet form: one entry per host
                for entry in rec:
                    out.append(f"PENDING ROLLBACK v{version} "
                               f"[{entry.get('host')}]: "
                               f"{entry.get('reason')}")
            else:
                out.append(f"PENDING ROLLBACK v{version}: "
                           f"{rec.get('reason')}")
        out.append("")
    history = verdicts.get("history") or []
    if history:
        rows = [(time.strftime("%H:%M:%S", time.localtime(h["time"])),
                 str(h.get("canary_version")),
                 str(h.get("incumbent_version")),
                 str(h.get("verdict")), str(h.get("reason"))[:70])
                for h in history[-tail:]]
        out.extend(format_table(("time", "canary", "incumbent",
                                 "verdict", "reason"), rows))
    targets = doc.get("targets")
    if targets:  # a fleet pod aggregate: per-host summaries ride along
        out.append("")
        rows = [(str(t.get("host")), _fmt(t.get("evaluations")),
                 ",".join(t.get("pending_rollbacks") or []) or "-",
                 str(t.get("note") or "-"))
                for t in targets]
        out.extend(format_table(("host", "evals", "pending", "note"),
                                rows))
    return "\n".join(out).rstrip()


def render_requests(doc: dict, tail: int = 12) -> str:
    """Render a ``/slowz`` body (or dumped snapshot / bundle
    ``requests.json`` / fleet pod aggregate): window stage
    decomposition with the dominant stage, then the exemplar table
    worst-first — each row naming where that request's time went."""
    head = ["# per-request stage decomposition & tail exemplars"]
    if doc.get("note"):
        head[0] += f" — note: {doc['note']}"
    if doc.get("target_s") is not None:
        head.append(f"slo: target {_fmt(doc['target_s'] * 1e3)} ms, "
                    f"objective {_fmt(doc.get('objective'))}")
    bits = []
    for key in ("count", "violations", "shed", "window_fill"):
        if doc.get(key) is not None:
            bits.append(f"{key}={_fmt(doc[key])}")
    if doc.get("burn_rate") is not None:
        bits.append(f"burn_rate={_fmt(doc['burn_rate'])}")
    if doc.get("p99_ms") is not None:
        bits.append(f"p99={_fmt(doc['p99_ms'])} ms")
    if bits:
        head.append(", ".join(bits))
    out = head + [""]

    frac = doc.get("stage_frac") or {}
    totals = doc.get("stage_totals_s") or {}
    if frac:
        dominant = doc.get("dominant_stage")
        rows = [(s + (" *" if s == dominant else ""),
                 _fmt(totals.get(s)), _fmt(f))
                for s, f in sorted(frac.items(),
                                   key=lambda kv: -kv[1])]
        out.extend(format_table(("stage", "total_s", "frac"), rows))
        out.append("")

    exemplars = doc.get("exemplars") or []
    if exemplars:
        out.append(f"exemplars worst-first (showing "
                   f"{min(tail, len(exemplars))} of {len(exemplars)}):")
        rows = [(str(e.get("host", "-")) if "host" in e else
                 str(e.get("seq", "-")),
                 str(e.get("kind")), _fmt((e.get("wall_s") or 0.0) * 1e3),
                 str(e.get("dominant_stage") or "-"),
                 str(e.get("catalog_version")),
                 str(e.get("queue_depth") if e.get("queue_depth")
                     is not None else "-"),
                 str(e.get("bucket") or "-"),
                 str(e.get("admission_level") or "-"))
                for e in exemplars[:tail]]
        out.extend(format_table(
            ("id", "kind", "wall_ms", "dominant", "ver", "qdepth",
             "bucket", "admission"), rows))
    elif not doc.get("note"):
        out.append("(no exemplars kept — no traffic noted yet)")
    targets = doc.get("targets")
    if targets:  # a fleet pod aggregate: per-host summaries ride along
        out.append("")
        rows = [(str(t.get("host")), _fmt(t.get("count")),
                 _fmt(t.get("violations")), _fmt(t.get("shed")),
                 _fmt(t.get("p99_ms")),
                 str(t.get("dominant_stage") or "-"),
                 str(t.get("note") or "-"))
                for t in targets]
        out.extend(format_table(("host", "count", "viol", "shed",
                                 "p99_ms", "dominant", "note"), rows))
    return "\n".join(out).rstrip()


QUALITY_PREFIXES = ("eval_", "dataq_", "lineage_")


def render_quality(doc: dict, name_filter: str | None = None) -> str:
    """Render the model-quality plane from a ``/seriesz`` body (or a
    dumped recorder snapshot / bundle ``series.json``): the lead-up of
    every ``eval_*`` / ``dataq_*`` / ``lineage_*`` series — or, given a
    bundle ``lineage.json`` (``quality``/``data_quality`` metric
    lists), the latest frozen instrument values."""
    if "quality" in doc and "lineage" in doc:  # a bundle lineage.json
        rows = []
        for m in doc.get("quality", []) + doc.get("data_quality", []):
            val = m.get("value", m.get("count"))
            rows.append((m["name"], _label_str(m.get("labels", {})),
                         _fmt(val), m.get("type", "-")))
        if not rows:
            return "(no quality/data-quality instruments frozen)"
        return "\n".join(["# model-quality snapshot (bundle)", ""]
                         + format_table(("metric", "labels", "value",
                                         "type"), rows))
    series = doc.get("series", {})
    keys = sorted(k for k in series
                  if k.startswith(QUALITY_PREFIXES)
                  and (name_filter is None or name_filter in k))
    out = [f"# model-quality series ({len(keys)} of {len(series)})", ""]
    if not keys:
        out.append("(no eval_/dataq_/lineage_ series recorded — attach "
                   "an OnlineEvaluator/DataQualityInspector and a "
                   "flight recorder)")
        return "\n".join(out)
    rows = []
    for key in keys:
        vals = [v for _, v in series[key]["points"]] or [None]
        numeric = [v for v in vals if isinstance(v, (int, float))]
        rows.append((key, str(len(series[key]["points"])),
                     _fmt(vals[0]),
                     _fmt(min(numeric) if numeric else None),
                     _fmt(max(numeric) if numeric else None),
                     _fmt(vals[-1])))
    out.extend(format_table(("series", "n", "first", "min", "max",
                             "last"), rows))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="snapshot JSON / metrics JSONL file, or "
                         "a live /varz URL")
    # (--bundle/--roofline/--lineage/--quality below are the artifact
    # renderers; path is only required for the snapshot/watch modes)
    ap.add_argument("--line", type=int, default=None,
                    help="0-based JSONL line (default: last)")
    ap.add_argument("--name", default=None,
                    help="only metrics whose name contains this")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="poll every N seconds and render deltas/rates")
    ap.add_argument("--count", type=int, default=None,
                    help="number of --watch polls (default: forever)")
    ap.add_argument("--bundle", default=None, metavar="DIR",
                    help="validate + render a postmortem bundle directory")
    ap.add_argument("--roofline", default=None, metavar="SRC",
                    help="render a per-kernel roofline table from a "
                         "/rooflinez URL or a dumped roofline JSON file")
    ap.add_argument("--lineage", default=None, metavar="SRC",
                    help="render catalog lineage from a /lineagez URL, "
                         "a dumped lineage JSON, or a bundle's "
                         "lineage.json")
    ap.add_argument("--quality", default=None, metavar="SRC",
                    help="render the eval_*/dataq_*/lineage_* series "
                         "from a /seriesz URL or dumped series JSON "
                         "(or a bundle lineage.json's frozen snapshot)")
    ap.add_argument("--critical-path", default=None, metavar="SRC",
                    dest="critical_path",
                    help="render the ingest→servable critical-path "
                         "stage table from a /criticalpathz URL or a "
                         "dumped analyzer snapshot JSON")
    ap.add_argument("--contention", default=None, metavar="SRC",
                    help="render the concurrency/saturation table "
                         "(Amdahl summary + contended locks + "
                         "per-partition blocked shares) from a "
                         "/contentionz URL, a dumped snapshot JSON, a "
                         "bundle contention.json, or a fleet pod "
                         "aggregate")
    ap.add_argument("--transfers", default=None, metavar="SRC",
                    help="render the device↔host transfer ledger "
                         "(per-site bytes/wait/GB/s + implicit-transfer "
                         "attribution + retrace ring) from a /transferz "
                         "URL, a dumped snapshot JSON, a bundle "
                         "transfers.json, or a fleet pod aggregate")
    ap.add_argument("--budget", default=None, metavar="SRC",
                    help="render the rollout error-budget plane "
                         "(multi-window burn rates + per-catalog-version "
                         "cohort attribution + canary verdict tail) from "
                         "a /budgetz URL, a dumped snapshot JSON, a "
                         "bundle budget.json, or a fleet pod aggregate")
    ap.add_argument("--requests", default=None, metavar="SRC",
                    help="render the per-request plane (window stage "
                         "decomposition + dominant stage + tail "
                         "exemplars worst-first) from a /slowz URL, a "
                         "dumped snapshot JSON, a bundle requests.json, "
                         "or a fleet pod aggregate")
    args = ap.parse_args(argv)
    if args.bundle is not None:
        print(render_bundle(args.bundle, args.name))
        return 0
    if args.roofline is not None:
        print(render_roofline(fetch_snapshot(args.roofline), args.name))
        return 0
    if args.lineage is not None:
        print(render_lineage(fetch_snapshot(args.lineage)))
        return 0
    if args.quality is not None:
        print(render_quality(fetch_snapshot(args.quality), args.name))
        return 0
    if args.critical_path is not None:
        print(render_critical_path(fetch_snapshot(args.critical_path)))
        return 0
    if args.contention is not None:
        print(render_contention(fetch_snapshot(args.contention)))
        return 0
    if args.transfers is not None:
        print(render_transfers(fetch_snapshot(args.transfers)))
        return 0
    if args.budget is not None:
        print(render_budget(fetch_snapshot(args.budget)))
        return 0
    if args.requests is not None:
        print(render_requests(fetch_snapshot(args.requests)))
        return 0
    if args.path is None:
        ap.error("path is required unless --bundle is given")
    if args.watch is not None:
        try:
            return watch(args.path, args.watch, args.count, args.name)
        except KeyboardInterrupt:
            return 0
    snap = fetch_snapshot(args.path, args.line)
    print(render_snapshot(snap, args.name))
    return 0


if __name__ == "__main__":
    sys.exit(main())
