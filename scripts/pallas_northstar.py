"""Full north-star DSGD training through the Pallas kernel, on-device A/B.

The r5 in-bench amortized probe measured the VMEM-staged Pallas loop
kernel at 20.2M ratings/s vs 17.3M for the best XLA variant at the SAME
shape (rank 128, mb 2048, k=16 block visit) — the first shape where the
Pallas path wins. This script answers the question that matters before
any default flips: does that kernel win survive the FULL north-star
training run (convergence to the pre-registered RMSE target included)?

Both arms share one blocked layout (k=16 — the Pallas VMEM budget for
rank 128 — mb 2048, item-sorted) and the bench's exact hyperparameters
(warm_boost lr 0.3, λ=0.1, target 0.155), so the only variable is the
kernel. The bench headline (k=8, mb 32768, XLA) is the production
reference point: docs/PERF.md records today's 17.6M r/s / 4.05 s there.

Prints one JSON line. Runs on the current device (intended: the tunneled
TPU; nothing but a PRNG key crosses the link).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    if os.environ.get("PROBE_CPU") == "1":
        # the axon site hook pins jax_platforms — a plain JAX_PLATFORMS=cpu
        # env var is overridden and the process wedges on a dead tunnel
        # (utils/platform.py); the config-level override is the only safe
        # CPU smoke path
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()
    import jax
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.core.updaters import warm_boost_lr
    from large_scale_recommendation_tpu.data.device_blocking import (
        device_block_problem,
        init_factors_device,
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.ops import sgd as sgd_ops
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )
    from large_scale_recommendation_tpu.utils.platform import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    dev = jax.devices()[0]

    nnz = int(os.environ.get("BENCH_NNZ", 25_000_095))
    rank = int(os.environ.get("BENCH_RANK", 128))
    k = int(os.environ.get("NS_BLOCKS", 16))
    mb = int(os.environ.get("NS_MB", 2048))
    target = float(os.environ.get("BENCH_RMSE_TARGET", 0.155))
    max_sweeps = int(os.environ.get("BENCH_ITERS", 12))
    variants = [v.strip() for v in
                os.environ.get("NS_VARIANTS", "pallas,xla").split(",")]
    bad = [v for v in variants if v not in ("pallas", "xla")]
    if bad:
        # fail LOUDLY before burning a tunnel window: a typo'd variant
        # would otherwise run the XLA arm under the wrong label and emit
        # a plausible-looking but wrong A/B
        raise SystemExit(f"NS_VARIANTS must be pallas|xla, got {bad}")
    out: dict = {"device": str(dev.device_kind) + str(dev.id), "rank": rank,
                 "blocks": k, "minibatch": mb, "nnz": nnz,
                 "rmse_target": target}

    from large_scale_recommendation_tpu.data.movielens import (
        vocab_overrides_from_env,
    )

    num_users, num_items = vocab_overrides_from_env()
    (du, di, dr), (dhu, dhi, dhv), (nu, ni) = synthetic_like_device(
        "ml-25m", nnz=nnz, rank=16, noise=0.1, seed=0, skew_lam=2.0,
        num_users=num_users, num_items=num_items)
    jax.block_until_ready(dr)
    t0 = time.perf_counter()
    p = device_block_problem(du, di, dr, nu, ni, num_blocks=k,
                             minibatch_multiple=mb, seed=0,
                             minibatch_sort="item")
    jax.block_until_ready(p.su)
    out["blocking_wall_s"] = round(time.perf_counter() - t0, 1)
    out["max_pad_ratio"] = round(p.max_pad_ratio, 3)
    train_nnz = int(du.shape[0])

    cfg = DSGDConfig(num_factors=rank, lambda_=0.1, iterations=1,
                     learning_rate=0.3, lr_schedule="warm_boost", seed=0,
                     minibatch_size=mb, init_scale=0.08,
                     collision_mode="mean")
    solver = DSGD(cfg)
    schedule = warm_boost_lr()  # the bench default: 2.5x for 2 sweeps
    hur_d, hir_d, hmask = p.holdout_rows(dhu, dhi)
    n_eval = float(np.asarray(hmask).sum())

    def rmse(U, V):
        sse = sgd_ops.sse_rows(U, V, hur_d, hir_d, dhv, hmask)
        return float(np.sqrt(float(sse) / n_eval))

    args = (p.su, p.si, p.sv, p.sw, p.omega_u, p.omega_v, p.icu, p.icv)

    for variant in variants:
        U, V = init_factors_device(p, rank, scale=cfg.init_scale)

        if variant == "pallas":
            def sweep(U, V, t):
                return dsgd_train_pallas(
                    U, V, *args, lr=cfg.learning_rate, lam=cfg.lambda_,
                    minibatch=mb, num_blocks=k, iterations=1,
                    schedule=schedule, t0=t)
        else:
            kw = dict(updater=solver.updater, minibatch=mb, num_blocks=k,
                      iterations=1, collision="mean")

            def sweep(U, V, t):
                return sgd_ops.dsgd_train(U, V, *args, **kw, t0=t)

        try:
            t0 = time.perf_counter()
            Uw, Vw = sweep(U, V, 0)
            jax.block_until_ready((Uw, Vw))
            out[f"{variant}_compile_wall_s"] = round(
                time.perf_counter() - t0, 1)
            del Uw, Vw
        except Exception as ex:
            out[f"{variant}_error"] = f"{type(ex).__name__}: {ex}"[:500]
            continue

        wall = 0.0
        curve = [round(rmse(U, V), 4)]
        tt = st = None
        for it in range(max_sweeps):
            t0 = time.perf_counter()
            U, V = sweep(U, V, it)
            jax.block_until_ready((U, V))
            wall += time.perf_counter() - t0
            curve.append(round(rmse(U, V), 4))
            if tt is None and curve[-1] <= target:
                tt, st = wall, it + 1
                break
        sweeps = st or max_sweeps
        out[f"{variant}_rmse_curve"] = curve
        out[f"{variant}_time_to_target_s"] = (None if tt is None
                                              else round(tt, 2))
        out[f"{variant}_ratings_per_s"] = round(
            train_nnz * sweeps / wall, 1)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
