"""Durable-ingest micro-bench: ratings/s through log→queue→online_train.

The streaming acceptance number for the ingest runtime (``streams/``):
the SAME micro-batch stream driven two ways —

- **bare**: ``OnlineMF.partial_fit`` straight off in-memory batches —
  the demo loop the repo had before the durable tier existed. Fast, and
  a crash loses everything since the last factor snapshot.
- **durable**: the full ``StreamingDriver`` path — fsync-less event-log
  appends (fsync is a knob; CI machines' fsync latency would measure
  the disk, not the runtime), ``LogTailSource`` offset-stamped reads
  through the bounded backpressure queue, per-batch (U, V, offset)
  checkpoints, crash-recoverable by contract.

``value`` is the durable path's ratings/s; ``vs_baseline`` is
durable/bare — the *throughput retention* of durability (1.0 = free;
~1.0 measured on CPU at default sizes, where the queue overlaps host
batch prep with device compute). tests/test_bench_contract.py pins the
JSON contract structurally; the retention number itself is bench-round
evidence (``streams_ingest_vs_bare``), not a CI gate. The log-append
leg is also timed alone (``log_append_ratings_per_s``).

Contract: the LAST stdout line is one JSON object
``{"metric", "value", "unit", "vs_baseline", "extra"}``.

Env knobs: STREAMS_USERS, STREAMS_ITEMS, STREAMS_RANK, STREAMS_BATCHES,
STREAMS_BATCH (records per micro-batch), STREAMS_CHECKPOINT_EVERY,
STREAMS_FSYNC (=1 to fsync appends), STREAMS_FORCE_CPU (=0 for the
default jax backend).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(num_users=20_000, num_items=5_000, rank=32, n_batches=10,
        batch_records=50_000, checkpoint_every=1, fsync=False,
        seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.streams import (
        EventLog,
        StreamingDriver,
        StreamingDriverConfig,
    )

    gen = SyntheticMFGenerator(num_users=num_users, num_items=num_items,
                               rank=16, noise=0.1, seed=seed, skew_lam=2.0)
    batches = [gen.generate(batch_records) for _ in range(n_batches)]
    warm = gen.generate(batch_records)
    total = n_batches * batch_records

    def make_model():
        return OnlineMF(OnlineMFConfig(
            num_factors=rank, learning_rate=0.05,
            minibatch_size=min(16384, batch_records),
            init_capacity=1 << 15))

    extra = {
        "device": str(jax.devices()[0]), "num_users": num_users,
        "num_items": num_items, "rank": rank, "n_batches": n_batches,
        "batch_records": batch_records,
        "checkpoint_every": checkpoint_every, "fsync": fsync,
    }

    with tempfile.TemporaryDirectory() as tmp:
        # ---- log append leg (host-only) -------------------------------
        log = EventLog(os.path.join(tmp, "log"), fsync=fsync)
        # file creation / first-segment cost; the acked end offset (not
        # batch_records — append drops weight-0 padding) is where the
        # timed stream starts
        _, warm_end = log.append(0, warm)
        t0 = time.perf_counter()
        for b in batches:
            log.append(0, b)
        append_wall = time.perf_counter() - t0
        extra["log_append_ratings_per_s"] = round(total / append_wall, 1)

        # ---- bare baseline: partial_fit off in-memory batches ---------
        bare = make_model()
        bare.partial_fit(warm, emit_updates=False)  # compile+grow warm-up
        t0 = time.perf_counter()
        for b in batches:
            bare.partial_fit(b, emit_updates=False)
        jax.block_until_ready(bare.users.array)
        bare_wall = time.perf_counter() - t0
        extra["bare_ratings_per_s"] = round(total / bare_wall, 1)

        # ---- durable path: log → queue → online_train -----------------
        model = make_model()
        model.partial_fit(warm, emit_updates=False)  # same warm-up
        drv = StreamingDriver(
            model, log, os.path.join(tmp, "ckpt"),
            config=StreamingDriverConfig(
                batch_records=batch_records,
                checkpoint_every=checkpoint_every))
        # the warm batch occupies [0, warm_end) of the log; skip it so
        # both timed paths train the identical stream
        model.consumed_offsets[0] = warm_end
        t0 = time.perf_counter()
        applied = drv.run()
        jax.block_until_ready(model.users.array)
        durable_wall = time.perf_counter() - t0
        tele = drv.telemetry()
        extra["ingest_ratings_per_s"] = round(total / durable_wall, 1)
        extra["ingest_wall_s"] = round(durable_wall, 3)
        extra["ingest_batches"] = applied
        extra["ingest_lag_records"] = tele["lag_records"]
        extra["checkpoints_written"] = tele["checkpoints_written"]
        extra["queue_depth_high_water"] = (
            tele["queue"].get("depth_high_water", 0))
        log.close()

    retention = (total / durable_wall) / (total / bare_wall)
    return {
        "metric": (f"durable ingest ratings/s (log→queue→online_train, "
                   f"{num_users}x{num_items} rank={rank}, "
                   f"{n_batches}x{batch_records} micro-batches, "
                   f"ckpt every {checkpoint_every})"),
        "value": extra["ingest_ratings_per_s"],
        "unit": "ratings/s",
        "vs_baseline": round(retention, 3),
        "extra": extra,
    }


def main() -> None:
    if os.environ.get("STREAMS_FORCE_CPU", "1") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()
    result = run(
        num_users=int(os.environ.get("STREAMS_USERS", 20_000)),
        num_items=int(os.environ.get("STREAMS_ITEMS", 5_000)),
        rank=int(os.environ.get("STREAMS_RANK", 32)),
        n_batches=int(os.environ.get("STREAMS_BATCHES", 10)),
        batch_records=int(os.environ.get("STREAMS_BATCH", 50_000)),
        checkpoint_every=int(os.environ.get("STREAMS_CHECKPOINT_EVERY", 1)),
        fsync=os.environ.get("STREAMS_FSYNC") == "1",
    )
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    sys.exit(main())
