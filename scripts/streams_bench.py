"""Durable-ingest micro-bench: ratings/s through log→queue→online_train.

The streaming acceptance number for the ingest runtime (``streams/``):
the SAME micro-batch stream driven two ways —

- **bare**: ``OnlineMF.partial_fit`` straight off in-memory batches —
  the demo loop the repo had before the durable tier existed. Fast, and
  a crash loses everything since the last factor snapshot.
- **durable**: the full ``StreamingDriver`` path — fsync-less event-log
  appends (fsync is a knob; CI machines' fsync latency would measure
  the disk, not the runtime), ``LogTailSource`` offset-stamped reads
  through the bounded backpressure queue, per-batch (U, V, offset)
  checkpoints, crash-recoverable by contract.

``value`` is the durable path's ratings/s; ``vs_baseline`` is
durable/bare — the *throughput retention* of durability (1.0 = free;
~1.0 measured on CPU at default sizes, where the queue overlaps host
batch prep with device compute). tests/test_bench_contract.py pins the
JSON contract structurally; the retention number itself is bench-round
evidence (``streams_ingest_vs_bare``), not a CI gate. The log-append
leg is also timed alone (``log_append_ratings_per_s``).

**N_CONSUMERS mode** (``STREAMS_CONSUMERS=1,2,4,8``): the parallel
ingest round (``INGEST_r*.json``, ISSUE 13) — STRONG scaling: for each
N on the curve, the SAME fixed-universe workload (``STREAMS_USERS`` ×
``STREAMS_ITEMS``, ``STREAMS_BATCHES`` total micro-batches) is
stratum-routed across an N-partition WAL (partition p's users ≡ p mod
N, its items in block p — the Gemulla row-disjointness the concurrent
applies exploit; the model geometry is IDENTICAL at every N, so the
curve measures parallelism, not table growth) and drained by a
``ParallelIngestRunner`` with N consumers; the headline is sustained
aggregate ratings/s at the largest N, ``vs_baseline`` the speedup over
N=1, and ``scaling_eff_n<K>`` = rate_K / (K · rate_1) the scaling
efficiency the ``--family ingest`` gate watches. The round also measures
recovery-after-kill at the largest N (one consumer crashes mid-stream
with partitions at different offsets; a fresh runner resumes from the
cross-partition barrier snapshot and re-drains — ``recovery_s``, with
the per-partition duplicate window in batches) and a sustained
follow-mode pass with lineage + critical-path armed
(``freshness_slo_held``: the ingest→serve ``FreshnessCheck`` stayed
green under continuous N-consumer write load;
``critical_path_partitions``: ``/criticalpathz`` samples resolved for
every partition). Machines with fewer cores than N cap thread scaling
at ~min(N, cores); the result carries an explicit ``error`` caveat
when that happens so cross-machine gating reads it.

Contract: the LAST stdout line is one JSON object
``{"metric", "value", "unit", "vs_baseline", "extra"}``, emitted after
a stderr flush (the bench.py/serving_bench hardening, so 2>&1-merged
wrappers always parse the last line).

Every result header stamps ``cpu_count`` and ``jax_platforms`` (the
round's machine identity — cross-machine gating must read them), the
1-core ``error`` caveat auto-emits whenever ``cores < max(N_CONSUMERS)``,
and each scaling rung runs with the contention plane armed
(``obs.enable_contention``): ``serial_fraction_n<K>`` (the Karp–Flatt
Amdahl estimate over the rung's window, N>1 rungs) and
``lock_wait_s_total_n<K>`` extras say WHERE a flat curve's headroom
went (ISSUE 14 — the ``--family ingest`` gate watches them as
lower-is-better via direction rules). The sustained pass serves
``/contentionz`` over a real socket and dumps the body to
``STREAMS_CONTENTION_OUT`` (the CI smoke's structural-assert artifact).

**TIERED mode** (``STREAMS_TIER_SLOTS=8192``): the tiered-factor-store
round (``TIERED_r*.json``, ISSUE 17) — the SAME bounded-Zipf WAL
stream (rank-weighted ``r^-s`` ids over a 1M universe) driven all-HBM
and through a ``TieredFactorStore`` whose device slot pool holds a
fraction of the user table (default geometry: ~36k realized rows over
8k slots, a ≥4× simulated device budget), with the driver's feeder
queue announcing batches to the async prefetcher two ahead (short
lead measured best: staged rows survive to their acquire and
not-yet-registered ids are exactly the ones LRU still holds).
``value`` is the tiered path's ratings/s, ``vs_baseline`` the
throughput retention vs all-HBM, and the round hard-checks the pinned
invariant end-to-end: final user tables AND both engines' served top-K
(the tiered engine gather-on-miss through ``user_store``) must be
bit-identical. Extras carry the tier's report card
(``tier_hit_rate``, ``tier_prefetch_wait_s``, ``tier_evictions``,
``tier_host_bytes``, serve hit/miss split) — the ``--family tier``
gate's keys. The simulated-budget caveat is ALWAYS stamped in
``error``: the slot pool caps rows on a CPU host, so the overhead is
real but HBM pressure is not.

Every mode stamps ``retrace_total`` / ``implicit_transfers_total``
from the transfer plane (``obs.transfers``, ISSUE 18) into the result
header, measured over the round's post-warmup streamed phase (the
ledger resets at each warm/stream boundary — steady state should be
ZERO on both). TIERED mode additionally stamps measured per-site
transfer GB/s for both legs (h2d stage-in sites like
``transfer_store_prefetch_gbs``, the d2h
``transfer_store_writeback_gbs`` leg) plus the h2d/d2h byte totals —
honest on CPU: the rates price the host↔"device" copy machinery on
this backend, not a real PCIe/ICI link (the simulated-budget caveat
above still rides ``error``).

Env knobs: STREAMS_USERS, STREAMS_ITEMS, STREAMS_RANK, STREAMS_BATCHES,
STREAMS_BATCH (records per micro-batch), STREAMS_CHECKPOINT_EVERY,
STREAMS_FSYNC (=1 to fsync appends), STREAMS_FORCE_CPU (=0 for the
default jax backend). Parallel mode adds: STREAMS_CONSUMERS (the N
curve; presence selects the mode), STREAMS_FRESHNESS_S (sustained-pass
duration, 0 skips), STREAMS_RECOVERY (=0 skips the kill/restart pass),
STREAMS_CONTENTION_OUT (path for the sustained pass's /contentionz
dump), STREAMS_TRANSFERS_OUT (path for its /transferz dump — fetched
over the same real socket). Tiered mode is selected by
STREAMS_TIER_SLOTS (the device slot pool size; takes precedence over
STREAMS_CONSUMERS) and adds STREAMS_TIER_ZIPF_S (the Zipf exponent,
default 1.25). STREAMS_TRANSFER_GUARD (off|log|disallow, default off)
arms the implicit-transfer guard around the hot paths in every mode —
CI runs the ingest smoke with ``disallow`` so any unplanned host
round-trip aborts the round instead of hiding in the wall time.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit_final(result: dict) -> None:
    """Flush stderr BEFORE printing the final JSON line so a
    2>&1-merged capture always parses the last line (the same
    hardening bench.py / serving_bench / pallas_probe / pod_dryrun
    carry)."""
    sys.stderr.flush()
    print(json.dumps(result), flush=True)


def run(num_users=20_000, num_items=5_000, rank=32, n_batches=10,
        batch_records=50_000, checkpoint_every=1, fsync=False,
        seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.streams import (
        EventLog,
        StreamingDriver,
        StreamingDriverConfig,
    )

    gen = SyntheticMFGenerator(num_users=num_users, num_items=num_items,
                               rank=16, noise=0.1, seed=seed, skew_lam=2.0)
    batches = [gen.generate(batch_records) for _ in range(n_batches)]
    warm = gen.generate(batch_records)
    total = n_batches * batch_records

    def make_model():
        return OnlineMF(OnlineMFConfig(
            num_factors=rank, learning_rate=0.05,
            minibatch_size=min(16384, batch_records),
            init_capacity=1 << 15))

    extra = {
        "device": str(jax.devices()[0]), "cpu_count": os.cpu_count() or 1,
        "jax_platforms": os.environ.get("JAX_PLATFORMS",
                                        jax.default_backend()),
        "num_users": num_users,
        "num_items": num_items, "rank": rank, "n_batches": n_batches,
        "batch_records": batch_records,
        "checkpoint_every": checkpoint_every, "fsync": fsync,
    }

    # the transfer plane rides the round (ISSUE 18): registry stays
    # NULL (the ledger keeps its own totals), the reset at the durable
    # warm/stream boundary makes the stamped retrace count a
    # steady-state number
    ledger = obs.enable_transfers(
        guard=os.environ.get("STREAMS_TRANSFER_GUARD", "off"))

    with tempfile.TemporaryDirectory() as tmp:
        # ---- log append leg (host-only) -------------------------------
        log = EventLog(os.path.join(tmp, "log"), fsync=fsync)
        # file creation / first-segment cost; the acked end offset (not
        # batch_records — append drops weight-0 padding) is where the
        # timed stream starts
        _, warm_end = log.append(0, warm)
        t0 = time.perf_counter()
        for b in batches:
            log.append(0, b)
        append_wall = time.perf_counter() - t0
        extra["log_append_ratings_per_s"] = round(total / append_wall, 1)

        # ---- bare baseline: partial_fit off in-memory batches ---------
        bare = make_model()
        bare.partial_fit(warm, emit_updates=False)  # compile+grow warm-up
        t0 = time.perf_counter()
        for b in batches:
            bare.partial_fit(b, emit_updates=False)
        jax.block_until_ready(bare.users.array)
        bare_wall = time.perf_counter() - t0
        extra["bare_ratings_per_s"] = round(total / bare_wall, 1)

        # ---- durable path: log → queue → online_train -----------------
        model = make_model()
        model.partial_fit(warm, emit_updates=False)  # same warm-up
        drv = StreamingDriver(
            model, log, os.path.join(tmp, "ckpt"),
            config=StreamingDriverConfig(
                batch_records=batch_records,
                checkpoint_every=checkpoint_every))
        # the warm batch occupies [0, warm_end) of the log; skip it so
        # both timed paths train the identical stream
        model.consumed_offsets[0] = warm_end
        ledger.reset()  # warm/stream boundary: stamps cover the
        # durable leg only (the headline)
        t0 = time.perf_counter()
        applied = drv.run()
        jax.block_until_ready(model.users.array)
        durable_wall = time.perf_counter() - t0
        tele = drv.telemetry()
        extra["ingest_ratings_per_s"] = round(total / durable_wall, 1)
        extra["ingest_wall_s"] = round(durable_wall, 3)
        extra["ingest_batches"] = applied
        extra["ingest_lag_records"] = tele["lag_records"]
        extra["checkpoints_written"] = tele["checkpoints_written"]
        extra["queue_depth_high_water"] = (
            tele["queue"].get("depth_high_water", 0))
        ledger.poll_retraces()
        extra["retrace_total"] = int(ledger.retrace_total)
        extra["implicit_transfers_total"] = int(ledger.implicit_total)
        log.close()

    obs.disable()
    retention = (total / durable_wall) / (total / bare_wall)
    return {
        "metric": (f"durable ingest ratings/s (log→queue→online_train, "
                   f"{num_users}x{num_items} rank={rank}, "
                   f"{n_batches}x{batch_records} micro-batches, "
                   f"ckpt every {checkpoint_every})"),
        "value": extra["ingest_ratings_per_s"],
        "unit": "ratings/s",
        "vs_baseline": round(retention, 3),
        "extra": extra,
    }


# --------------------------------------------------------------------------
# TIERED mode: the tiered-factor-store round (TIERED_r*.json)
# --------------------------------------------------------------------------


def _zipf_batches(num_users, num_items, n_batches, batch_records,
                  seed, zipf_s):
    """Bounded-Zipf rating stream: user ids rank-weighted ``r^-s``
    over the full universe. The generator's truncated-exponential
    skew can't express a tiered workload — its tail is so thin that
    realized rows ≈ 3N/λ while 90% hot-mass needs slots ≥ 2.3N/λ,
    capping the honest overcommit near 1.3×. A Zipf tail keeps
    registering fresh rows for the WHOLE stream (the table outgrows
    the pool) while revisit mass stays concentrated (the pool can
    still serve it) — the actual access pattern tiering exists for."""
    from large_scale_recommendation_tpu.core.types import Ratings

    rng = np.random.default_rng(seed)
    ranks = np.arange(1, num_users + 1, dtype=np.float64)
    p = ranks ** -zipf_s
    p /= p.sum()

    def draw():
        return Ratings.from_arrays(
            rng.choice(num_users, size=batch_records, p=p),
            rng.integers(0, num_items, batch_records),
            rng.uniform(1.0, 5.0, batch_records).astype(np.float32))

    return [draw() for _ in range(n_batches)], draw()


def run_tiered(num_users=1_000_000, num_items=4_000, rank=32,
               n_batches=24, batch_records=20_000, slot_capacity=8_192,
               zipf_s=1.25, checkpoint_every=8, fsync=False, seed=0,
               serve_requests=16) -> dict:
    """Tiered-store round: the SAME Zipfian WAL stream driven twice —
    all-HBM (plain ``GrowableFactorTable``) and tiered (a
    ``TieredFactorStore`` whose device slot pool is a fraction of the
    user table, async-prefetched from the WAL lookahead the driver's
    feeder queue announces). The headline is the tiered ingest rate;
    ``vs_baseline`` is tiered/all-HBM (the throughput retention of the
    tier); the round also proves the pinned invariant on the real
    pipeline: the two final user tables and the two engines' top-K
    answers must be BIT-IDENTICAL (``bit_exact`` / ``serve_bit_exact``
    are hard evidence, not vibes). Default geometry: a 1M-id Zipf(1.25)
    universe realizing ~36k user rows over an 8k-slot pool (≥4× device
    budget), per-batch working set ~3.3k rows — the pinned batch plus
    the announced lookahead fit the pool, so the steady-state hit rate
    is LRU residency plus the prefetcher's report card. The
    simulated-budget caveat is stamped in ``error``."""
    import jax

    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.core.initializers import (
        PseudoRandomFactorInitializer,
    )
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.serving.engine import ServingEngine
    from large_scale_recommendation_tpu.store import TieredFactorStore
    from large_scale_recommendation_tpu.streams import (
        EventLog,
        StreamingDriver,
        StreamingDriverConfig,
    )

    batches, warm = _zipf_batches(num_users, num_items, n_batches,
                                  batch_records, seed, zipf_s)
    total = n_batches * batch_records

    cfg = OnlineMFConfig(num_factors=rank, learning_rate=0.05,
                         minibatch_size=min(16384, batch_records),
                         init_capacity=1 << 15)

    def make_model(tiered: bool) -> OnlineMF:
        m = OnlineMF(cfg)
        if tiered:
            # the EXACT initializer OnlineMF builds, so any divergence
            # can only come from the tier itself
            m.users = TieredFactorStore(
                PseudoRandomFactorInitializer(cfg.num_factors,
                                              scale=cfg.init_scale),
                capacity=cfg.init_capacity,
                slot_capacity=slot_capacity)
        return m

    # the transfer plane rides the round (ISSUE 18): registry stays
    # NULL (the ledger keeps its own totals); each leg's drive resets
    # the ledger at its warm/stream boundary, so the per-site GB/s
    # stamps below cover exactly the tiered streamed phase
    ledger = obs.enable_transfers(
        guard=os.environ.get("STREAMS_TRANSFER_GUARD", "off"))

    def drive(model, log, tmp, name, warm_end) -> float:
        model.partial_fit(warm, emit_updates=False)  # compile warm-up
        drv = StreamingDriver(
            model, log, os.path.join(tmp, name),
            config=StreamingDriverConfig(
                batch_records=batch_records,
                checkpoint_every=checkpoint_every,
                # bounded lookahead: the feeder announces at most 2
                # batches ahead. Short lead wins twice: an announced id
                # whose rows were staged is acquired before eviction
                # pressure ages it out, and ids unseen at announce time
                # (dropped — prefetch never registers vocabulary) are
                # exactly the recently-first-seen rows LRU still holds.
                # Measured: lead 2 ≈ 0.91 hit, lead 8 ≈ 0.79, lead 16
                # (the default) ≈ 0.77 on the default geometry
                queue_capacity=2))
        model.consumed_offsets[0] = warm_end  # both paths skip warm
        ledger.reset()  # warm/stream boundary (ISSUE 18): cold-start
        # faults and compile traces are warm-up, not steady state
        t0 = time.perf_counter()
        drv.run()
        jax.block_until_ready(model.users.array)
        return time.perf_counter() - t0

    extra = {
        "device": str(jax.devices()[0]), "cpu_count": os.cpu_count() or 1,
        "jax_platforms": os.environ.get("JAX_PLATFORMS",
                                        jax.default_backend()),
        "num_users": num_users, "num_items": num_items, "rank": rank,
        "n_batches": n_batches, "batch_records": batch_records,
        "slot_capacity": slot_capacity,
    }

    with tempfile.TemporaryDirectory() as tmp:
        log = EventLog(os.path.join(tmp, "log"), fsync=fsync)
        _, warm_end = log.append(0, warm)
        for b in batches:
            log.append(0, b)

        hbm = make_model(tiered=False)
        hbm_wall = drive(hbm, log, tmp, "ckpt_hbm", warm_end)

        tiered = make_model(tiered=True)
        st = tiered.users
        # isolate the streamed phase: the warm-up batch's cold-start
        # demand faults are compile-time noise, not steady state
        st.stats.hits = st.stats.misses = 0
        st.stats.demand_fault_s = 0.0
        tier_wall = drive(tiered, log, tmp, "ckpt_tier", warm_end)
        log.close()

        rows = int(st.num_rows)
        assert rows == int(hbm.users.num_rows)
        U_h = np.asarray(hbm.users.full_table())[:rows]
        U_t = np.asarray(st.full_table())[:rows]
        bit_exact = bool(np.array_equal(U_t, U_h))

        extra["hbm_ratings_per_s"] = round(total / hbm_wall, 1)
        extra["tiered_ratings_per_s"] = round(total / tier_wall, 1)
        extra["tiered_vs_hbm_frac"] = round(hbm_wall / tier_wall, 3)
        extra["user_rows"] = rows
        extra["device_budget_x"] = round(rows / slot_capacity, 2)
        extra["tier_hit_rate"] = round(st.stats.hit_rate, 4)
        extra["tier_prefetch_wait_s"] = round(st.stats.demand_fault_s, 4)
        extra["tier_evictions"] = int(st.stats.evictions)
        extra["tier_writebacks"] = int(st.stats.writebacks)
        extra["tier_host_bytes"] = int(st.stats.host_bytes)
        extra["tier_prefetched_rows"] = int(st.stats.prefetched)
        extra["bit_exact"] = bit_exact

        # measured per-site transfer GB/s for both legs (h2d stage-in
        # sites, the d2h write-back site) over the tiered streamed
        # phase, plus the steady-state retrace/guard stamps. CPU
        # caveat unchanged: the rates price the host<->"device"
        # copy machinery on this backend, not a real PCIe/ICI link.
        snap = ledger.snapshot()
        for site, s in snap["sites"].items():
            if s["effective_gbs"] is not None:
                extra["transfer_" + site.replace(".", "_") + "_gbs"] = (
                    round(s["effective_gbs"], 3))
        extra["transfer_h2d_bytes"] = sum(
            s["h2d_bytes"] for s in snap["sites"].values())
        extra["transfer_d2h_bytes"] = sum(
            s["d2h_bytes"] for s in snap["sites"].values())
        extra["retrace_total"] = int(snap["retraces"]["total"])
        extra["implicit_transfers_total"] = int(
            snap["implicit_transfers_total"])

        # ---- serve both sides over identical requests ----------------
        rng = np.random.default_rng(seed + 1)
        requests = [rng.integers(0, rows, 64).astype(np.int64)
                    for _ in range(serve_requests)]
        eng_h = ServingEngine(hbm.to_model(), k=10)
        t0 = time.perf_counter()
        res_h = eng_h.serve(requests)
        extra["serve_hbm_wall_s"] = round(time.perf_counter() - t0, 4)
        eng_t = ServingEngine(tiered.to_model(), k=10, user_store=st)
        t0 = time.perf_counter()
        res_t = eng_t.serve(requests)
        extra["serve_tiered_wall_s"] = round(time.perf_counter() - t0, 4)
        serve_exact = all(
            np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
            and np.array_equal(np.asarray(a[1]), np.asarray(b[1]))
            for a, b in zip(res_h, res_t))
        extra["serve_bit_exact"] = bool(serve_exact)
        extra["tier_serve_hits"] = int(st.stats.serve_hits)
        extra["tier_serve_misses"] = int(st.stats.serve_misses)

    obs.disable()
    return {
        "metric": (f"tiered ingest ratings/s (user table {rows} rows "
                   f"over {slot_capacity} device slots, "
                   f"{extra['device_budget_x']}x device budget, "
                   f"rank={rank})"),
        "value": extra["tiered_ratings_per_s"],
        "unit": "ratings/s",
        "vs_baseline": extra["tiered_vs_hbm_frac"],
        # honest caveat, the INGEST_r01 precedent: stamped on EVERY
        # tiered round, because the budget is simulated by capping the
        # slot pool on a CPU host — it prices the tier's bookkeeping,
        # transfers and prefetch machinery, not real HBM pressure
        "error": ("simulated device budget: the slot pool caps rows on "
                  "a CPU host; bookkeeping+transfer overhead is real, "
                  "HBM pressure is not"),
        "extra": extra,
    }


# --------------------------------------------------------------------------
# N_CONSUMERS mode: the parallel-ingest round (INGEST_r*.json)
# --------------------------------------------------------------------------


def _stratum_batch(rng, p: int, n_consumers: int, total_users: int,
                   total_items: int, count: int):
    """ONE stratum-routed batch for partition ``p`` over the FIXED
    shared universe: users ≡ p (mod N), items in block p of the same
    ``total_items`` catalog — two partitions' batches never share a
    user OR item row (the Gemulla disjointness that lets the N applies
    commute), and the model trained at N=8 has the same table geometry
    as at N=1, so the curve measures PARALLELISM, not table growth
    (full-table scatter cost scales with table size — a per-partition
    universe would confound the two). The ONE copy of the routing rule
    all three passes share."""
    u_blk = max(1, total_users // n_consumers)
    i_blk = max(1, total_items // n_consumers)
    u = rng.integers(0, u_blk, count) * n_consumers + p
    i = rng.integers(0, i_blk, count) + p * i_blk
    return u, i, rng.random(count).astype(np.float32)


def _fill_strata(log, n_consumers: int, total_users: int,
                 total_items: int, batches_per_part: int,
                 batch_records: int, seed: int = 0) -> None:
    """Fill each partition with ``batches_per_part`` stratum-routed
    batches (``_stratum_batch``)."""
    rng = np.random.default_rng(seed)
    for p in range(n_consumers):
        for _ in range(batches_per_part):
            u, i, r = _stratum_batch(rng, p, n_consumers, total_users,
                                     total_items, batch_records)
            log.append_arrays(p, u, i, r)


def _make_parallel(tmp, name, n_consumers, rank, batch_records,
                   checkpoint_every, fsync, minibatch):
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.streams import (
        EventLog,
        ParallelIngestRunner,
        StreamingDriverConfig,
    )

    log = EventLog(os.path.join(tmp, name), num_partitions=n_consumers,
                   fsync=fsync)
    model = OnlineMF(OnlineMFConfig(
        num_factors=rank, learning_rate=0.05,
        minibatch_size=minibatch, init_capacity=1 << 15))
    runner = ParallelIngestRunner(
        model, log, os.path.join(tmp, name + "_ckpt"),
        config=StreamingDriverConfig(batch_records=batch_records,
                                     checkpoint_every=checkpoint_every))
    return log, model, runner


def run_parallel(curve=(1, 2, 4, 8), total_users=32_000,
                 total_items=8_000, rank=32, n_batches=16,
                 batch_records=20_000, checkpoint_every=4, fsync=False,
                 freshness_s=2.0, recovery=True, seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu import obs

    minibatch = min(8192, batch_records)
    curve = sorted(set(int(n) for n in curve))
    cores = os.cpu_count() or 1
    extra = {
        "device": str(jax.devices()[0]), "cpu_count": cores,
        "jax_platforms": os.environ.get("JAX_PLATFORMS",
                                        jax.default_backend()),
        "curve": list(curve), "total_users": total_users,
        "total_items": total_items, "rank": rank,
        "n_batches_total": n_batches,
        "batch_records": batch_records,
        "checkpoint_every": checkpoint_every, "fsync": fsync,
    }

    # the contention plane rides every rung (ISSUE 14): the locks bind
    # at model/runner construction, the window resets per rung, and
    # serial_fraction_n<K>/lock_wait_s_total_n<K> say where a flat
    # curve's headroom went. Registry stays NULL here — the tracker
    # keeps its own stats, so the rungs pay only the (µs-scale)
    # wrapped-lock accounting, not the full obs stack.
    tracker = obs.enable_contention(interval_s=0.2)
    # the transfer plane rides the rungs the same way (ISSUE 18): null
    # registry, own totals; reset alongside each rung's window so the
    # round-header stamps cover the largest-N rung's timed drain
    ledger = obs.enable_transfers(
        guard=os.environ.get("STREAMS_TRANSFER_GUARD", "off"))

    rates: dict[int, float] = {}
    with tempfile.TemporaryDirectory() as tmp:
        # ---- scaling curve (STRONG scaling): the same fixed-universe
        # workload split over N stratum-routed partitions ---------------
        for n in curve:
            bpp = max(1, n_batches // n)  # batches per partition
            log, model, runner = _make_parallel(
                tmp, f"log_n{n}", n, rank, batch_records,
                checkpoint_every, fsync, minibatch)
            # warm: one batch per partition through the FULL path
            # (compiles the concurrent-apply kernels + grows tables)
            _fill_strata(log, n, total_users, total_items,
                         1 + bpp, batch_records, seed=seed)
            runner.run(max_batches=1)
            total = n * bpp * batch_records
            tracker.reset_window()
            ledger.reset()  # warm/stream boundary per rung
            t0 = time.perf_counter()
            applied = runner.run()
            jax.block_until_ready(model.users.array)
            wall = time.perf_counter() - t0
            sat = obs.SaturationAnalyzer(tracker).snapshot()
            tele = runner.telemetry()
            assert applied == n * bpp, (applied, n, bpp)
            assert all(v == 0 for v in tele["lag_records"].values())
            rates[n] = total / wall
            extra[f"ingest_n{n}_ratings_per_s"] = round(rates[n], 1)
            extra[f"lock_wait_s_total_n{n}"] = round(
                sat["lock_wait_s_total"], 4)
            if n > 1:
                if sat["serial_fraction"] is not None:
                    extra[f"serial_fraction_n{n}"] = round(
                        sat["serial_fraction"], 4)
                if 1 in rates:
                    # efficiency is DEFINED against the true N=1 rate;
                    # a curve without N=1 has no honest baseline —
                    # rate_K/(K·rate_minN) would halve the number and
                    # still gate under the same key
                    extra[f"scaling_eff_n{n}"] = round(
                        rates[n] / (n * rates[1]), 4)
                if tele.get("gate"):
                    extra[f"gate_waits_n{n}"] = tele["gate"]["waits"]
            extra[f"checkpoints_n{n}"] = tele["checkpoints_written"]
            log.close()
            top = (sat["top_contended"][0] if sat["top_contended"]
                   else None)
            print(f"[parallel] N={n}: {rates[n]:,.0f} ratings/s "
                  f"({applied} batches; lock wait "
                  f"{sat['lock_wait_s_total']:.3f}s"
                  + (f", top {top['lock']}" if top else "") + ")",
                  file=sys.stderr)

        n_max = max(curve)

        # round-header stamps (ISSUE 18): the largest-N rung's timed
        # drain, captured BEFORE the recovery/sustained passes (the
        # sustained pass tears the whole obs stack down in its finally)
        ledger.poll_retraces()
        extra["retrace_total"] = int(ledger.retrace_total)
        extra["implicit_transfers_total"] = int(ledger.implicit_total)

        # ---- recovery after a mid-stream kill at N=max --------------
        if recovery:
            extra.update(_recovery_pass(
                tmp, n_max, total_users, total_items, rank,
                max(4, n_batches // n_max), batch_records,
                checkpoint_every, fsync, minibatch, seed))

        # ---- sustained follow-mode pass: freshness SLO + critical
        # path per partition -------------------------------------------
        if freshness_s > 0:
            extra.update(_sustained_pass(
                tmp, n_max, total_users, total_items, rank,
                batch_records, checkpoint_every, fsync, minibatch,
                freshness_s, seed))

    obs.disable()  # the rungs' tracker (the sustained pass tears its
    # own stack down; with freshness_s=0 this is what stops the
    # contention sampler)
    speedup = rates[n_max] / rates[min(curve)]
    result = {
        "metric": (f"parallel ingest ratings/s (N={n_max} per-partition "
                   f"consumers, stratum-routed strong scaling, "
                   f"rank={rank}, {n_batches} total x {batch_records}, "
                   f"barrier every {checkpoint_every})"),
        "value": round(rates[n_max], 1),
        "unit": "ratings/s",
        "vs_baseline": round(speedup, 3),
        "extra": extra,
    }
    if cores < n_max:
        result["error"] = (
            f"only {cores} CPU core(s) for N={n_max} consumers: speedup "
            f"beyond ~min(N, cores)x is physically unreachable here — "
            f"the measured curve is host/device pipeline overlap plus "
            f"contention on {cores} core(s), not N-core parallel "
            f"capacity; re-run on a machine with >= {n_max} cores to "
            f"price the scaling target")
    return result


def _recovery_pass(tmp, n, total_users, total_items, rank,
                   batches_per_part, batch_records, checkpoint_every,
                   fsync, minibatch, seed) -> dict:
    """Kill one consumer mid-stream (partitions at different offsets),
    resume a fresh runner from the barrier snapshot, re-drain. Returns
    recovery_s + the per-partition duplicate window in batches."""
    import jax

    class _Kill(RuntimeError):
        pass

    # the kill must land AFTER at least one barrier (else there is
    # nothing to resume from — a different scenario than the one this
    # pass prices): clamp the cadence to the stream length and kill on
    # partition 0's OWN (ck+1)-th batch — p0 crossing ck guarantees a
    # barrier fired, and counting p0's batches (not a global counter)
    # makes the kill deterministic under any thread schedule (a global
    # threshold could let p0 drain before its siblings ever counted)
    ck = min(checkpoint_every, max(1, batches_per_part // 2))
    log, model, runner = _make_parallel(
        tmp, "log_recov", n, rank, batch_records, ck, fsync, minibatch)
    # uneven partitions: p gets batches_per_part + p extra batches, so
    # the kill leaves every partition at a DIFFERENT offset
    rng = np.random.default_rng(seed + 1)
    for p in range(n):
        for _ in range(batches_per_part + p):
            u, i, r = _stratum_batch(rng, p, n, total_users,
                                     total_items, batch_records)
            log.append_arrays(p, u, i, r)
    p0_seen = [0]

    def kill_late(batch):
        if batch.partition == 0:
            p0_seen[0] += 1
            if p0_seen[0] > ck:
                raise _Kill("mid-stream kill")

    runner.on_batch = kill_late
    t_kill = None
    try:
        runner.run()
    except _Kill:
        t_kill = time.perf_counter()
    assert t_kill is not None, "kill never fired"
    frontier_at_kill = runner.applied_frontier()

    m2_log, m2, r2 = _make_parallel(
        tmp, "log_recov", n, rank, batch_records, ck, fsync, minibatch)
    t0 = time.perf_counter()
    assert r2.resume(), "no barrier snapshot to resume from"
    restored = dict(m2.consumed_offsets)
    r2.run()
    jax.block_until_ready(m2.users.array)
    recovery_s = time.perf_counter() - t0
    tele = r2.telemetry()
    assert all(v == 0 for v in tele["lag_records"].values()), \
        "records lost after resume"
    # duplicate window: batches applied past the restored offset at the
    # kill instant — the replay each partition pays, bounded by the
    # barrier cadence
    dup = {p: max(0, -(-(frontier_at_kill.get(p, 0)
                         - restored.get(p, 0)) // batch_records))
           for p in range(n)}
    m2_log.close()
    return {
        "recovery_s": round(recovery_s, 3),
        "recovery_replayed_records": int(sum(
            max(0, frontier_at_kill.get(p, 0) - restored.get(p, 0))
            for p in range(n))),
        "duplicate_window_batches_max": int(max(dup.values())),
        "duplicate_window_bound": int(ck),
    }


def _sustained_pass(tmp, n, total_users, total_items, rank,
                    batch_records, checkpoint_every, fsync, minibatch,
                    duration_s, seed) -> dict:
    """Follow-mode N-consumer run under continuous producer load with
    lineage + critical path armed: periodic coalesced delta refreshes
    must keep the ingest→serve ``FreshnessCheck`` green, and
    ``/criticalpathz`` samples must resolve for every partition."""
    import json as _json

    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.obs.health import OK
    from large_scale_recommendation_tpu.obs.lineage import FreshnessCheck
    from large_scale_recommendation_tpu.obs.server import (
        ObsServer,
        http_get,
    )

    per = max(1024, batch_records // 8)  # smaller sustained batches
    try:
        obs.enable()
        obs.enable_lineage()
        analyzer = obs.enable_disttrace()
        # the contention plane re-arms ON TOP of the live registry (the
        # rungs ran it against the null one) so /contentionz joins the
        # per-partition streams_* gauges — locks bind at the runner
        # construction below
        tracker = obs.enable_contention(interval_s=0.2)
        log, model, runner = _make_parallel(
            tmp, "log_sustained", n, rank, per, checkpoint_every,
            fsync, minibatch)
        engine = runner.serving_engine(k=10, max_batch=256)
        server = ObsServer().start()
        tracker.reset_window()
        check = FreshnessCheck(obs.get_lineage(),
                               degraded_after_s=max(2.0, duration_s),
                               critical_after_s=4 * max(2.0, duration_s))
        rng = np.random.default_rng(seed + 2)
        stop = threading.Event()

        def produce():
            while not stop.is_set():
                for p in range(n):
                    u, i, r = _stratum_batch(rng, p, n, total_users,
                                             total_items, per)
                    log.append_arrays(p, u, i, r)
                time.sleep(0.01)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        runner.start(follow=True)
        t_end = time.perf_counter() + duration_s
        verdicts = []
        while time.perf_counter() < t_end:
            time.sleep(0.1)
            runner.refresh_serving()
            verdicts.append(check().status)
        # /contentionz over the REAL socket while the N consumers are
        # still following (live threads, live lock traffic) — the body
        # the CI smoke structurally asserts on and the --contention
        # renderer's artifact
        code, body = http_get(server.url + "/contentionz")
        contention_doc = _json.loads(body) if code == 200 else {
            "note": f"fetch failed: {code}", "locks": [],
            "partitions": {}}
        out_path = os.environ.get("STREAMS_CONTENTION_OUT")
        if out_path:
            with open(out_path, "w") as f:
                _json.dump(contention_doc, f, indent=2)
        # /transferz over the SAME real socket (ISSUE 18): the round's
        # ledger survives the obs.enable() above (only disable() clears
        # it), so the served body carries the sustained pass's live
        # site totals + the retrace ring — the CI smoke's
        # transferz_ci.json artifact
        tout = os.environ.get("STREAMS_TRANSFERS_OUT")
        if tout:
            code, tbody = http_get(server.url + "/transferz")
            with open(tout, "w") as f:
                f.write(tbody if code == 200
                        else _json.dumps({"note": f"fetch failed: {code}",
                                          "sites": {}}))
        stop.set()
        producer.join()
        runner.stop()
        runner.join()
        runner.refresh_serving()  # final covering swap
        verdicts.append(check().status)
        parts = {s["partition"] for s in analyzer.samples()}
        tele = runner.telemetry()
        server.stop()
        log.close()
        return {
            "freshness_slo_held": int(all(v == OK for v in verdicts)),
            "freshness_checks": len(verdicts),
            "critical_path_partitions": len(parts),
            "critical_path_samples": analyzer.samples_total,
            "contention_partitions": len(contention_doc.get(
                "partitions", {})),
            "contention_locks": len(contention_doc.get("locks", [])),
            "sustained_records": tele["records_processed"],
            "sustained_refreshes_coalesced": tele["refreshes_coalesced"],
            "sustained_catalog_swaps": len(tele["catalog_versions"]),
        }
    finally:
        obs.disable()  # back to the zero-cost null layer for any
        # passes that follow — the bench owns the whole process


def main() -> None:
    if os.environ.get("STREAMS_FORCE_CPU", "1") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu()
    consumers = os.environ.get("STREAMS_CONSUMERS")
    tier_slots = os.environ.get("STREAMS_TIER_SLOTS")
    if tier_slots:
        result = run_tiered(
            num_users=int(os.environ.get("STREAMS_USERS", 1_000_000)),
            num_items=int(os.environ.get("STREAMS_ITEMS", 4_000)),
            rank=int(os.environ.get("STREAMS_RANK", 32)),
            n_batches=int(os.environ.get("STREAMS_BATCHES", 24)),
            batch_records=int(os.environ.get("STREAMS_BATCH", 20_000)),
            slot_capacity=int(tier_slots),
            zipf_s=float(os.environ.get("STREAMS_TIER_ZIPF_S", 1.25)),
            checkpoint_every=int(
                os.environ.get("STREAMS_CHECKPOINT_EVERY", 8)),
            fsync=os.environ.get("STREAMS_FSYNC") == "1",
        )
    elif consumers:
        result = run_parallel(
            curve=[int(x) for x in consumers.split(",")],
            total_users=int(os.environ.get("STREAMS_USERS", 32_000)),
            total_items=int(os.environ.get("STREAMS_ITEMS", 8_000)),
            rank=int(os.environ.get("STREAMS_RANK", 32)),
            n_batches=int(os.environ.get("STREAMS_BATCHES", 16)),
            batch_records=int(os.environ.get("STREAMS_BATCH", 20_000)),
            checkpoint_every=int(
                os.environ.get("STREAMS_CHECKPOINT_EVERY", 4)),
            fsync=os.environ.get("STREAMS_FSYNC") == "1",
            freshness_s=float(os.environ.get("STREAMS_FRESHNESS_S", 2.0)),
            recovery=os.environ.get("STREAMS_RECOVERY", "1") == "1",
        )
    else:
        result = run(
            num_users=int(os.environ.get("STREAMS_USERS", 20_000)),
            num_items=int(os.environ.get("STREAMS_ITEMS", 5_000)),
            rank=int(os.environ.get("STREAMS_RANK", 32)),
            n_batches=int(os.environ.get("STREAMS_BATCHES", 10)),
            batch_records=int(os.environ.get("STREAMS_BATCH", 50_000)),
            checkpoint_every=int(
                os.environ.get("STREAMS_CHECKPOINT_EVERY", 1)),
            fsync=os.environ.get("STREAMS_FSYNC") == "1",
        )
    _emit_final(result)


if __name__ == "__main__":
    sys.exit(main())
