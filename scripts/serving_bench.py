"""Sustained-serving micro-bench: ServingEngine vs the per-call mesh path.

The serving acceptance pin, CPU-measurable and repeatable: a stream of
mixed-size recommend requests (the "millions of users" shape — many
small queries, not one big batch) served two ways over the SAME prebuilt
sharded catalog:

- **per-call**: one ``mesh_top_k_recommend`` invocation per request —
  what a naive service loop around ``MFModel.recommend(mesh=...)`` does.
  Each request pays its own dispatch, exclusion build, and a
  request-sized (pow2-padded) kernel call that leaves the matmul units
  mostly idle.
- **engine**: ``ServingEngine.serve`` — requests coalesce into
  ``max_batch``-row micro-batches from a bounded pow2 bucket family, so
  the dispatch count collapses and every kernel call runs at a
  throughput-shaped batch size. A bf16-catalog pass rides along.

Contract: the LAST stdout line is one JSON object
``{"metric", "value", "unit", "vs_baseline", "extra"}`` — ``value`` is
engine users/s, ``vs_baseline`` is the engine/per-call speedup
(the acceptance bar is ≥ 1.5). ``extra`` carries both raw rates, the
compiled-executable count (O(#buckets) evidence), and the workload knobs.

Env knobs: SERVE_USERS, SERVE_ITEMS, SERVE_RANK, SERVE_REQUESTS,
SERVE_REQ_MAX (request sizes are uniform in [1, SERVE_REQ_MAX]),
SERVE_K, SERVE_MAX_BATCH, SERVE_DEVICES (virtual CPU mesh width),
SERVE_FORCE_CPU (=0 to use the default jax backend).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(num_users: int, num_items: int, rank: int, seed: int = 0):
    """A seeded random-factor MFModel with identity id maps — serving
    cost does not depend on how the factors were fit."""
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, rank)).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=(num_items, rank)).astype(np.float32)),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)),
    )


def run(num_users=20_000, num_items=8_192, rank=64, n_requests=400,
        req_max=64, k=10, max_batch=1024, n_dev=None, seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu.models.mf import MFModel  # noqa: F401
    from large_scale_recommendation_tpu.parallel.mesh import make_block_mesh
    from large_scale_recommendation_tpu.parallel.serving import (
        mesh_top_k_recommend,
        shard_catalog,
    )
    from large_scale_recommendation_tpu.serving.engine import ServingEngine

    model = build_model(num_users, num_items, rank, seed)
    mesh = make_block_mesh(n_dev)
    rng = np.random.default_rng(seed + 1)
    requests = [
        rng.integers(0, num_users, int(sz)).astype(np.int64)
        for sz in rng.integers(1, req_max + 1, n_requests)
    ]
    total_rows = sum(len(r) for r in requests)
    extra = {
        "device": str(jax.devices()[0]), "mesh_devices": len(mesh.devices),
        "catalog_rows": num_items, "num_users": num_users, "rank": rank,
        "requests": n_requests, "request_rows": total_rows,
        "req_size_max": req_max, "k": k, "max_batch": max_batch,
    }

    # ---- engine path FIRST: its executable-variant count must be its
    # own (the per-call baseline shares the per-mesh step cache, so
    # running it first would misattribute baseline compiles to the
    # engine) — and any shape the engine leaves warm only HELPS the
    # baseline below, keeping the reported speedup conservative
    engine = ServingEngine(model, k=k, mesh=mesh, max_batch=max_batch)
    engine.serve(requests[:4])  # warm the bucket family's hot entries
    # the published micro-batch/bucket evidence must describe the TIMED
    # run only — clear the warm-up's counters
    engine.stats.update(requests=0, rows=0, microbatches=0, buckets={})
    t0 = time.perf_counter()
    engine.serve(requests)
    engine_wall = time.perf_counter() - t0
    extra["engine_users_per_s"] = round(total_rows / engine_wall, 1)
    extra["engine_wall_s"] = round(engine_wall, 3)
    extra["engine_executable_variants"] = engine.executable_variants
    extra["engine_bucket_family_size"] = len(engine.bucket_family)
    extra["engine_microbatches"] = engine.stats["microbatches"]
    extra["engine_bucket_histogram"] = {
        str(b): c for b, c in sorted(engine.stats["buckets"].items())}

    # ---- per-call path: one mesh_top_k_recommend per request ----------
    # over a PREBUILT catalog and a device-RESIDENT U (what
    # model.recommend(mesh=...) holds), with every request-size bucket
    # pre-warmed — the strongest per-call baseline: its remaining cost
    # is per-request dispatch + undersized kernel calls, which is
    # exactly the overhead the engine claims to remove
    import jax.numpy as jnp

    catalog = shard_catalog(np.asarray(model.V), mesh)
    U = jnp.asarray(model.U)
    from large_scale_recommendation_tpu.utils.shapes import pow2_pad

    warm_sizes = sorted({min(pow2_pad(len(r)), 2048) for r in requests})
    for ws in warm_sizes:
        mesh_top_k_recommend(U, None, np.zeros(ws, np.int64), k=k,
                             catalog=catalog)
    t0 = time.perf_counter()
    for r in requests:
        mesh_top_k_recommend(U, None, r, k=k, catalog=catalog)
    percall_wall = time.perf_counter() - t0
    extra["percall_users_per_s"] = round(total_rows / percall_wall, 1)
    extra["percall_wall_s"] = round(percall_wall, 3)

    # ---- bf16 catalog rides along -------------------------------------
    bf16 = ServingEngine(model, k=k, mesh=mesh, max_batch=max_batch,
                         dtype="bfloat16")
    bf16.serve(requests[:4])
    t0 = time.perf_counter()
    bf16.serve(requests)
    extra["engine_bf16_users_per_s"] = round(
        total_rows / (time.perf_counter() - t0), 1)

    # ---- observability overhead: the SAME engine loop with the obs
    # layer live (registry + tracer, per-bucket histograms, spans) vs
    # the disabled run above — the acceptance pin is ≤3% regression,
    # and the disabled run costs nothing by construction (null layer)
    if os.environ.get("SERVE_OBS", "1") == "1":
        # Methodology matters more than the instrumentation here: (a) the
        # timed engine run above may still pay bucket-family compiles its
        # short warm-up missed, and the obs engine would inherit those
        # shapes warm (per-mesh step cache) — a serial comparison against
        # it misreads compile savings as negative overhead; (b) serial
        # passes also conflate machine drift with overhead (measured:
        # ±20% drift between identical disabled passes vs ~2% true
        # overhead). So: one obs-enabled engine, both fully warmed, then
        # INTERLEAVED timed passes, min-of-reps per side.
        from large_scale_recommendation_tpu import obs
        from large_scale_recommendation_tpu.obs.registry import (
            get_registry,
            set_registry,
        )
        from large_scale_recommendation_tpu.obs.trace import (
            get_tracer,
            set_tracer,
        )

        # save/restore whatever obs layer the CALLER had installed:
        # bench.py drives run() in-process, and clobbering a live
        # registry with the null layer would silently eat every metric
        # recorded after this section
        prev_reg, prev_tracer = get_registry(), get_tracer()
        reg, _tracer = obs.enable()
        try:
            oeng = ServingEngine(model, k=k, mesh=mesh,
                                 max_batch=max_batch)
            oeng.serve(requests)  # warm (all buckets, same shapes)
            engine.serve(requests)
            off_walls, on_walls = [], []
            for _ in range(int(os.environ.get("SERVE_OBS_REPS", 3))):
                t0 = time.perf_counter()
                engine.serve(requests)
                off_walls.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                oeng.serve(requests)
                on_walls.append(time.perf_counter() - t0)
            warm_wall, obs_wall = min(off_walls), min(on_walls)
            extra["engine_warm_users_per_s"] = round(
                total_rows / warm_wall, 1)
            extra["engine_obs_users_per_s"] = round(
                total_rows / obs_wall, 1)
            extra["obs_overhead_pct"] = round(
                100.0 * (obs_wall - warm_wall) / warm_wall, 2)
            extra["obs_metric_names"] = len(reg.names())
        finally:
            set_registry(prev_reg)
            set_tracer(prev_tracer)

    speedup = percall_wall / engine_wall
    return {
        "metric": (f"sustained serving users/s (engine vs per-call mesh "
                   f"path, {num_users}x{num_items} rank={rank}, "
                   f"{n_requests} requests ≤{req_max} users)"),
        "value": extra["engine_users_per_s"],
        "unit": "users/s",
        "vs_baseline": round(speedup, 2),
        "extra": extra,
    }


def main() -> None:
    if os.environ.get("SERVE_FORCE_CPU", "1") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu(n_devices=int(os.environ.get("SERVE_DEVICES", 8)))
    result = run(
        num_users=int(os.environ.get("SERVE_USERS", 20_000)),
        num_items=int(os.environ.get("SERVE_ITEMS", 8_192)),
        rank=int(os.environ.get("SERVE_RANK", 64)),
        n_requests=int(os.environ.get("SERVE_REQUESTS", 400)),
        req_max=int(os.environ.get("SERVE_REQ_MAX", 64)),
        k=int(os.environ.get("SERVE_K", 10)),
        max_batch=int(os.environ.get("SERVE_MAX_BATCH", 1024)),
    )
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    sys.exit(main())
