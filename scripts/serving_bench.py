"""Serving bench: engine micro-bench + closed-loop traffic simulator.

Two modes, selected by ``SERVE_MODE``:

**micro** (default) — the PR-1 acceptance pin: a stream of mixed-size
recommend requests served by ``ServingEngine.serve`` vs one
``mesh_top_k_recommend`` call per request over the SAME prebuilt
catalog. ``value`` is engine users/s, ``vs_baseline`` the
engine/per-call speedup (bar ≥ 1.5).

**traffic** — the ROADMAP-item-3 acceptance harness: a traffic
simulator drives the two-stage quantized fast path
(``serving.retrieval``) and the exact full-catalog engine through
timed arrival streams (``SERVE_PATTERN``: poisson / diurnal / bursty)
over a *structured* synthetic catalog (a mixture of ``SERVE_CENTERS``
Gaussian centers — real embedding catalogs cluster, which is the
regime IVF routing is for; recall is MEASURED and reported either
way). It emits:

- saturation throughput for both engines (same bucket warmup) —
  ``fast_users_per_s`` / ``exact_users_per_s`` / ``fast_vs_exact``
  (the ≥3× @ 1M-items acceptance);
- ``recall_at_10`` of the fast path against the exact answers;
- a p99-latency-vs-offered-QPS curve (per-level p50/p99/achieved QPS/
  shed/degraded fractions) and ``qps_at_slo`` — the highest offered
  level whose p99 still met ``SERVE_SLO_MS``;
- an overload pass: offered load ≳3× capacity with admission control
  armed (``serving.admission``) — p99 of ACCEPTED requests stays
  bounded while load sheds (``overload_fast_p99_ms``,
  ``overload_shed_frac``, ``admission_transitions``), vs the
  admissionless exact baseline saturating (``overload_exact_p99_ms``);
- a rollout canary pass (``obs.budget``): a deliberately poisoned
  catalog version (row-shuffled item factors) served next to the
  healthy incumbent — per-version cohort rows, the service-level
  ``slo_burn_rate_fast`` / ``slo_burn_rate_slow`` pair, and
  ``verdict_latency_batches`` (canary batches until the verdict
  engine returns ROLLBACK on the poisoned leg).

Arrivals are open-loop (scheduled independently of completions — the
only shape that exposes saturation); the *control* loop is closed: the
engine's SLO tracker feeds the admission ladder which feeds back into
batching/degrade/shed decisions.

Contract (both modes): the LAST stdout line is one JSON object
``{"metric", "value", "unit", "vs_baseline", "extra"}``; stderr is
flushed before that line is printed, so ``2>&1``-merged wrappers always
parse it (the bench.py/pallas_probe/pod_dryrun hardening). Traffic-mode
rounds are committed as ``SERVING_r*.json`` and gated by
``scripts/bench_regress.py --family serving``.

Env knobs (micro): SERVE_USERS, SERVE_ITEMS, SERVE_RANK,
SERVE_REQUESTS, SERVE_REQ_MAX, SERVE_K, SERVE_MAX_BATCH, SERVE_DEVICES,
SERVE_FORCE_CPU (=0 to use the default jax backend).
Traffic adds: SERVE_CENTERS, SERVE_CLUSTERS (0 = flat int8 stage 1),
SERVE_PROBE, SERVE_OVERFETCH, SERVE_PATTERN, SERVE_LEVELS (offered-QPS
multipliers of measured capacity), SERVE_SLO_MS, SERVE_DEADLINE_MS,
SERVE_TRAFFIC_REQUESTS, SERVE_RECALL_SAMPLE.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit_final(result: dict) -> None:
    """The machine-readable emit contract: flush stderr BEFORE printing
    the final JSON line, so a 2>&1-merged capture can always parse the
    last line (the same hardening bench.py / pallas_probe / pod_dryrun
    carry — an unflushed stderr write landing after the summary once
    cost a round its parsed result)."""
    sys.stderr.flush()
    print(json.dumps(result), flush=True)


def build_model(num_users: int, num_items: int, rank: int, seed: int = 0):
    """A seeded random-factor MFModel with identity id maps — serving
    cost does not depend on how the factors were fit."""
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, rank)).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=(num_items, rank)).astype(np.float32)),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)),
    )


def run(num_users=20_000, num_items=8_192, rank=64, n_requests=400,
        req_max=64, k=10, max_batch=1024, n_dev=None, seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu.models.mf import MFModel  # noqa: F401
    from large_scale_recommendation_tpu.parallel.mesh import make_block_mesh
    from large_scale_recommendation_tpu.parallel.serving import (
        mesh_top_k_recommend,
        shard_catalog,
    )
    from large_scale_recommendation_tpu.serving.engine import ServingEngine

    model = build_model(num_users, num_items, rank, seed)
    mesh = make_block_mesh(n_dev)
    rng = np.random.default_rng(seed + 1)
    requests = [
        rng.integers(0, num_users, int(sz)).astype(np.int64)
        for sz in rng.integers(1, req_max + 1, n_requests)
    ]
    total_rows = sum(len(r) for r in requests)
    extra = {
        "device": str(jax.devices()[0]), "mesh_devices": len(mesh.devices),
        "catalog_rows": num_items, "num_users": num_users, "rank": rank,
        "requests": n_requests, "request_rows": total_rows,
        "req_size_max": req_max, "k": k, "max_batch": max_batch,
    }

    # ---- engine path FIRST: its executable-variant count must be its
    # own (the per-call baseline shares the per-mesh step cache, so
    # running it first would misattribute baseline compiles to the
    # engine) — and any shape the engine leaves warm only HELPS the
    # baseline below, keeping the reported speedup conservative
    engine = ServingEngine(model, k=k, mesh=mesh, max_batch=max_batch)
    engine.serve(requests[:4])  # warm the bucket family's hot entries
    # the published micro-batch/bucket evidence must describe the TIMED
    # run only — clear the warm-up's counters
    engine.stats.update(requests=0, rows=0, microbatches=0, buckets={})
    t0 = time.perf_counter()
    engine.serve(requests)
    engine_wall = time.perf_counter() - t0
    extra["engine_users_per_s"] = round(total_rows / engine_wall, 1)
    extra["engine_wall_s"] = round(engine_wall, 3)
    extra["engine_executable_variants"] = engine.executable_variants
    extra["engine_bucket_family_size"] = len(engine.bucket_family)
    extra["engine_microbatches"] = engine.stats["microbatches"]
    extra["engine_bucket_histogram"] = {
        str(b): c for b, c in sorted(engine.stats["buckets"].items())}

    # ---- per-call path: one mesh_top_k_recommend per request ----------
    # over a PREBUILT catalog and a device-RESIDENT U (what
    # model.recommend(mesh=...) holds), with every request-size bucket
    # pre-warmed — the strongest per-call baseline: its remaining cost
    # is per-request dispatch + undersized kernel calls, which is
    # exactly the overhead the engine claims to remove
    import jax.numpy as jnp

    catalog = shard_catalog(np.asarray(model.V), mesh)
    U = jnp.asarray(model.U)
    from large_scale_recommendation_tpu.utils.shapes import pow2_pad

    warm_sizes = sorted({min(pow2_pad(len(r)), 2048) for r in requests})
    for ws in warm_sizes:
        mesh_top_k_recommend(U, None, np.zeros(ws, np.int64), k=k,
                             catalog=catalog)
    t0 = time.perf_counter()
    for r in requests:
        mesh_top_k_recommend(U, None, r, k=k, catalog=catalog)
    percall_wall = time.perf_counter() - t0
    extra["percall_users_per_s"] = round(total_rows / percall_wall, 1)
    extra["percall_wall_s"] = round(percall_wall, 3)

    # ---- bf16 catalog rides along -------------------------------------
    bf16 = ServingEngine(model, k=k, mesh=mesh, max_batch=max_batch,
                         dtype="bfloat16")
    bf16.serve(requests[:4])
    t0 = time.perf_counter()
    bf16.serve(requests)
    extra["engine_bf16_users_per_s"] = round(
        total_rows / (time.perf_counter() - t0), 1)

    # ---- observability overhead: the SAME engine loop with the obs
    # layer live (registry + tracer, per-bucket histograms, spans) vs
    # the disabled run above — the acceptance pin is ≤3% regression,
    # and the disabled run costs nothing by construction (null layer)
    if os.environ.get("SERVE_OBS", "1") == "1":
        # Methodology matters more than the instrumentation here: (a) the
        # timed engine run above may still pay bucket-family compiles its
        # short warm-up missed, and the obs engine would inherit those
        # shapes warm (per-mesh step cache) — a serial comparison against
        # it misreads compile savings as negative overhead; (b) serial
        # passes also conflate machine drift with overhead (measured:
        # ±20% drift between identical disabled passes vs ~2% true
        # overhead). So: one obs-enabled engine, both fully warmed, then
        # INTERLEAVED timed passes, min-of-reps per side.
        from large_scale_recommendation_tpu import obs
        from large_scale_recommendation_tpu.obs.registry import (
            get_registry,
            set_registry,
        )
        from large_scale_recommendation_tpu.obs.trace import (
            get_tracer,
            set_tracer,
        )

        # save/restore whatever obs layer the CALLER had installed:
        # bench.py drives run() in-process, and clobbering a live
        # registry with the null layer would silently eat every metric
        # recorded after this section
        prev_reg, prev_tracer = get_registry(), get_tracer()
        reg, _tracer = obs.enable()
        try:
            oeng = ServingEngine(model, k=k, mesh=mesh,
                                 max_batch=max_batch)
            oeng.serve(requests)  # warm (all buckets, same shapes)
            engine.serve(requests)
            off_walls, on_walls = [], []
            for _ in range(int(os.environ.get("SERVE_OBS_REPS", 3))):
                t0 = time.perf_counter()
                engine.serve(requests)
                off_walls.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                oeng.serve(requests)
                on_walls.append(time.perf_counter() - t0)
            warm_wall, obs_wall = min(off_walls), min(on_walls)
            extra["engine_warm_users_per_s"] = round(
                total_rows / warm_wall, 1)
            extra["engine_obs_users_per_s"] = round(
                total_rows / obs_wall, 1)
            extra["obs_overhead_pct"] = round(
                100.0 * (obs_wall - warm_wall) / warm_wall, 2)
            extra["obs_metric_names"] = len(reg.names())
        finally:
            set_registry(prev_reg)
            set_tracer(prev_tracer)

    speedup = percall_wall / engine_wall
    return {
        "metric": (f"sustained serving users/s (engine vs per-call mesh "
                   f"path, {num_users}x{num_items} rank={rank}, "
                   f"{n_requests} requests ≤{req_max} users)"),
        "value": extra["engine_users_per_s"],
        "unit": "users/s",
        "vs_baseline": round(speedup, 2),
        "extra": extra,
    }


# --------------------------------------------------------------------------
# Traffic simulator (SERVE_MODE=traffic)
# --------------------------------------------------------------------------


def build_structured_model(num_users: int, num_items: int, rank: int,
                           n_centers: int = 256, spread: float = 2.0,
                           noise: float = 0.3, seed: int = 0):
    """A catalog with planted cluster structure: items drawn around
    ``n_centers`` Gaussian centers (the shape real embedding catalogs
    have — and the regime clustered MIPS routing exists for; the flat
    int8 path doesn't care). Queries stay isotropic Gaussian."""
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_centers, rank)) * spread
    V = (centers[rng.integers(0, n_centers, num_items)]
         + noise * rng.normal(size=(num_items, rank))).astype(np.float32)
    U = rng.normal(size=(num_users, rank)).astype(np.float32)
    return MFModel(
        U=jnp.asarray(U), V=jnp.asarray(V),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)))


def make_arrivals(pattern: str, n: int, qps: float,
                  rng: np.random.Generator) -> np.ndarray:
    """Arrival offsets (seconds, sorted) for ``n`` requests at mean
    rate ``qps``: ``poisson`` (exponential gaps), ``diurnal`` (one
    compressed sinusoidal day — rate swings ±80% around the mean),
    ``bursty`` (alternating 4× on-bursts and 0.25× lulls)."""
    if pattern == "poisson":
        gaps = rng.exponential(1.0 / qps, n)
    elif pattern == "diurnal":
        # inhomogeneous Poisson by gap scaling: rate(t) tracks one
        # sine period over the stream
        gaps = np.empty(n)
        t = 0.0
        period = n / qps
        for i in range(n):
            rate = qps * (1.0 + 0.8 * np.sin(2 * np.pi * t / period))
            rate = max(rate, 0.05 * qps)
            gaps[i] = rng.exponential(1.0 / rate)
            t += gaps[i]
    elif pattern == "bursty":
        burst = int(max(8, n // 8))
        gaps = np.empty(n)
        for i in range(n):
            on = (i // burst) % 2 == 0
            gaps[i] = rng.exponential(1.0 / (qps * (4.0 if on else 0.25)))
    else:
        raise ValueError(f"unknown arrival pattern {pattern!r}")
    return np.cumsum(gaps)


def run_traffic_level(engine, requests, arrivals, deadline_s: float,
                      slo_ms: float) -> dict:
    """Drive one offered-load level through the engine: submit each
    request at its arrival offset, flush when the coalescing window
    fills (``max_batch`` rows, admission-widened) or the oldest pending
    ticket hits the batching deadline, measure per-request latency
    (completion − scheduled arrival: a backlogged engine pays its queue
    honestly). Returns the level's latency/QPS/shed/degraded stats."""
    from large_scale_recommendation_tpu.serving import (
        AdmissionRejectedError,
    )

    n = len(requests)
    lat = np.full(n, np.nan)
    shed = np.zeros(n, bool)
    degraded = np.zeros(n, bool)
    pending: list[tuple[int, float]] = []  # (request idx, arrival)
    pending_rows = 0
    t0 = time.perf_counter()
    i = 0

    def flush_pending():
        nonlocal pending, pending_rows
        results = engine.flush()
        done = time.perf_counter() - t0
        for (idx, arr), res in zip(pending, results):
            lat[idx] = done - arr
            degraded[idx] = getattr(res, "degraded", False)
        pending = []
        pending_rows = 0

    while i < n or pending:
        now = time.perf_counter() - t0
        while i < n and arrivals[i] <= now:
            try:
                engine.submit(requests[i])
                pending.append((i, arrivals[i]))
                pending_rows += len(requests[i])
            except AdmissionRejectedError:
                shed[i] = True
            i += 1
        widen = 1.0
        if engine.admission is not None:
            widen = engine.admission.widen_factor
        limit = int(engine.max_batch * widen)
        oldest = pending[0][1] if pending else None
        if pending and (pending_rows >= limit
                        or now - oldest >= deadline_s * widen
                        or i >= n):
            flush_pending()
            continue
        # idle until the next edge: an arrival or the deadline
        next_t = arrivals[i] if i < n else np.inf
        if oldest is not None:
            next_t = min(next_t, oldest + deadline_s * widen)
        sleep = min(max(next_t - (time.perf_counter() - t0), 0.0), 0.01)
        if sleep > 0:
            time.sleep(sleep)

    wall = time.perf_counter() - t0
    served = lat[~np.isnan(lat)]
    out = {
        "offered_qps": round(float(len(requests) / arrivals[-1]), 2),
        "achieved_qps": round(float(len(served) / wall), 2),
        "served": int(len(served)),
        "shed": int(shed.sum()),
        "shed_frac": round(float(shed.mean()), 4),
        "degraded_frac": round(float(degraded.mean()), 4),
        "p50_ms": (round(float(np.percentile(served, 50) * 1e3), 2)
                   if len(served) else None),
        "p99_ms": (round(float(np.percentile(served, 99) * 1e3), 2)
                   if len(served) else None),
        "met_slo": (bool(np.percentile(served, 99) * 1e3 <= slo_ms)
                    if len(served) else False),
    }
    return out


def run_traffic(num_users=20_000, num_items=262_144, rank=64,
                n_requests=400, req_max=32, k=10, max_batch=1024,
                n_centers=256, n_clusters=512, n_probe=16, overfetch=4,
                kmeans_sample=65536, pattern="poisson",
                levels=(0.02, 0.05, 0.1, 0.25, 0.5, 1.0), slo_ms=200.0,
                deadline_ms=25.0, recall_sample=256,
                overload_mult=3.0, seed=0) -> dict:
    import jax

    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.obs import health
    from large_scale_recommendation_tpu.serving import (
        AdmissionConfig,
        AdmissionController,
        RetrievalConfig,
        ServingEngine,
        recall_at_k,
    )

    # the rollout budget plane must exist BEFORE the engines are built
    # (each engine binds its handle at construction): every traffic
    # pass below is then attributed to the catalog version that served
    # it, and the canary pass at the end exercises the verdict engine
    budget = obs.enable_budget(
        slo_ms / 1e3, objective=0.9, fast_window=32, slow_window=256,
        min_samples=8, sample_budget=64)
    # the request plane rides the same lifecycle (ISSUE 20): engines
    # bind the handle at construction, so it too must exist first —
    # every flush below then carries a stage ledger and the sustained
    # pass's tail lands in the exemplar reservoir
    telemetry = obs.enable_requests(
        slo_ms / 1e3, objective=0.9, window=512, max_exemplars=64,
        slow_keep=16)

    model = build_structured_model(num_users, num_items, rank,
                                   n_centers=n_centers, seed=seed)
    rng = np.random.default_rng(seed + 1)
    requests = [rng.integers(0, num_users, int(sz)).astype(np.int64)
                for sz in rng.integers(1, req_max + 1, n_requests)]
    total_rows = sum(len(r) for r in requests)
    retrieval = RetrievalConfig(
        overfetch=overfetch,
        n_clusters=(n_clusters if n_clusters > 0 else None),
        n_probe=n_probe, kmeans_sample=kmeans_sample, seed=seed)
    t0 = time.perf_counter()
    fast = ServingEngine(model, k=k, retrieval=retrieval,
                         max_batch=max_batch)
    build_s = time.perf_counter() - t0
    exact = ServingEngine(model, k=k, max_batch=max_batch)
    extra = {
        "device": str(jax.devices()[0]), "catalog_rows": num_items,
        "num_users": num_users, "rank": rank, "k": k,
        "requests": n_requests, "request_rows": total_rows,
        "req_size_max": req_max, "max_batch": max_batch,
        "pattern": pattern, "slo_ms": slo_ms, "deadline_ms": deadline_ms,
        "catalog_build_s": round(build_s, 2),
        "index": dict(fast.retriever.catalog.stats),
    }

    # ---- saturation throughput, same bucket warmup both engines ------
    # best-of-reps per side: one descheduled slice on a shared 2-core
    # box can halve a single pass's rate (measured), and the ratio is
    # the acceptance bar — noise must not decide it
    warm = requests[:4]
    reps = int(os.environ.get("SERVE_SAT_REPS", 2))
    rates = {}
    for eng, name in ((fast, "fast"), (exact, "exact")):
        eng.serve(warm)
        best = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            eng.serve(requests)
            best = max(best, total_rows / (time.perf_counter() - t0))
        rates[name] = best
        extra[f"{name}_users_per_s"] = round(best, 1)
    extra["fast_vs_exact"] = round(rates["fast"] / rates["exact"], 2)

    # ---- recall of the fast path against the exact answers -----------
    sample = rng.integers(0, num_users, recall_sample).astype(np.int64)
    ie, _ = exact.recommend(sample)
    ia, _ = fast.recommend(sample)
    extra["recall_at_10" if k == 10 else f"recall_at_{k}"] = round(
        recall_at_k(ia, ie), 4)

    # ---- warm the WHOLE bucket family, both stages -------------------
    # the curve flushes small deadline-bounded batches (buckets 8..256)
    # the saturation pass above never compiled, and the degrade level
    # additionally compiles stage-1-only variants: without this warmup
    # the low-QPS levels' p99 is XLA compile time, not serving latency
    import jax.numpy as jnp

    empty_excl = (np.zeros(8, np.int32), np.zeros(8, np.int32),
                  np.full(8, np.inf, np.float32))
    bucket = 8
    while bucket <= min(max_batch, fast.retriever.config.max_bucket):
        for stage1_only in (False, True):
            fast.retriever.topk(
                jnp.zeros((bucket, rank), jnp.float32), empty_excl,
                k=k, stage1_only=stage1_only)
        exact.recommend(np.zeros(bucket, np.int64))
        bucket <<= 1

    # ---- p99-vs-offered-QPS curve (admission armed) ------------------
    # capacity in requests/s: saturation users/s over mean request size.
    # NOTE the two operating modes: saturation throughput comes from
    # max_batch-deep coalescing, while the curve's deadline-bounded
    # flushes serve SMALL buckets whose per-row cost is far higher —
    # the latency knee sits well below multiplier 1.0, which is exactly
    # what the low rungs of the ladder exist to bracket.
    cap_qps = rates["fast"] / (total_rows / n_requests)
    slo = health.SLOTracker(target_s=slo_ms / 1e3, objective=0.9,
                            window=64)
    fast.attach_admission(AdmissionController(slo, AdmissionConfig()))
    curve = []
    for mult in levels:
        qps = cap_qps * mult
        # bound each level's wall: low rungs don't need the full
        # request stream to measure a stable p99
        n_lv = int(min(n_requests, max(60, qps * 20)))
        arr = make_arrivals(pattern, n_lv, qps, rng)
        level = run_traffic_level(fast, requests[:n_lv], arr,
                                  deadline_s=deadline_ms / 1e3,
                                  slo_ms=slo_ms)
        level["level"] = mult
        curve.append(level)
    extra["curve"] = curve
    met = [lv for lv in curve if lv["met_slo"]]
    extra["qps_at_slo"] = max((lv["achieved_qps"] for lv in met),
                              default=0.0)
    one_x = min(curve, key=lambda lv: abs(lv["level"] - 1.0))
    extra["p99_ms"] = one_x["p99_ms"]
    extra["p50_ms"] = one_x["p50_ms"]

    # ---- overload: admission sheds/degrades, p99 stays bounded -------
    qps = cap_qps * overload_mult
    arr = make_arrivals(pattern, n_requests, qps, rng)
    over = run_traffic_level(fast, requests, arr,
                             deadline_s=deadline_ms / 1e3, slo_ms=slo_ms)
    snap = fast.admission.snapshot()
    extra["overload_fast_p99_ms"] = over["p99_ms"]
    extra["overload_shed_frac"] = over["shed_frac"]
    extra["overload_degraded_frac"] = over["degraded_frac"]
    extra["admission_transitions"] = snap["transitions"]
    extra["admission_final_level"] = snap["level"]
    # the exact engine, admissionless, under the SAME offered load:
    # nothing sheds, the queue eats the backlog, p99 saturates
    over_exact = run_traffic_level(exact, requests, arr,
                                   deadline_s=deadline_ms / 1e3,
                                   slo_ms=slo_ms)
    extra["overload_exact_p99_ms"] = over_exact["p99_ms"]

    # ---- request-plane stamp: where the sustained pass's time went ---
    # per-stage medians/p99s over the plane's window (the curve +
    # overload passes fed it) plus the exemplar-reservoir census; the
    # full /slowz body optionally dumps for CI artifacts. Stamped keys
    # match the bench_regress DEFAULT_LOWER patterns ("request_stage",
    # "queue_wait") — watched via explicit --key only.
    req_snap = telemetry.snapshot()
    for stage, q in telemetry.stage_quantiles().items():
        # queue_wait stamps under its own name (its regress pattern)
        key = "queue_wait" if stage == "queue_wait" \
            else f"request_stage_{stage}"
        extra[f"{key}_s_p50"] = round(q["p50"], 6)
        extra[f"{key}_s_p99"] = round(q["p99"], 6)
    extra["request_dominant_stage"] = req_snap["dominant_stage"]
    extra["request_exemplars_kept"] = req_snap["kept"]
    extra["request_noted"] = req_snap["count"]
    extra["request_shed_noted"] = req_snap["shed"]
    slowz_out = os.environ.get("SERVING_SLOWZ_OUT")
    if slowz_out:
        with open(slowz_out, "w") as f:
            json.dump(req_snap, f, indent=1)

    # ---- rollout canary: poisoned catalog version, verdict latency ---
    # The canary serves a deliberately poisoned catalog (item factors
    # row-shuffled: identical latency, garbage answers) against the
    # healthy exact incumbent. Shadow recall of the canary against the
    # incumbent's answers feeds the budget plane as the shared eval
    # key, the verdict engine attributes the regression to the
    # canary's catalog version, and the verdict latency is the number
    # of canary batches until ROLLBACK.
    from large_scale_recommendation_tpu.models.mf import MFModel

    traffic_snap = budget.snapshot()
    extra["rollout_traffic_cohorts"] = traffic_snap["cohorts"]
    # service-level multi-window burn pair from the traffic phase (the
    # overload pass is what moves it); the canary pass below resets
    extra["slo_burn_rate_fast"] = round(
        traffic_snap["burn_rates"].get("fast", 0.0), 4)
    extra["slo_burn_rate_slow"] = round(
        traffic_snap["burn_rates"].get("slow", 0.0), 4)
    budget.reset()
    poisoned = MFModel(U=model.U,
                       V=model.V[rng.permutation(num_items)],
                       users=model.users, items=model.items)
    canary = ServingEngine(poisoned, k=k, max_batch=max_batch)
    inc_ver, can_ver = exact.version, canary.version
    verdict_batches = None
    last = None
    for b in range(1, 17):
        reqs = [rng.integers(0, num_users, 8).astype(np.int64)
                for _ in range(4)]
        inc_res = exact.serve(reqs)
        can_res = canary.serve(reqs)
        shadow = float(np.mean([recall_at_k(c[0], i[0])
                                for c, i in zip(can_res, inc_res)]))
        budget.note_eval(inc_ver, {"shadow_recall": 1.0})
        budget.note_eval(can_ver, {"shadow_recall": shadow})
        last = budget.verdicts.evaluate(can_ver, inc_ver)
        if last["verdict"] == "ROLLBACK":
            verdict_batches = b
            break
    if verdict_batches is not None:
        budget.verdicts.mark_rolled_back(can_ver)
    snap = budget.snapshot()
    extra["verdict_latency_batches"] = verdict_batches
    extra["rollout"] = {
        "incumbent_version": inc_ver,
        "canary_version": can_ver,
        "burn_rates": snap["burn_rates"],
        "cohorts": snap["cohorts"],
        "verdict": None if last is None else last["verdict"],
        "verdict_reason": None if last is None else last["reason"],
        "verdict_latency_batches": verdict_batches,
    }

    return {
        "metric": (f"two-stage quantized serving users/s vs exact "
                   f"full-catalog ({num_users}x{num_items} rank={rank}, "
                   f"{pattern} traffic, "
                   f"{'clustered' if n_clusters > 0 else 'flat'} "
                   f"stage 1)"),
        "value": extra["fast_users_per_s"],
        "unit": "users/s",
        "vs_baseline": extra["fast_vs_exact"],
        "extra": extra,
    }


def main() -> None:
    if os.environ.get("SERVE_FORCE_CPU", "1") == "1":
        from large_scale_recommendation_tpu.utils.platform import force_cpu

        force_cpu(n_devices=int(os.environ.get("SERVE_DEVICES", 8)))
    env = os.environ.get
    if env("SERVE_MODE", "micro") == "traffic":
        result = run_traffic(
            num_users=int(env("SERVE_USERS", 20_000)),
            num_items=int(env("SERVE_ITEMS", 262_144)),
            rank=int(env("SERVE_RANK", 64)),
            n_requests=int(env("SERVE_TRAFFIC_REQUESTS", 400)),
            req_max=int(env("SERVE_REQ_MAX", 32)),
            k=int(env("SERVE_K", 10)),
            max_batch=int(env("SERVE_MAX_BATCH", 1024)),
            n_centers=int(env("SERVE_CENTERS", 256)),
            n_clusters=int(env("SERVE_CLUSTERS", 512)),
            n_probe=int(env("SERVE_PROBE", 16)),
            overfetch=int(env("SERVE_OVERFETCH", 4)),
            kmeans_sample=int(env("SERVE_KMEANS_SAMPLE", 65536)),
            pattern=env("SERVE_PATTERN", "poisson"),
            levels=tuple(float(x) for x in
                         env("SERVE_LEVELS", "0.02,0.05,0.1,0.25,0.5,1").split(",")),
            slo_ms=float(env("SERVE_SLO_MS", 200)),
            deadline_ms=float(env("SERVE_DEADLINE_MS", 25)),
            recall_sample=int(env("SERVE_RECALL_SAMPLE", 256)),
            overload_mult=float(env("SERVE_OVERLOAD_MULT", 3.0)),
        )
    else:
        result = run(
            num_users=int(env("SERVE_USERS", 20_000)),
            num_items=int(env("SERVE_ITEMS", 8_192)),
            rank=int(env("SERVE_RANK", 64)),
            n_requests=int(env("SERVE_REQUESTS", 400)),
            req_max=int(env("SERVE_REQ_MAX", 64)),
            k=int(env("SERVE_K", 10)),
            max_batch=int(env("SERVE_MAX_BATCH", 1024)),
        )
    _emit_final(result)


if __name__ == "__main__":
    sys.exit(main())
