"""Chip-free Mosaic lowering verdicts: AOT-compile the Pallas DSGD kernel
against a real TPU topology.

The round-4 kernel was validated only in interpreter mode; interpret mode
validates semantics, not lowerability (VERDICT r4 "what's weak" #2). This
script retires that risk WITHOUT a live chip: ``libtpu`` is installed, and
Mosaic compilation happens inside the XLA:TPU compiler at ``.compile()``
time, so a compile-only PJRT client reached through
``jax.experimental.topologies.get_topology_desc`` runs the REAL lowering
pipeline — BlockSpec legalization, Mosaic vectorization, VMEM allocation —
with no device attached.

Usage:  python scripts/pallas_aot.py [topology]   (default v5e:2x2)

Prints one JSON line per (kernel, config, gather):
``{"kernel": ..., "config": ..., "gather": ..., "topology": ...,
"ok": bool, "detail": ...}`` with the verbatim Mosaic error on failure,
and writes the full list to ``docs/MOSAIC_AOT.json`` (default topology
only — exploratory topologies get a ``docs/MOSAIC_AOT.<topology>.json``
suffix so the committed v5e verdicts that PERF.md cites are never
clobbered). Exit code 0 iff every production (gather="loop") variant
compiled; "take" failures are recorded verdicts, not regressions.
Narrative in docs/PERF.md ("Mosaic lowering verdicts").
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.utils.platform import force_cpu  # noqa: E402

jax = force_cpu()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: E402


def tpu_sharding(topology_name: str):
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name)
    mesh = Mesh(np.array(topo.devices[:1]).reshape(1), ("d",))
    return NamedSharding(mesh, PartitionSpec())


def compile_block_sweep(s, *, rank, mb, rpb_u, rpb_v, nnz, gather,
                        dtype=jnp.float32):
    """AOT-compile one pallas_block_sweep variant; returns (ok, detail)."""
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        pallas_block_sweep,
    )

    e = nnz - nnz % mb

    def make(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt, sharding=s)

    args = (
        make((rpb_u, rank), dtype), make((rpb_v, rank), dtype),
        make((e,), jnp.int32), make((e,), jnp.int32),
        make((e,), jnp.float32), make((e,), jnp.float32),
        make((e,), jnp.float32), make((e,), jnp.float32),
        make((rpb_u,), jnp.float32), make((rpb_v,), jnp.float32),
    )
    f = jax.jit(lambda *a: pallas_block_sweep(
        *a, lr=0.1, lam=0.1, minibatch=mb, gather=gather))
    try:
        f.lower(*args).compile()
        return True, "compiled"
    except Exception as ex:  # noqa: BLE001 — the error text IS the result
        return False, f"{type(ex).__name__}: {str(ex)[:400]}"


def compile_stratum_sweep(s, *, rank, mb, rpb_u, rpb_v, nnz, k,
                          dtype=jnp.float32):
    """AOT-compile the double-buffered stratum kernel (ISSUE 6): one
    pallas_call per stratum, pipeline-fetched slice/stream/index blocks."""
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        pallas_stratum_sweep,
    )

    e = nnz - nnz % mb
    n_mb = e // mb
    rows6 = -(-6 * n_mb // 8) * 8  # stream sublanes, f32-tile padded

    def make(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt, sharding=s)

    args = (
        make((k * rpb_u, rank), dtype), make((k * rpb_v, rank), dtype),
        make((k * k, 2, e), jnp.int32),
        make((k * k, rows6, mb), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.int32, sharding=s),
    )
    f = jax.jit(lambda *a: pallas_stratum_sweep(
        *a, lr=0.1, lam=0.1, minibatch=mb, num_blocks=k))
    try:
        f.lower(*args).compile()
        return True, "compiled"
    except Exception as ex:  # noqa: BLE001
        return False, f"{type(ex).__name__}: {str(ex)[:400]}"


def compile_full_training(s, *, rank, mb, rpb_u, rpb_v, k, gather,
                          pipeline=False, dtype=jnp.float32):
    """AOT-compile dsgd_train_pallas (the lax.scan-of-pallas_call loop)."""
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    b = mb  # one minibatch per block visit is enough to exercise lowering

    def make(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt, sharding=s)

    args = (
        make((k * rpb_u, rank), dtype),
        make((k * rpb_v, rank), dtype),
        make((k, k, b), jnp.int32), make((k, k, b), jnp.int32),
        make((k, k, b), jnp.float32), make((k, k, b), jnp.float32),
        make((k * rpb_u,), jnp.float32), make((k * rpb_v,), jnp.float32),
        make((k, k, b), jnp.float32), make((k, k, b), jnp.float32),
    )
    f = jax.jit(lambda *a: dsgd_train_pallas(
        *a, lr=0.1, lam=0.1, minibatch=mb, num_blocks=k, iterations=1,
        gather=gather, pipeline=pipeline))
    try:
        f.lower(*args).compile()
        return True, "compiled"
    except Exception as ex:  # noqa: BLE001
        return False, f"{type(ex).__name__}: {str(ex)[:400]}"


# (config label, kwargs) — the north-star block shape at k=16 (ML-25M
# geometry: 162541/16=10160 user rows, 59047/16=3696 item rows per block,
# 25M/256 visits ≈ 92K nnz per block visit), the k=32 halving, and the
# rank-64 twin (k=16: the k=8 rank-64 shape is SMEM-infeasible — two full
# 184K-entry index copies need 1.5 MB of v5e's 1.0 MB scoped SMEM; the
# wrapper's budget check now rejects it up front).
BLOCK_CONFIGS = [
    ("k16_rank128_mb2048",
     dict(rank=128, mb=2048, rpb_u=10160, rpb_v=3696, nnz=92160)),
    ("k32_rank128_mb2048",
     dict(rank=128, mb=2048, rpb_u=5080, rpb_v=1848, nnz=46080)),
    ("k16_rank64_mb2048",
     dict(rank=64, mb=2048, rpb_u=10160, rpb_v=3696, nnz=92160)),
    ("k32_rank128_mb2048_bf16",
     dict(rank=128, mb=2048, rpb_u=5080, rpb_v=1848, nnz=46080,
          dtype=jnp.bfloat16)),
]

# ISSUE 6 double-buffered stratum kernel at the ML-25M operating points
# the pipelined budget admits (manual two-slot DMA buffering: 2 slice
# pairs + 2 stream blocks + the bf16-only f32 work pair — see
# ops.pallas_sgd.stratum_pipeline_budget): k ≥ 32 at rank 128 / mb 2048
# for BOTH dtypes. nnz is the PER-VISIT entry count (NNZ/k²).
STRATUM_CONFIGS = [
    # rpb values are the TILE-ALIGNED table heights dsgd_train_pallas
    # pads to (8-row f32 / 16-row bf16 sublane tiles — the kernel's DMA
    # endpoints must match the VMEM slot memref exactly). Operating
    # points per the calibrated stratum_pipeline_budget: ML-25M k=32
    # needs mb ≤ 1024; k=64 admits mb 2048 in both dtypes. The k=32
    # mb=2048 point is the recorded VMEM-stack negative that calibrated
    # the budget's temporaries term (kept here so a Mosaic that learns
    # to fit it shows up as a flipped verdict, not silence).
    ("k32_rank128_mb1024_f32",
     dict(rank=128, mb=1024, rpb_u=5080, rpb_v=1848, nnz=24576, k=32)),
    ("k64_rank128_mb2048_f32",
     dict(rank=128, mb=2048, rpb_u=2544, rpb_v=928, nnz=6144, k=64)),
    ("k64_rank128_mb2048_bf16",
     dict(rank=128, mb=2048, rpb_u=2544, rpb_v=928, nnz=6144, k=64,
          dtype=jnp.bfloat16)),
    ("k32_rank128_mb2048_f32",
     dict(rank=128, mb=2048, rpb_u=5080, rpb_v=1848, nnz=24576, k=32)),
]


def compile_mesh_step(topology_name, *, rank, mb, rpb_u, rpb_v, k):
    """AOT-compile the MESH route: shard_map + ppermute rotation with
    per-device block sweeps through the Pallas kernel (kernel='pallas' on
    MeshDSGDConfig), over all devices of the topology."""
    from large_scale_recommendation_tpu.core.updaters import (
        RegularizedSGDUpdater,
        inverse_sqrt_lr,
    )
    from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
        build_mesh_dsgd_step,
    )
    from large_scale_recommendation_tpu.parallel.mesh import BLOCK_AXIS

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name)
    devs = np.array(topo.devices[:k])
    mesh = Mesh(devs, (BLOCK_AXIS,))
    shard = NamedSharding(mesh, PartitionSpec(BLOCK_AXIS))
    repl = NamedSharding(mesh, PartitionSpec())
    upd = RegularizedSGDUpdater(learning_rate=0.05, lambda_=0.1,
                                schedule=inverse_sqrt_lr)
    step = build_mesh_dsgd_step(mesh, upd, mb, k, 1, "mean", True,
                                "pallas", False)

    def sh(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32, sharding=shard)

    def shi(shape):
        return jax.ShapeDtypeStruct(shape, jnp.int32, sharding=shard)

    b = mb  # one minibatch per block visit exercises the whole lowering
    args = (sh((k * rpb_u, rank)), sh((k * rpb_v, rank)),
            shi((k, k, b)), shi((k, k, b)),
            sh((k, k, b)), sh((k, k, b)),
            sh((k * rpb_u,)), sh((k * rpb_v,)),
            sh((k, k, b)), sh((k, k, b)),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=repl))
    try:
        step.lower(*args).compile()
        return True, "compiled"
    except Exception as ex:  # noqa: BLE001
        return False, f"{type(ex).__name__}: {str(ex)[:400]}"


def main() -> int:
    topology_name = sys.argv[1] if len(sys.argv) > 1 else "v5e:2x2"
    try:
        s = tpu_sharding(topology_name)
    except Exception as ex:  # noqa: BLE001 — no libtpu on this machine
        # CI runners without the TPU compiler stack skip CLEANLY (and
        # loudly) rather than false-failing the lowering gate; set
        # AOT_REQUIRE=1 where libtpu is known-present to forbid skipping
        msg = (f"SKIPPED: no chip-free TPU AOT support here "
               f"({type(ex).__name__}: {str(ex)[:200]})")
        print(json.dumps({"kernel": "ALL", "topology": topology_name,
                          "ok": None, "detail": msg}), flush=True)
        return 1 if os.environ.get("AOT_REQUIRE") == "1" else 0
    results = []
    for label, cfg in BLOCK_CONFIGS:
        for gather in ("take", "loop"):
            ok, detail = compile_block_sweep(s, gather=gather, **cfg)
            results.append({
                "kernel": "block_sweep", "config": label,
                "gather": gather, "topology": topology_name,
                "ok": ok, "detail": detail,
            })
            print(json.dumps(results[-1]), flush=True)
    for label, cfg in STRATUM_CONFIGS:
        ok, detail = compile_stratum_sweep(s, **cfg)
        results.append({
            "kernel": "stratum_sweep", "config": label,
            "gather": "loop", "topology": topology_name,
            "ok": ok, "detail": detail,
        })
        print(json.dumps(results[-1]), flush=True)
    for gather in ("take", "loop"):
        ok, detail = compile_full_training(
            s, rank=128, mb=2048, rpb_u=10160, rpb_v=3696, k=4,
            gather=gather)
        results.append({
            "kernel": "dsgd_train_pallas", "config": "k4_rank128_mb2048",
            "gather": gather, "topology": topology_name,
            "ok": ok, "detail": detail,
        })
        print(json.dumps(results[-1]), flush=True)
    # the pipelined full loop (auto-routes per budget; pipeline=True
    # forces the stratum kernel) at a geometry its budget admits
    ok, detail = compile_full_training(
        s, rank=128, mb=2048, rpb_u=2540, rpb_v=924, k=4,
        gather="loop", pipeline=True)
    results.append({
        "kernel": "dsgd_train_pallas[pipeline]",
        "config": "k4_rank128_mb2048_smallrows",
        "gather": "loop", "topology": topology_name,
        "ok": ok, "detail": detail,
    })
    print(json.dumps(results[-1]), flush=True)

    ok, detail = compile_mesh_step(
        topology_name, rank=128, mb=2048, rpb_u=10160, rpb_v=3696, k=4)
    results.append({
        "kernel": "mesh_dsgd_step[kernel=pallas]",
        "config": "k4_rank128_mb2048", "gather": "loop",
        "topology": topology_name, "ok": ok, "detail": detail,
    })
    print(json.dumps(results[-1]), flush=True)

    suffix = "" if topology_name == "v5e:2x2" else (
        "." + topology_name.replace(":", "_").replace("/", "_"))
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", f"MOSAIC_AOT{suffix}.json")
    with open(out, "w") as fh:
        json.dump(results, fh, indent=1)

    # gather="loop" is the production path: it must compile at every
    # production geometry. gather="take" failures are recorded verdicts,
    # not regressions (tpu.dynamic_gather cannot span vregs — see
    # ops/pallas_sgd.py), and so is k16_rank128 loop: this jax's
    # pipeline double-buffers the stream/SMEM operands, which pushed the
    # k=16 ML-25M geometry over budget for good — k≥32 is the
    # production operating point (docs/PERF.md "Double-buffering & bf16
    # factors").
    recorded_negatives = {"k16_rank128_mb2048", "k32_rank128_mb2048_f32"}
    return 1 if any(
        not r["ok"] for r in results
        if r["gather"] == "loop"
        and r["config"] not in recorded_negatives) else 0


if __name__ == "__main__":
    sys.exit(main())
