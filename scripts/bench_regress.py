"""Bench regression gate: diff the newest bench round against a baseline.

The bench rounds (``BENCH_r*.json``) are the repo's perf evidence, but
nothing *reads* them across rounds — a 30% serving regression would ship
silently as long as tier-1 stays green. This gate closes that gap::

    python scripts/bench_regress.py                   # newest vs previous
    python scripts/bench_regress.py --baseline BENCH_r03.json
    python scripts/bench_regress.py --key serving_users_per_s=10
    python scripts/bench_regress.py --report out.txt  # also write the table
    python scripts/bench_regress.py --family multichip  # pod_dryrun rounds
                                      # (MULTICHIP_r*.json: pad ratio and
                                      # layout lower-is-better, sharded
                                      # train/ALS throughput higher)
    python scripts/bench_regress.py --family serving  # traffic-sim rounds
                                      # (SERVING_r*.json: p99 latencies
                                      # lower-is-better; fast/exact
                                      # throughput, QPS-at-SLO and
                                      # recall@10 higher)
    python scripts/bench_regress.py --family quality  # model-quality keys
                                      # inside the BENCH rounds: implicit
                                      # ndcg/hr10/coverage + the eval_*
                                      # family higher-is-better,
                                      # eval_rmse lower (ISSUE 10)
    python scripts/bench_regress.py --family ingest   # parallel-ingest
                                      # rounds (INGEST_r*.json: rates and
                                      # scaling efficiency higher-is-
                                      # better; recovery wall + duplicate
                                      # window lower, ISSUE 13)

It loads both rounds, compares the watched keys (higher-is-better rates
by default; ``--lower`` flags wall-clock-style keys), prints a table,
and exits non-zero iff any watched key regressed past its percentage
threshold. Keys missing on either side are reported but only fail under
``--strict`` (machine/config drift between rounds routinely drops
extras). Rounds flagged as CPU-fallback runs (an ``error`` field in the
result) are compared anyway but the caveat is printed — cross-backend
comparisons are noise, and CI runs this step non-blocking for exactly
that reason.

File formats accepted, per side:

- a driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed``
  is used when present; otherwise numeric ``"key": value`` pairs are
  regex-salvaged from the (possibly front-truncated) ``tail``;
- a raw bench JSON line (``{"metric", "value", "unit", "extra": {...}}``);
- a flat ``{key: number}`` dict (hand-built baselines).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# watched keys → allowed regression (percent). Rates: higher is better.
# Thresholds are deliberately loose — rounds run on shared machines with
# real drift; the gate exists to catch step-function regressions, not
# 5% noise (tighten per-key via --key NAME=PCT).
DEFAULT_KEYS: dict[str, float] = {
    "value": 30.0,  # the headline metric line
    "e2e_ratings_per_s_incl_setup": 30.0,
    "serving_users_per_s": 30.0,
    "online_ratings_per_s": 30.0,
    "online_ratings_per_s_steady": 30.0,
    "ps_ratings_per_s": 30.0,
    "als_rank32_rows_per_s": 30.0,
    # achieved-bandwidth gate (ISSUE 6): the DSGD hot loop's whole perf
    # story is effective HBM throughput — a regression here is a kernel
    # regression even when ratings/s noise hides it
    "effective_hbm_gbs": 30.0,
    "pct_of_hbm_peak": 30.0,
    # compile-time gate (ISSUE 9): compile_wall_s is the headline
    # kernel's hand-bracketed warm-up, compile_count /
    # xla_compile_wall_s the introspection hook's whole-run totals —
    # LOWER is better (a bucket-family explosion or a cache miss shows
    # up here long before throughput noise admits it). compile_count is
    # near-deterministic for the same code path, so its threshold is
    # tight; walls ride shared machines, so loose.
    "compile_wall_s": 50.0,
    "xla_compile_wall_s": 50.0,
    "compile_count": 10.0,
}

# watched keys for the MULTICHIP_r*.json trajectory (the pod_dryrun
# acceptance harness, ISSUE 7): sharded-training throughput is
# higher-is-better like every rate; pad ratio and layout bytes are
# LOWER-is-better — a growing pad ratio is a blocking-layout regression
# even when throughput noise hides it. Thresholds are tight for the
# deterministic geometry keys (same code + seed ⇒ same layout) and
# loose for walls-derived rates (shared machines).
MULTICHIP_KEYS: dict[str, float] = {
    "train_ratings_per_s": 30.0,
    "als_rows_per_s": 30.0,
    "max_pad_ratio": 10.0,
    "layout_mb": 10.0,
}

# watched keys for the SERVING_r*.json trajectory (the serving_bench
# traffic-simulator rounds, ISSUE 8): fast-path/exact throughput, the
# fast-vs-exact ratio, QPS-at-SLO and recall are higher-is-better;
# p99 latencies are LOWER-is-better — a p99 blowup under the overload
# pass is an admission-control regression even when throughput noise
# hides it. Latency thresholds are loose (shared machines double tail
# latencies routinely); recall is tight (same code + seed ⇒ same
# index ⇒ same recall, drift means the retrieval math changed).
SERVING_KEYS: dict[str, float] = {
    "value": 30.0,  # fast-path users/s headline
    "fast_users_per_s": 30.0,
    "exact_users_per_s": 30.0,
    "fast_vs_exact": 30.0,
    "qps_at_slo": 30.0,
    "recall_at_10": 5.0,
    "p99_ms": 50.0,
    "overload_fast_p99_ms": 50.0,
}

# watched keys for the MODEL-QUALITY trajectory (ISSUE 10): the keys
# the BENCH rounds ACTUALLY carry — the implicit-ranking metrics
# (sampled-negative protocol, obs.quality.sampled_ranking_metrics —
# planted-structure-pinned) and the headline run's holdout rmse_final.
# Ranking metrics and coverage are higher-is-better; rmse is
# LOWER-is-better. The online evaluator's eval_* family is covered by
# the DIRECTION rules below (watch via --key when a quality-bearing
# round carries them), not listed here: a default watch key no round
# can contain is permanent "missing" noise and an unconditional
# --strict failure. Thresholds loose: ranking metrics on synthetic
# workloads carry sampling noise, and the gate exists to catch the
# ndcg-0.003-class collapse, not 5% drift.
QUALITY_KEYS: dict[str, float] = {
    "als_implicit_ndcg": 30.0,
    "als_implicit_hr10": 30.0,
    "als_implicit_coverage": 30.0,
    "rmse_final": 30.0,
}

# watched keys for the INGEST_r*.json trajectory (the streams_bench
# N_CONSUMERS rounds, ISSUE 13): aggregate/per-N ingest rates and the
# scaling efficiency (rate_N / (N·rate_1)) are higher-is-better;
# recovery-after-kill wall and the per-partition duplicate window are
# LOWER-is-better — a growing replay window is a barrier-cadence
# regression even when throughput noise hides it. Rates loose (shared
# machines, and the curve is thread-scheduling sensitive); the
# duplicate window is near-deterministic (the barrier cadence bounds
# it), so tight.
INGEST_KEYS: dict[str, float] = {
    "value": 30.0,  # max-N aggregate ratings/s headline
    "ingest_n1_ratings_per_s": 30.0,
    "ingest_n4_ratings_per_s": 30.0,
    "scaling_eff_n4": 30.0,
    "recovery_s": 50.0,
    "duplicate_window_batches_max": 10.0,
}

# per-family round-file prefix + default watch set. The quality family
# reads the BENCH rounds — quality keys ride inside the bench extras,
# they just gate under their own watch set (and direction rules).
# watched keys for the TIERED_r*.json trajectory (the streams_bench
# tiered-store mode, ISSUE 17): the tiered ingest rate and its
# fraction of the all-HBM baseline regress when they DROP; the Zipfian
# hit rate is near-deterministic (same trace, same slot budget), so
# tight; prefetch stall time and eviction count regress UP — a rising
# eviction count at fixed capacity means the prefetcher stopped
# keeping the working set resident.
TIER_KEYS: dict[str, float] = {
    "value": 30.0,  # tiered ratings/s headline
    "tier_hit_rate": 10.0,
    "tiered_vs_hbm_frac": 30.0,
    "tier_prefetch_wait_s": 50.0,
    "tier_evictions": 30.0,
}

FAMILIES = {
    "bench": ("BENCH", DEFAULT_KEYS),
    "multichip": ("MULTICHIP", MULTICHIP_KEYS),
    "serving": ("SERVING", SERVING_KEYS),
    "quality": ("BENCH", QUALITY_KEYS),
    "ingest": ("INGEST", INGEST_KEYS),
    "tier": ("TIERED", TIER_KEYS),
}

# keys where HIGHER is explicitly better (throughputs, achieved
# bandwidth). These win over any accidental DEFAULT_LOWER substring
# match — a throughput key must NEVER be gated as lower-is-better, and
# before this list only ``*_wall_s``-style keys had an explicit rule
# while every rate relied on the absence of a pattern collision.
DEFAULT_HIGHER = ("_ratings_per_s", "_rows_per_s", "_users_per_s",
                  "_per_s", "effective_hbm_gbs", "pct_of_hbm_peak",
                  "_hbm_gbs", "_tflops", "_mbps", "qps_at_slo",
                  "recall_at", "_vs_exact",
                  # quality family (ISSUE 10): ranking metrics and
                  # catalog coverage regress when they DROP
                  "_ndcg", "_hr10", "_hr_at", "ndcg_at", "coverage",
                  # ingest family (ISSUE 13): the N-consumer scaling
                  # efficiency regresses when it drops
                  "scaling_eff",
                  # rank-sharded 2-D mesh pass (ISSUE 16): the 'model'-
                  # axis training throughput regresses when it drops
                  # (already covered by _ratings_per_s — listed so the
                  # direction is pinned even if the key is renamed
                  # without the suffix)
                  "rank_sharded",
                  # tiered store (ISSUE 17): the hot-set hit rate
                  # regresses when it drops. No suffix rule covers it —
                  # "_hit_rate" shares no pattern with _hr10/_hr_at —
                  # so the direction is pinned explicitly.
                  "tier_hit_rate",
                  # rollout budget plane (ISSUE 19): the remaining
                  # error budget regresses when it DROPS (burn eats
                  # it). No LOWER pattern matches the key — "_rmse"
                  # does not occur in "error_budget_remaining" — and
                  # the HIGHER rule wins precedence regardless.
                  "error_budget_remaining")

# keys where LOWER is better (walls, latencies, pad/layout overheads,
# compile counts, eval error, ingest→servable critical-path walls)
# when watched explicitly. ``critical_path`` covers
# critical_path_total_s and the per-stage critical_path_s keys
# (ISSUE 12): a growing ingest→servable wall is a freshness regression
# even when throughput noise hides it.
DEFAULT_LOWER = ("_wall_s", "_ms_", "time_to_", "_s_p", "_pad_ratio",
                 "layout_mb", "layout_bytes", "p99_ms", "p50_ms",
                 "shed_frac", "compile_count", "_rmse", "eval_rmse",
                 "rmse_final", "staleness_s", "critical_path",
                 # ingest family (ISSUE 13): recovery-after-kill wall
                 # and the per-partition replay window regress UP
                 "recovery_s", "duplicate_window",
                 # contention plane (ISSUE 14): a rising Amdahl serial
                 # fraction or per-rung lock-wait total is a
                 # serialization regression even when throughput noise
                 # hides it (covers serial_fraction_n<K> and
                 # lock_wait_s_total_n<K>). Watched via --key on rounds
                 # that carry them — not in the family default set: the
                 # pre-ISSUE-14 committed round lacks the keys, and a
                 # default watch key the baseline can't contain is
                 # permanent "missing" noise (the PR 10/13 lesson).
                 "serial_fraction", "lock_wait",
                 # rank-sharded footprint (ISSUE 16): growing per-device
                 # factor+catalog bytes (or the ratio vs model=1) is a
                 # sharding regression — the whole point of the 'model'
                 # axis is dividing them. Covers rank_shard_bytes_per_
                 # device[_m1] and rank_shard_bytes_ratio_vs_m1. Watched
                 # via --key, NOT in MULTICHIP_KEYS: rounds before r07
                 # lack the keys (the PR 10/13 lesson again).
                 "rank_shard_bytes",
                 # tiered store (ISSUE 17): time the trainer spends
                 # stalled on demand faults, and the eviction count at
                 # fixed slot capacity, both regress UP. Note
                 # tier_prefetch_wait_s does NOT collide with the
                 # _per_s HIGHER pattern ("_pre" != "_per") — pinned by
                 # the direction tests.
                 "prefetch_wait", "tier_evictions",
                 # transfer plane (ISSUE 18): steady-state retraces,
                 # implicit hot-path transfers, and blocked device↔host
                 # wait all regress UP — any of them growing means the
                 # pow2-padding/compile-cache or explicit-staging
                 # contract broke. Watched via --key on rounds that
                 # carry them, NOT in any family default set: committed
                 # rounds predating ISSUE 18 lack the keys (the
                 # PR 10/13 lesson). "transfer_wait" shares no pattern
                 # with the _per_s HIGHER rule; "retrace" and
                 # "implicit_transfers" collide with nothing — pinned
                 # by the direction tests.
                 "retrace", "implicit_transfers", "transfer_wait",
                 # rollout budget plane (ISSUE 19): the multi-window
                 # SLO burn pair (slo_burn_rate_fast/_slow) and the
                 # canary verdict latency (batches-to-ROLLBACK on a
                 # poisoned leg) both regress UP. Watched via --key on
                 # rounds that carry them, NOT in SERVING_KEYS:
                 # SERVING_r01 predates the plane (the PR 10/13
                 # lesson). "burn_rate" and "verdict_latency" collide
                 # with no HIGHER pattern — error_budget_remaining
                 # (higher-better) contains neither — pinned by the
                 # direction tests.
                 "burn_rate", "verdict_latency",
                 # request plane (ISSUE 20): per-stage serving walls
                 # (request_stage_*_s_p99 and friends) and per-request
                 # queue wait both regress UP — a stage's p99 growing
                 # means a serving seam got slower, queue_wait growing
                 # means admission/batching backpressure. Watched via
                 # --key on rounds that carry them, NOT in
                 # SERVING_KEYS: committed rounds predating ISSUE 20
                 # lack the keys (the PR 10/13 lesson). Neither
                 # "request_stage" nor "queue_wait" is a substring of
                 # any HIGHER pattern — pinned by the direction tests.
                 "request_stage", "queue_wait")

_NUM_PAIR = re.compile(
    r'"([A-Za-z_][A-Za-z0-9_]*)":\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)')


def _salvage_numeric_pairs(text: str) -> dict[str, float]:
    """Numeric ``"key": value`` pairs from a (possibly front-truncated)
    stdout tail — array elements don't match (no preceding key), so
    ``rmse_curve`` entries and friends are skipped."""
    return {k: float(v) for k, v in _NUM_PAIR.findall(text)}


def flatten_result(doc: dict) -> dict[str, float]:
    """One flat {key: number} view of any accepted format. The headline
    ``value`` keeps its name; ``extra.*`` keys are lifted to top level
    (they don't collide — bench extras never use 'value')."""
    if "tail" in doc or "parsed" in doc:  # driver wrapper
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            doc = parsed
        else:
            return _salvage_numeric_pairs(doc.get("tail") or "")
    out: dict[str, float] = {}
    if isinstance(doc.get("value"), (int, float)):
        out["value"] = float(doc["value"])
    extra = doc.get("extra")
    if isinstance(extra, dict):
        for k, v in extra.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = float(v)
    if not out:  # flat {key: number} baseline
        out = {k: float(v) for k, v in doc.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    return out


_ERR_PAIR = re.compile(r'"error":\s*"((?:[^"\\]|\\.)*)"')


def load_result(path: str) -> tuple[dict[str, float], str | None]:
    """(flat metrics, caveat-or-None) for one bench file."""
    with open(path) as f:
        doc = json.load(f)
    inner = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else doc
    err = inner.get("error") or doc.get("error")
    if not err and isinstance(doc.get("tail"), str):
        # tail-salvaged rounds (parsed=null) carry the CPU-fallback
        # caveat inside the tail text — a cross-backend comparison must
        # not print caveat-free
        m = _ERR_PAIR.search(doc["tail"])
        if m:
            err = m.group(1)
    return flatten_result(doc), (str(err) if err else None)


def find_rounds(directory: str = REPO, prefix: str = "BENCH") -> list[str]:
    """``<prefix>_r*.json`` sorted by round number, oldest first
    (``BENCH`` bench rounds, ``MULTICHIP`` pod_dryrun rounds)."""
    paths = glob.glob(os.path.join(directory, f"{prefix}_r*.json"))

    def round_no(p: str) -> int:
        m = re.search(rf"{prefix}_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return sorted((p for p in paths if round_no(p) >= 0), key=round_no)


def is_lower_better(key: str, lower_flags: set[str]) -> bool:
    if key in lower_flags:
        return True  # an explicit --lower flag always wins
    if any(pat in key for pat in DEFAULT_HIGHER):
        return False  # rates/bandwidths are higher-is-better, full stop
    return any(pat in key for pat in DEFAULT_LOWER)


def compare(baseline: dict[str, float], current: dict[str, float],
            keys: dict[str, float],
            lower_flags: set[str] | None = None) -> list[dict]:
    """One row per watched key: baseline, current, delta %, verdict.
    Verdicts: ``ok`` / ``REGRESSION`` / ``missing`` (either side)."""
    lower_flags = lower_flags or set()
    rows = []
    for key, pct in keys.items():
        b, c = baseline.get(key), current.get(key)
        row = {"key": key, "baseline": b, "current": c,
               "threshold_pct": pct, "delta_pct": None, "verdict": "missing"}
        if b is not None and c is not None:
            lower = is_lower_better(key, lower_flags)
            delta = ((c - b) / abs(b) * 100.0) if b else 0.0
            row["delta_pct"] = delta
            worse = -delta if not lower else delta
            row["verdict"] = "REGRESSION" if worse > pct else "ok"
        rows.append(row)
    return rows


def render_table(rows: list[dict], baseline_path: str,
                 current_path: str) -> str:
    sys.path.insert(0, REPO)  # absolute, so the script works from any cwd
    from scripts.obs_report import format_table

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:,.1f}" if abs(v) >= 100 else f"{v:.4g}"
        return str(v)

    header = ("key", "baseline", "current", "delta%", "allowed%", "verdict")
    body = [(r["key"], fmt(r["baseline"]), fmt(r["current"]),
             fmt(r["delta_pct"]), fmt(r["threshold_pct"]), r["verdict"])
            for r in rows]
    lines = [f"baseline: {baseline_path}", f"current:  {current_path}", ""]
    lines.extend(format_table(header, body))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--family", choices=sorted(FAMILIES), default="bench",
                    help="round family to gate: 'bench' (BENCH_r*.json, "
                         "default), 'multichip' (MULTICHIP_r*.json "
                         "pod_dryrun rounds — pad ratio lower-is-better, "
                         "sharded throughput higher-is-better) or "
                         "'serving' (SERVING_r*.json traffic-sim rounds "
                         "— p99 lower-is-better, throughput/QPS-at-SLO/"
                         "recall higher-is-better) or 'quality' (the "
                         "model-quality keys inside the BENCH rounds — "
                         "ranking/coverage higher-is-better, eval_rmse "
                         "lower) or 'ingest' (INGEST_r*.json parallel-"
                         "ingest rounds — rates/scaling-efficiency "
                         "higher-is-better, recovery wall and duplicate "
                         "window lower-is-better)")
    ap.add_argument("--current", default=None,
                    help="current round file (default: newest round of "
                         "the family)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: previous round of the "
                         "family)")
    ap.add_argument("--key", action="append", default=[],
                    metavar="NAME[=PCT]",
                    help="watch NAME at PCT%% (repeatable; replaces the "
                         "default key set when given)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="override every watched key's threshold %%")
    ap.add_argument("--lower", action="append", default=[], metavar="NAME",
                    help="NAME is lower-is-better (walls/latency)")
    ap.add_argument("--report", default=None,
                    help="also write the table to this path")
    ap.add_argument("--strict", action="store_true",
                    help="missing watched keys fail too")
    args = ap.parse_args(argv)

    prefix, family_keys = FAMILIES[args.family]
    current, baseline = args.current, args.baseline
    if current is None or baseline is None:
        rounds = find_rounds(prefix=prefix)
        if current is None:
            if not rounds:
                print(f"no {prefix}_r*.json rounds found — nothing to gate")
                return 2 if args.strict else 0
            current = rounds[-1]
        if baseline is None:
            prior = [p for p in rounds if os.path.abspath(p)
                     != os.path.abspath(current)]
            if not prior:
                print(f"only one round ({current}) — no baseline to "
                      "diff against")
                return 2 if args.strict else 0
            baseline = prior[-1]

    if args.key:
        keys = {}
        for spec in args.key:
            name, _, pct = spec.partition("=")
            keys[name] = float(pct) if pct else 30.0
    else:
        keys = dict(family_keys)
    if args.threshold is not None:
        keys = {k: args.threshold for k in keys}

    base_flat, base_caveat = load_result(baseline)
    cur_flat, cur_caveat = load_result(current)
    rows = compare(base_flat, cur_flat, keys, set(args.lower))
    table = render_table(rows, baseline, current)
    caveats = []
    if base_caveat:
        caveats.append(f"baseline caveat: {base_caveat}")
    if cur_caveat:
        caveats.append(f"current caveat:  {cur_caveat}")
    out = table + ("\n\n" + "\n".join(caveats) if caveats else "")
    print(out)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out + "\n")

    regressed = [r["key"] for r in rows if r["verdict"] == "REGRESSION"]
    missing = [r["key"] for r in rows if r["verdict"] == "missing"]
    if regressed:
        print(f"\nREGRESSION in: {', '.join(regressed)}")
        return 1
    if missing and args.strict:
        print(f"\nmissing watched keys (strict): {', '.join(missing)}")
        return 1
    print("\nno regressions in watched keys")
    return 0


if __name__ == "__main__":
    sys.exit(main())
