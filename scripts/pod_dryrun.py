"""Pod-shaped validation: virtual-mesh suite, at-scale geometry, and a
2-process local cluster — the acceptance harness for the unified
Partitioner layer (ISSUE 7; seeded as the VERDICT r4 #7 dryrun).

Layers, all chip-free:

1. ``dryrun_multichip(N)`` — the full sharded path suite (mesh DSGD via
   both data pipelines, global blocking, mesh ALS, per-shard
   checkpointing) at tiny shapes on N virtual CPU devices.
2. Partitioner rules-table resolution at N devices: every logical axis
   of ``DEFAULT_RULES`` must resolve to a ``NamedSharding`` on the
   ``('data', 'model')`` mesh — the 16-device half of the rules
   coverage (in-process tests cover 1/4/8 on the conftest mesh).
3. A POD-SHAPED at-scale pass: the blueprint's 10:1 user:item geometry
   (SURVEY §6 scales to 10M×1M) at rank 128 with k = N blocks, skewed
   draws, through ``device_block_problem`` + mesh-DSGD training over
   the Partitioner. Catches the k-scaling pathologies 8 devices cannot:
   pad-ratio blowup at high k (k² buckets over skewed data), per-shard
   minibatch divisibility, high-k layout memory — and now also measures
   training THROUGHPUT (``train_ratings_per_s``) so
   ``scripts/bench_regress.py --family multichip`` can gate rounds
   against each other.
4. A mesh-ALS throughput probe (rank 32) for the second solver family.
5. A RANK-SHARDED 2-D MESH pass (ISSUE 16): the same N devices
   reshaped as (N/2)×2 and (N/4)×4 ``('data','model')`` meshes.
   Mesh-DSGD trains on rank-sharded factor slices (prediction dots
   psum over ``'model'``), parity-pinned against model=1 at EQUAL
   data-axis size; the rank-sharded two-stage retriever must return
   identical top-k ids and its per-device factor+catalog bytes at
   model=4 must be ≤ ~30% of model=1 (``rank_sharded_ratings_per_s``,
   ``rank_shard_bytes_per_device`` → the multichip regress keys).
6. A 2-PROCESS LOCAL CLUSTER pass (skippable: ``--no-two-process`` /
   ``LSR_DRYRUN_NO_2PROC=1``): two real processes coordinate over
   localhost (``jax.distributed``), the global 4-device ring spans both
   — proving cross-process global arrays, ppermute across the process
   boundary, sharded checkpoint save/restore, AND pod observability:
   each process serves its own ``/metrics``+``/healthz``, process 0
   aggregates them through ``obs.fleet`` over real sockets and asserts
   the merged pod ``/metrics`` parses with both hosts labeled and the
   pod ``/healthz`` is OK (the ``POD FLEET OK`` marker → ``fleet_ok``)
   — AND distributed tracing (ISSUE 12): process 0 produces a WAL,
   process 1 consumes it into an online model + serving engine, the
   pod ``/podtracez`` merge is validated as one Chrome trace, and a
   sampled record's id resolves to ONE assembled distributed trace
   spanning WAL append → ingest → partial_fit → swap → flush ACROSS
   the process boundary (the ``POD TRACE OK`` marker → ``trace_ok``;
   the merged ``pod_trace.json`` is copied to ``LSR_POD_TRACE_OUT``
   when set — the CI artifact) (examples/distributed_demo.py is the
   workload).

Prints ONE machine-readable JSON line LAST (stderr flushed first, so
2>&1-merged wrappers always parse it) with pad-ratio, layout-bytes and
throughput fields; asserts the pinned bounds. Driven by
``tests/test_pod_scale.py`` in a 16-device subprocess; run standalone as

    python scripts/pod_dryrun.py 16        # or 32

(the script sets its own XLA_FLAGS device count before importing jax).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_two_process_pass(timeout_s: float = 420.0) -> dict:
    """The 2-process local-cluster smoke: launch the distributed demo as
    two coordinated processes (own env — the parent's virtual-device
    XLA flags must not leak) and report pass/fail + the markers that
    prove each multi-host piece ran."""
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    out: dict = {"n_processes": 2}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as ckdir, \
            tempfile.TemporaryDirectory() as obsdir:
        env_base.update({
            "LSR_COORDINATOR": f"127.0.0.1:{port}",
            "LSR_NUM_PROCESSES": "2",
            "JAX_PLATFORMS": "cpu",
            "LSR_CKPT_DIR": ckdir,
            # pod observability: each process serves /metrics+/healthz,
            # process 0 aggregates them through obs.fleet over real
            # sockets and prints POD FLEET OK after asserting the
            # merged pod /metrics parses and pod /healthz is OK
            "LSR_OBS_DIR": obsdir,
        })
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "examples", "distributed_demo.py")],
                env={**env_base, "LSR_PROCESS_ID": str(p)},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=REPO,
            )
            for p in range(2)
        ]
        outs = []
        try:
            for p in procs:
                text, _ = p.communicate(timeout=timeout_s)
                outs.append(text)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            out.update(ok=False, error=f"timeout after {timeout_s}s")
            return out
        finally:
            for p in procs:
                p.kill()
        shard_files = os.listdir(ckdir)
        # persist the merged pod trace before the tempdir dies — the
        # Perfetto-loadable artifact CI uploads (LSR_POD_TRACE_OUT)
        trace_src = os.path.join(obsdir, "pod_trace.json")
        trace_out = os.environ.get("LSR_POD_TRACE_OUT")
        if trace_out and os.path.exists(trace_src):
            import shutil

            shutil.copyfile(trace_src, trace_out)
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    joined = "\n".join(outs)
    if "Multiprocess computations aren't implemented" in joined:
        # the jaxlib lacks cross-process CPU collectives (gloo knob
        # absent/renamed — initialize_distributed tolerates that): an
        # environment limitation, not a regression. Report skipped so
        # the harness degrades the same way TestTwoProcessSmoke does.
        out.update(skipped=True,
                   reason="jaxlib lacks cross-process CPU collectives")
        return out
    out["fleet_ok"] = "POD FLEET OK" in joined
    out["trace_ok"] = "POD TRACE OK" in joined
    out["ok"] = (
        all(p.returncode == 0 for p in procs)
        and "DISTRIBUTED DEMO PASS" in joined          # global-ring train
        and joined.count("SHARDED CKPT RESUME OK") == 2  # per-shard ckpt
        and joined.count("parity OK") == 2             # mesh ALS parity
        and "POD FLEET OK" in joined                   # pod /metrics+/healthz
        and "POD TRACE OK" in joined                   # pod trace assembly
        and any(".shard0of2" in n for n in shard_files)
        and any(".shard1of2" in n for n in shard_files)
    )
    if not out["ok"]:
        out["error"] = ("rc=" + ",".join(str(p.returncode) for p in procs)
                        + " tail=" + joined[-1500:])
    return out


def main(n_devices: int = 16, two_process: bool = True) -> dict:
    sys.path.insert(0, REPO)
    from large_scale_recommendation_tpu.utils.platform import force_cpu

    force_cpu(n_devices=n_devices)

    import numpy as np

    import __graft_entry__ as ge

    out: dict = {"n_devices": n_devices}

    t0 = time.perf_counter()
    ge.dryrun_multichip(n_devices)
    out["dryrun_wall_s"] = round(time.perf_counter() - t0, 1)

    # ---- partitioner rules-table resolution at N devices --------------
    from large_scale_recommendation_tpu.parallel.partitioner import (
        DEFAULT_RULES,
        Partitioner,
    )

    part = Partitioner(num_devices=n_devices)
    assert part.num_blocks == n_devices, dict(part.mesh.shape)
    for logical, _role in DEFAULT_RULES:
        part.sharding(logical)  # every logical axis must resolve
    assert part.spec("users", "rank") == part.spec("items", "rank")
    out["partitioner_axes_resolved"] = len(DEFAULT_RULES)

    # ---- pod-shaped at-scale pass ------------------------------------
    # 10:1 vocab at rank 128 with k = n_devices. nnz sized for geometry
    # validation (pads, divisibility, memory), not convergence: the
    # recoverability bound (~100 obs/row, docs/PERF.md) would need ~100×
    # more data than a CI-sized run can hold.
    from large_scale_recommendation_tpu.data.device_blocking import (
        device_block_problem,
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
        MeshDSGD,
        MeshDSGDConfig,
    )

    import jax

    k = n_devices
    num_users, num_items = 10_240 * k, 1_024 * k
    rank, mb = 128, 4096
    # draws scale linearly past k=32: with k² buckets over fixed draws,
    # the mean bucket at k=64 (~1.5K nnz) falls below the minibatch
    # rounding unit and the pad ratio is dominated by that CI-size
    # artifact instead of the serpentine deal this pass validates (the
    # REAL pod config holds ~244K nnz/bucket — docs/PERF.md memory table)
    nnz = 6_000_000 * max(1, k // 32)
    (u, i, r), _, _ = synthetic_like_device(
        "ml-25m", nnz=nnz, rank=16, noise=0.1, seed=1, skew_lam=2.0,
        num_users=num_users, num_items=num_items)

    t0 = time.perf_counter()
    p = device_block_problem(u, i, r, num_users, num_items, k,
                             minibatch_multiple=mb, seed=0,
                             minibatch_sort="item")
    jax.block_until_ready(p.sv)
    out["blocking_wall_s"] = round(time.perf_counter() - t0, 1)
    out["max_pad_ratio"] = round(float(p.max_pad_ratio), 3)
    out["layout_bytes"] = int(6 * p.sv.size * 4)
    out["layout_mb"] = round(out["layout_bytes"] / 2**20, 1)
    # per-shard minibatch divisibility at high k: the padded block size
    # must honor minibatch_multiple exactly
    assert p.sv.shape[2] % mb == 0, (p.sv.shape, mb)
    # pad-ratio pin: measured 1.10 at k=16 / 1.47 at k=32 (6M draws) and
    # 1.472 at k=64 (12M draws) — EXACTLY the k=64 rounding floor
    # (bmax == mb): zero layout excess.
    # The unavoidable floor from minibatch rounding alone is k²·mb/nnz
    # (every bucket pads to a multiple of mb); the alarm fires when the
    # measured ratio exceeds 1.5× that floor AND the 2.0 absolute line —
    # i.e. only for genuine serpentine-deal/bucket-layout regressions,
    # at every k, not for the CI-size rounding artifact.
    # floor over the ACTUAL blocked nnz (the 95% train split), the same
    # denominator max_pad_ratio uses — with the requested nnz the two
    # numbers differ by the split factor and aren't comparable
    rounding_floor = k * k * mb / p.nnz
    out["pad_rounding_floor"] = round(rounding_floor, 3)
    assert p.max_pad_ratio < max(2.0, 1.5 * rounding_floor), \
        (p.max_pad_ratio, rounding_floor)

    cfg = MeshDSGDConfig(num_factors=rank, lambda_=0.1, iterations=4,
                         learning_rate=0.1, lr_schedule="constant",
                         seed=0, minibatch_size=mb, init_scale=0.08)
    t0 = time.perf_counter()
    model = MeshDSGD(cfg, partitioner=part).fit_device(
        u, i, r, num_users, num_items)
    jax.block_until_ready((model.U, model.V))
    train_wall = time.perf_counter() - t0  # rate from the UNROUNDED wall
    out["train_wall_s"] = round(train_wall, 1)
    # sweep throughput under the unified layer (includes the one-time
    # compile, as every MULTICHIP round's wall always has — rounds
    # compare like against like). The blocked nnz is the visit count.
    # NOTE the block_until_ready above: the pre-refactor script stopped
    # the clock on the async dispatch (obs disabled ⇒ the segment timer
    # never synced), so its wall under-measured — this round starts the
    # honest trajectory, and 1D-vs-2D interleaved reps measure the
    # partitioner mesh at parity with the replaced hand-rolled ring.
    out["train_ratings_per_s"] = round(
        p.nnz * cfg.iterations / max(train_wall, 1e-9))

    # holdout-free sanity: finite factors, and the TRAIN risk moved below
    # the predict-zero plateau (data std) — geometry validation, not a
    # convergence claim (see nnz note above)
    hu, hi = np.asarray(u[:200_000]), np.asarray(i[:200_000])
    hv = np.asarray(r[:200_000])
    from large_scale_recommendation_tpu.core.types import Ratings

    rmse = model.rmse(Ratings.from_arrays(hu, hi, hv))
    out["train_rmse_after_4_sweeps"] = round(rmse, 4)
    data_std = float(np.std(hv))
    out["data_std"] = round(data_std, 4)
    assert np.isfinite(rmse)
    assert rmse < data_std, (rmse, data_std)

    # ---- mesh-ALS throughput probe (second solver family) ------------
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.als import ALSConfig
    from large_scale_recommendation_tpu.parallel.als_mesh import MeshALS

    als_nu, als_ni, als_iters = 4_000, 2_000, 2
    als_ratings = SyntheticMFGenerator(
        num_users=als_nu, num_items=als_ni, rank=8, noise=0.1,
        seed=2).generate(400_000)
    t0 = time.perf_counter()
    als_model = MeshALS(
        ALSConfig(num_factors=32, lambda_=0.1, iterations=als_iters,
                  seed=0),
        partitioner=part).fit(als_ratings)
    jax.block_until_ready((als_model.U, als_model.V))
    als_wall = time.perf_counter() - t0
    out["als_wall_s"] = round(als_wall, 1)
    out["als_rows_per_s"] = round(
        (als_nu + als_ni) * als_iters / max(als_wall, 1e-9))
    assert np.isfinite(als_model.rmse(als_ratings))

    # ---- rank-sharded 2-D mesh pass (ISSUE 16) -------------------------
    # The 'model' axis end-to-end at pod-dryrun device counts: the same
    # N devices reshaped as (N/2)×2 and (N/4)×4 ('data','model') meshes,
    # mesh-DSGD training on rank-sharded factor slices (the u·v dot
    # psums over 'model'), then the rank-sharded two-stage retriever.
    # Parity is pinned against model=1 at EQUAL data-axis size — blocking
    # pads tables per k, so (N/4)×4 compares against a k=N/4 1-D mesh,
    # same padded shapes, same serpentine deal, same minibatch order.
    rs_nu, rs_ni, rs_rank, rs_mb = 20_480, 8_192, 128, 1024
    (ru, ri, rr), _, _ = synthetic_like_device(
        "ml-25m", nnz=1_500_000, rank=16, noise=0.1, seed=3, skew_lam=2.0,
        num_users=rs_nu, num_items=rs_ni)
    rs_cfg = MeshDSGDConfig(num_factors=rs_rank, lambda_=0.1, iterations=2,
                            learning_rate=0.1, lr_schedule="constant",
                            seed=0, minibatch_size=rs_mb, init_scale=0.08)

    def rs_fit(p2d):
        t0 = time.perf_counter()
        mdl = MeshDSGD(rs_cfg, partitioner=p2d).fit_device(
            ru, ri, rr, rs_nu, rs_ni)
        jax.block_until_ready((mdl.U, mdl.V))
        return mdl, time.perf_counter() - t0

    def max_shard_bytes(arr):
        return max(int(np.asarray(s.data).nbytes)
                   for s in arr.addressable_shards)

    from large_scale_recommendation_tpu.serving.retrieval import (
        RetrievalConfig,
        TwoStageRetriever,
    )

    def rs_footprint(p2d, mdl):
        # per-device serving+factor bytes: the rank-sharded two-stage
        # retriever (int8 stage-1 codes + exact-rescore f32 rows column-
        # sliced over 'model') plus this device's U factor shard
        retr = TwoStageRetriever(
            np.asarray(mdl.V), config=RetrievalConfig(n_clusters=None),
            partitioner=p2d)
        return retr, retr.nbytes_per_device() + max_shard_bytes(mdl.U)

    m4 = 4 if n_devices % 4 == 0 else 1
    part_m1 = Partitioner(num_devices=n_devices // m4)  # k equal to 2-D
    part_m4 = Partitioner(num_devices=n_devices, model_parallel=m4)
    model_m1, _ = rs_fit(part_m1)
    model_m4, wall_m4 = rs_fit(part_m4)
    # nnz accounting: the train split's visits per sweep
    rs_nnz_blocked = int(np.shape(ru)[0])
    out["rank_sharded_ratings_per_s"] = round(
        rs_nnz_blocked * rs_cfg.iterations / max(wall_m4, 1e-9))
    delta = float(np.max(np.abs(np.asarray(model_m4.U, np.float32)
                                - np.asarray(model_m1.U, np.float32))))
    out["rank_shard_parity_max_abs_delta"] = delta
    # fp tolerance only: psum reduction order vs a single fused dot
    assert delta < 1e-4, delta

    retr_m1, bytes_m1 = rs_footprint(part_m1, model_m1)
    retr_m4, bytes_m4 = rs_footprint(part_m4, model_m4)
    out["rank_shard_bytes_per_device"] = bytes_m4
    out["rank_shard_bytes_per_device_m1"] = bytes_m1
    ratio = bytes_m4 / max(bytes_m1, 1)
    out["rank_shard_bytes_ratio_vs_m1"] = round(ratio, 3)
    # footprint acceptance: sharded int8 codes + f32 rescore rows + U
    # divide by m=4; only per-row scales/weights replicate. ≤ ~30% of
    # the model=1 per-device bytes at rank 128 (ISSUE 16 acceptance).
    assert m4 == 1 or ratio <= 0.32, (bytes_m4, bytes_m1)
    # retrieval parity: same seed, same queries ⇒ same top-k ids
    q = np.asarray(model_m1.U, np.float32)[:256]
    empty_excl = (np.zeros(8, np.int32), np.zeros(8, np.int32),
                  np.full(8, np.inf, np.float32))
    _, ids_m1 = retr_m1.topk(q, empty_excl, k=10)
    _, ids_m4 = retr_m4.topk(q, empty_excl, k=10)
    assert np.array_equal(np.asarray(ids_m1), np.asarray(ids_m4))

    # second mesh shape (N/2)×2 — throughput only (its k differs from
    # both runs above, so no equal-k parity partner without a third fit)
    if n_devices % 2 == 0 and n_devices > 2:
        _, wall_m2 = rs_fit(Partitioner(num_devices=n_devices,
                                        model_parallel=2))
        out["rank_sharded_8x2_ratings_per_s"] = round(
            rs_nnz_blocked * rs_cfg.iterations / max(wall_m2, 1e-9))

    # ---- 2-process local cluster -------------------------------------
    if not two_process or os.environ.get("LSR_DRYRUN_NO_2PROC"):
        out["two_process"] = {"skipped": True,
                              "reason": "disabled by flag/env"}
    else:
        out["two_process"] = run_two_process_pass()
        assert out["two_process"].get("ok") or \
            out["two_process"].get("skipped"), out["two_process"]

    # machine-readable contract (same as bench.py::_emit_final and
    # scripts/pallas_probe.py): flush stderr BEFORE the final JSON line
    # so wrappers that merge 2>&1 still parse the LAST line
    sys.stderr.flush()
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    main(int(args[0]) if args else 16,
         two_process="--no-two-process" not in sys.argv)
