"""Pod-shaped virtual-mesh validation past 8 devices (VERDICT r4 #7).

Two layers, both on N virtual CPU devices (no chip needed):

1. ``dryrun_multichip(N)`` — the full sharded path suite (mesh DSGD via
   both data pipelines, global blocking, mesh ALS, per-shard
   checkpointing) at tiny shapes.
2. A POD-SHAPED at-scale pass: the blueprint's 10:1 user:item geometry
   (SURVEY §6 scales to 10M×1M) at rank 128 with k = N blocks, skewed
   draws, through ``device_block_problem`` + one mesh-DSGD training
   segment. This catches exactly the k-scaling pathologies 8 devices
   cannot: pad-ratio blowup at high k (k² buckets over skewed data),
   per-shard minibatch divisibility at high k, and the high-k layout
   memory (k²·bmax·6 arrays).

Prints ONE JSON line with the measured pad ratio, layout bytes, RMSE
trajectory and walls; asserts the pinned bounds. Driven by
``tests/test_pod_scale.py`` in a 16-device subprocess; run standalone as

    python scripts/pod_dryrun.py 16        # or 32

(the script sets its own XLA_FLAGS device count before importing jax).
"""

from __future__ import annotations

import json
import os
import sys
import time


def main(n_devices: int = 16) -> dict:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from large_scale_recommendation_tpu.utils.platform import force_cpu

    force_cpu(n_devices=n_devices)

    import numpy as np

    import __graft_entry__ as ge

    out: dict = {"n_devices": n_devices}

    t0 = time.perf_counter()
    ge.dryrun_multichip(n_devices)
    out["dryrun_wall_s"] = round(time.perf_counter() - t0, 1)

    # ---- pod-shaped at-scale pass ------------------------------------
    # 10:1 vocab at rank 128 with k = n_devices. nnz sized for geometry
    # validation (pads, divisibility, memory), not convergence: the
    # recoverability bound (~100 obs/row, docs/PERF.md) would need ~100×
    # more data than a CI-sized run can hold.
    from large_scale_recommendation_tpu.data.device_blocking import (
        device_block_problem,
        synthetic_like_device,
    )
    from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
        MeshDSGD,
        MeshDSGDConfig,
    )
    from large_scale_recommendation_tpu.parallel.mesh import make_block_mesh

    import jax

    k = n_devices
    num_users, num_items = 10_240 * k, 1_024 * k
    rank, mb = 128, 4096
    # draws scale linearly past k=32: with k² buckets over fixed draws,
    # the mean bucket at k=64 (~1.5K nnz) falls below the minibatch
    # rounding unit and the pad ratio is dominated by that CI-size
    # artifact instead of the serpentine deal this pass validates (the
    # REAL pod config holds ~244K nnz/bucket — docs/PERF.md memory table)
    nnz = 6_000_000 * max(1, k // 32)
    (u, i, r), _, _ = synthetic_like_device(
        "ml-25m", nnz=nnz, rank=16, noise=0.1, seed=1, skew_lam=2.0,
        num_users=num_users, num_items=num_items)

    t0 = time.perf_counter()
    p = device_block_problem(u, i, r, num_users, num_items, k,
                             minibatch_multiple=mb, seed=0,
                             minibatch_sort="item")
    jax.block_until_ready(p.sv)
    out["blocking_wall_s"] = round(time.perf_counter() - t0, 1)
    out["max_pad_ratio"] = round(float(p.max_pad_ratio), 3)
    out["layout_mb"] = round(6 * p.sv.size * 4 / 2**20, 1)
    # per-shard minibatch divisibility at high k: the padded block size
    # must honor minibatch_multiple exactly
    assert p.sv.shape[2] % mb == 0, (p.sv.shape, mb)
    # pad-ratio pin: measured 1.10 at k=16 / 1.47 at k=32 (6M draws) and
    # 1.472 at k=64 (12M draws) — EXACTLY the k=64 rounding floor
    # (bmax == mb): zero layout excess.
    # The unavoidable floor from minibatch rounding alone is k²·mb/nnz
    # (every bucket pads to a multiple of mb); the alarm fires when the
    # measured ratio exceeds 1.5× that floor AND the 2.0 absolute line —
    # i.e. only for genuine serpentine-deal/bucket-layout regressions,
    # at every k, not for the CI-size rounding artifact.
    # floor over the ACTUAL blocked nnz (the 95% train split), the same
    # denominator max_pad_ratio uses — with the requested nnz the two
    # numbers differ by the split factor and aren't comparable
    rounding_floor = k * k * mb / p.nnz
    out["pad_rounding_floor"] = round(rounding_floor, 3)
    assert p.max_pad_ratio < max(2.0, 1.5 * rounding_floor), \
        (p.max_pad_ratio, rounding_floor)

    mesh = make_block_mesh(k)
    cfg = MeshDSGDConfig(num_factors=rank, lambda_=0.1, iterations=4,
                         learning_rate=0.1, lr_schedule="constant",
                         seed=0, minibatch_size=mb, init_scale=0.08)
    t0 = time.perf_counter()
    model = MeshDSGD(cfg, mesh=mesh).fit_device(
        u, i, r, num_users, num_items)
    out["train_wall_s"] = round(time.perf_counter() - t0, 1)

    # holdout-free sanity: finite factors, and the TRAIN risk moved below
    # the predict-zero plateau (data std) — geometry validation, not a
    # convergence claim (see nnz note above)
    hu, hi = np.asarray(u[:200_000]), np.asarray(i[:200_000])
    hv = np.asarray(r[:200_000])
    from large_scale_recommendation_tpu.core.types import Ratings

    rmse = model.rmse(Ratings.from_arrays(hu, hi, hv))
    out["train_rmse_after_4_sweeps"] = round(rmse, 4)
    data_std = float(np.std(hv))
    out["data_std"] = round(data_std, 4)
    assert np.isfinite(rmse)
    assert rmse < data_std, (rmse, data_std)

    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 16)
