"""Observability demo: train → serve → stream, then dump every artifact.

One run produces, under ``--out`` (default ``obs_out/``):

- ``metrics.prom``   — Prometheus text snapshot (serving latency
  summaries, train step time, ingest counters, side by side)
- ``metrics.jsonl``  — the same snapshot as one JSONL line
  (``scripts/obs_report.py metrics.jsonl`` renders the table)
- ``trace.json``     — Chrome trace-event JSON; open it at
  https://ui.perfetto.dev (or chrome://tracing) and the DSGD segments
  show as ``compile`` then ``execute`` spans, the serving flushes as
  nested spans under their thread lane.

Run: ``JAX_PLATFORMS=cpu python examples/obs_demo.py``
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="obs_out", help="artifact directory")
    args = ap.parse_args(argv)

    from large_scale_recommendation_tpu import obs

    # enable FIRST: instruments bind at construction time
    reg, tracer = obs.enable()
    tracer.install_jax_compile_hook()

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.serving.engine import ServingEngine
    from large_scale_recommendation_tpu.streams.driver import (
        StreamingDriver,
        StreamingDriverConfig,
    )
    from large_scale_recommendation_tpu.streams.log import EventLog

    # ---- train: segmented so compile vs execute splits in the trace ----
    print("# train: DSGD, 2 segments (first carries the compile)")
    gen = SyntheticMFGenerator(num_users=500, num_items=200, rank=8,
                               noise=0.1, seed=0)
    ratings = gen.generate(20_000)
    solver = DSGD(DSGDConfig(num_factors=16, iterations=2, num_blocks=2,
                             minibatch_size=1024, learning_rate=0.05))
    model = solver.fit(ratings, checkpoint_every=1)

    # ---- serve: a mixed-size request stream through the engine ---------
    print("# serve: 40 mixed-size requests through ServingEngine")
    engine = ServingEngine(model, k=10, max_batch=256)
    rng = np.random.default_rng(1)
    engine.serve([rng.integers(0, 500, int(sz)).astype(np.int64)
                  for sz in rng.integers(1, 48, 40)])

    # ---- stream: durable log → online model, checkpointed --------------
    print("# stream: 3 micro-batches through the durable ingest driver")
    with tempfile.TemporaryDirectory() as tmp:
        log = EventLog(os.path.join(tmp, "log"))
        for _ in range(3):
            ru, ri, rv, _ = gen.generate(2_000).to_numpy()
            log.append_arrays(0, ru, ri, rv)
        online = OnlineMF(OnlineMFConfig(num_factors=8,
                                         minibatch_size=512))
        driver = StreamingDriver(
            online, log, os.path.join(tmp, "ckpt"),
            config=StreamingDriverConfig(batch_records=2_000))
        driver.run()
        driver.telemetry()  # publishes lag/queue gauges

    # ---- dump the three artifacts --------------------------------------
    os.makedirs(args.out, exist_ok=True)
    prom_path = os.path.join(args.out, "metrics.prom")
    with open(prom_path, "w") as f:
        f.write(reg.to_prometheus())
    jsonl_path = os.path.join(args.out, "metrics.jsonl")
    reg.append_jsonl(jsonl_path)
    trace_path = os.path.join(args.out, "trace.json")
    doc = tracer.to_chrome_trace(trace_path)

    from large_scale_recommendation_tpu.obs.trace import (
        validate_chrome_trace,
    )

    events = validate_chrome_trace(doc)
    cats = sorted({e["cat"] for e in events})
    print(f"# wrote {prom_path}, {jsonl_path}, {trace_path}")
    print(f"# trace: {len(events)} spans, categories {cats} "
          f"— open trace.json in https://ui.perfetto.dev")

    from scripts.obs_report import render_snapshot

    print()
    print(render_snapshot(reg.snapshot()))
    obs.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
