"""Observability demo: train → serve → stream behind a LIVE health layer.

One run starts the endpoint server, drives every tier through it, then
deliberately poisons the stream to show the watchdog + ``/healthz``
doing their job:

1. ``obs.enable()`` + ``ObsServer`` — ``/metrics``, ``/healthz``,
   ``/varz``, ``/tracez`` served over a real socket (port printed).
2. DSGD training (2 segments: compile vs execute split in the trace).
3. ``ServingEngine`` with an ``SLOTracker`` — flush walls feed the
   attainment window; the serving health check reads its burn rate.
4. Durable streaming ingest with a ``TrainingWatchdog(policy=
   "rollback")``, a stream-lag check, a checkpoint-staleness check, and
   the timed telemetry export keeping the lag gauges fresh.
5. **The model plane (ISSUE 10)**: an ``OnlineEvaluator`` reservoir
   holdout (split out of every batch BEFORE ``partial_fit`` trains —
   the eval set is never trained on) shadow-scored into ``eval_*``
   gauges with threshold-free quality anomaly checks armed
   (``watch_quality``), a ``DataQualityInspector`` in front of
   training, and a ``LineageJournal`` stamping every catalog swap.
   **A staleness condition is injected** — ingest continues while
   swaps stop — and the freshness SLO check flips ``/healthz`` to 503;
   the ``/lineagez`` tail shows every served ``catalog_version``'s
   provenance (WAL watermark, train step, source); a re-swap recovers.
6. ``curl /healthz`` → 200, every check OK.
7. **A NaN micro-batch is injected**: the watchdog trips BEFORE the
   offset stamp, rolls the model back to the last durable checkpoint,
   and ``/healthz`` flips to 503 with the training check CRITICAL —
   the poisoned batch never reaches a checkpoint or a catalog swap.
   Because a flight recorder is running (step 1), the trip also
   FREEZES A POSTMORTEM BUNDLE — recent metric series, the structured
   event tail (catalog swaps, checkpoints, the trip itself), the span
   tail, and the health/registry snapshots — whose path is printed and
   which ``scripts/obs_report.py --bundle <dir>`` renders.

Artifacts under ``--out`` (default ``obs_out/``): ``metrics.prom``
(fetched from the live ``/metrics`` route), ``metrics.jsonl``,
``trace.json`` (Perfetto-loadable), ``healthz.json`` (the final
CRITICAL report), ``roofline.json`` (the per-kernel roofline table —
XLA cost analysis joined with measured walls, rendered inline and by
``scripts/obs_report.py --roofline``), and
``postmortem/bundle_watchdog_trip_*/`` (the validated incident bundle,
with a short ``profile/`` capture attached). ``scripts/obs_report.py <url>/varz
--watch 2`` tails the same server live; ``/seriesz`` and ``/eventz``
serve the recorder's history and the event ring.

Run: ``JAX_PLATFORMS=cpu python examples/obs_demo.py``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.obs.server import http_get as _curl  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="obs_out", help="artifact directory")
    args = ap.parse_args(argv)

    from large_scale_recommendation_tpu import obs

    # enable FIRST: instruments bind at construction time — and the
    # flight recorder right after, so event hooks bind too and the
    # sampler is already recording the lead-up when the incident hits
    reg, tracer = obs.enable()
    tracer.install_jax_compile_hook()
    recorder, journal = obs.enable_flight_recorder(
        interval_s=0.25, bundle_dir=os.path.join(args.out, "postmortem"),
        # watchdog-trip bundles get a short jax.profiler capture
        # attached (<bundle>/profile/)
        profile_on_trip_s=0.2)
    # XLA introspection: every compile below lands in the roofline
    # table (cost analysis joined with measured execute walls), the
    # device-memory sampler feeds the recorder, and /rooflinez serves it
    introspector = obs.enable_introspection(interval_s=0.25)
    # catalog lineage: every swap below stamps its provenance, every
    # flush joins the served version back — /lineagez serves the journal
    lineage = obs.enable_lineage()

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.core.types import Ratings
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.obs.health import (
        HealthMonitor,
        SLOTracker,
        TrainingDivergedError,
        TrainingWatchdog,
    )
    from large_scale_recommendation_tpu.obs.server import ObsServer
    from large_scale_recommendation_tpu.serving.engine import ServingEngine
    from large_scale_recommendation_tpu.streams.driver import (
        StreamingDriver,
        StreamingDriverConfig,
    )
    from large_scale_recommendation_tpu.streams.log import EventLog

    monitor = HealthMonitor()
    server = ObsServer(monitor=monitor).start()
    print(f"# endpoint server live at {server.url} "
          f"(/metrics /healthz /varz /tracez)")

    # ---- train: segmented so compile vs execute splits in the trace ----
    print("# train: DSGD, 2 segments (first carries the compile)")
    gen = SyntheticMFGenerator(num_users=500, num_items=200, rank=8,
                               noise=0.1, seed=0)
    ratings = gen.generate(20_000)
    solver = DSGD(DSGDConfig(num_factors=16, iterations=2, num_blocks=2,
                             minibatch_size=1024, learning_rate=0.05))
    model = solver.fit(ratings, checkpoint_every=1)

    # ---- serve: SLO-tracked mixed-size request stream ------------------
    # target is deliberately loose (10s): demo flushes carry XLA compiles
    # and run on arbitrary CI hosts — the point here is the wiring, not a
    # latency claim. A deployment would set its real target.
    print("# serve: 40 mixed-size requests, SLO 99% of flushes < 10s")
    slo = SLOTracker(target_s=10.0, objective=0.99, window=256)
    monitor.watch_slo(slo)
    engine = ServingEngine(model, k=10, max_batch=256, slo=slo)
    rng = np.random.default_rng(1)
    engine.serve([rng.integers(0, 500, int(sz)).astype(np.int64)
                  for sz in rng.integers(1, 48, 40)])
    print(f"#   slo: attainment={slo.attainment:.3f} "
          f"burn={slo.burn_rate:.2f} "
          f"budget_remaining={slo.error_budget_remaining:.2f}")

    # ---- stream: watchdog-guarded durable ingest -----------------------
    print("# stream: 3 micro-batches through the durable ingest driver, "
          "watchdog armed (policy=rollback)")
    with tempfile.TemporaryDirectory() as tmp:
        log = EventLog(os.path.join(tmp, "log"))
        for _ in range(3):
            ru, ri, rv, _ = gen.generate(2_000).to_numpy()
            log.append_arrays(0, ru, ri, rv)
        online = OnlineMF(OnlineMFConfig(num_factors=8,
                                         minibatch_size=512))
        # the model plane (ISSUE 10): a reservoir holdout the model
        # NEVER trains on (split before partial_fit sees each batch)
        # and a per-batch data-quality inspector in front of training
        from large_scale_recommendation_tpu.obs.dataquality import (
            DataQualityInspector,
        )
        from large_scale_recommendation_tpu.obs.quality import (
            OnlineEvaluator,
        )

        evaluator = OnlineEvaluator(online, holdout_fraction=0.15,
                                    reservoir_size=2048,
                                    min_eval_rows=64)
        # duplicate policy priced at THIS workload's baseline (the
        # synthetic stream has ~1% natural birthday collisions in
        # 2K-pair batches over a 100K-pair space); the corruption
        # classes keep the tight defaults
        inspector = DataQualityInspector(
            rating_range=(-50.0, 50.0),
            class_policy={"duplicate_key": (0.05, 0.5)})
        driver = StreamingDriver(
            online, log, os.path.join(tmp, "ckpt"),
            config=StreamingDriverConfig(batch_records=2_000),
            inspector=inspector, evaluator=evaluator)
        watchdog = TrainingWatchdog(policy="rollback",
                                    manager=driver.manager)
        online.watchdog = watchdog
        monitor.watch_watchdog(watchdog)
        monitor.watch_driver(driver, degraded_lag=50_000)
        monitor.watch_checkpoints(driver.manager, degraded_after_s=300)
        monitor.watch_data_quality(inspector)
        # quality anomaly checks: eval_rmse spikes / eval_ndcg drops
        # flip /healthz with zero static per-model thresholds — they
        # learn the series' normal from the flight recorder
        monitor.watch_quality(recorder)
        driver.start_telemetry_export(interval_s=1.0)  # fresh lag gauges
        driver.run()

        # ---- quality: shadow-score the never-trained-on holdout --------
        qm = evaluator.evaluate()
        print(f"# quality: holdout={evaluator.holdout_rows} rows "
              f"(never trained on), eval_rmse={qm['rmse']:.3f} "
              f"ndcg@10={qm.get('ndcg', float('nan')):.3f} "
              f"hr@10={qm.get('hr', float('nan')):.3f} "
              f"coverage={qm.get('coverage', float('nan')):.3f}")
        print(f"# data quality: {inspector.batches} batches inspected, "
              f"status={inspector.status()[0]!r}")

        # ---- lineage + staleness: ingest continues, swaps stop ---------
        sengine = driver.serving_engine(k=5, max_batch=64)
        driver.refresh_serving()  # swap: provenance gains the watermark
        r0 = sengine.recommend(np.arange(16, dtype=np.int64))
        rec0 = lineage.resolve(r0.catalog_version)
        print(f"# lineage: served catalog_version={r0.catalog_version} "
              f"→ watermark={rec0['wal_offset_watermark']} "
              f"step={rec0['train_step']} source={rec0['source']!r}")
        monitor.watch_freshness(lineage, degraded_after_s=0.05,
                                critical_after_s=0.2)
        print("# inject: ingest continues while catalog swaps STOP")
        ru, ri, rv, _ = gen.generate(2_000).to_numpy()
        log.append_arrays(0, ru, ri, rv)
        driver.run()  # applies the new records — but nobody refreshes
        import time as _time

        _time.sleep(0.3)  # the unservable records age past the SLO
        # absorb the ok→CRITICAL transition in-process first: the
        # transition freezes a postmortem bundle (+ profiler capture),
        # and that work belongs here, not inside the HTTP request the
        # assertion below times
        monitor.run()
        code, body = _curl(server.url + "/healthz")
        report = json.loads(body)
        print(f"# healthz (stale): HTTP {code}, "
              f"freshness={report['checks']['freshness']['status']!r} "
              f"(unservable_age_s="
              f"{report['checks']['freshness']['detail'].get('unservable_age_s')})")
        assert code == 503, body
        _, lineagez = _curl(server.url + "/lineagez")
        ltail = json.loads(lineagez)
        print(f"# lineagez: {ltail['swaps']} swaps, tail:")
        for r in ltail["records"][-3:]:
            print(f"#   version={r['catalog_version']} "
                  f"watermark={r['wal_offset_watermark']} "
                  f"source={r['source']!r}")
        driver.refresh_serving()  # the fix: swap → freshness recovers
        code, _ = _curl(server.url + "/healthz")
        print(f"# healthz (re-swapped): HTTP {code} — freshness OK again")
        assert code == 200

        # ---- healthy: /healthz is 200 with every check OK --------------
        code, body = _curl(server.url + "/healthz")
        report = json.loads(body)
        checks = {k: v["status"] for k, v in report["checks"].items()}
        print(f"# healthz (healthy): HTTP {code}, status="
              f"{report['status']!r}, checks={checks}")
        assert code == 200, body

        # ---- poison: a NaN batch trips the watchdog --------------------
        print("# inject: one NaN micro-batch")
        bad = Ratings.from_arrays(
            np.arange(16, dtype=np.int64) % 500,
            np.arange(16, dtype=np.int64) % 200,
            np.full(16, np.nan, np.float32))
        try:
            online.partial_fit(bad, offset=(0, driver.consumed_offset + 16))
            print("#   ERROR: watchdog did not trip")
            return 1
        except TrainingDivergedError as e:
            print(f"#   tripped: reason={e.reason!r} "
                  f"rolled_back={e.rolled_back} — the poisoned offset was "
                  "never stamped, no checkpoint/catalog swap saw NaNs")

        # ---- the trip froze a postmortem bundle ------------------------
        from large_scale_recommendation_tpu.obs.recorder import (
            validate_bundle,
        )

        bundle = watchdog.last_bundle
        assert bundle is not None, "watchdog trip wrote no bundle"
        manifest = validate_bundle(bundle)  # the schema contract holds
        print(f"# postmortem bundle: {bundle}")
        print(f"#   trigger={manifest['trigger']!r} "
              f"series={manifest['counts']['series']} "
              f"events={manifest['counts']['events']} "
              f"spans={manifest['counts']['spans']} — render it with "
              f"scripts/obs_report.py --bundle {bundle}")
        _, eventz = _curl(server.url + "/eventz")
        kinds = sorted({e["kind"]
                        for e in json.loads(eventz)["recent"]})
        print(f"# eventz: {len(journal)} journaled, kinds={kinds}")

        code, body = _curl(server.url + "/healthz")
        report = json.loads(body)
        print(f"# healthz (tripped): HTTP {code}, "
              f"training={report['checks']['training']['status']!r}")
        assert code == 503, body
        driver.stop_telemetry_export()
        recorder.stop()

        # ---- dump the artifacts ----------------------------------------
        os.makedirs(args.out, exist_ok=True)
        with open(os.path.join(args.out, "healthz.json"), "w") as f:
            json.dump(report, f, indent=2)
        _, prom = _curl(server.url + "/metrics")  # the SERVED text
        prom_path = os.path.join(args.out, "metrics.prom")
        with open(prom_path, "w") as f:
            f.write(prom)
        # the model plane's artifacts (the CI quality smoke parses
        # both): the SERVED /lineagez body and the recorder's series
        # snapshot — eval_*/dataq_* series must be present in it
        _, lineagez_body = _curl(server.url + "/lineagez")
        with open(os.path.join(args.out, "lineagez.json"), "w") as f:
            f.write(lineagez_body)
        recorder.sample()  # one last point: eval_*/dataq_* are current
        with open(os.path.join(args.out, "seriesz.json"), "w") as f:
            json.dump(recorder.snapshot(), f, indent=2)
    jsonl_path = os.path.join(args.out, "metrics.jsonl")
    reg.append_jsonl(jsonl_path)
    trace_path = os.path.join(args.out, "trace.json")
    doc = tracer.to_chrome_trace(trace_path)
    server.stop()

    from large_scale_recommendation_tpu.obs.trace import (
        validate_chrome_trace,
    )

    events = validate_chrome_trace(doc)
    cats = sorted({e["cat"] for e in events})
    print(f"# wrote {prom_path}, {jsonl_path}, {trace_path}, "
          f"{os.path.join(args.out, 'healthz.json')}")
    print(f"# trace: {len(events)} spans, categories {cats} "
          f"— open trace.json in https://ui.perfetto.dev")

    from scripts.obs_report import (
        render_lineage,
        render_quality,
        render_roofline,
        render_snapshot,
    )

    # ---- the model-quality & lineage tables (ISSUE 10) -----------------
    print()
    print(render_lineage(lineage.snapshot()))
    print()
    print(render_quality(recorder.snapshot()))

    # ---- the per-kernel roofline table (ISSUE 9) -----------------------
    # every compile above was captured at the funnel: XLA's own
    # flops/bytes-accessed per compile key, joined with the measured
    # execute walls — rendered here and dumped for
    # `scripts/obs_report.py --roofline`
    roofline = introspector.roofline()
    roofline_path = os.path.join(args.out, "roofline.json")
    with open(roofline_path, "w") as f:
        json.dump(roofline, f, indent=2)
    print(f"# wrote {roofline_path} "
          f"({len(roofline['rows'])} compile keys, "
          f"{roofline['compile_count']} compiles)")
    print()
    print(render_roofline(roofline))
    print()
    print(render_snapshot(reg.snapshot()))
    obs.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
