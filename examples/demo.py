"""Runnable demo: online MF vs combined online+batch on a tiny stream.

≙ the reference's runnable example (reference:
spark-adaptive-recom/.../SparkExample.scala:10-105): a small hardcoded
workload fed as three micro-batches, choosing the online-only or combined
path, printing the update stream. Here the workload is generated (same
shape: ~50 ratings, 10 users × 15 items, rank 4, 3 micro-batches) and both
paths run back-to-back.

Run: python examples/demo.py [online|combined]
"""

import sys

import numpy as np

from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.models.adaptive import (
    AdaptiveMF,
    AdaptiveMFConfig,
)
from large_scale_recommendation_tpu.models.online import OnlineMF, OnlineMFConfig

RANK = 4
BATCHES = 3


def micro_batches():
    """~50 ratings over 10 users × 15 items in 3 micro-batches
    (the SparkExample.scala:14,24-30 shape)."""
    gen = SyntheticMFGenerator(num_users=10, num_items=15, rank=2,
                               noise=0.2, seed=7)
    for _ in range(BATCHES):
        r = gen.generate(16)
        # integer 1..5 star ratings like the reference demo data
        ru, ri, rv, _ = r.to_numpy()
        stars = np.clip(np.round(rv * 2 + 3), 1, 5).astype(np.float32)
        yield Ratings.from_arrays(ru, ri, stars)


def run_online():
    print("== online-only (≙ buildModelWithMap) ==")
    model = OnlineMF(OnlineMFConfig(num_factors=RANK, learning_rate=0.1,
                                    minibatch_size=16))
    for b, updates in enumerate(model.run(micro_batches())):
        for u in updates.user_updates:
            print(f"batch {b} user {u.vector.id}: "
                  f"{np.round(u.vector.factors, 3)}")
        for i in updates.item_updates:
            print(f"batch {b} item {i.vector.id}: "
                  f"{np.round(i.vector.factors, 3)}")
    return model


def run_combined():
    print("== combined online + periodic batch retrain "
          "(≙ buildModelCombineOffline) ==")
    model = AdaptiveMF(AdaptiveMFConfig(
        num_factors=RANK, learning_rate=0.1, minibatch_size=16,
        offline_every=2, offline_algorithm="als", offline_iterations=10,
        lambda_=0.05,
    ))
    for b, updates in enumerate(model.run(micro_batches())):
        n_u = len(updates.user_updates)
        n_i = len(updates.item_updates)
        print(f"batch {b}: {n_u} user updates, {n_i} item updates "
              f"(retrains so far: {model.retrain_count})")
    return model


def run_ps_combo():
    print("== PS-hosted online + batch combo (≙ offlineOnlinePS) ==")
    from large_scale_recommendation_tpu.ps import (
        BATCH_TRIGGER,
        PSOnlineBatchConfig,
        PSOnlineBatchMF,
    )

    events: list = []
    for j, batch in enumerate(micro_batches()):
        ru, ri, rv, _ = batch.to_numpy()
        if j == 2:
            events.append(BATCH_TRIGGER)  # mid-stream retrain
        events.extend(zip(ru.tolist(), ri.tolist(), rv.tolist()))
    solver = PSOnlineBatchMF(PSOnlineBatchConfig(
        num_factors=RANK, iterations=4, learning_rate=0.1,
        lr_schedule="constant", worker_parallelism=2, ps_parallelism=2,
        chunk_size=8, minibatch_size=16,
    ))
    users, items = solver.run(events)
    print(f"PS combo: {len(users)} user vectors, {len(items)} item vectors, "
          f"batches per worker: {[w.batches_run for w in solver.workers]}")
    return solver


def run_batch_device():
    print("== batch DSGD via the on-device pipeline (fit_device) ==")
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=50, num_items=40, rank=2,
                               noise=0.05, seed=11)
    train, test = gen.generate(4000), gen.generate(400)
    ru, ri, rv, _ = train.to_numpy()
    solver = DSGD(DSGDConfig(num_factors=RANK, lambda_=0.05, iterations=12,
                             learning_rate=0.2, lr_schedule="constant",
                             minibatch_size=64, seed=0, init_scale=0.1))
    # dense-id COO straight in; blocking/init/training all on device
    model = solver.fit_device(ru, ri, rv, 50, 40, num_blocks=2)
    print(f"fit_device: holdout RMSE {model.rmse(test):.3f} "
          f"(noise floor 0.05)")
    return model


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("online", "both"):
        m = run_online()
        print(f"online model: {m.users.num_rows} users, "
              f"{m.items.num_rows} items\n")
    if which in ("combined", "both"):
        m = run_combined()
        print(f"combined model: {m.online.users.num_rows} users, "
              f"{m.online.items.num_rows} items\n")
    if which in ("ps", "both"):
        run_ps_combo()
        print()
    if which in ("batch", "both"):
        run_batch_device()


if __name__ == "__main__":
    main()
