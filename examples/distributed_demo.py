"""Two-process CPU demo of the multi-host DSGD path.

Run ME on every host of the process group (here: two local processes):

    LSR_COORDINATOR=127.0.0.1:<port> LSR_NUM_PROCESSES=2 LSR_PROCESS_ID=0 \
        python examples/distributed_demo.py &
    LSR_COORDINATOR=127.0.0.1:<port> LSR_NUM_PROCESSES=2 LSR_PROCESS_ID=1 \
        python examples/distributed_demo.py

Each process owns 2 virtual CPU devices → a global 4-device block ring
spanning both processes. The demo shows the three multi-host pieces the
reference delegates to its engines (SURVEY §2.3):

1. **cluster bring-up** — ``initialize_distributed`` (≙ Flink/Spark
   job-manager → task-manager wiring);
2. **per-host ingest** — ``host_rating_shard`` + a cross-process ``psum``
   proving the shards tile the dataset (≙ partitionCustom shipping rating
   partitions, PSOfflineMF.scala:70-72);
3. **global mesh training** — the UNCHANGED jitted mesh-DSGD superstep loop
   (``parallel.dsgd_mesh.build_mesh_dsgd_step``) over a mesh whose ppermute
   ring crosses the process boundary — the DCN/ICI hop the engines' network
   shuffles become (DSGDforMF.scala:611-619 ≙ one collective permute).

Process 0 prints ``DISTRIBUTED DEMO PASS`` when the trained model reaches
the planted noise floor.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_LOCAL_DEVICES = 2


def main() -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={N_LOCAL_DEVICES}"
    )
    from large_scale_recommendation_tpu.utils.platform import force_cpu

    force_cpu(n_devices=N_LOCAL_DEVICES)

    from large_scale_recommendation_tpu.parallel import (
        DistributedConfig,
        Partitioner,
        host_rating_shard,
        initialize_distributed,
    )

    cfg = DistributedConfig.from_env()
    multi = initialize_distributed(cfg)

    # LSR_OBS_DIR=<shared dir>: run the whole demo with the obs layer +
    # XLA introspection live and, at the end, aggregate every process's
    # /metrics + /healthz into ONE pod endpoint (obs.fleet) — the
    # pod_dryrun acceptance marker. Enabled FIRST so instruments bind
    # at construction, like every obs consumer.
    obs_dir = os.environ.get("LSR_OBS_DIR")
    if obs_dir:
        from large_scale_recommendation_tpu import obs as _obs

        _obs.enable()
        _obs.enable_introspection(start=False)
        # the causal plane (ISSUE 12): lineage + critical-path analyzer
        # armed BEFORE any log/driver/engine is built, so the
        # cross-process trace pass below stamps every hop
        _obs.enable_lineage()
        _obs.enable_disttrace()

    import jax
    import jax.numpy as jnp
    import numpy as np

    pid = jax.process_index()
    nproc = jax.process_count()
    assert multi == (nproc > 1)
    # ONE partitioner over the GLOBAL device set: every sharding below —
    # training strata, factor tables, the proof-of-tiling counts, the
    # checkpoint re-shard — resolves through its logical-axis rules
    # table; the identical construction runs single-process on a laptop
    part = Partitioner()
    k = part.num_blocks
    print(f"[p{pid}] {nproc} processes, global devices: {k}", flush=True)

    # -- per-host ingest (every host range-reads the same seeded synthetic
    # stream; the shard filter keeps only its part) -------------------------
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )

    gen = SyntheticMFGenerator(num_users=400, num_items=200, rank=4,
                               noise=0.05, seed=7)
    ratings = gen.generate(30_000)
    test = gen.generate(3_000)
    ru, ri, rv, _ = ratings.to_numpy()
    mu, mi, mv = host_rating_shard(ru, ri, rv, pid, nproc)

    # cross-process sum proves the shards tile the dataset exactly
    counts = part.make_global_array(
        np.full(k, len(mu) / N_LOCAL_DEVICES, np.float32), "ratings")
    total = jax.jit(
        lambda c: jnp.sum(c), out_shardings=part.replicated()
    )(counts)
    # each process wrote its count spread over its local shard entries
    print(f"[p{pid}] local={len(mu)}", flush=True)

    # -- global-mesh DSGD: identical blocking on every host (deterministic
    # given the same seed), global arrays assembled from local shards -------
    from large_scale_recommendation_tpu.data import blocking
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
        build_mesh_dsgd_step,
        device_major_local_strata,
    )
    from large_scale_recommendation_tpu.core.updaters import (
        RegularizedSGDUpdater,
        constant_lr,
    )

    mb = 32
    problem = blocking.block_problem(ratings, num_blocks=k, seed=0,
                                     minibatch_multiple=mb)
    sru, sri, srv, srw = device_major_local_strata(problem)
    U0, V0 = DSGD(DSGDConfig(num_factors=8, seed=0, init_scale=0.3)
                  )._init_factors(problem)

    U = part.make_global_array(np.asarray(U0), "users", "rank")
    V = part.make_global_array(np.asarray(V0), "items", "rank")
    args = tuple(part.make_global_array(x, "ratings")
                 for x in (sru, sri, srv, srw))
    ou = part.make_global_array(problem.users.omega, "users")
    ov = part.make_global_array(problem.items.omega, "items")

    updater = RegularizedSGDUpdater(learning_rate=0.1, lambda_=0.01,
                                    schedule=constant_lr)
    step = build_mesh_dsgd_step(part, updater, mb, k, iterations=20)
    U, V = step(U, V, *args, ou, ov, jnp.asarray(0, jnp.int32))

    # gather the trained tables to every host for scoring
    rep = part.replicated()
    Uh = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(U))
    Vh = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(V))

    tu, ti, tv, _ = test.to_numpy()

    def score(Uhost, Vhost, urows, umask, irows, imask):
        m = (umask * imask) > 0
        pred = np.einsum("nk,nk->n", Uhost[urows[m]], Vhost[irows[m]])
        return float(np.sqrt(np.mean((tv[m] - pred) ** 2)))

    rmse = score(Uh, Vh, *problem.users.rows_for(tu),
                 *problem.items.rows_for(ti))
    print(f"[p{pid}] rmse={rmse:.4f} total_ratings={float(total):.0f}",
          flush=True)
    assert abs(float(total) - len(ru)) < 1e-3, (float(total), len(ru))
    assert rmse < 0.1, rmse

    # -- the same training, but with the blocking computed GLOBALLY ON THE
    # MESH (the multi-host form of the on-device pipeline): each process
    # contributes only ITS shard, padded with weight-0 no-ops to the common
    # length; XLA inserts the cross-process collectives the blocking
    # shuffle needs. No host ever holds the global layout. ------------------
    from large_scale_recommendation_tpu.parallel.distributed import (
        global_device_blocked,
    )

    shard_sizes = np.bincount(np.abs(ru) % nproc, minlength=nproc)
    n_pad = int(-(-shard_sizes.max() // N_LOCAL_DEVICES) * N_LOCAL_DEVICES)
    wz = np.zeros(n_pad, np.float32)
    wz[: len(mu)] = 1.0
    pad1 = lambda a: np.concatenate(
        [a, np.zeros(n_pad - len(a), a.dtype)])
    g = global_device_blocked(
        pad1(mu), pad1(mi), pad1(mv.astype(np.float32)), wz,
        400, 200, part, minibatch_multiple=mb, seed=0, rank=8,
        init_scale=0.3)
    gstep = build_mesh_dsgd_step(part, updater, mb, k, iterations=20,
                                 with_inv=True)
    Ug, Vg = gstep(g.U, g.V, g.ru, g.ri, g.rv, g.rw, g.omega_u, g.omega_v,
                   g.icu, g.icv, jnp.asarray(0, jnp.int32))
    Ugh = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(Ug))
    Vgh = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(Vg))
    gur, gir, gm = g.holdout_rows(tu, ti)
    grmse = score(Ugh, Vgh, gur, np.asarray(gm), gir, np.ones_like(gm))
    print(f"[p{pid}] global-device-blocked rmse={grmse:.4f}", flush=True)
    assert grmse < 0.1, grmse

    # -- per-shard checkpointing across the process-spanning mesh: each
    # process durably writes ONLY the rows its devices hold (no gather —
    # the save path that still works when the model cannot fit one host),
    # then a simulated restart restores + re-shards and finishes training;
    # the result must equal the straight 20-sweep run above. Set
    # LSR_CKPT_DIR to a directory visible to all processes to enable. ------
    ckdir = os.environ.get("LSR_CKPT_DIR")
    if ckdir:
        from jax.experimental import multihost_utils

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        mgr = ShardedCheckpointManager(ckdir)
        half = build_mesh_dsgd_step(part, updater, mb, k, iterations=10,
                                    with_inv=True)
        Us, Vs = half(g.U, g.V, g.ru, g.ri, g.rv, g.rw, g.omega_u,
                      g.omega_v, g.icu, g.icv, jnp.asarray(0, jnp.int32))
        jax.block_until_ready((Us, Vs))
        mgr.save(10, {"U": Us, "V": Vs}, {"kind": "demo"})
        # both processes must finish writing before anyone restores
        multihost_utils.sync_global_devices("sharded-ckpt-written")
        Ur, Vr, done = restore_segment_state_sharded(mgr, "demo", g.U, g.V,
                                                     partitioner=part)
        assert done == 10
        Us2, Vs2 = half(Ur, Vr, g.ru, g.ri, g.rv, g.rw, g.omega_u,
                        g.omega_v, g.icu, g.icv,
                        jnp.asarray(done, jnp.int32))
        U2h = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(Us2))
        np.testing.assert_allclose(U2h, Ugh, rtol=1e-5, atol=1e-6)
        print(f"[p{pid}] SHARDED CKPT RESUME OK", flush=True)

    # -- mesh ALS across the process-spanning mesh (the MLlib retrain
    # branch, OnlineSpark.scala:125-131, out-scaled: the only cross-host
    # traffic is the two factor-table all_gathers per round on the mesh;
    # MLlib routed factor blocks through the block manager). Parity: the
    # identical config fit single-device on this host must agree. --------
    from large_scale_recommendation_tpu.models.als import ALS, ALSConfig
    from large_scale_recommendation_tpu.parallel.als_mesh import MeshALS

    acfg = ALSConfig(num_factors=8, iterations=3, lambda_=0.02,
                     reg_mode="als_wr", seed=0)
    mals = MeshALS(acfg, partitioner=part).fit(ratings)
    Uma = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(mals.U))
    Vma = np.asarray(jax.jit(lambda x: x, out_shardings=rep)(mals.V))
    armse = score(Uma, Vma, *mals.users.rows_for(tu),
                  *mals.items.rows_for(ti))
    # parity vs the identical config fit on this host's single device —
    # row layouts differ (k-block vs 1-block deal), so compare by score,
    # the same contract tests/test_als.py pins single-process
    local_rmse = ALS(acfg).fit(ratings).rmse(test)
    assert abs(armse - local_rmse) < 2e-2, (armse, local_rmse)
    print(f"[p{pid}] mesh-ALS rmse={armse:.4f} single={local_rmse:.4f} "
          "(parity OK)", flush=True)
    assert armse < 0.1, armse

    if obs_dir:
        _stream_trace_pass(obs_dir, pid)
        _fleet_pass(obs_dir, pid, nproc)

    if pid == 0:
        print("DISTRIBUTED DEMO PASS", flush=True)


def _atomic_write(path: str, text: str) -> None:
    with open(path + ".tmp", "w") as f:
        f.write(text)
    os.replace(path + ".tmp", path)  # readers never see a torn file


def _wait_for(path: str, deadline: float) -> None:
    import time as _time

    while not os.path.exists(path):
        if _time.monotonic() > deadline:
            raise TimeoutError(f"{path} never appeared")
        _time.sleep(0.05)


def _stream_trace_pass(obs_dir: str, pid: int,
                       timeout_s: float = 60.0) -> None:
    """The CROSS-PROCESS half of the distributed-tracing acceptance
    (ISSUE 12): process 0 is the WAL producer (its tracer stamps
    ``wal/append`` spans whose trace ids derive from the acked
    offsets), process 1 the ingest→train→swap→serve consumer (its
    tracer stamps the ingest/partial_fit/swap/flush hops). No context
    ever crosses the boundary except through the WAL offsets
    themselves — the deterministic-trace-id design the pod assembler
    joins on. Process 1 publishes the sampled record id
    (``sample.json``); ``_fleet_pass`` later resolves it against the
    ``/podtracez`` merge and prints the ``POD TRACE OK`` marker."""
    import json as _json
    import time as _time

    import numpy as np

    from large_scale_recommendation_tpu.streams.log import EventLog

    deadline = _time.monotonic() + timeout_s
    wal_dir = os.path.join(obs_dir, "wal")
    wal_done = os.path.join(obs_dir, "wal.done")
    sample_path = os.path.join(obs_dir, "sample.json")
    if pid == 0:
        rng = np.random.default_rng(11)
        log = EventLog(wal_dir, fsync=False)
        for _ in range(3):
            log.append_arrays(0, rng.integers(0, 300, 2000),
                              rng.integers(0, 150, 2000),
                              rng.random(2000).astype(np.float32) * 5)
        end = log.end_offset(0)
        log.close()
        _atomic_write(wal_done, str(end))
        # the consumer's spans must exist before the pod-trace fetch in
        # _fleet_pass — wait for its sampled-record marker
        _wait_for(sample_path, deadline)
        print(f"[p{pid}] trace pass: produced {end} records", flush=True)
        return
    if pid != 1:
        return
    import jax

    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.parallel.partitioner import (
        Partitioner,
    )
    from large_scale_recommendation_tpu.streams.driver import (
        StreamingDriver,
        StreamingDriverConfig,
    )

    _wait_for(wal_done, deadline)
    log = EventLog(wal_dir, fsync=False)
    model = OnlineMF(OnlineMFConfig(num_factors=8, minibatch_size=256))
    driver = StreamingDriver(
        model, log, os.path.join(obs_dir, "trace_ckpt"),
        config=StreamingDriverConfig(batch_records=1024,
                                     checkpoint_every=8))
    # the engine must NOT span the process-global mesh: this consumer
    # serves alone, and a default (global) partitioner would turn its
    # catalog shard into a collective the producer never joins — pin it
    # to ONE local device
    engine = driver.serving_engine(
        k=5, max_batch=64,
        mesh=Partitioner(devices=jax.local_devices()[:1]))
    driver.run()                      # catch up on the foreign appends
    driver.refresh_serving()          # the covering servable swap
    engine.recommend(np.arange(8, dtype=np.int64))  # first serve
    log.close()
    sampled = int(driver.consumed_offset) - 1
    _atomic_write(sample_path,
                  _json.dumps({"partition": 0, "offset": sampled}))
    print(f"[p{pid}] trace pass: consumed through offset {sampled}",
          flush=True)


def _fleet_pass(obs_dir: str, pid: int, nproc: int,
                timeout_s: float = 60.0) -> None:
    """The pod-observability half of the 2-process pass: every process
    serves its own ``ObsServer`` and drops the URL into the shared dir;
    process 0 aggregates them through ``obs.fleet`` over REAL sockets,
    asserts the merged pod ``/metrics`` parses with every host present
    and the pod ``/healthz`` is OK, and prints the ``POD FLEET OK``
    marker ``scripts/pod_dryrun.py`` keys on. File-based sync: peers
    keep their servers up until process 0 writes ``fleet.done``."""
    import time as _time

    from large_scale_recommendation_tpu.obs.fleet import (
        FleetAggregator,
        FleetServer,
        parse_prometheus,
    )
    from large_scale_recommendation_tpu.obs.server import ObsServer, http_get

    server = ObsServer().start()
    own = os.path.join(obs_dir, f"proc{pid}.url")
    _atomic_write(own, server.url)  # readers never see a torn URL
    done_marker = os.path.join(obs_dir, "fleet.done")
    deadline = _time.monotonic() + timeout_s
    try:
        if pid != 0:
            while not os.path.exists(done_marker):
                if _time.monotonic() > deadline:
                    raise TimeoutError("fleet.done never appeared")
                _time.sleep(0.05)
            return
        urls = []
        for p in range(nproc):
            path = os.path.join(obs_dir, f"proc{p}.url")
            while not os.path.exists(path):
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"{path} never appeared")
                _time.sleep(0.05)
            with open(path) as f:
                urls.append(f.read().strip())
        fleet = FleetServer(FleetAggregator(urls)).start()
        try:
            code, text = http_get(fleet.url + "/metrics")
            assert code == 200, (code, text[:300])
            samples = parse_prometheus(text)  # strict: malformed raises
            hosts = {labels.get("host") for _, labels, _ in samples}
            assert len(hosts) == nproc, (hosts, nproc)
            code, body = http_get(fleet.url + "/healthz")
            import json as _json

            report = _json.loads(body)
            assert code == 200 and report["status"] == "ok", (code, body)
            assert report["reachable"] == nproc, report
            print(f"POD FLEET OK hosts={len(hosts)} "
                  f"samples={len(samples)} url={fleet.url}", flush=True)
            _pod_trace_pass(fleet.url, obs_dir)
        finally:
            fleet.stop()
            _atomic_write(done_marker, "done")
    finally:
        server.stop()


def _pod_trace_pass(fleet_url: str, obs_dir: str) -> None:
    """Fetch the ``/podtracez`` merge over a real socket, validate it
    as a Chrome trace, resolve the sampled record's id to ONE assembled
    distributed trace spanning WAL append → ingest batch → partial_fit
    → catalog swap → first servable flush ACROSS the process boundary
    (≥ 2 source pids on the chain), persist ``pod_trace.json``
    (Perfetto-loadable — the CI artifact), and print the
    ``POD TRACE OK`` marker ``scripts/pod_dryrun.py`` keys on."""
    import json as _json

    from large_scale_recommendation_tpu.obs.disttrace import (
        resolve_record_trace,
    )
    from large_scale_recommendation_tpu.obs.server import http_get
    from large_scale_recommendation_tpu.obs.trace import (
        validate_chrome_trace,
    )

    code, body = http_get(fleet_url + "/podtracez")
    assert code == 200, (code, body[:300])
    doc = _json.loads(body)
    validate_chrome_trace(doc)  # the merge is a well-formed trace
    with open(os.path.join(obs_dir, "sample.json")) as f:
        sample = _json.load(f)
    chain = resolve_record_trace(doc, sample["partition"],
                                 sample["offset"])
    assert chain["complete"], chain
    assert len(chain["processes"]) >= 2, chain  # crossed the boundary
    with open(os.path.join(obs_dir, "pod_trace.json"), "w") as f:
        _json.dump(doc, f)
    print(f"POD TRACE OK record={chain['trace_id']} "
          f"hops={len(chain['hops'])} "
          f"processes={len(chain['processes'])} "
          f"events={len(doc['traceEvents'])}", flush=True)


if __name__ == "__main__":
    main()
