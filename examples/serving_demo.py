"""Runnable demo: a request stream through the serving engine.

Trains a small ALS model, stands up a ``ServingEngine`` over a device
mesh, serves a stream of mixed-size recommend requests (watch the
micro-batcher pack them into pow2 buckets), then retrains and refreshes
the catalog in place — the version token moves, the compiled executables
do not. docs/SERVING.md is the narrative version.

Run: python examples/serving_demo.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.core.generators import (  # noqa: E402
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.als import ALS, ALSConfig  # noqa: E402
from large_scale_recommendation_tpu.parallel.mesh import (  # noqa: E402
    make_block_mesh,
)
from large_scale_recommendation_tpu.serving import ServingEngine  # noqa: E402


def main():
    gen = SyntheticMFGenerator(num_users=500, num_items=200, rank=8,
                               noise=0.05, seed=0)
    train = gen.generate(30_000)
    model = ALS(ALSConfig(num_factors=16, lambda_=0.05,
                          iterations=5)).fit(train)

    mesh = make_block_mesh()  # all available devices
    engine = ServingEngine(model, k=5, mesh=mesh, train=train,
                           max_batch=256)
    print(f"engine up: catalog v{engine.version}, "
          f"{mesh.devices.size}-device mesh")

    # a stream of mixed-size requests (the serving workload shape:
    # many small queries, not one big batch)
    rng = np.random.default_rng(1)
    requests = [rng.integers(0, 500, int(sz)).astype(np.int64)
                for sz in rng.integers(1, 48, 64)]
    results = engine.serve(requests)
    ids, scores = results[0]
    print(f"served {engine.stats['requests']} requests "
          f"({engine.stats['rows']} users) in "
          f"{engine.stats['microbatches']} micro-batches, "
          f"buckets={dict(sorted(engine.stats['buckets'].items()))}, "
          f"{engine.executable_variants} compiled executables")
    print(f"request 0, user {requests[0][0]}: items {ids[0].tolist()}")

    # retrain → refresh: new catalog version, zero recompiles
    variants_before = engine.executable_variants
    retrained = ALS(ALSConfig(num_factors=16, lambda_=0.05,
                              iterations=9)).fit(train)
    engine.refresh(retrained)
    engine.serve(requests[:8])
    print(f"after retrain swap: catalog v{engine.version}, "
          f"executables {variants_before} -> {engine.executable_variants} "
          f"(refresh is rebind, not recompile)")


if __name__ == "__main__":
    main()
