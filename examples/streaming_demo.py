"""Runnable demo: durable ingest → crash → resume → retrain → serve.

A synthetic rating stream is made durable through the partitioned event
log, driven into an ``AdaptiveMF`` by the ``StreamingDriver``, killed
mid-stream, and restarted from the checkpointed WAL offset — watch the
resume pick up exactly where the crash left off, the replayed tail stay
bounded to one micro-batch, and the post-restart retrain land in the
serving engine as a fresh catalog version. docs/STREAMING.md is the
narrative version.

Run: python examples/streaming_demo.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.core.generators import (  # noqa: E402
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.adaptive import (  # noqa: E402
    AdaptiveMF,
    AdaptiveMFConfig,
)
from large_scale_recommendation_tpu.streams import (  # noqa: E402
    EventLog,
    GeneratorSource,
    StreamingDriver,
    StreamingDriverConfig,
    pump_to_log,
)


def make_model():
    return AdaptiveMF(AdaptiveMFConfig(
        num_factors=8, minibatch_size=256, offline_every=4,
        offline_iterations=3))


class SimulatedCrash(RuntimeError):
    pass


def main():
    root = tempfile.mkdtemp(prefix="streaming_demo_")
    log_dir, ckpt_dir = os.path.join(root, "log"), os.path.join(root, "ckpt")

    # ---- produce: make the stream durable first ------------------------
    log = EventLog(log_dir, segment_records=4096)
    gen = SyntheticMFGenerator(num_users=800, num_items=300, rank=8,
                               noise=0.1, seed=0, skew_lam=2.0)
    n = pump_to_log(GeneratorSource(gen, batch_records=1000,
                                    num_batches=12), log)
    print(f"log: {n} ratings appended, end offset {log.end_offset(0)}")

    # ---- drive, and kill the driver mid-stream -------------------------
    cfg = StreamingDriverConfig(batch_records=1000)

    def crash_at_5(batch):
        if batch.end_offset >= 5000:
            raise SimulatedCrash(f"killed after batch ending at "
                                 f"{batch.end_offset}")

    d1 = StreamingDriver(make_model(), log, ckpt_dir, config=cfg,
                         on_batch=crash_at_5)
    try:
        d1.run()
    except SimulatedCrash as ex:
        print(f"crash: {ex} — its checkpoint never landed, so the "
              "restart below replays that one batch (and nothing more)")

    # ---- restart: a fresh process would do exactly this ----------------
    model = make_model()
    d2 = StreamingDriver(model, log, ckpt_dir, config=cfg)
    resumed = d2.resume()
    print(f"resume: restored={resumed}, replay from offset "
          f"{d2.consumed_offset} "
          f"(lag {log.lag({0: d2.consumed_offset})} records)")

    engine = d2.serving_engine(k=5)
    v0 = engine.version
    d2.run()  # replays the unacked batch + the tail; retrains en route
    tele = d2.telemetry()
    print(f"caught up: offset {tele['consumed_offset']}, lag "
          f"{tele['lag_records']}, {tele['checkpoints_written']} "
          f"checkpoints, {model.retrain_count} retrains")
    print(f"serving: catalog v{v0} -> v{engine.version} "
          f"(swaps observed: {tele['catalog_versions']})")

    ids, scores = engine.recommend([1, 2, 3])
    print(f"user 1 top-5 items: {ids[0].tolist()}")
    log.close()


if __name__ == "__main__":
    main()
