"""Online/streaming MF path: growable tables, micro-batch updates,
updates-only output, convergence.

Mirrors the behaviors of the reference online paths (FlinkOnlineMF.scala,
OnlineSpark.buildModelWithMap) that SURVEY §4 says must be covered by our
own test pyramid.
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.core.initializers import (
    PseudoRandomFactorInitializer,
)
from large_scale_recommendation_tpu.core.types import Ratings, UserUpdate
from large_scale_recommendation_tpu.core.updaters import SGDUpdater
from large_scale_recommendation_tpu.data.tables import GrowableFactorTable
from large_scale_recommendation_tpu.models.online import (
    BatchUpdates,
    OnlineMF,
    OnlineMFConfig,
)


class TestGrowableFactorTable:
    def test_array_snapshot_survives_ensure(self):
        """The documented ingest polling pattern: a .array snapshot taken
        between micro-batches must stay readable after later ensure()
        calls register fresh ids (the padded install must NOT donate the
        old buffer away)."""
        init = PseudoRandomFactorInitializer(4, scale=1.0)
        t = GrowableFactorTable(init, capacity=64)
        t.ensure(np.array([1, 2, 3]))
        snap = t.array
        before = np.asarray(snap).copy()
        t.ensure(np.array([10, 11, 12, 13]))  # fresh ids -> install
        np.testing.assert_array_equal(np.asarray(snap), before)

    def test_pow2_vocab_does_not_double_capacity(self):
        """A vocab that exactly fills a pow2 capacity must not trigger a
        growth (and its memory doubling + downstream recompiles) for
        install-padding headroom alone."""
        init = PseudoRandomFactorInitializer(2, scale=1.0)
        t = GrowableFactorTable(init, capacity=256)
        t.ensure(np.arange(200))
        t.ensure(np.arange(200, 256))  # lands exactly at capacity
        assert t.num_rows == 256
        assert t.capacity == 256, t.capacity

    def test_ensure_registers_and_initializes_by_id(self):
        init = PseudoRandomFactorInitializer(4, scale=1.0)
        t = GrowableFactorTable(init, capacity=8)
        rows = t.ensure(np.array([100, 7, 100]))
        assert rows.tolist() == [0, 1, 0]
        # row content is f(id): matches the initializer called directly
        import jax.numpy as jnp

        expected = np.asarray(init(jnp.asarray([100, 7])))
        np.testing.assert_allclose(np.asarray(t.array[:2]), expected, rtol=1e-6)

    def test_growth_preserves_existing_rows(self):
        init = PseudoRandomFactorInitializer(4)
        t = GrowableFactorTable(init, capacity=8)
        t.ensure(np.arange(6))
        before = np.asarray(t.array[:6]).copy()
        t.ensure(np.arange(100))  # forces capacity doubling(s)
        assert t.capacity >= 100
        np.testing.assert_array_equal(np.asarray(t.array[:6]), before)
        assert t.num_rows == 100

    def test_ensure_mixed_known_unknown_interleaved(self):
        """Rows for a batch mixing seen/unseen/duplicate ids must match the
        sequential getOrElseUpdate semantics id-for-id."""
        init = PseudoRandomFactorInitializer(3, scale=1.0)
        t = GrowableFactorTable(init, capacity=8)
        t.ensure(np.array([50, 60]))
        rows = t.ensure(np.array([60, 9, 50, 9, 8]))
        # 60→1 (seen), 9→2 (first new), 50→0 (seen), 9→2 (dup), 8→3
        assert rows.tolist() == [1, 2, 0, 2, 3]
        assert t.ids() == [50, 60, 9, 8]
        import jax.numpy as jnp

        expected = np.asarray(init(jnp.asarray([9, 8])))
        np.testing.assert_allclose(np.asarray(t.array[2:4]), expected,
                                   rtol=1e-6)

    def test_ensure_1m_fresh_ids_is_fast(self):
        """Bulk registration must be vectorized: 1M fresh ids in well under
        a second (round-1 weak spot #6 — per-id loops are fatal at the
        10M x 1M synthetic target)."""
        import time

        init = PseudoRandomFactorInitializer(8)
        ids = np.random.default_rng(0).permutation(1_000_000)
        # warm every jit cache on a throwaway table (same shapes): the timed
        # region measures registration machinery, not one-off XLA compiles
        GrowableFactorTable(init, capacity=1024).ensure(ids)
        t = GrowableFactorTable(init, capacity=1024)
        t0 = time.perf_counter()
        rows = t.ensure(ids)
        dt = time.perf_counter() - t0
        assert t.num_rows == 1_000_000
        assert rows.max() == 999_999
        # bound leaves headroom for a contended CI host (observed flaky at
        # 2.0 under a parallel TPU-probe workload): measured ~0.5s idle
        # vectorized vs ~10s+ for the pre-vectorization per-id loop
        assert dt < 4.0, f"ensure(1M fresh ids) took {dt:.2f}s"
        # re-ensure (all known) must also be fast
        t0 = time.perf_counter()
        rows2 = t.ensure(ids[:500_000])
        assert time.perf_counter() - t0 < 1.0
        np.testing.assert_array_equal(rows2, rows[:500_000])

    def test_rows_for_unknown_ids_masked(self):
        t = GrowableFactorTable(PseudoRandomFactorInitializer(2), capacity=8)
        t.ensure(np.array([5]))
        rows, mask = t.rows_for(np.array([5, 42]))
        assert mask.tolist() == [1.0, 0.0]
        assert rows[0] == 0


class TestOnlineMF:
    def test_updates_only_output(self):
        """Only vectors touched by the batch are emitted
        (≙ UpdateSeparatedHashMap.updates, OfflineSpark.scala:33-67)."""
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=8))
        b1 = Ratings.from_arrays([1, 2], [10, 20], [5.0, 3.0])
        out1 = m.partial_fit(b1)
        assert sorted(u.vector.id for u in out1.user_updates) == [1, 2]
        assert sorted(i.vector.id for i in out1.item_updates) == [10, 20]
        b2 = Ratings.from_arrays([1], [30], [4.0])
        out2 = m.partial_fit(b2)
        assert [u.vector.id for u in out2.user_updates] == [1]
        assert [i.vector.id for i in out2.item_updates] == [30]

    def test_empty_and_padded_batches_are_noops(self):
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=8))
        m.partial_fit(Ratings.from_arrays([1], [1], [2.0]))
        before = np.asarray(m.users.array).copy()
        out = m.partial_fit(
            Ratings.from_arrays([0], [0], [9.0], weights=[0.0])
        )
        assert out.user_updates == [] and out.item_updates == []
        np.testing.assert_array_equal(np.asarray(m.users.array), before)

    def test_minibatch1_matches_sequential_numpy_sgd(self):
        """batch size 1 recovers the reference's exact per-rating sequential
        semantics (FactorUpdater.scala:37-53 plain SGD rule)."""
        rng = np.random.default_rng(0)
        n = 40
        users = rng.integers(0, 5, n)
        items = rng.integers(0, 6, n)
        vals = rng.normal(0, 1, n).astype(np.float32)
        lr = 0.05

        cfg = OnlineMFConfig(num_factors=3, learning_rate=lr, minibatch_size=1)
        m = OnlineMF(cfg)
        m.partial_fit(Ratings.from_arrays(users, items, vals))

        # numpy oracle: same init (pseudo-random per id), strictly sequential
        import jax.numpy as jnp

        init = PseudoRandomFactorInitializer(3, scale=cfg.init_scale)
        uids = sorted(set(users.tolist()))
        iids = sorted(set(items.tolist()))
        U = {i: np.asarray(init(jnp.asarray([i])))[0].astype(np.float64)
             for i in uids}
        V = {i: np.asarray(init(jnp.asarray([i])))[0].astype(np.float64)
             for i in iids}
        for u, i, r in zip(users, items, vals):
            e = r - U[u] @ V[i]
            nu = U[u] + lr * e * V[i]
            nv = V[i] + lr * e * U[u]
            U[u], V[i] = nu, nv

        got = m.user_factors()
        for i in uids:
            np.testing.assert_allclose(got[i], U[i], rtol=1e-4, atol=1e-5)

    def test_stream_converges_on_planted_model(self):
        gen = SyntheticMFGenerator(num_users=50, num_items=40, rank=4,
                                   noise=0.05, seed=1)
        test = gen.generate(2000)
        m = OnlineMF(OnlineMFConfig(num_factors=8, learning_rate=0.05,
                                    minibatch_size=64,
                                    iterations_per_batch=2))
        first_rmse = None
        for _ in range(30):
            m.partial_fit(gen.generate(1000))
            if first_rmse is None:
                first_rmse = m.rmse(test)
        final = m.rmse(test)
        assert final < first_rmse * 0.7, (first_rmse, final)
        assert final < 0.35

    def test_determinism(self):
        """Same stream twice → identical model (seeded-by-construction,
        the property the reference gates behind Seed, SURVEY §4)."""
        def build():
            gen = SyntheticMFGenerator(num_users=20, num_items=20, rank=3,
                                       noise=0.1, seed=7)
            m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=32))
            for _ in range(5):
                m.partial_fit(gen.generate(200))
            return m

        a, b = build(), build()
        np.testing.assert_array_equal(np.asarray(a.users.array),
                                      np.asarray(b.users.array))
        np.testing.assert_array_equal(np.asarray(a.items.array),
                                      np.asarray(b.items.array))

    def test_run_stream_driver(self):
        gen = SyntheticMFGenerator(num_users=10, num_items=10, rank=2,
                                   noise=0.1, seed=3)
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=16))
        outs = list(m.run(gen.generate(50) for _ in range(3)))
        assert len(outs) == 3
        assert all(isinstance(o, BatchUpdates) for o in outs)
        assert m.step == 3

    def test_predict_unseen_scores_zero(self):
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=8))
        m.partial_fit(Ratings.from_arrays([1], [2], [3.0]))
        s = m.predict([1, 99], [2, 2])
        assert s[1] == 0.0
        assert s[0] != 0.0

    @pytest.mark.slow
    def test_fuzz_pathological_streams(self):
        """Adversarial micro-batch patterns: single-rating batches, all-one-
        user batches, duplicate-heavy batches, and id ranges that force
        repeated capacity growth mid-stream — every batch must apply
        cleanly, tables stay finite, mappings stay consistent."""
        import numpy as np

        rng = np.random.default_rng(123)
        m = OnlineMF(OnlineMFConfig(num_factors=4, learning_rate=0.05,
                                    minibatch_size=32, init_capacity=16))
        seen_users: set = set()
        for trial in range(30):
            kind = trial % 4
            if kind == 0:  # tiny batch
                n = int(rng.integers(1, 4))
                u = rng.integers(0, 50, n)
                i = rng.integers(0, 40, n)
            elif kind == 1:  # all one user, duplicate items
                n = 64
                u = np.full(n, int(rng.integers(0, 1000)))
                i = rng.integers(0, 3, n)
            elif kind == 2:  # fresh id block far beyond capacity
                n = 100
                base = 1000 * (trial + 1)
                u = np.arange(base, base + n)
                i = np.arange(base, base + n)
            else:  # heavy duplicates both sides
                n = 128
                u = rng.integers(0, 5, n)
                i = rng.integers(0, 5, n)
            r = rng.normal(0, 0.5, n).astype(np.float32)
            ups = m.partial_fit(Ratings.from_arrays(u, i, r))
            ids, vecs = ups.user_arrays
            assert set(ids.tolist()) == set(np.unique(u).tolist()), trial
            assert np.isfinite(vecs).all(), trial
            seen_users.update(u.tolist())
        # table capacity grew past every id; every seen id maps to a
        # distinct live row
        rows = m.users.rows_for(np.asarray(sorted(seen_users)))[0]
        assert len(set(rows.tolist())) == len(seen_users)
        assert np.isfinite(np.asarray(m.users.array)).all()

    def test_pluggable_updater(self):
        """The updater seam accepts any FactorUpdater impl
        (≙ FlinkOnlineMF.scala:19-23 injectable factorUpdate)."""
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=8),
                     updater=SGDUpdater(learning_rate=0.0))
        out = m.partial_fit(Ratings.from_arrays([1], [2], [3.0]))
        # lr=0 → vectors unchanged from init
        init = PseudoRandomFactorInitializer(4, scale=0.1)
        import jax.numpy as jnp

        np.testing.assert_allclose(
            out.user_updates[0].vector.factors,
            np.asarray(init(jnp.asarray([1])))[0], rtol=1e-6)


class TestToModel:
    """OnlineMF.to_model: the streaming state as a standard MFModel —
    serving/evaluation/persistence for stream-trained factors."""

    def _stream(self, seed=0):
        gen = SyntheticMFGenerator(num_users=80, num_items=50, rank=4,
                                   noise=0.05, seed=seed)
        m = OnlineMF(OnlineMFConfig(num_factors=6, learning_rate=0.1,
                                    minibatch_size=64))
        for _ in range(5):
            m.partial_fit(gen.generate(3000), emit_updates=False)
        return gen, m

    def test_snapshot_predictions_match_live(self):
        gen, m = self._stream()
        model = m.to_model()
        te = gen.generate(1000)
        ru, ri, _, _ = te.to_numpy()
        s_live, seen_live = m.predict(ru, ri, return_mask=True)
        s_snap, seen_snap = model.predict(ru, ri, return_mask=True)
        np.testing.assert_array_equal(np.asarray(seen_live),
                                      np.asarray(seen_snap))
        np.testing.assert_allclose(np.asarray(s_snap),
                                   np.asarray(s_live), rtol=1e-6)
        assert abs(m.rmse(te) - model.rmse(te)) < 1e-6

    def test_snapshot_serves_and_persists(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            CheckpointManager,
            restore_mf_model,
            save_mf_model,
        )

        gen, m = self._stream(seed=2)
        model = m.to_model()
        # top-K serving from stream-trained factors
        known_users = np.asarray(sorted(model.users.sorted_ids[:5]))
        ids, scores = model.recommend(known_users, k=5)
        assert (ids >= 0).all()
        assert (np.diff(scores, axis=1) <= 1e-6).all()
        # persistence round-trip
        mgr = CheckpointManager(str(tmp_path))
        save_mf_model(mgr, model, 1)
        loaded, _ = restore_mf_model(mgr)
        te = gen.generate(500)
        assert abs(loaded.rmse(te) - model.rmse(te)) < 1e-6

    def test_snapshot_is_immutable_under_further_ingest(self):
        gen, m = self._stream(seed=3)
        model = m.to_model()
        U_before = np.asarray(model.U).copy()
        m.partial_fit(gen.generate(3000), emit_updates=False)
        np.testing.assert_array_equal(np.asarray(model.U), U_before)

    def test_empty_snapshot_predicts_zero(self):
        """to_model() before any ingest: the snapshot must score 0 with
        a false seen-mask, like the live model — not crash on a 0-row
        factor gather (review-found regression)."""
        m = OnlineMF(OnlineMFConfig(num_factors=4))
        model = m.to_model()
        s, seen = model.predict(np.array([1, 7]), np.array([2, 9]),
                                return_mask=True)
        np.testing.assert_array_equal(np.asarray(s), 0.0)
        assert not np.asarray(seen).any()
