"""Ingest data-quality gate (``obs.dataquality``): per-class violation
pins (NaN/Inf, out-of-range, out-of-vocab, duplicate-key, arrival
skew), the windowed degraded/critical policy behind ``DataQualityCheck``,
the driver chaining (inspect runs in front of ``partial_fit``, the
batch trains unmodified), journal emission, and the zero-cost-off pin.
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.dataquality import (
    VIOLATION_CLASSES,
    DataQualityInspector,
)
from large_scale_recommendation_tpu.obs.events import get_events, set_events
from large_scale_recommendation_tpu.obs.health import (
    CRITICAL,
    DEGRADED,
    OK,
    DataQualityCheck,
    HealthMonitor,
)
from large_scale_recommendation_tpu.obs.recorder import (
    get_recorder,
    set_recorder,
)
from large_scale_recommendation_tpu.obs.registry import (
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import get_tracer, set_tracer


@pytest.fixture
def live_obs():
    prev = (get_registry(), get_tracer(), get_events(), get_recorder())
    reg, tracer = obs.enable()
    yield reg
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])


def _clean(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 1000, n), np.arange(n) % 997,
            rng.normal(3.0, 1.0, n).astype(np.float32))


class TestViolationClasses:
    def test_clean_batch_zero_violations(self, live_obs):
        insp = DataQualityInspector(rating_range=(-10, 10),
                                    max_user_id=2000, max_item_id=2000)
        counts = insp.inspect(*_clean())
        assert counts == {c: 0 for c in VIOLATION_CLASSES}
        assert insp.status()[0] == OK

    def test_non_finite(self, live_obs):
        insp = DataQualityInspector()
        u, i, v = _clean()
        v[3], v[7] = np.nan, np.inf
        assert insp.inspect(u, i, v)["non_finite"] == 2

    def test_out_of_range(self, live_obs):
        insp = DataQualityInspector(rating_range=(1.0, 5.0))
        u, i, _ = _clean()
        v = np.full(100, 3.0, np.float32)
        v[0], v[1] = 0.5, 6.0
        assert insp.inspect(u, i, v)["out_of_range"] == 2
        # a NaN is non_finite, never double-counted as out-of-range
        v[2] = np.nan
        counts = insp.inspect(u, i, v)
        assert counts["non_finite"] == 1
        assert counts["out_of_range"] == 2

    def test_range_check_off_without_config(self, live_obs):
        insp = DataQualityInspector()
        u, i, _ = _clean()
        assert insp.inspect(u, i, np.full(100, 999.0,
                                          np.float32))["out_of_range"] == 0

    def test_out_of_vocab(self, live_obs):
        insp = DataQualityInspector(max_user_id=999, max_item_id=999)
        u, i, v = _clean()
        u[0] = -1         # negative always counts
        u[1] = 5000       # past the user ceiling
        i[2] = 1500       # past the item ceiling
        assert insp.inspect(u, i, v)["out_of_vocab"] == 3

    def test_negative_ids_count_without_ceilings(self, live_obs):
        insp = DataQualityInspector()
        u, i, v = _clean()
        u[0] = -7
        assert insp.inspect(u, i, v)["out_of_vocab"] == 1

    def test_duplicate_keys(self, live_obs):
        insp = DataQualityInspector()
        u = np.array([1, 1, 1, 2, 3])
        i = np.array([5, 5, 5, 6, 7])
        v = np.ones(5, np.float32)
        # three copies of (1,5) = two duplicates past the first
        assert insp.inspect(u, i, v)["duplicate_key"] == 2

    def test_duplicate_keys_no_collision_on_corrupt_ids(self, live_obs):
        """Distinct pairs with negative / ≥2³¹ ids (exactly the corrupt
        batches this inspector exists to catch) must not collide into
        phantom duplicates — a packed scalar key would fold
        (7, -5) and (6, 2³¹-5) onto one value."""
        insp = DataQualityInspector()
        u = np.array([7, 6], np.int64)
        i = np.array([-5, 2 ** 31 - 5], np.int64)
        counts = insp.inspect(u, i, np.ones(2, np.float32))
        assert counts["duplicate_key"] == 0
        assert counts["out_of_vocab"] == 1  # the negative id still flags

    def test_weight_zero_rows_excluded(self, live_obs):
        """Padding / already-quarantined rows never reach a kernel and
        never count as violations either."""
        insp = DataQualityInspector()
        u = np.array([1, 2])
        i = np.array([1, 2])
        v = np.array([np.nan, 3.0], np.float32)
        w = np.array([0.0, 1.0], np.float32)
        counts = insp.inspect(u, i, v, weights=w)
        assert counts["non_finite"] == 0

    def test_arrival_skew(self, live_obs):
        insp = DataQualityInspector(skew_threshold=3.0,
                                    skew_window_s=60.0)
        u, i, v = _clean(10)
        insp.inspect(u, i, v, partition=0)
        assert insp.last_skew == 1.0  # one partition can't be skewed
        for _ in range(9):
            insp.inspect(u, i, v, partition=0)
        insp.inspect(u[:1], i[:1], v[:1], partition=1)
        # partition 0: 100 records, partition 1: 1 → max/mean ≈ 1.98
        assert insp.last_skew > 1.9
        status, detail = insp.status()
        assert "partition_skew" in detail


class TestPolicyWindow:
    def test_degraded_then_critical_fractions(self, live_obs):
        insp = DataQualityInspector(degraded_frac=0.05,
                                    critical_frac=0.5, window=4)
        u, i, v = _clean()
        v_bad = v.copy()
        v_bad[:10] = np.nan  # 10% violation fraction
        insp.inspect(u, i, v_bad)
        status, detail = insp.status()
        assert status == DEGRADED
        assert "non_finite" in detail["offending"]
        v_worse = v.copy()
        v_worse[:60] = np.nan  # 60% ≥ critical_frac
        insp.inspect(u, i, v_worse)
        assert insp.status()[0] == CRITICAL

    def test_window_makes_verdict_sticky_then_recovers(self, live_obs):
        """One bad batch degrades for a WINDOW of clean batches, then
        ages out — per-request /healthz evaluation can't consume it
        (the StreamHealthCheck stickiness lesson)."""
        insp = DataQualityInspector(degraded_frac=0.01,
                                    critical_frac=0.5, window=4)
        u, i, v = _clean()
        bad = v.copy()
        bad[:20] = np.nan
        insp.inspect(u, i, bad)
        assert insp.status()[0] == DEGRADED
        for _ in range(2):
            insp.inspect(u, i, v)
            assert insp.status()[0] == DEGRADED  # still in window
        for _ in range(4):
            insp.inspect(u, i, v)
        assert insp.status()[0] == OK  # aged out

    def test_skew_alone_degrades_never_criticals(self, live_obs):
        insp = DataQualityInspector(skew_threshold=2.0)
        u, i, v = _clean()
        for _ in range(10):
            insp.inspect(u, i, v, partition=0)
        insp.inspect(u[:1], i[:1], v[:1], partition=1)
        status, detail = insp.status()
        assert status == DEGRADED
        assert detail.get("skewed") is True

    def test_per_class_policy_overrides(self, live_obs):
        """A dense/replayed stream's NATURAL duplicate rate must be
        priceable per class without loosening the corruption classes:
        23% duplicates stay OK under a (0.3, 0.8) duplicate policy
        while 2% NaN still degrades under the tight default."""
        insp = DataQualityInspector(
            degraded_frac=0.01, critical_frac=0.10,
            class_policy={"duplicate_key": (0.3, 0.8)})
        u = np.zeros(100, np.int64)  # every row duplicates (0, 0)...
        u[:77] = np.arange(77)       # ...except the unique prefix
        i = np.zeros(100, np.int64)
        v = np.ones(100, np.float32)
        insp.inspect(u, i, v)  # 23 duplicate rows = 23% < 30%
        assert insp.status()[0] == OK
        v2 = v.copy()
        v2[:2] = np.nan  # 2% NaN ≥ the tight 1% default
        insp.inspect(np.arange(100), i, v2)  # no dupes this batch
        assert insp.status()[0] == DEGRADED

    def test_validation(self):
        with pytest.raises(ValueError):
            DataQualityInspector(degraded_frac=0.0)
        with pytest.raises(ValueError):
            DataQualityInspector(degraded_frac=0.5, critical_frac=0.1)
        with pytest.raises(ValueError):
            DataQualityInspector(window=0)
        with pytest.raises(ValueError):
            DataQualityInspector(class_policy={"no_such_class": (0.1, 0.2)})
        with pytest.raises(ValueError):
            DataQualityInspector(class_policy={"non_finite": (0.5, 0.1)})


class TestHealthCheckAndMetrics:
    def test_data_quality_check_surface(self, live_obs):
        insp = DataQualityInspector(degraded_frac=0.01)
        check = DataQualityCheck(insp)
        res = check()
        assert res.status == OK  # nothing inspected: not an incident
        assert "no batches" in res.detail["note"]
        u, i, v = _clean()
        bad = v.copy()
        bad[:50] = np.inf
        insp.inspect(u, i, bad)
        assert check().status == CRITICAL

    def test_watch_data_quality_registers(self, live_obs):
        insp = DataQualityInspector()
        monitor = HealthMonitor()
        monitor.watch_data_quality(insp)
        assert "data_quality" in monitor.names()
        assert monitor.run()["status"] == OK

    def test_metrics_published(self, live_obs):
        insp = DataQualityInspector()
        u, i, v = _clean()
        v[0] = np.nan
        insp.inspect(u, i, v)
        names = {(m["name"], tuple(sorted(m["labels"].items())))
                 for m in live_obs.snapshot()["metrics"]}
        assert ("dataq_batches_total", ()) in names
        assert ("dataq_violations_total",
                (("cls", "non_finite"),)) in names
        assert ("dataq_violation_frac",
                (("cls", "non_finite"),)) in names
        assert ("dataq_partition_skew", ()) in names

    def test_event_journaled_once_per_offending_batch(self, live_obs):
        _, journal = obs.enable_flight_recorder(start=False)
        try:
            insp = DataQualityInspector()
            u, i, v = _clean()
            v[:5] = np.nan
            insp.inspect(u, i, v)
            insp.inspect(u, i, _clean()[2])  # clean: no event
            evs = journal.events(kind="data.quality_violation")
            assert len(evs) == 1
            assert evs[0]["detail"]["non_finite"] == 5
        finally:
            rec = get_recorder()
            if rec is not None:
                rec.stop()
            set_recorder(None)
            set_events(None)

    def test_snapshot_json_safe(self, live_obs):
        import json

        insp = DataQualityInspector(rating_range=(0, 5))
        u, i, v = _clean()
        insp.inspect(u, i, v)
        json.dumps(insp.snapshot())


class TestDriverChaining:
    def test_driver_inspects_every_batch_without_mutating(self,
                                                          live_obs,
                                                          tmp_path):
        """The front-of-partial_fit chaining: every applied batch is
        inspected (batch counts match) and training consumed the SAME
        rows it would have uninspected."""
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
            StreamingDriverConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        log = EventLog(str(tmp_path / "log"))
        rng = np.random.default_rng(0)
        for _ in range(3):
            log.append_arrays(0, rng.integers(0, 50, 400),
                              rng.integers(0, 30, 400),
                              rng.normal(3, 1, 400).astype(np.float32))
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        minibatch_size=128))
        insp = DataQualityInspector(rating_range=(-10, 10))
        driver = StreamingDriver(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400),
            inspector=insp)
        applied = driver.run()
        assert applied == 3
        assert insp.batches == 3
        assert insp.records == 1200
        assert driver.records_processed == 1200  # observe-only

    def test_zero_cost_off(self, tmp_path):
        """No inspector, no evaluator → the driver's hooks are None and
        nothing data-quality-shaped exists anywhere (one pointer test
        per batch, the package discipline)."""
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        from large_scale_recommendation_tpu.obs.lineage import (
            get_lineage,
            set_lineage,
        )

        prev = get_lineage()
        set_lineage(None)  # force the true disabled state (an OBS_OUT
        try:  # session may run a suite-wide journal)
            log = EventLog(str(tmp_path / "log"))
            model = OnlineMF(OnlineMFConfig(num_factors=4))
            driver = StreamingDriver(model, log, str(tmp_path / "ckpt"))
            assert driver.inspector is None
            assert driver.evaluator is None
            assert driver._lineage is None
        finally:
            set_lineage(prev)