"""Catalog lineage (``obs.lineage``): journal upsert/eviction/resolve
semantics, the freshness state machine behind the staleness SLO, the
``/lineagez`` route, and the acceptance paths — every served
``RecResult.catalog_version`` on a real ``StreamingDriver`` run joins
to a provenance record whose watermark ≤ the consumed offset at serve
time (surviving a kill/restart resume), and an injected staleness
condition (ingest continues, swaps stop) flips ``/healthz`` to 503
over a real socket.
"""

import json
import os
import time

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.events import get_events, set_events
from large_scale_recommendation_tpu.obs.health import (
    CRITICAL,
    DEGRADED,
    OK,
    HealthMonitor,
)
from large_scale_recommendation_tpu.obs.lineage import (
    FreshnessCheck,
    LineageJournal,
    get_lineage,
    set_lineage,
)
from large_scale_recommendation_tpu.obs.recorder import (
    get_recorder,
    set_recorder,
)
from large_scale_recommendation_tpu.obs.registry import (
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import get_tracer, set_tracer


@pytest.fixture
def lineage_obs():
    """Live registry + installed lineage journal, previous layer
    restored after (an OBS_OUT session may run its own suite-wide)."""
    prev = (get_registry(), get_tracer(), get_events(), get_recorder(),
            get_lineage())
    reg, _ = obs.enable()
    journal = obs.enable_lineage(capacity=64)
    yield reg, journal
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])
    set_lineage(prev[4])


class TestJournal:
    def test_record_upsert_merges_by_version(self, lineage_obs):
        """The multi-site stamping contract: the engine stamps first
        (no watermark), the driver enriches the SAME record — one
        record per servable build, first wall_time wins."""
        _, j = lineage_obs
        a = j.record_swap(5, source="engine_refresh")
        assert a["wal_offset_watermark"] is None
        t0 = a["wall_time"]
        b = j.record_swap(5, wal_offset_watermark=400, train_step=7,
                          source="stream_refresh")
        assert b["wall_time"] == t0  # creation instant preserved
        assert b["wal_offset_watermark"] == 400
        assert b["train_step"] == 7
        assert len(j) == 1
        assert j.swaps == 2

    def test_eviction_is_bounded(self, lineage_obs):
        _, j = lineage_obs
        for v in range(100):
            j.record_swap(v)
        assert len(j) == 64  # capacity
        assert j.evicted == 36
        assert j.resolve(0) is None  # oldest evicted
        assert j.resolve(99) is not None

    def test_resolve_unknown_none(self, lineage_obs):
        _, j = lineage_obs
        assert j.resolve(12345) is None

    def test_observe_serve_publishes_staleness_and_join_counters(
            self, lineage_obs):
        reg, j = lineage_obs
        j.record_swap(3, wal_offset_watermark=10)
        stale = j.observe_serve(3, requests=4)
        assert stale is not None and stale >= 0.0
        assert j.observe_serve(999) is None  # unresolved
        metrics = {(m["name"], tuple(sorted(m["labels"].items()))): m
                   for m in reg.snapshot()["metrics"]}
        assert metrics[("lineage_serve_joins_total",
                        (("resolved", "true"),))]["value"] == 4
        assert metrics[("lineage_serve_joins_total",
                        (("resolved", "false"),))]["value"] == 1
        assert ("lineage_staleness_s", ()) in metrics

    def test_ingest_to_servable_freshness_priced_once(self, lineage_obs):
        """The freshness histogram observes when a record FIRST gains a
        watermark: the newest covered ingest mark prices how long data
        waited to become servable."""
        reg, j = lineage_obs
        t0 = time.time()
        j.note_ingest(100, t=t0 - 5.0)
        j.note_ingest(200, t=t0 - 1.0)
        j.record_swap(1, wal_offset_watermark=150, wall_time=t0)
        metrics = {m["name"]: m for m in reg.snapshot()["metrics"]}
        h = metrics["lineage_ingest_to_servable_s"]
        assert h["count"] == 1
        # watermark 150 covers only the offset-100 mark (5 s old)
        assert h["max"] == pytest.approx(5.0, abs=0.2)
        j.record_swap(1, train_step=3)  # re-stamp: no second observe
        assert reg.snapshot()["metrics"]
        h = {m["name"]: m for m in reg.snapshot()["metrics"]}[
            "lineage_ingest_to_servable_s"]
        assert h["count"] == 1

    def test_snapshot_and_tail(self, lineage_obs):
        _, j = lineage_obs
        for v in range(5):
            j.record_swap(v, wal_offset_watermark=v * 10)
        doc = j.snapshot(limit=3)
        assert doc["returned"] == 3
        assert doc["swaps"] == 5
        assert [r["catalog_version"] for r in j.tail(2)] == [3, 4]
        json.dumps(doc)  # JSON-safe

    def test_validation(self):
        with pytest.raises(ValueError):
            LineageJournal(capacity=0)


class TestFreshness:
    def test_no_ingest_is_ok(self, lineage_obs):
        _, j = lineage_obs
        check = FreshnessCheck(j, degraded_after_s=1.0)
        assert check().status == OK

    def test_servable_covers_ingest_is_ok(self, lineage_obs):
        _, j = lineage_obs
        j.note_ingest(100)
        j.record_swap(1, wal_offset_watermark=100)
        check = FreshnessCheck(j, degraded_after_s=0.0)
        assert check().status == OK

    def test_ingest_ahead_ages_to_degraded_then_critical(self,
                                                         lineage_obs):
        _, j = lineage_obs
        t0 = time.time()
        j.record_swap(1, wal_offset_watermark=100, wall_time=t0 - 10)
        j.note_ingest(100, t=t0 - 10)
        j.note_ingest(250, t=t0 - 2.0)  # ingested, never became servable
        check = FreshnessCheck(j, degraded_after_s=1.0,
                               critical_after_s=5.0)
        res = check()
        assert res.status == DEGRADED
        assert res.detail["ingest_ahead"] is True
        assert res.detail["unservable_age_s"] == pytest.approx(2.0,
                                                               abs=0.5)
        tight = FreshnessCheck(j, degraded_after_s=0.5,
                               critical_after_s=1.0)
        assert tight().status == CRITICAL

    def test_oldest_unservable_record_prices_the_age(self, lineage_obs):
        """The SLO ages from the OLDEST waiting record, not the newest
        arrival — a stream that keeps ingesting must not keep resetting
        its own staleness clock."""
        _, j = lineage_obs
        t0 = time.time()
        j.record_swap(1, wal_offset_watermark=100, wall_time=t0 - 30)
        j.note_ingest(150, t=t0 - 20)  # oldest unservable: 20 s
        j.note_ingest(300, t=t0 - 0.1)  # still arriving
        f = j.freshness()
        assert f["unservable_age_s"] == pytest.approx(20.0, abs=0.5)

    def test_ingest_without_any_swap_pages(self, lineage_obs):
        _, j = lineage_obs
        j.note_ingest(100, t=time.time() - 10)
        check = FreshnessCheck(j, degraded_after_s=1.0)
        res = check()
        assert res.status == DEGRADED
        assert "no servable watermark" in res.detail["note"]

    def test_partitions_are_independent_offset_spaces(self, lineage_obs):
        """Two drivers sharing the journal: partition 1 sits at offset
        50,000 while partition 0's swap covers offset 100 — neither a
        false page (p1's high offsets are NOT 'ahead' of p0's swap) nor
        a masked one (p0 falling behind still ages) may result."""
        _, j = lineage_obs
        t0 = time.time()
        j.note_ingest(100, partition=0, t=t0 - 5)
        j.note_ingest(50_000, partition=1, t=t0 - 5)
        j.record_swap(1, wal_offset_watermark=100, partition=0,
                      wall_time=t0 - 4)
        j.record_swap(2, wal_offset_watermark=50_000, partition=1,
                      wall_time=t0 - 4)
        f = j.freshness()
        assert f["ingest_ahead"] is False  # both partitions covered
        assert f["partitions"][0]["servable_watermark"] == 100
        assert f["partitions"][1]["servable_watermark"] == 50_000
        check = FreshnessCheck(j, degraded_after_s=0.5)
        assert check().status == OK
        # now ONLY partition 0 falls behind: the high-offset partition
        # must not mask it
        j.note_ingest(300, partition=0, t=t0 - 3)
        res = check()
        assert res.status == DEGRADED
        assert res.detail["partitions"][0]["ingest_ahead"] is True
        f = j.freshness()
        assert f["partitions"][1]["ingest_ahead"] is False

    def test_multi_partition_record_merges_watermarks(self, lineage_obs):
        """An adaptive retrain over several partitions stamps one
        record with a per-partition watermark map; the flat field keeps
        the max for single-partition readers."""
        _, j = lineage_obs
        j.record_swap(9, wal_offset_watermark=100, partition=0)
        rec = j.record_swap(9, wal_offset_watermark=7_000, partition=1)
        assert rec["watermarks"] == {0: 100, 1: 7_000}
        assert rec["wal_offset_watermark"] == 7_000
        assert len(j) == 1

    def test_validation(self, lineage_obs):
        _, j = lineage_obs
        with pytest.raises(ValueError):
            FreshnessCheck(j, degraded_after_s=-1.0)
        with pytest.raises(ValueError):
            FreshnessCheck(j, degraded_after_s=5.0, critical_after_s=1.0)

    def test_watch_freshness_registers(self, lineage_obs):
        _, j = lineage_obs
        monitor = HealthMonitor()
        monitor.watch_freshness(j, degraded_after_s=1.0)
        assert "freshness" in monitor.names()
        assert monitor.run()["status"] == OK


def _fill_log(log, gen, n_batches=3, n=1500):
    for _ in range(n_batches):
        ru, ri, rv, _ = gen.generate(n).to_numpy()
        log.append_arrays(0, ru, ri, rv)


def _driver(model, log, ckpt_dir, **kwargs):
    from large_scale_recommendation_tpu.streams.driver import (
        StreamingDriver,
        StreamingDriverConfig,
    )

    return StreamingDriver(model, log, ckpt_dir,
                           config=StreamingDriverConfig(
                               batch_records=1500),
                           **kwargs)


class TestDriverJoinEndToEnd:
    def test_every_served_version_resolves_with_covering_watermark(
            self, lineage_obs, tmp_path):
        """THE acceptance join on a real driver run: every served
        ``RecResult.catalog_version`` resolves in the journal to a
        record whose WAL watermark ≤ the consumed offset at serve time
        — across initial bind, delta refresh, and full refresh."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        _, journal = lineage_obs
        gen = SyntheticMFGenerator(num_users=200, num_items=80, rank=4,
                                   noise=0.1, seed=0)
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, gen, n_batches=2)
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        minibatch_size=512))
        driver = _driver(model, log, str(tmp_path / "ckpt"))
        engine = driver.serving_engine(k=5, max_batch=64)
        served = []

        def serve_and_check():
            res = engine.recommend(np.arange(16, dtype=np.int64))
            rec = journal.resolve(res.catalog_version)
            assert rec is not None, res.catalog_version
            assert rec["wal_offset_watermark"] is not None
            assert rec["wal_offset_watermark"] <= driver.consumed_offset
            served.append((res.catalog_version,
                           rec["wal_offset_watermark"]))

        serve_and_check()  # the bind itself is provenanced
        driver.run()
        driver.refresh_serving()  # delta path
        serve_and_check()
        _fill_log(log, gen, n_batches=1)
        driver.run()
        driver.refresh_serving(delta=False)  # full-rebuild path
        serve_and_check()
        # watermarks advance with the stream
        assert served[-1][1] > served[0][1]
        # and the engine flushes joined: resolved counter ≥ serves
        reg = get_registry()
        joins = reg.counter("lineage_serve_joins_total", resolved="true")
        assert joins.value >= 3

    def test_join_survives_kill_restart_resume(self, lineage_obs,
                                               tmp_path):
        """Kill/restart: a NEW driver+model resumed from the checkpoint
        re-binds serving, and served versions STILL resolve with a
        covering watermark (fresh records — the provenance chain
        continues across the crash)."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        _, journal = lineage_obs
        gen = SyntheticMFGenerator(num_users=200, num_items=80, rank=4,
                                   noise=0.1, seed=0)
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, gen, n_batches=2)
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        minibatch_size=512))
        driver = _driver(model, log, str(tmp_path / "ckpt"))
        driver.run()  # checkpoints (factors, step, offset) atomically
        pre_crash_offset = driver.consumed_offset

        # ---- crash: everything in-process dies except the journal
        # (in a real restart the journal is fresh — new swaps re-stamp;
        # here it persists, which also pins that STALE records from the
        # previous life don't satisfy the new serve joins)
        del driver, model
        _fill_log(log, gen, n_batches=1)  # the tail the crash missed

        model2 = OnlineMF(OnlineMFConfig(num_factors=4,
                                         minibatch_size=512))
        driver2 = _driver(model2, log, str(tmp_path / "ckpt"))
        assert driver2.resume()
        assert driver2.consumed_offset == pre_crash_offset
        driver2.run()  # replays the tail
        engine = driver2.serving_engine(k=5, max_batch=64)
        driver2.refresh_serving()
        res = engine.recommend(np.arange(16, dtype=np.int64))
        rec = journal.resolve(res.catalog_version)
        assert rec is not None
        assert rec["wal_offset_watermark"] == driver2.consumed_offset
        assert rec["wal_offset_watermark"] > pre_crash_offset

    def test_adaptive_retrain_swap_carries_retrain_id(self, lineage_obs,
                                                      tmp_path):
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        _, journal = lineage_obs
        gen = SyntheticMFGenerator(num_users=120, num_items=50, rank=4,
                                   noise=0.1, seed=0)
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, gen, n_batches=3, n=800)
        model = AdaptiveMF(AdaptiveMFConfig(
            num_factors=4, minibatch_size=256, offline_every=2,
            offline_iterations=2, background=False))
        driver = _driver(model, log, str(tmp_path / "ckpt"))
        engine = driver.serving_engine(k=5, max_batch=64)
        driver.run()  # 3 batches → at least one retrain swap
        assert model.retrain_count >= 1
        res = engine.recommend(np.arange(8, dtype=np.int64))
        rec = journal.resolve(res.catalog_version)
        assert rec is not None
        assert rec["source"] == "retrain_install"
        assert rec["retrain_id"] == model.retrain_count
        assert rec["wal_offset_watermark"] is not None
        assert rec["wal_offset_watermark"] <= driver.consumed_offset


class TestLineagezRoute:
    def test_lineagez_served_over_socket(self, lineage_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        _, j = lineage_obs
        j.note_ingest(100)
        j.record_swap(1, wal_offset_watermark=100, train_step=3,
                      source="test")
        with ObsServer() as server:
            code, body = http_get(server.url + "/lineagez")
            assert code == 200
            doc = json.loads(body)
            code, root = http_get(server.url + "/")
            assert "/lineagez" in json.loads(root)["routes"]
        assert doc["swaps"] == 1
        assert doc["records"][0]["catalog_version"] == 1
        assert doc["records"][0]["wal_offset_watermark"] == 100
        assert doc["freshness"]["servable_watermark"] == 100

    def test_route_without_journal_notes(self):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        prev = get_lineage()
        set_lineage(None)
        try:
            with ObsServer() as server:
                code, body = http_get(server.url + "/lineagez")
        finally:
            set_lineage(prev)
        assert code == 200
        doc = json.loads(body)
        assert "note" in doc and doc["records"] == []


class TestStalenessFlipsHealthz:
    def test_ingest_continues_swaps_stop_503s_healthz(self, lineage_obs,
                                                      tmp_path):
        """THE staleness acceptance pin (ISSUE 10): ingest keeps
        applying WAL batches while nobody refreshes serving → the
        freshness SLO check flips /healthz to 503 over a real socket;
        a re-swap recovers it to 200."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        _, journal = lineage_obs
        gen = SyntheticMFGenerator(num_users=200, num_items=80, rank=4,
                                   noise=0.1, seed=0)
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, gen, n_batches=2)
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        minibatch_size=512))
        driver = _driver(model, log, str(tmp_path / "ckpt"))
        driver.serving_engine(k=5, max_batch=64)
        driver.run()
        driver.refresh_serving()
        monitor = HealthMonitor()
        monitor.watch_freshness(journal, degraded_after_s=0.02,
                                critical_after_s=0.05)
        with ObsServer(monitor=monitor) as server:
            code, body = http_get(server.url + "/healthz")
            assert code == 200, body  # servable covers ingest
            # the injection: ingest continues, swaps STOP
            _fill_log(log, gen, n_batches=1)
            driver.run()
            time.sleep(0.1)  # unservable records age past the SLO
            code, body = http_get(server.url + "/healthz")
            assert code == 503, body
            report = json.loads(body)
            assert report["checks"]["freshness"]["status"] == CRITICAL
            assert report["checks"]["freshness"]["detail"][
                "ingest_ahead"] is True
            driver.refresh_serving()  # the fix
            code, body = http_get(server.url + "/healthz")
        assert code == 200, body