"""Anomaly detection: EWMA mean/variance z-score pinned against a numpy
reference, rate-of-change semantics, AnomalyCheck verdict mapping, and
the acceptance path — an injected throughput collapse flips ``/healthz``
through an ``AnomalyCheck`` with NO static threshold configured.
"""

import json

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.anomaly import (
    AnomalyCheck,
    MonotonicGrowthCheck,
    ewma_mean_var,
    ewma_zscore,
    rate_of_change,
)
from large_scale_recommendation_tpu.obs.events import get_events, set_events
from large_scale_recommendation_tpu.obs.health import (
    CRITICAL,
    DEGRADED,
    OK,
    HealthMonitor,
)
from large_scale_recommendation_tpu.obs.recorder import (
    get_recorder,
    set_recorder,
)
from large_scale_recommendation_tpu.obs.registry import (
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import get_tracer, set_tracer


@pytest.fixture
def flight_obs():
    prev = (get_registry(), get_tracer(), get_events(), get_recorder())
    reg, tracer = obs.enable()
    recorder, journal = obs.enable_flight_recorder(start=False)
    yield reg, tracer, recorder, journal
    recorder.stop()
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])


def _reference_ewma(values, alpha):
    """Independent loop form of the exponentially weighted mean/variance
    (West 1979 incremental update) — the pin ewma_mean_var must match."""
    means, variances = [], []
    m = var = 0.0
    for i, x in enumerate(np.asarray(values, float)):
        if i == 0:
            m, var = x, 0.0
        else:
            diff = x - m
            incr = alpha * diff
            m = m + incr
            var = (1.0 - alpha) * (var + diff * incr)
        means.append(m)
        variances.append(var)
    return np.asarray(means), np.asarray(variances)


class TestEwmaMath:
    @pytest.mark.parametrize("alpha", [0.05, 0.25, 0.9])
    def test_mean_var_match_numpy_reference(self, alpha):
        rng = np.random.default_rng(0)
        values = rng.normal(5.0, 2.0, size=300)
        means, variances = ewma_mean_var(values, alpha)
        ref_m, ref_v = _reference_ewma(values, alpha)
        np.testing.assert_allclose(means, ref_m, rtol=1e-12)
        np.testing.assert_allclose(variances, ref_v, rtol=1e-12)

    def test_mean_converges_to_level_var_to_noise(self):
        rng = np.random.default_rng(1)
        values = 100.0 + rng.normal(0, 3.0, size=2000)
        means, variances = ewma_mean_var(values, alpha=0.1)
        assert abs(means[-1] - 100.0) < 1.0
        # EWMA variance of iid noise approaches the true variance
        assert 0.5 * 9.0 < variances[-1] < 2.0 * 9.0

    def test_zscore_zero_on_flat_and_signed_on_steps(self):
        flat = [10.0] * 50
        assert ewma_zscore(flat) == 0.0
        rng = np.random.default_rng(2)
        noisy = list(100.0 + rng.normal(0, 1.0, 60))
        z_drop = ewma_zscore(noisy + [50.0])
        z_spike = ewma_zscore(noisy + [150.0])
        assert z_drop < -6.0
        assert z_spike > 6.0
        # last value never contaminates its own baseline: appending a
        # huge value yields the same z as judging it against the prefix
        assert ewma_zscore(noisy + [1e6]) > 100.0

    def test_zscore_finite_on_step_off_flat_baseline(self):
        z = ewma_zscore([10.0] * 30 + [20.0])
        assert np.isfinite(z) and z > 100.0

    def test_rate_of_change(self):
        assert rate_of_change([100.0, 50.0]) == pytest.approx(-0.5)
        assert rate_of_change([100.0, 90.0, 80.0],
                              span=2) == pytest.approx(-0.2)
        assert rate_of_change([5.0]) == 0.0
        with pytest.raises(ValueError):
            rate_of_change([1.0, 2.0], span=0)

    def test_validation(self):
        with pytest.raises(ValueError):
            ewma_mean_var([1.0], alpha=0.0)
        with pytest.raises(ValueError):
            ewma_mean_var([1.0], alpha=1.5)


class TestAnomalyCheck:
    def _fill(self, reg, rec, name, values):
        g = reg.gauge(name)
        for v in values:
            g.set(v)
            rec.sample()

    def test_warming_then_ok_then_critical_on_collapse(self, flight_obs):
        reg, _, rec, _ = flight_obs
        check = AnomalyCheck(rec, "tput", direction="drop")
        assert check().status == OK  # missing series = warming, not an
        assert "warming" in check().detail["note"]  # incident
        rng = np.random.default_rng(3)
        self._fill(reg, rec, "tput", 1000.0 + rng.normal(0, 10, 60))
        res = check()
        assert res.status == OK
        assert abs(res.detail["z"]) < 3.0
        self._fill(reg, rec, "tput", [12.0])  # collapse
        res = check()
        assert res.status == CRITICAL
        assert res.detail["z"] < -6.0
        assert res.detail["rate_of_change"] < -0.9

    def test_nan_last_value_is_critical_not_silent_ok(self, flight_obs):
        # z=NaN compares False against every threshold — without the
        # explicit guard a NaN gauge (the classic incident precursor)
        # would read as ok and leak a bare NaN token into /healthz JSON
        reg, _, rec, _ = flight_obs
        rng = np.random.default_rng(11)
        self._fill(reg, rec, "sig", 1000.0 + rng.normal(0, 10, 40))
        check = AnomalyCheck(rec, "sig", direction="drop")
        assert check().status == OK
        self._fill(reg, rec, "sig", [float("nan")])
        res = check()
        assert res.status == CRITICAL
        assert res.detail["reason"] == "non_finite_value"
        json.dumps(res.detail, allow_nan=False)  # strict-JSON safe

    def test_nan_in_window_does_not_mask_later_collapse(self, flight_obs):
        reg, _, rec, _ = flight_obs
        rng = np.random.default_rng(12)
        self._fill(reg, rec, "sig2", 1000.0 + rng.normal(0, 10, 30))
        self._fill(reg, rec, "sig2", [float("nan")])  # transient NaN
        self._fill(reg, rec, "sig2", 1000.0 + rng.normal(0, 10, 10))
        check = AnomalyCheck(rec, "sig2", direction="drop")
        res = check()
        assert res.status == OK  # recovered: the NaN is filtered out...
        assert res.detail["non_finite_dropped"] == 1
        json.dumps(res.detail, allow_nan=False)
        self._fill(reg, rec, "sig2", [12.0])  # ...so a real collapse
        res = check()                         # still pages
        assert res.status == CRITICAL
        assert res.detail["z"] < -6.0

    def test_direction_filter(self, flight_obs):
        reg, _, rec, _ = flight_obs
        rng = np.random.default_rng(4)
        self._fill(reg, rec, "lat", 0.01 + rng.normal(0, 0.0005, 40))
        spike_watch = AnomalyCheck(rec, "lat", direction="spike")
        drop_watch = AnomalyCheck(rec, "lat", direction="drop")
        assert spike_watch().status == OK
        self._fill(reg, rec, "lat", [0.5])  # latency explosion
        assert spike_watch().status == CRITICAL
        # a drop-watcher must NOT page on a spike
        assert drop_watch().status == OK

    def test_degraded_band(self, flight_obs):
        reg, _, rec, _ = flight_obs
        rng = np.random.default_rng(5)
        base = 100.0 + rng.normal(0, 2.0, 80)
        self._fill(reg, rec, "mid", base)
        check = AnomalyCheck(rec, "mid", direction="both")
        z_ok = check()
        assert z_ok.status == OK
        # a ~4-sigma move lands between degraded_z (3) and critical_z (6)
        sd = float(np.std(base))
        self._fill(reg, rec, "mid", [float(np.mean(base) + 4.3 * sd)])
        res = check()
        assert res.status == DEGRADED, res.detail

    def test_delta_mode_turns_counter_into_rate_signal(self, flight_obs):
        reg, _, rec, _ = flight_obs
        c = reg.counter("reqs_total")
        rng = np.random.default_rng(6)
        for _ in range(50):  # steady ~1000/sample
            c.inc(1000 + int(rng.normal(0, 20)))
            rec.sample()
        check = AnomalyCheck(rec, "reqs_total", mode="delta",
                             direction="drop")
        assert check().status == OK
        c.inc(5)  # throughput collapse: the counter still RISES
        rec.sample()
        res = check()
        assert res.status == CRITICAL
        # a value-mode check on the raw monotonic counter can't see it
        raw = AnomalyCheck(rec, "reqs_total", direction="drop")
        assert raw().status == OK

    def test_config_validation(self, flight_obs):
        _, _, rec, _ = flight_obs
        with pytest.raises(ValueError):
            AnomalyCheck(rec, "x", direction="sideways")
        with pytest.raises(ValueError):
            AnomalyCheck(rec, "x", mode="wavelet")
        with pytest.raises(ValueError):
            AnomalyCheck(rec, "x", warmup=1)
        with pytest.raises(ValueError):
            AnomalyCheck(rec, "x", degraded_z=5, critical_z=3)


class TestMonotonicGrowth:
    """The HBM leak detector (ISSUE 9): monotonic growth is the signal
    the EWMA z-score can't see — each step sits inside the learned
    variance; the unbroken run is what kills the process."""

    def _fill(self, reg, rec, name, values, device="tpu:0"):
        g = reg.gauge(name, device=device)
        for v in values:
            g.set(v)
            rec.sample()

    def test_absent_series_is_ok_graceful(self, flight_obs):
        # CPU: no allocator stats surface → the sampler publishes no
        # device_bytes_in_use series — the documented graceful path
        _, _, rec, _ = flight_obs
        check = MonotonicGrowthCheck(rec)
        res = check()
        assert res.status == OK
        assert "absent" in res.detail["note"]

    def test_steady_then_leak_degrades_then_criticals(self, flight_obs):
        reg, _, rec, _ = flight_obs
        check = MonotonicGrowthCheck(rec, min_run=8,
                                     degraded_growth_frac=0.05,
                                     critical_growth_frac=0.5)
        base = 1000.0
        # steady state with jitter: runs keep breaking, never flags
        rng = np.random.default_rng(5)
        self._fill(reg, rec, "device_bytes_in_use",
                   base + rng.normal(0, 5, 30))
        assert check().status == OK
        # a slow monotonic climb: +1% per sample — EWMA-invisible
        self._fill(reg, rec, "device_bytes_in_use",
                   [base * (1 + 0.01 * i) for i in range(1, 12)])
        res = check()
        assert res.status == DEGRADED
        assert res.detail["run_points"] >= 8
        # keep leaking past +50% of the run start → CRITICAL
        self._fill(reg, rec, "device_bytes_in_use",
                   [base * (1.12 + 0.1 * i) for i in range(1, 8)])
        assert check().status == CRITICAL

    def test_flat_run_is_not_growth(self, flight_obs):
        reg, _, rec, _ = flight_obs
        check = MonotonicGrowthCheck(rec, min_run=4)
        self._fill(reg, rec, "device_bytes_in_use", [512.0] * 20)
        assert check().status == OK  # non-decreasing but never growing

    def test_startup_ramp_then_plateau_clears(self, flight_obs):
        """A normal allocation ramp (near-zero → model resident) that
        then goes FLAT must clear within min_run plateau samples — a
        plateau is stability, not a leak; without the recency guard the
        near-zero ramp base made growth_frac astronomical and the check
        read CRITICAL until the ramp aged out of the whole window."""
        reg, _, rec, _ = flight_obs
        check = MonotonicGrowthCheck(rec, min_run=4)
        # the ramp itself IS monotonic growth: flagging during it is
        # the detector's contract
        self._fill(reg, rec, "device_bytes_in_use",
                   [10.0 * 2 ** i for i in range(8)])
        assert check().status == CRITICAL
        # plateau: min_run flat samples later the verdict is clean
        self._fill(reg, rec, "device_bytes_in_use", [10.0 * 2 ** 7] * 4)
        assert check().status == OK

    def test_worst_wins_across_devices(self, flight_obs):
        reg, _, rec, _ = flight_obs
        check = MonotonicGrowthCheck(rec, min_run=4,
                                     degraded_growth_frac=0.05,
                                     critical_growth_frac=10.0)
        for i in range(10):
            reg.gauge("device_bytes_in_use", device="tpu:0").set(100.0)
            reg.gauge("device_bytes_in_use",
                      device="tpu:1").set(100.0 * (1 + 0.05 * i))
            rec.sample()
        res = check()
        assert res.status == DEGRADED
        assert 'tpu:1' in res.detail["series"]

    def test_watch_device_memory_registers(self, flight_obs):
        _, _, rec, _ = flight_obs
        monitor = HealthMonitor()
        monitor.watch_device_memory(rec)
        assert "device_memory" in monitor.names()
        assert monitor.run()["status"] == OK  # absent series on CPU

    def test_validation(self, flight_obs):
        _, _, rec, _ = flight_obs
        with pytest.raises(ValueError):
            MonotonicGrowthCheck(rec, min_run=1)
        with pytest.raises(ValueError):
            MonotonicGrowthCheck(rec, degraded_growth_frac=0.9,
                                 critical_growth_frac=0.1)


class TestHealthzFlipsOnCollapse:
    def test_throughput_collapse_503s_healthz_with_no_static_threshold(
            self, flight_obs):
        """The acceptance pin: a collapse flips /healthz to 503 through
        the anomaly check ALONE — no degraded_lag, no critical_burn, no
        absolute number anywhere in the wiring."""
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        reg, _, rec, _ = flight_obs
        monitor = HealthMonitor()
        monitor.watch_series(rec, "stream_tput", direction="drop")
        g = reg.gauge("stream_tput")
        rng = np.random.default_rng(7)
        for v in 5000.0 + rng.normal(0, 40, 64):
            g.set(v)
            rec.sample()
        with ObsServer(monitor=monitor) as server:
            code, body = http_get(server.url + "/healthz")
            assert code == 200, body
            assert json.loads(body)["status"] == OK
            g.set(3.0)  # the collapse
            rec.sample()
            code, body = http_get(server.url + "/healthz")
        assert code == 503, body
        report = json.loads(body)
        check = report["checks"]["anomaly:stream_tput"]
        assert check["status"] == CRITICAL
        assert check["detail"]["z"] < -6.0
