"""Checkpoint/resume + metrics utilities.

SURVEY §5 aux subsystems: snapshot atomicity/retention, MFModel and
online-state round trips, segmented DSGD fit with resume (the η/√t schedule
must continue across the boundary), adaptive periodic snapshots.
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.models.adaptive import (
    AdaptiveMF,
    AdaptiveMFConfig,
)
from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
from large_scale_recommendation_tpu.models.online import OnlineMF, OnlineMFConfig
from large_scale_recommendation_tpu.utils import metrics as M
from large_scale_recommendation_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_mf_model,
    restore_online_state,
    save_mf_model,
    save_online_state,
)


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        mgr.save(5, {"x": a}, {"note": "hello"})
        ck = mgr.restore()
        assert ck.step == 5
        np.testing.assert_array_equal(ck["x"], a)
        assert ck.meta["note"] == "hello"

    def test_retention_keeps_last_k(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, {"x": np.zeros(1)})
        assert mgr.steps() == [3, 4]

    def test_restore_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            CheckpointManager(str(tmp_path)).restore()

    def test_bfloat16_roundtrips_exactly(self, tmp_path):
        """np.savez silently degrades ml_dtypes arrays (bf16 reloads as a
        void '|V2' dtype); the manager's bit-view encoding must bring the
        dtype AND the exact bits back (ISSUE 6: factor_dtype checkpoint
        round-trip)."""
        import ml_dtypes

        mgr = CheckpointManager(str(tmp_path))
        rng = np.random.default_rng(0)
        bf = rng.normal(0, 1, (5, 4)).astype(ml_dtypes.bfloat16)
        f32 = rng.normal(0, 1, (3, 2)).astype(np.float32)
        mgr.save(1, {"U": bf, "V": f32}, {"note": "mixed"})
        ck = mgr.restore()
        assert ck["U"].dtype == ml_dtypes.bfloat16
        assert ck["V"].dtype == np.float32
        np.testing.assert_array_equal(
            ck["U"].view(np.uint16), bf.view(np.uint16))
        assert ck.meta == {"note": "mixed"}  # the dtype tag is internal

    def test_no_tmp_litter(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"x": np.zeros(3)})
        leftovers = [p for p in tmp_path.iterdir()
                     if p.suffix == ".tmp"]
        assert leftovers == []


class TestModelRoundtrip:
    def test_mf_model_roundtrip(self, tmp_path):
        gen = SyntheticMFGenerator(num_users=40, num_items=30, rank=4, seed=0)
        train = gen.generate(3000)
        model = DSGD(DSGDConfig(num_factors=6, iterations=3,
                                minibatch_size=128)).fit(train)
        mgr = CheckpointManager(str(tmp_path))
        save_mf_model(mgr, model, step=3)
        restored, ck = restore_mf_model(mgr)
        assert ck.meta["kind"] == "mf_model"
        np.testing.assert_array_equal(np.asarray(restored.U),
                                      np.asarray(model.U))
        # scoring equivalence incl. the id→row lookup tables
        test = gen.generate(500)
        assert abs(restored.rmse(test) - model.rmse(test)) < 1e-6

    def test_online_state_roundtrip(self, tmp_path):
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3, seed=1)
        m = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        for _ in range(4):
            m.partial_fit(gen.generate(500))
        mgr = CheckpointManager(str(tmp_path))
        save_online_state(mgr, m, step=4)

        m2 = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        restore_online_state(mgr, m2)
        assert m2.step == 4
        test = gen.generate(500)
        assert abs(m2.rmse(test) - m.rmse(test)) < 1e-6
        # rows were re-registered in saved order → tables bit-identical
        np.testing.assert_array_equal(
            np.asarray(m2.users.array[: m2.users.num_rows]),
            np.asarray(m.users.array[: m.users.num_rows]))


class TestSegmentedDSGD:
    def test_segmented_equals_straight_run(self, tmp_path):
        """Checkpoint boundaries must not change the math: the t0 offset
        keeps the η/√t schedule continuous across segments."""
        gen = SyntheticMFGenerator(num_users=60, num_items=50, rank=4, seed=2)
        train = gen.generate(4000)
        cfg = DSGDConfig(num_factors=4, iterations=6, seed=0,
                         minibatch_size=128)  # default inverse_sqrt decay
        straight = DSGD(cfg).fit(train, num_blocks=2)

        mgr = CheckpointManager(str(tmp_path))
        segmented = DSGD(cfg).fit(train, num_blocks=2,
                                  checkpoint_manager=mgr,
                                  checkpoint_every=2)
        np.testing.assert_allclose(np.asarray(segmented.U),
                                   np.asarray(straight.U),
                                   rtol=1e-5, atol=1e-6)
        assert mgr.latest_step() == 6

    def test_resume_from_partial(self, tmp_path):
        gen = SyntheticMFGenerator(num_users=60, num_items=50, rank=4, seed=3)
        train = gen.generate(4000)
        cfg = DSGDConfig(num_factors=4, iterations=6, seed=0,
                         minibatch_size=128)
        mgr = CheckpointManager(str(tmp_path))
        # simulate a crash after 4 of 6 iterations
        half_cfg = DSGDConfig(num_factors=4, iterations=4, seed=0,
                              minibatch_size=128)
        DSGD(half_cfg).fit(train, num_blocks=2, checkpoint_manager=mgr,
                           checkpoint_every=2)
        assert mgr.latest_step() == 4

        resumed = DSGD(cfg).fit(train, num_blocks=2, checkpoint_manager=mgr,
                                checkpoint_every=2, resume=True)
        straight = DSGD(cfg).fit(train, num_blocks=2)
        np.testing.assert_allclose(np.asarray(resumed.U),
                                   np.asarray(straight.U),
                                   rtol=1e-5, atol=1e-6)

    def test_bf16_segmented_resume_roundtrips_dtype(self, tmp_path):
        """factor_dtype='bfloat16' through the segmented fit: snapshots
        store half-width tables (bit-view encoded), resume restores them
        AS bf16, and the resumed run equals the straight bf16 run."""
        import jax.numpy as jnp

        gen = SyntheticMFGenerator(num_users=60, num_items=50, rank=4,
                                   seed=7)
        train = gen.generate(4000)
        cfg = DSGDConfig(num_factors=4, iterations=6, seed=0,
                         minibatch_size=128, factor_dtype="bfloat16")
        mgr = CheckpointManager(str(tmp_path))
        half_cfg = DSGDConfig(num_factors=4, iterations=4, seed=0,
                              minibatch_size=128, factor_dtype="bfloat16")
        DSGD(half_cfg).fit(train, num_blocks=2, checkpoint_manager=mgr,
                           checkpoint_every=2)
        ck = mgr.restore()
        assert str(ck["U"].dtype) == "bfloat16"  # half-width at rest

        resumed = DSGD(cfg).fit(train, num_blocks=2,
                                checkpoint_manager=mgr,
                                checkpoint_every=2, resume=True)
        # compare against an UNINTERRUPTED equally-segmented run: bf16
        # tables round once per jitted segment, so only runs with the
        # same segment boundaries are bit-comparable
        mgr2 = CheckpointManager(str(tmp_path / "full"))
        full = DSGD(cfg).fit(train, num_blocks=2, checkpoint_manager=mgr2,
                             checkpoint_every=2)
        assert resumed.U.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(resumed.U).view(np.uint16),
            np.asarray(full.U).view(np.uint16))

    def test_resume_shape_mismatch_raises(self, tmp_path):
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3, seed=4)
        train = gen.generate(1000)
        mgr = CheckpointManager(str(tmp_path))
        DSGD(DSGDConfig(num_factors=4, iterations=2,
                        minibatch_size=64)).fit(
            train, checkpoint_manager=mgr, checkpoint_every=1)
        with pytest.raises(ValueError, match="shape mismatch"):
            DSGD(DSGDConfig(num_factors=8, iterations=2,
                            minibatch_size=64)).fit(
                train, checkpoint_manager=mgr, resume=True)


class TestAdaptiveCheckpoint:
    def test_periodic_snapshot_and_resume(self, tmp_path):
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3, seed=5)
        cfg = AdaptiveMFConfig(num_factors=4, offline_every=None,
                               minibatch_size=64, checkpoint_every=2,
                               checkpoint_dir=str(tmp_path))
        a = AdaptiveMF(cfg)
        for _ in range(5):
            a.process(gen.generate(300))
        assert a._manager.latest_step() is not None

        b = AdaptiveMF(cfg)
        assert b.resume()
        assert b.online.step == a._manager.restore().meta["step"]


class TestMetrics:
    def test_step_timer_blocks_on_device_values(self):
        import jax.numpy as jnp

        t = M.StepTimer("matmul")
        out = []
        with t.time(out):
            out.append(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
        assert t.count == 1 and t.last_s > 0

    def test_throughput_meter(self):
        m = M.ThroughputMeter()
        m.record(1000, 2.0)
        m.record(1000, 2.0)
        assert m.rate == 500.0

    def test_metrics_log(self):
        log = M.MetricsLog(log_to=None)
        log.log("epoch", rmse=0.1)
        log.log("epoch", rmse=0.05)
        log.log("other", x=1)
        assert [r["rmse"] for r in log.of("epoch")] == [0.1, 0.05]

    def test_profile_noop_without_dir(self):
        with M.profile(None):
            pass

    def test_profile_writes_trace(self, tmp_path):
        import jax.numpy as jnp

        with M.profile(str(tmp_path)):
            (jnp.ones((32, 32)) @ jnp.ones((32, 32))).block_until_ready()
        assert any(tmp_path.rglob("*"))


class TestSegmentedMeshDSGD:
    """Same checkpoint contract on the multi-chip driver (VERDICT r2 #7):
    segment boundaries and resume must not change the math on the mesh."""

    def _mesh_cfg(self):
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
            MeshDSGDConfig,
        )

        return MeshDSGDConfig(num_factors=4, iterations=6, seed=0,
                              minibatch_size=64)  # default η/√t decay

    def test_segmented_equals_straight_run(self, tmp_path):
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import MeshDSGD
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4, seed=5)
        train = gen.generate(4000)
        straight = MeshDSGD(self._mesh_cfg()).fit(train)

        mgr = ShardedCheckpointManager(str(tmp_path))
        segmented = MeshDSGD(self._mesh_cfg()).fit(
            train, checkpoint_manager=mgr, checkpoint_every=2)
        np.testing.assert_allclose(np.asarray(segmented.U),
                                   np.asarray(straight.U),
                                   rtol=1e-5, atol=1e-6)
        assert mgr.latest_step() == 6
        # the save path must be shard files + manifest, and must never
        # write a monolithic full-model snapshot
        import os as _os
        names = sorted(_os.listdir(tmp_path))
        assert any(".shard0of" in n for n in names), names
        assert any(n.endswith(".manifest.json") for n in names), names
        assert not any(n.endswith(".npz") and ".shard" not in n
                       for n in names), names

    def test_resume_from_partial(self, tmp_path):
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
            MeshDSGD,
            MeshDSGDConfig,
        )
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4, seed=6)
        train = gen.generate(4000)
        mgr = ShardedCheckpointManager(str(tmp_path))
        half = MeshDSGDConfig(num_factors=4, iterations=4, seed=0,
                              minibatch_size=64)
        MeshDSGD(half).fit(train, checkpoint_manager=mgr, checkpoint_every=2)
        assert mgr.latest_step() == 4

        resumed = MeshDSGD(self._mesh_cfg()).fit(
            train, checkpoint_manager=mgr, checkpoint_every=2, resume=True)
        straight = MeshDSGD(self._mesh_cfg()).fit(train)
        np.testing.assert_allclose(np.asarray(resumed.U),
                                   np.asarray(straight.U),
                                   rtol=1e-5, atol=1e-6)

    def test_bf16_sharded_roundtrip(self, tmp_path):
        """factor_dtype='bfloat16' on the mesh driver: shard files carry
        the bit-view encoding, restore re-views to bf16, resume matches
        the uninterrupted equally-segmented run bit-exactly."""
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
            MeshDSGD,
            MeshDSGDConfig,
        )
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        def cfg(iters):
            return MeshDSGDConfig(num_factors=4, iterations=iters, seed=0,
                                  minibatch_size=64,
                                  factor_dtype="bfloat16")

        gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4,
                                   seed=8)
        train = gen.generate(4000)
        mgr = ShardedCheckpointManager(str(tmp_path / "a"))
        MeshDSGD(cfg(4)).fit(train, checkpoint_manager=mgr,
                             checkpoint_every=2)
        resumed = MeshDSGD(cfg(6)).fit(train, checkpoint_manager=mgr,
                                       checkpoint_every=2, resume=True)
        mgr2 = ShardedCheckpointManager(str(tmp_path / "b"))
        full = MeshDSGD(cfg(6)).fit(train, checkpoint_manager=mgr2,
                                    checkpoint_every=2)
        assert resumed.U.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(resumed.U).view(np.uint16),
            np.asarray(full.U).view(np.uint16))
        np.testing.assert_array_equal(
            np.asarray(resumed.V).view(np.uint16),
            np.asarray(full.V).view(np.uint16))

    def test_plain_manager_is_retargeted_to_sharded_format(self, tmp_path):
        """API compatibility: passing a plain CheckpointManager to the mesh
        driver writes the sharded format into the same directory (and a
        ShardedCheckpointManager on that directory can resume from it)."""
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import MeshDSGD
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4, seed=7)
        train = gen.generate(4000)
        mgr = CheckpointManager(str(tmp_path))
        MeshDSGD(self._mesh_cfg()).fit(
            train, checkpoint_manager=mgr, checkpoint_every=3)
        assert ShardedCheckpointManager(str(tmp_path)).latest_step() == 6


class TestShardedManagerGuards:
    def test_legacy_monolithic_dir_refused_on_resume(self, tmp_path):
        """A directory of old-format monolithic snapshots must not be
        silently restarted-over (and later swept) by the sharded manager."""
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        old = CheckpointManager(str(tmp_path))
        old.save(3, {"U": np.zeros((4, 2), np.float32),
                     "V": np.zeros((4, 2), np.float32)}, {"kind": "host"})
        mgr = ShardedCheckpointManager(str(tmp_path))
        with pytest.raises(ValueError, match="legacy monolithic"):
            restore_segment_state_sharded(mgr, "host",
                                          np.zeros((4, 2), np.float32),
                                          np.zeros((4, 2), np.float32))

    def test_column_sharding_round_trips_dim2_refused(self, tmp_path):
        """Pieces are keyed (row_start, col_start): dim-0 AND dim-1
        sharding round-trip (the rank-sharded factor layout, ISSUE 16).
        Sharding over dimensions ≥ 2 would still alias offsets and
        silently drop slabs — save must refuse it loudly."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        devs = jax.devices("cpu")[:2]
        mesh = Mesh(np.asarray(devs), ("m",))
        want = np.arange(32, dtype=np.float32).reshape(4, 8)
        col_shd = NamedSharding(mesh, P(None, "m"))
        cols = jax.device_put(want, col_shd)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, {"U": cols}, {})
        got = mgr.restore_array(1, "U", col_shd, want.shape, want.dtype)
        np.testing.assert_array_equal(np.asarray(got), want)

        deep = jax.device_put(
            np.ones((4, 8, 2), np.float32),
            NamedSharding(mesh, P(None, None, "m")))
        with pytest.raises(ValueError, match="dim"):
            mgr.save(2, {"W": deep}, {})

    def test_restore_array_only_reads_overlapping_pieces(self, tmp_path):
        """Round-trip on an uneven host stand-in: restore serves each
        device range from the right pieces and errors on missing rows."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        devs = jax.devices("cpu")[:4]
        mesh = Mesh(np.asarray(devs), ("m",))
        shard = NamedSharding(mesh, P("m"))
        rng = np.random.default_rng(0)
        A = rng.normal(size=(16, 3)).astype(np.float32)
        g = jax.device_put(A, shard)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(2, {"U": g}, {"kind": "k"})
        back = mgr.restore_array(2, "U", shard, (16, 3), np.float32)
        np.testing.assert_array_equal(np.asarray(back), A)
        # shape drift is a loud error
        with pytest.raises(ValueError, match="shape"):
            mgr.restore_array(2, "U", shard, (20, 3), np.float32)


class TestShardedManagerFuzz:
    def test_random_layout_roundtrips(self, tmp_path):
        """Randomized shard layouts: any (rows, rank, mesh size) with dim-0
        sharding must round-trip exactly through per-shard save/restore,
        including restore into a DIFFERENT valid mesh size (re-sharding is
        the manager's contract — shard files store global row offsets)."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        rng = np.random.default_rng(0)
        devs = jax.devices("cpu")
        for trial in range(6):
            n_dev = int(rng.choice([d for d in (1, 2, 4, 8)
                                    if d <= len(devs)]))
            rank = int(rng.integers(1, 9))
            rows = n_dev * int(rng.integers(1, 40))
            mesh = Mesh(np.asarray(devs[:n_dev]), ("m",))
            shard = NamedSharding(mesh, P("m"))
            A = rng.normal(size=(rows, rank)).astype(np.float32)
            d = str(tmp_path / f"t{trial}")
            mgr = ShardedCheckpointManager(d)
            mgr.save(1, {"U": jax.device_put(A, shard)}, {"kind": "f"})
            back = mgr.restore_array(1, "U", shard, (rows, rank),
                                     np.float32)
            np.testing.assert_array_equal(np.asarray(back), A)
            # restore into a different mesh size that divides rows
            others = [d2 for d2 in (1, 2, 4)
                      if d2 <= len(devs) and rows % d2 == 0
                      and d2 != n_dev]
            if others:
                n2 = others[0]
                mesh2 = Mesh(np.asarray(devs[:n2]), ("m",))
                shard2 = NamedSharding(mesh2, P("m"))
                back2 = mgr.restore_array(1, "U", shard2, (rows, rank),
                                          np.float32)
                np.testing.assert_array_equal(np.asarray(back2), A)


class TestIncompleteCheckpointSurfacing:
    """ADVICE r4 #4: a manifest whose shard files are missing (crashed
    save) must be invisible to steps() but LOUD on restore."""

    def test_incomplete_step_warns_and_falls_back(self, tmp_path):
        import json
        import warnings

        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        devs = jax.devices("cpu")[:4]
        mesh = Mesh(np.asarray(devs), ("m",))
        shard = NamedSharding(mesh, P("m"))
        U = jax.device_put(np.arange(32.0, dtype=np.float32).reshape(8, 4),
                           shard)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(3, {"U": U, "V": U}, {"kind": "t"})
        # simulate a crashed newer save: manifest exists, shard missing
        with open(tmp_path / "ckpt_9.manifest.json", "w") as f:
            json.dump({"step": 9, "nproc": 1,
                       "shards": ["ckpt_9.shard0of1.npz"],
                       "arrays": {}, "meta": {"kind": "t"}}, f)
        assert mgr.steps() == [3]
        assert mgr.incomplete_steps() == [9]
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            U2, _, done = restore_segment_state_sharded(
                mgr, "t", U, U, sharding=shard)
        assert done == 3
        assert any("incomplete" in str(x.message) for x in w)
        np.testing.assert_array_equal(np.asarray(U2), np.asarray(U))

    def test_older_incomplete_step_does_not_warn(self, tmp_path):
        """A retired/incomplete step OLDER than the latest complete one is
        normal retention debris — no warning."""
        import json
        import warnings

        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        devs = jax.devices("cpu")[:2]
        mesh = Mesh(np.asarray(devs), ("m",))
        shard = NamedSharding(mesh, P("m"))
        U = jax.device_put(np.arange(16.0, dtype=np.float32).reshape(8, 2),
                           shard)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(5, {"U": U, "V": U}, {"kind": "t"})
        with open(tmp_path / "ckpt_2.manifest.json", "w") as f:
            json.dump({"step": 2, "nproc": 1,
                       "shards": ["ckpt_2.shard0of1.npz"],
                       "arrays": {}, "meta": {"kind": "t"}}, f)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            _, _, done = restore_segment_state_sharded(
                mgr, "t", U, U, sharding=shard)
        assert done == 5
        assert not [x for x in w if "incomplete" in str(x.message)]
