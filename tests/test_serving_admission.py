"""Admission control: the SLO-burn brownout ladder under injected burn.

Every transition the ladder can make is driven here by stuffing an
``SLOTracker`` window with synthetic latencies (the injected-SLO-burn
acceptance): escalation jumps straight to the warranted level, recovery
steps down through hysteresis, warmup can't trip it, shedding raises
the typed error (with the probe fraction that lets the window refresh),
and the engine integration serves stage-1-only ``degraded`` results at
the degrade level.
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu.obs.events import (
    EventJournal,
    set_events,
)
from large_scale_recommendation_tpu.obs.health import SLOTracker
from large_scale_recommendation_tpu.obs.registry import MetricsRegistry
from large_scale_recommendation_tpu.serving import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejectedError,
    RetrievalConfig,
    ServingEngine,
)
from large_scale_recommendation_tpu.serving.admission import (
    DEGRADE,
    NORMAL,
    SHED,
    WIDEN,
)


def make_tracker(objective=0.9, window=32):
    # null registry by default: these tests pass explicit registries
    # where they assert on metrics
    return SLOTracker(target_s=0.1, objective=objective, window=window)


def burn_to(slo: SLOTracker, violation_frac: float, n: int = 32):
    """Fill the window to an exact violation fraction (burn =
    frac / (1 - objective))."""
    n_viol = int(round(violation_frac * n))
    for i in range(n):
        slo.record(1.0 if i < n_viol else 0.01)


class TestLadder:
    def test_escalates_directly_to_warranted_level(self):
        slo = make_tracker()  # 1-obj = 0.1: frac 0.5 -> burn 5 >= shed
        ctl = AdmissionController(slo, AdmissionConfig())
        burn_to(slo, 0.5)
        assert ctl.observe() == SHED
        assert ctl.level == SHED
        assert ctl.transitions == 1  # jumped, not laddered

    def test_each_threshold_maps_to_its_level(self):
        cfg = AdmissionConfig()
        for frac, expect in ((0.05, NORMAL), (0.15, WIDEN),
                             (0.25, DEGRADE), (0.45, SHED)):
            slo = make_tracker()
            ctl = AdmissionController(slo, cfg)
            burn_to(slo, frac)
            assert ctl.observe() == expect, (frac, expect)

    def test_warmup_window_cannot_trip(self):
        slo = make_tracker()
        ctl = AdmissionController(slo, AdmissionConfig(min_samples=8))
        for _ in range(7):  # all violations, but under min_samples
            slo.record(1.0)
        assert ctl.observe() == NORMAL
        slo.record(1.0)  # 8th sample arms the ladder
        assert ctl.observe() == SHED

    def test_recovery_steps_down_with_hysteresis(self):
        slo = make_tracker(window=20)
        ctl = AdmissionController(slo, AdmissionConfig())
        burn_to(slo, 0.5, n=20)
        assert ctl.observe() == SHED
        # burn just under the shed threshold: hysteresis holds the level
        burn_to(slo, 0.3, n=20)  # burn 3 >= 4*0.7=2.8 -> hold
        assert ctl.observe() == SHED
        # below recover_ratio * shed_burn: ONE step down, not a jump
        burn_to(slo, 0.15, n=20)  # burn 1.5 < 2.8 -> step to degrade
        assert ctl.observe() == DEGRADE
        burn_to(slo, 0.0, n=20)
        assert ctl.observe() == WIDEN  # stepwise…
        assert ctl.observe() == NORMAL  # …not instant

    def test_shed_raises_typed_error_with_probe_fraction(self):
        slo = make_tracker()
        ctl = AdmissionController(
            slo, AdmissionConfig(shed_probe=0.25))
        burn_to(slo, 0.6)
        ctl.observe()
        outcomes = []
        for _ in range(20):
            try:
                ctl.check_admit()
                outcomes.append("admit")
            except AdmissionRejectedError as e:
                assert e.level == SHED and e.burn > 4
                outcomes.append("shed")
        # every 4th request is the recovery probe
        assert outcomes.count("admit") == 5
        assert ctl.sheds == 15

    def test_transition_events_and_metrics(self):
        reg = MetricsRegistry()
        journal = EventJournal(registry=reg)
        set_events(journal)
        try:
            slo = SLOTracker(target_s=0.1, objective=0.9, window=32,
                             registry=reg)
            ctl = AdmissionController(slo, AdmissionConfig(),
                                      registry=reg)
            burn_to(slo, 0.5)
            ctl.observe()
            burn_to(slo, 0.0)
            ctl.observe()
            events = journal.events(kind="serving.admission_transition")
            assert len(events) == 2
            up, down = events
            assert up["severity"] == "warning"
            assert up["detail"]["from_level"] == NORMAL
            assert up["detail"]["to_level"] == SHED
            assert down["severity"] == "info"
            snap = reg.snapshot()
            gauges = {(m["name"], tuple(sorted(m["labels"].items()))):
                      m["value"] for m in snap["metrics"]}
            assert gauges[("serving_admission_level", ())] == 2.0
            assert ("serving_admission_transitions_total",
                    (("from_level", "normal"),
                     ("to_level", "shed"))) in gauges
        finally:
            set_events(None)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="ordered"):
            AdmissionConfig(widen_burn=3.0, degrade_burn=2.0)
        with pytest.raises(ValueError, match="recover_ratio"):
            AdmissionConfig(recover_ratio=1.5)
        with pytest.raises(ValueError, match="widen_factor"):
            AdmissionConfig(widen_factor=0.5)
        with pytest.raises(ValueError, match="shed_probe"):
            AdmissionConfig(shed_probe=0.0)

    def test_widen_factor_tracks_level(self):
        slo = make_tracker()
        ctl = AdmissionController(slo,
                                  AdmissionConfig(widen_factor=3.0))
        assert ctl.widen_factor == 1.0
        burn_to(slo, 0.15)
        ctl.observe()
        assert ctl.level == WIDEN and ctl.widen_factor == 3.0
        assert not ctl.degrade_active


class TestEngineIntegration:
    def _model(self):
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data.blocking import (
            flat_index,
        )
        from large_scale_recommendation_tpu.models.mf import MFModel

        rng = np.random.default_rng(20)
        return MFModel(
            U=jnp.asarray(rng.normal(size=(50, 8)).astype(np.float32)),
            V=jnp.asarray(rng.normal(size=(256, 8)).astype(np.float32)),
            users=flat_index(np.arange(50, dtype=np.int64)),
            items=flat_index(np.arange(256, dtype=np.int64)))

    def test_degrade_serves_stage1_only_flagged(self):
        """At the degrade level a two-stage engine serves stage-1-only
        results and flags them — and the flag clears on recovery."""
        slo = make_tracker()
        ctl = AdmissionController(slo, AdmissionConfig())
        eng = ServingEngine(self._model(), k=5,
                            retrieval=RetrievalConfig(overfetch=4),
                            admission=ctl)
        res = eng.recommend(np.arange(10))
        assert res.degraded is False
        burn_to(slo, 0.25)  # burn 2.5: degrade band
        ctl.observe()
        res = eng.recommend(np.arange(10))
        assert res.degraded is True
        assert (res[0] >= -1).all()  # plausible ids either way
        eng.admission.count_degraded(0)  # no-op guard
        burn_to(slo, 0.0)
        ctl.observe()
        ctl.observe()
        res = eng.recommend(np.arange(10))
        assert res.degraded is False

    def test_shed_rejects_submit_and_recovers(self):
        """An engine at shed rejects new submits with the typed error;
        the probe fraction keeps flushes flowing so fast service brings
        the ladder back down and admits resume."""
        slo = make_tracker()
        ctl = AdmissionController(slo,
                                  AdmissionConfig(shed_probe=0.5))
        eng = ServingEngine(self._model(), k=5, admission=ctl)
        burn_to(slo, 0.6)
        ctl.observe()
        rejected = admitted = 0
        for _ in range(40):
            try:
                eng.recommend(np.arange(4))
                admitted += 1
            except AdmissionRejectedError:
                rejected += 1
        assert rejected > 0 and admitted > 0
        # probe flushes recorded REAL (fast) latencies: the window
        # refreshed and the ladder stepped down from shed
        assert ctl.level != SHED

    def test_serve_returns_shed_markers_in_order(self):
        """A mid-stream shed must not discard computed results or
        orphan tickets: serve() slots the AdmissionRejectedError
        instance where the shed request's result would be, and every
        served request still gets ITS OWN answer."""
        slo = make_tracker()
        ctl = AdmissionController(slo,
                                  AdmissionConfig(shed_probe=0.5))
        model = self._model()
        eng = ServingEngine(model, k=4, max_batch=16, admission=ctl)
        burn_to(slo, 0.6)
        ctl.observe()
        assert ctl.level == SHED
        reqs = [np.arange(i, i + 3) for i in range(12)]
        out = eng.serve(reqs)
        assert len(out) == len(reqs)
        sheds = [r for r in out if isinstance(r, AdmissionRejectedError)]
        served = [(i, r) for i, r in enumerate(out)
                  if not isinstance(r, AdmissionRejectedError)]
        assert sheds and served  # probe fraction admitted some
        for i, r in served:  # alignment: each got its own answer
            ids0, _ = model.recommend(reqs[i], k=4)
            np.testing.assert_array_equal(r[0], ids0)
        assert eng._pending == []  # no orphan tickets left behind

    def test_attach_admission_swap_rebinds_adopted_tracker(self):
        """Swapping controllers on a live engine rebinds the ADOPTED
        tracker: flush latencies must feed the ladder that's actually
        deciding, or the new controller starves below its warmup guard
        and never escalates."""
        eng = ServingEngine(self._model(), k=4)
        c1 = AdmissionController(make_tracker(), AdmissionConfig())
        eng.attach_admission(c1)
        eng.recommend(np.arange(4))
        assert c1.slo.count > 0
        c2 = AdmissionController(make_tracker(), AdmissionConfig())
        eng.attach_admission(c2)
        before = c2.slo.count
        eng.recommend(np.arange(4))
        assert c2.slo.count > before  # the NEW ladder sees the burn

    def test_engine_adopts_controller_tracker(self):
        slo = make_tracker()
        ctl = AdmissionController(slo, AdmissionConfig())
        eng = ServingEngine(self._model(), k=5, admission=ctl)
        assert eng._slo is slo  # flush walls feed the ladder's burn
        eng.recommend(np.arange(5))
        assert slo.count > 0

    def test_attach_admission_on_live_engine(self):
        eng = ServingEngine(self._model(), k=5)
        assert eng.admission is None
        slo = make_tracker()
        ctl = AdmissionController(slo, AdmissionConfig())
        eng.attach_admission(ctl)
        assert eng.admission is ctl and eng._slo is slo
        eng.recommend(np.arange(5))
        assert slo.count > 0

    def test_widen_threshold_stretches_serve_coalescing(self):
        """At widen, serve() coalesces up to widen_factor × max_batch
        rows per flush: fewer flushes for the same stream. A pinned
        fake tracker holds the ladder at each level — real latencies
        (warmup compiles, CI machine speed) must not steer this test."""

        class PinnedSLO:
            burn = 0.0
            count = 0

            def record(self, latency_s):
                self.count += 1

            @property
            def burn_rate(self):
                return self.burn

            def snapshot(self):
                return {"burn_rate": self.burn, "window_fill": 32,
                        "attainment": 1.0, "count": self.count}

        slo = PinnedSLO()
        ctl = AdmissionController(slo,
                                  AdmissionConfig(widen_factor=4.0))
        eng = ServingEngine(self._model(), k=5, max_batch=16,
                            admission=ctl)
        reqs = [np.arange(8) for _ in range(16)]  # 128 rows
        eng.serve(reqs)
        assert ctl.level == NORMAL
        normal_flushes = eng.stats["flushes"]
        slo.burn = 1.5  # the pin: widen band, held there
        ctl.observe()
        assert ctl.level == WIDEN
        eng.stats["flushes"] = 0
        eng.serve(reqs)
        widened_flushes = eng.stats["flushes"]
        # the bucket family (micro-batch shapes) is untouched — widening
        # coalesces MORE rows per flush, so the same stream takes fewer
        # dispatch+drain round-trips
        assert widened_flushes < normal_flushes
