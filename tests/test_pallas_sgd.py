"""Pallas DSGD block-sweep: interpret-mode parity against the XLA kernel.

The Pallas kernel (ops/pallas_sgd.py) exists to attack the measured HBM
row-gather ceiling on real TPU hardware; on CPU we can only pin its MATH.
These tests run it in interpreter mode and require exact agreement with
``ops.sgd.sgd_block_sweep`` under the same updater rule — including
duplicate rows inside a minibatch (the sequential RMW scatter must
accumulate like ``.at[].add``) and weight-0 padding no-ops. Throughput is
measured by scripts/pallas_probe.py on the device that matters.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from large_scale_recommendation_tpu.core.updaters import (
    RegularizedSGDUpdater,
    constant_lr,
)
from large_scale_recommendation_tpu.ops import sgd as sgd_ops
from large_scale_recommendation_tpu.ops.pallas_sgd import pallas_block_sweep


def _problem(seed, e, rpb_u, rpb_v, rank, pad_frac=0.0):
    rng = np.random.default_rng(seed)
    ur = rng.integers(0, rpb_u, e).astype(np.int32)
    ir = rng.integers(0, rpb_v, e).astype(np.int32)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    if pad_frac:
        w[rng.random(e) < pad_frac] = 0.0
    U = rng.normal(0, 0.1, (rpb_u, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (rpb_v, rank)).astype(np.float32)
    omega_u = np.maximum(
        np.bincount(ur, weights=w, minlength=rpb_u), 0).astype(np.float32)
    omega_v = np.maximum(
        np.bincount(ir, weights=w, minlength=rpb_v), 0).astype(np.float32)
    return ur, ir, vals, w, U, V, omega_u, omega_v


def _inv_counts(rows, w, mb):
    """Per-entry 1/occurrence within each minibatch (the precomputed
    collision scales, data.blocking.minibatch_inv_counts semantics)."""
    inv = np.ones_like(w)
    for s in range(0, len(rows), mb):
        sl = slice(s, s + mb)
        cnt = {}
        for r, ww in zip(rows[sl], w[sl]):
            if ww > 0:
                cnt[r] = cnt.get(r, 0) + 1
        inv[sl] = [1.0 / max(cnt.get(r, 1), 1) if ww > 0 else 1.0
                   for r, ww in zip(rows[sl], w[sl])]
    return inv.astype(np.float32)


@pytest.mark.parametrize("gather", ["take", "loop"])
@pytest.mark.parametrize("pad_frac", [0.0, 0.15])
def test_matches_xla_kernel(gather, pad_frac):
    lr, lam, mb, rank = 0.1, 0.05, 64, 8
    ur, ir, vals, w, U, V, ou, ov = _problem(0, 256, 40, 24, rank,
                                             pad_frac)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)

    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=lam,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V),
        jnp.asarray(ur), jnp.asarray(ir), jnp.asarray(vals),
        jnp.asarray(w), jnp.asarray(ou), jnp.asarray(ov),
        upd, 1, mb, "mean", jnp.asarray(icu), jnp.asarray(icv))

    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=lam, minibatch=mb, gather=gather, interpret=True)

    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def test_duplicate_rows_accumulate_not_overwrite():
    """Many entries hitting ONE row in the same minibatch: the scatter
    must behave like .at[].add (a bulk last-write-wins store would keep
    only one delta)."""
    lr, mb, rank = 0.1, 16, 4
    e = 16
    ur = np.zeros(e, np.int32)  # every entry → row 0
    ir = np.arange(e, dtype=np.int32)
    rng = np.random.default_rng(1)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    U = rng.normal(0, 0.1, (4, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (e, rank)).astype(np.float32)
    ou = np.maximum(np.bincount(ur, minlength=4), 1).astype(np.float32)
    ov = np.ones(e, np.float32)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)

    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=0.05,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(ou),
        jnp.asarray(ov), upd, 1, mb, "mean",
        jnp.asarray(icu), jnp.asarray(icv))
    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=0.05, minibatch=mb, gather="loop", interpret=True)
    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def test_minibatch_boundary_visibility():
    """Minibatch t+1 must read rows written by minibatch t (the lax.scan
    carry semantics) — two minibatches hitting the same row."""
    lr, mb, rank = 0.2, 8, 4
    e = 16  # two minibatches
    ur = np.full(e, 2, np.int32)
    ir = np.arange(e, dtype=np.int32) % 8
    rng = np.random.default_rng(2)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    U = rng.normal(0, 0.1, (4, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (8, rank)).astype(np.float32)
    ou = np.maximum(np.bincount(ur, minlength=4), 1).astype(np.float32)
    ov = np.maximum(np.bincount(ir, minlength=8), 1).astype(np.float32)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)
    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=0.05,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(ou),
        jnp.asarray(ov), upd, 1, mb, "mean",
        jnp.asarray(icu), jnp.asarray(icv))
    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=0.05, minibatch=mb, gather="take", interpret=True)
    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def _full_training_pair(minibatch_divisor: int, schedule, iters: int = 3,
                        t0: int = 0, gather: str = "loop"):
    """Run ops.sgd.dsgd_train and dsgd_train_pallas on the same blocked
    problem; ``minibatch = block_size // minibatch_divisor``. Returns
    ((Uref, Vref), (Up, Vp))."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.data import blocking
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    gen = SyntheticMFGenerator(num_users=48, num_items=40, rank=4,
                               noise=0.1, seed=0)
    train = gen.generate(2000)
    k = 2
    b = blocking.block_problem(train, num_blocks=k, seed=0,
                               minibatch_multiple=1).ratings.u_rows.shape[-1]
    # pad the block to a multiple of the divisor so mb divides b exactly
    mb_mult = -(-b // minibatch_divisor)
    problem = blocking.block_problem(train, num_blocks=k, seed=0,
                                     minibatch_multiple=mb_mult)
    b = problem.ratings.u_rows.shape[-1]
    mb = b // minibatch_divisor
    icu, icv = blocking.minibatch_inv_counts(problem.ratings, mb)
    U0, V0 = DSGD(DSGDConfig(num_factors=8, seed=0,
                             init_scale=0.2))._init_factors(problem)
    lr, lam = 0.05, 0.1
    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=lam,
                                schedule=schedule)
    args = (jnp.asarray(problem.ratings.u_rows, jnp.int32),
            jnp.asarray(problem.ratings.i_rows, jnp.int32),
            jnp.asarray(problem.ratings.values, jnp.float32),
            jnp.asarray(problem.ratings.weights, jnp.float32))
    common = (jnp.asarray(U0), jnp.asarray(V0), *args,
              jnp.asarray(problem.users.omega),
              jnp.asarray(problem.items.omega),
              jnp.asarray(icu), jnp.asarray(icv))
    Uref, Vref = sgd_ops.dsgd_train(
        *common, updater=upd, minibatch=mb, num_blocks=k,
        iterations=iters, collision="mean", t0=t0)
    # same positional order as dsgd_train (drop-in twin)
    Up, Vp = dsgd_train_pallas(
        *common, lr=lr, lam=lam, minibatch=mb, num_blocks=k,
        iterations=iters, gather=gather, interpret=True,
        schedule=None if schedule is constant_lr else schedule, t0=t0)
    return (Uref, Vref), (Up, Vp)


@pytest.mark.parametrize("gather", ["take", "loop"])
@pytest.mark.parametrize("divisor", [1, 4])
def test_full_training_matches_dsgd_train(divisor, gather):
    """dsgd_train_pallas (all strata × blocks × sweeps under one scan)
    must equal ops.sgd.dsgd_train — at minibatch == block size (divisor
    1: flat-stratum minibatches coincide with per-block visits) AND at
    minibatch < block size (divisor 4: the stratum-major layout deals
    entries block-major, so the flat chunk order still matches the
    per-block minibatch order) — on both gather paths (loop is the
    production path; take awaits a Mosaic that can gather across vregs)."""
    (Uref, Vref), (Up, Vp) = _full_training_pair(divisor, constant_lr,
                                                 gather=gather)
    np.testing.assert_allclose(np.asarray(Up), np.asarray(Uref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(Vp), np.asarray(Vref),
                               rtol=2e-5, atol=2e-6)


def test_dsgd_kernel_flag_routes_through_pallas():
    """DSGDConfig(kernel='pallas') must produce the same model as the XLA
    kernel through the PUBLIC fit surface (segmented twice to exercise the
    t0 continuation), and reject configurations the Pallas rule can't
    honor."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4,
                               noise=0.1, seed=1)
    train = gen.generate(3000)
    kw = dict(num_factors=8, lambda_=0.05, iterations=4,
              learning_rate=0.05, lr_schedule="inverse_sqrt", seed=0,
              minibatch_size=128, init_scale=0.3)
    mx = DSGD(DSGDConfig(**kw, kernel="xla")).fit(train, num_blocks=2)
    mp = DSGD(DSGDConfig(**kw, kernel="pallas")).fit(train, num_blocks=2)
    np.testing.assert_allclose(np.asarray(mp.U), np.asarray(mx.U),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(mp.V), np.asarray(mx.V),
                               rtol=2e-4, atol=2e-5)

    with pytest.raises(ValueError, match="pallas"):
        DSGD(DSGDConfig(**{**kw, "collision_mode": "sum"},
                        kernel="pallas")).fit(train, num_blocks=2)
    with pytest.raises(ValueError, match="kernel"):
        DSGD(DSGDConfig(**kw, kernel="tensorcore")).fit(train,
                                                        num_blocks=2)


def test_full_training_schedule_parity():
    """A decaying η/√t schedule with a nonzero t0 (checkpoint-segment
    continuation) must match the XLA path exactly — the schedule is
    evaluated at trace level and enters the kernel as a runtime scalar."""
    from large_scale_recommendation_tpu.core.updaters import inverse_sqrt_lr

    (Uref, Vref), (Up, Vp) = _full_training_pair(
        2, inverse_sqrt_lr, iters=3, t0=5)
    np.testing.assert_allclose(np.asarray(Up), np.asarray(Uref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(Vp), np.asarray(Vref),
                               rtol=2e-5, atol=2e-6)


# -- ISSUE 6: double-buffered stratum pipeline + bf16 factor storage -------


def _blocked_training_args(k=3, divisor=4, seed=0):
    """A small blocked problem in dsgd_train_pallas positional layout."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.data import blocking
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=48, num_items=40, rank=4,
                               noise=0.1, seed=seed)
    train = gen.generate(2000)
    b = blocking.block_problem(train, num_blocks=k, seed=0,
                               minibatch_multiple=1).ratings.u_rows.shape[-1]
    problem = blocking.block_problem(train, num_blocks=k, seed=0,
                                     minibatch_multiple=-(-b // divisor))
    b = problem.ratings.u_rows.shape[-1]
    mb = b // divisor
    icu, icv = blocking.minibatch_inv_counts(problem.ratings, mb)
    U0, V0 = DSGD(DSGDConfig(num_factors=8, seed=0,
                             init_scale=0.2))._init_factors(problem)
    common = (jnp.asarray(U0), jnp.asarray(V0),
              jnp.asarray(problem.ratings.u_rows, jnp.int32),
              jnp.asarray(problem.ratings.i_rows, jnp.int32),
              jnp.asarray(problem.ratings.values, jnp.float32),
              jnp.asarray(problem.ratings.weights, jnp.float32),
              jnp.asarray(problem.users.omega),
              jnp.asarray(problem.items.omega),
              jnp.asarray(icu), jnp.asarray(icv))
    return common, mb, k


def test_pipeline_matches_per_block_exactly():
    """The double-buffered stratum kernel is the SAME schedule as the
    sequential per-block path — only the copy/compute overlap differs —
    so the two must agree BIT-EXACTLY (and with the XLA reference to
    float tolerance), including at n_mb == 1 (prologue and epilogue in
    the same grid step)."""
    from large_scale_recommendation_tpu.core.updaters import (
        RegularizedSGDUpdater,
        constant_lr,
    )
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    for divisor in (1, 4):  # n_mb == 1 and n_mb > 1
        common, mb, k = _blocked_training_args(divisor=divisor)
        kw = dict(lr=0.05, lam=0.1, minibatch=mb, num_blocks=k,
                  iterations=3, gather="loop", interpret=True)
        Up, Vp = dsgd_train_pallas(*common, **kw, pipeline=True)
        Ub, Vb = dsgd_train_pallas(*common, **kw, pipeline=False)
        assert jnp.array_equal(Up, Ub) and jnp.array_equal(Vp, Vb)

        upd = RegularizedSGDUpdater(learning_rate=0.05, lambda_=0.1,
                                    schedule=constant_lr)
        Uref, Vref = sgd_ops.dsgd_train(
            *common, updater=upd, minibatch=mb, num_blocks=k,
            iterations=3, collision="mean", t0=0)
        np.testing.assert_allclose(np.asarray(Up), np.asarray(Uref),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(Vp), np.asarray(Vref),
                                   rtol=2e-5, atol=2e-6)


def test_pipeline_rejects_take_gather():
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    common, mb, k = _blocked_training_args()
    with pytest.raises(ValueError, match="loop"):
        dsgd_train_pallas(*common, lr=0.05, lam=0.1, minibatch=mb,
                          num_blocks=k, iterations=1, gather="take",
                          interpret=True, pipeline=True)


def test_stratum_pipeline_budget_operating_points():
    """The budget model admits the AOT-calibrated ML-25M production
    points (k=32 at mb ≤ 1024; k=64 at mb 2048, both dtypes) and
    rejects the measured VMEM-stack OOM geometries (k=32 at mb 2048,
    every k=16 point) — the routing contract docs/PERF.md records."""
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        stratum_pipeline_budget,
    )

    def fits(rpb_u, rpb_v, e, fac_bytes, mb=2048, rank=128):
        vmem_mb, smem_kb = stratum_pipeline_budget(
            rpb_u, rpb_v, rank, e, mb, fac_bytes)
        return vmem_mb <= 14 and smem_kb <= 900

    assert fits(5080, 1848, 24576, 4, mb=1024)  # k=32 f32 (AOT: compiles)
    assert fits(2540, 924, 6144, 4)     # k=64 f32 (AOT: compiles)
    assert fits(2540, 924, 6144, 2)     # k=64 bf16 (AOT: compiles)
    assert not fits(5080, 1848, 24576, 4)  # k=32 f32 mb2048: VMEM OOM
    assert not fits(5080, 1848, 24576, 2)  # k=32 bf16 mb2048: VMEM OOM
    assert not fits(10160, 3696, 92160, 4)  # k=16 f32: VMEM + SMEM
    assert not fits(10160, 3696, 92160, 2)  # k=16 bf16: SMEM (1.4 MB)


def test_bf16_training_parity_and_rmse():
    """factor_dtype='bfloat16' (half-width tables, f32 accumulation)
    converges to an RMSE within tolerance of the f32 run on BOTH
    kernels, through the public fit surface — and the fitted tables
    carry the storage dtype."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4,
                               noise=0.1, seed=1)
    train = gen.generate(3000)
    test = gen.generate(500)
    kw = dict(num_factors=8, lambda_=0.05, iterations=6,
              learning_rate=0.05, lr_schedule="inverse_sqrt", seed=0,
              minibatch_size=128, init_scale=0.3)

    def rmse(model):
        pred, mask = model.predict(test.users, test.items,
                                   return_mask=True)
        err = (np.asarray(pred, np.float64)
               - np.asarray(test.ratings, np.float64)) * np.asarray(mask)
        return float(np.sqrt((err ** 2).sum() / max(mask.sum(), 1)))

    for kernel in ("xla", "pallas"):
        m32 = DSGD(DSGDConfig(**kw, kernel=kernel)).fit(train,
                                                        num_blocks=2)
        m16 = DSGD(DSGDConfig(**kw, kernel=kernel,
                              factor_dtype="bfloat16")).fit(train,
                                                            num_blocks=2)
        assert m16.U.dtype == jnp.bfloat16
        assert m16.V.dtype == jnp.bfloat16
        assert m32.U.dtype == jnp.float32
        r32, r16 = rmse(m32), rmse(m16)
        # bf16 rounding perturbs the trajectory; it must not change the
        # model quality story (ALX's observation, training half)
        assert abs(r16 - r32) < 0.05 * max(r32, 1e-6), (kernel, r32, r16)
        # and the factors themselves stay close to the f32 run's
        np.testing.assert_allclose(
            np.asarray(m16.U, np.float32), np.asarray(m32.U),
            rtol=0.1, atol=0.05)


def test_bf16_rejects_unknown_dtype():
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=16, num_items=12, rank=2,
                               noise=0.1, seed=0)
    train = gen.generate(200)
    with pytest.raises(ValueError, match="factor_dtype"):
        DSGD(DSGDConfig(num_factors=4, iterations=1,
                        factor_dtype="float16")).fit(train, num_blocks=1)


def test_bf16_block_sweep_dtype_and_accumulation():
    """pallas_block_sweep on bf16 tables returns bf16 and tracks the f32
    reference within bf16 rounding — the f32 work-slice accumulation
    must not collapse duplicate-row updates to last-write-wins."""
    lr, lam, mb, rank = 0.1, 0.05, 64, 8
    ur, ir, vals, w, U, V, ou, ov = _problem(3, 256, 40, 24, rank)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)
    Uf, Vf = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=lam, minibatch=mb, gather="loop", interpret=True)
    Uh, Vh = pallas_block_sweep(
        jnp.asarray(U).astype(jnp.bfloat16),
        jnp.asarray(V).astype(jnp.bfloat16),
        jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=lam, minibatch=mb, gather="loop", interpret=True)
    assert Uh.dtype == jnp.bfloat16 and Vh.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits: the input quantization alone moves
    # values by up to ~0.4% — compare against that scale
    np.testing.assert_allclose(np.asarray(Uh, np.float32),
                               np.asarray(Uf), rtol=0.02, atol=0.01)
    np.testing.assert_allclose(np.asarray(Vh, np.float32),
                               np.asarray(Vf), rtol=0.02, atol=0.01)


def test_probe_script_emits_json_last_line():
    """scripts/pallas_probe.py ends with a machine-readable JSON summary
    as the genuinely LAST line even in a 2>&1-merged stream (the
    bench.py::_emit_final contract), carrying per-variant ratings/s and
    effective_hbm_gbs."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PROBE_CPU": "1", "PROBE_RANK": "8",
           "PROBE_MB": "64", "PROBE_RPB_U": "64", "PROBE_RPB_V": "48",
           "PROBE_NNZ": "128", "PROBE_REPS": "1",
           "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "pallas_probe.py")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=280, check=True)
    last = out.stdout.strip().splitlines()[-1]
    summary = json.loads(last)  # the merged stream still parses
    assert summary["tpu"] is False
    assert "xla_ratings_per_s" in summary
    assert "pallas_loop_effective_hbm_gbs" in summary


def test_stratum_pipeline_hbm_target_on_tpu():
    """The ISSUE-6 steady-state target: ≥10% of HBM peak on the
    double-buffered sweep — asserted ONLY where a real memory system
    exists (CPU interpret mode measures the interpreter, not HBM)."""
    import time

    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("HBM-peak target is asserted only on a real TPU")

    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    k, rank, mb, e = 32, 128, 1024, 24576  # ML-25M shape at k=32 (the
    # AOT-calibrated operating point: mb 2048 OOMs the VMEM stack)
    rpb_u, rpb_v = 5080, 1848
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 6)
    p_arr = jnp.arange(k, dtype=jnp.int32)
    q_arr = (p_arr[None, :] + p_arr[:, None]) % k
    su = (jax.random.randint(ks[0], (k, k, e), 0, rpb_u, jnp.int32)
          + (p_arr * rpb_u)[None, :, None])
    si = (jax.random.randint(ks[1], (k, k, e), 0, rpb_v, jnp.int32)
          + (q_arr * rpb_v)[:, :, None])
    sv = jax.random.normal(ks[2], (k, k, e), jnp.float32)
    sw = jnp.ones((k, k, e), jnp.float32)
    ic = jnp.ones((k, k, e), jnp.float32)
    U = 0.1 * jax.random.normal(ks[3], (k * rpb_u, rank), jnp.float32)
    V = 0.1 * jax.random.normal(ks[4], (k * rpb_v, rank), jnp.float32)
    ou = jnp.ones(k * rpb_u, jnp.float32)
    ov = jnp.ones(k * rpb_v, jnp.float32)

    def sweep(it):
        return dsgd_train_pallas(
            U, V, su, si, sv, sw, ou, ov, ic, ic, lr=0.01, lam=0.1,
            minibatch=mb, num_blocks=k, iterations=it, gather="loop",
            pipeline=True)

    jax.block_until_ready(sweep(1))  # compile + warm
    t0 = time.perf_counter()
    jax.block_until_ready(sweep(2))
    wall = (time.perf_counter() - t0) / 2
    nnz = k * k * e
    bps = sgd_ops.dsgd_bytes_per_sweep(
        nnz, rank, kernel="pallas", num_blocks=k,
        rows_u=k * rpb_u, rows_v=k * rpb_v)
    hbm_gbs = bps / wall / 1e9
    assert hbm_gbs >= 0.10 * 819.0, (
        f"steady-state sweep achieved {hbm_gbs:.1f} GB/s "
        f"< 10% of the 819 GB/s v5e HBM peak (wall {wall:.3f}s/sweep)")


def test_train_hbm_gbs_gauge_published():
    """With obs enabled, a segmented DSGD fit publishes the achieved-
    bandwidth gauge next to ratings/s — both phases — priced by the
    shared dsgd_bytes_per_sweep model (ISSUE 6)."""
    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    obs.enable()
    try:
        gen = SyntheticMFGenerator(num_users=32, num_items=24, rank=2,
                                   noise=0.1, seed=0)
        train = gen.generate(500)
        DSGD(DSGDConfig(num_factors=4, iterations=4, seed=0,
                        minibatch_size=64)).fit(train, num_blocks=1)
        snap = obs.get_registry().snapshot()
        names = {(m["name"], m["labels"].get("phase"))
                 for m in snap["metrics"]}
        assert ("train_hbm_gbs", "all") in names
        assert ("train_throughput_ratings_per_s", "all") in names
    finally:
        obs.disable()
