"""Pallas DSGD block-sweep: interpret-mode parity against the XLA kernel.

The Pallas kernel (ops/pallas_sgd.py) exists to attack the measured HBM
row-gather ceiling on real TPU hardware; on CPU we can only pin its MATH.
These tests run it in interpreter mode and require exact agreement with
``ops.sgd.sgd_block_sweep`` under the same updater rule — including
duplicate rows inside a minibatch (the sequential RMW scatter must
accumulate like ``.at[].add``) and weight-0 padding no-ops. Throughput is
measured by scripts/pallas_probe.py on the device that matters.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from large_scale_recommendation_tpu.core.updaters import (
    RegularizedSGDUpdater,
    constant_lr,
)
from large_scale_recommendation_tpu.ops import sgd as sgd_ops
from large_scale_recommendation_tpu.ops.pallas_sgd import pallas_block_sweep


def _problem(seed, e, rpb_u, rpb_v, rank, pad_frac=0.0):
    rng = np.random.default_rng(seed)
    ur = rng.integers(0, rpb_u, e).astype(np.int32)
    ir = rng.integers(0, rpb_v, e).astype(np.int32)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    if pad_frac:
        w[rng.random(e) < pad_frac] = 0.0
    U = rng.normal(0, 0.1, (rpb_u, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (rpb_v, rank)).astype(np.float32)
    omega_u = np.maximum(
        np.bincount(ur, weights=w, minlength=rpb_u), 0).astype(np.float32)
    omega_v = np.maximum(
        np.bincount(ir, weights=w, minlength=rpb_v), 0).astype(np.float32)
    return ur, ir, vals, w, U, V, omega_u, omega_v


def _inv_counts(rows, w, mb):
    """Per-entry 1/occurrence within each minibatch (the precomputed
    collision scales, data.blocking.minibatch_inv_counts semantics)."""
    inv = np.ones_like(w)
    for s in range(0, len(rows), mb):
        sl = slice(s, s + mb)
        cnt = {}
        for r, ww in zip(rows[sl], w[sl]):
            if ww > 0:
                cnt[r] = cnt.get(r, 0) + 1
        inv[sl] = [1.0 / max(cnt.get(r, 1), 1) if ww > 0 else 1.0
                   for r, ww in zip(rows[sl], w[sl])]
    return inv.astype(np.float32)


@pytest.mark.parametrize("gather", ["take", "loop"])
@pytest.mark.parametrize("pad_frac", [0.0, 0.15])
def test_matches_xla_kernel(gather, pad_frac):
    lr, lam, mb, rank = 0.1, 0.05, 64, 8
    ur, ir, vals, w, U, V, ou, ov = _problem(0, 256, 40, 24, rank,
                                             pad_frac)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)

    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=lam,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V),
        jnp.asarray(ur), jnp.asarray(ir), jnp.asarray(vals),
        jnp.asarray(w), jnp.asarray(ou), jnp.asarray(ov),
        upd, 1, mb, "mean", jnp.asarray(icu), jnp.asarray(icv))

    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=lam, minibatch=mb, gather=gather, interpret=True)

    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def test_duplicate_rows_accumulate_not_overwrite():
    """Many entries hitting ONE row in the same minibatch: the scatter
    must behave like .at[].add (a bulk last-write-wins store would keep
    only one delta)."""
    lr, mb, rank = 0.1, 16, 4
    e = 16
    ur = np.zeros(e, np.int32)  # every entry → row 0
    ir = np.arange(e, dtype=np.int32)
    rng = np.random.default_rng(1)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    U = rng.normal(0, 0.1, (4, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (e, rank)).astype(np.float32)
    ou = np.maximum(np.bincount(ur, minlength=4), 1).astype(np.float32)
    ov = np.ones(e, np.float32)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)

    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=0.05,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(ou),
        jnp.asarray(ov), upd, 1, mb, "mean",
        jnp.asarray(icu), jnp.asarray(icv))
    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=0.05, minibatch=mb, gather="loop", interpret=True)
    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def test_minibatch_boundary_visibility():
    """Minibatch t+1 must read rows written by minibatch t (the lax.scan
    carry semantics) — two minibatches hitting the same row."""
    lr, mb, rank = 0.2, 8, 4
    e = 16  # two minibatches
    ur = np.full(e, 2, np.int32)
    ir = np.arange(e, dtype=np.int32) % 8
    rng = np.random.default_rng(2)
    vals = rng.normal(0, 1, e).astype(np.float32)
    w = np.ones(e, np.float32)
    U = rng.normal(0, 0.1, (4, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (8, rank)).astype(np.float32)
    ou = np.maximum(np.bincount(ur, minlength=4), 1).astype(np.float32)
    ov = np.maximum(np.bincount(ir, minlength=8), 1).astype(np.float32)
    icu = _inv_counts(ur, w, mb)
    icv = _inv_counts(ir, w, mb)
    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=0.05,
                                schedule=constant_lr)
    U_ref, V_ref = sgd_ops.sgd_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(ou),
        jnp.asarray(ov), upd, 1, mb, "mean",
        jnp.asarray(icu), jnp.asarray(icv))
    U_p, V_p = pallas_block_sweep(
        jnp.asarray(U), jnp.asarray(V), jnp.asarray(ur), jnp.asarray(ir),
        jnp.asarray(vals), jnp.asarray(w), jnp.asarray(icu),
        jnp.asarray(icv), jnp.asarray(ou), jnp.asarray(ov),
        lr=lr, lam=0.05, minibatch=mb, gather="take", interpret=True)
    np.testing.assert_allclose(np.asarray(U_p), np.asarray(U_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_ref),
                               rtol=2e-5, atol=2e-6)


def _full_training_pair(minibatch_divisor: int, schedule, iters: int = 3,
                        t0: int = 0, gather: str = "loop"):
    """Run ops.sgd.dsgd_train and dsgd_train_pallas on the same blocked
    problem; ``minibatch = block_size // minibatch_divisor``. Returns
    ((Uref, Vref), (Up, Vp))."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.data import blocking
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
    from large_scale_recommendation_tpu.ops.pallas_sgd import (
        dsgd_train_pallas,
    )

    gen = SyntheticMFGenerator(num_users=48, num_items=40, rank=4,
                               noise=0.1, seed=0)
    train = gen.generate(2000)
    k = 2
    b = blocking.block_problem(train, num_blocks=k, seed=0,
                               minibatch_multiple=1).ratings.u_rows.shape[-1]
    # pad the block to a multiple of the divisor so mb divides b exactly
    mb_mult = -(-b // minibatch_divisor)
    problem = blocking.block_problem(train, num_blocks=k, seed=0,
                                     minibatch_multiple=mb_mult)
    b = problem.ratings.u_rows.shape[-1]
    mb = b // minibatch_divisor
    icu, icv = blocking.minibatch_inv_counts(problem.ratings, mb)
    U0, V0 = DSGD(DSGDConfig(num_factors=8, seed=0,
                             init_scale=0.2))._init_factors(problem)
    lr, lam = 0.05, 0.1
    upd = RegularizedSGDUpdater(learning_rate=lr, lambda_=lam,
                                schedule=schedule)
    args = (jnp.asarray(problem.ratings.u_rows, jnp.int32),
            jnp.asarray(problem.ratings.i_rows, jnp.int32),
            jnp.asarray(problem.ratings.values, jnp.float32),
            jnp.asarray(problem.ratings.weights, jnp.float32))
    common = (jnp.asarray(U0), jnp.asarray(V0), *args,
              jnp.asarray(problem.users.omega),
              jnp.asarray(problem.items.omega),
              jnp.asarray(icu), jnp.asarray(icv))
    Uref, Vref = sgd_ops.dsgd_train(
        *common, updater=upd, minibatch=mb, num_blocks=k,
        iterations=iters, collision="mean", t0=t0)
    # same positional order as dsgd_train (drop-in twin)
    Up, Vp = dsgd_train_pallas(
        *common, lr=lr, lam=lam, minibatch=mb, num_blocks=k,
        iterations=iters, gather=gather, interpret=True,
        schedule=None if schedule is constant_lr else schedule, t0=t0)
    return (Uref, Vref), (Up, Vp)


@pytest.mark.parametrize("gather", ["take", "loop"])
@pytest.mark.parametrize("divisor", [1, 4])
def test_full_training_matches_dsgd_train(divisor, gather):
    """dsgd_train_pallas (all strata × blocks × sweeps under one scan)
    must equal ops.sgd.dsgd_train — at minibatch == block size (divisor
    1: flat-stratum minibatches coincide with per-block visits) AND at
    minibatch < block size (divisor 4: the stratum-major layout deals
    entries block-major, so the flat chunk order still matches the
    per-block minibatch order) — on both gather paths (loop is the
    production path; take awaits a Mosaic that can gather across vregs)."""
    (Uref, Vref), (Up, Vp) = _full_training_pair(divisor, constant_lr,
                                                 gather=gather)
    np.testing.assert_allclose(np.asarray(Up), np.asarray(Uref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(Vp), np.asarray(Vref),
                               rtol=2e-5, atol=2e-6)


def test_dsgd_kernel_flag_routes_through_pallas():
    """DSGDConfig(kernel='pallas') must produce the same model as the XLA
    kernel through the PUBLIC fit surface (segmented twice to exercise the
    t0 continuation), and reject configurations the Pallas rule can't
    honor."""
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig

    gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4,
                               noise=0.1, seed=1)
    train = gen.generate(3000)
    kw = dict(num_factors=8, lambda_=0.05, iterations=4,
              learning_rate=0.05, lr_schedule="inverse_sqrt", seed=0,
              minibatch_size=128, init_scale=0.3)
    mx = DSGD(DSGDConfig(**kw, kernel="xla")).fit(train, num_blocks=2)
    mp = DSGD(DSGDConfig(**kw, kernel="pallas")).fit(train, num_blocks=2)
    np.testing.assert_allclose(np.asarray(mp.U), np.asarray(mx.U),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(mp.V), np.asarray(mx.V),
                               rtol=2e-4, atol=2e-5)

    with pytest.raises(ValueError, match="pallas"):
        DSGD(DSGDConfig(**{**kw, "collision_mode": "sum"},
                        kernel="pallas")).fit(train, num_blocks=2)
    with pytest.raises(ValueError, match="kernel"):
        DSGD(DSGDConfig(**kw, kernel="tensorcore")).fit(train,
                                                        num_blocks=2)


def test_full_training_schedule_parity():
    """A decaying η/√t schedule with a nonzero t0 (checkpoint-segment
    continuation) must match the XLA path exactly — the schedule is
    evaluated at trace level and enters the kernel as a runtime scalar."""
    from large_scale_recommendation_tpu.core.updaters import inverse_sqrt_lr

    (Uref, Vref), (Up, Vp) = _full_training_pair(
        2, inverse_sqrt_lr, iters=3, t0=5)
    np.testing.assert_allclose(np.asarray(Up), np.asarray(Uref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(Vp), np.asarray(Vref),
                               rtol=2e-5, atol=2e-6)
