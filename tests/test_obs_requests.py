"""REQUEST plane (``obs/requests.py``, ISSUE 20): per-request stage
decomposition, tail-based exemplar sampling, ``/slowz``.

The acceptance pin everything here defends: a REAL ``ServingEngine``
traffic run (two-stage retrieval, admission armed, at least one shed
and one degraded request) serves ``/slowz`` over a REAL socket where
EVERY exemplar's stage sum reconciles exactly (``math.fsum`` equality,
not approx) against its measured wall, the slowest injected request is
present worst-first with its dominant stage correctly named, and the
plane's violation accounting agrees with the engine's ``SLOTracker``
over the same window (both priced the IDENTICAL ``end - ts`` floats).
Covered besides: ledger mark/finish math, the reservoir policy
(violating/shed/degraded always kept, slowest-N floor for healthy
windows), the zero-cost disabled path (no clock reads, no ledger
allocation), ``Tracer.complete`` span trees, the server route +
``/`` index, fleet worst-first merge, postmortem bundles (v8
write/load, archived v7 synthesized), ``RequestStageCheck`` +
``HealthMonitor.watch_requests``, and the ``--requests`` renderer.
"""

import json
import math
import time

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.health import HealthMonitor
from large_scale_recommendation_tpu.obs.requests import (
    STAGES,
    FlushLedger,
    RequestStageCheck,
    RequestTelemetry,
    _pow2_bucket,
    get_requests,
    request_scope,
    set_requests,
    slowz,
)
from large_scale_recommendation_tpu.obs.server import ObsServer, http_get
from large_scale_recommendation_tpu.obs.transfers import _NULL_CONTEXT

RANK = 8


@pytest.fixture(autouse=True)
def _reset_planes():
    """Tests install telemetries — never leak the plane into the next
    test."""
    prev = get_requests()
    yield
    set_requests(prev)


def _telemetry(**kw):
    kw.setdefault("objective", 0.9)
    kw.setdefault("window", 64)
    kw.setdefault("max_exemplars", 8)
    kw.setdefault("slow_keep", 4)
    return RequestTelemetry(0.1, **kw)


def _model(num_users=50, num_items=256, seed=20):
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, RANK)).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=(num_items, RANK)).astype(np.float32)),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)))


def _noted_flush(t, walls, *, stage_s=0.01, version=1, degraded=False,
                 rows=None, admission_level=None):
    """Drive one synthetic flush through the real noting path: the
    oldest request waited ``max(walls)``, the flush itself took
    ``stage_s`` of gather."""
    end = time.perf_counter()
    t0 = end - stage_s
    led = t.ledger(t0)
    led.mark("gather", t0 + stage_s)
    stamps = tuple(end - w for w in sorted(walls, reverse=True))
    t.note_flush(led, end, stamps, version=version, degraded=degraded,
                 rows=rows, admission_level=admission_level)
    return end, stamps


# --------------------------------------------------------------------------
# Ledger math: exact-by-construction reconciliation
# --------------------------------------------------------------------------


class TestLedgerMath:
    def test_marks_partition_the_wall_exactly(self):
        led = FlushLedger(100.0)
        led.mark("batch_form", 100.25)
        led.mark("gather", 100.5)
        led.mark("score_stage1", 101.0)
        total = led.finish(101.1)
        assert total == 101.1 - 100.0
        # the fsum of the stages IS the wall — equality, not approx
        assert math.fsum(led.stages.values()) == total
        assert led.stages["batch_form"] == 0.25
        assert led.stages["gather"] == 0.25
        assert led.stages["score_stage1"] == 0.5
        # the residual landed in host_post
        assert led.stages["host_post"] == pytest.approx(0.1)

    def test_residual_stage_is_configurable(self):
        led = FlushLedger(0.0)
        led.mark("score_stage1", 1.0)
        led.finish(1.5, residual_stage="topk_merge")
        assert led.stages["topk_merge"] == pytest.approx(0.5)
        assert math.fsum(led.stages.values()) == 1.5

    def test_repeated_marks_accumulate(self):
        led = FlushLedger(0.0)
        led.mark("gather", 1.0)
        led.mark("score_stage1", 2.0)
        led.mark("gather", 2.5)  # second chunk's gather
        led.finish(3.0)
        assert led.stages["gather"] == 1.5
        assert math.fsum(led.stages.values()) == 3.0

    def test_shared_clock_read_is_honored(self):
        """Passing ``now`` must not read the clock — the engine shares
        its assembly-histogram read with the batch_form mark."""
        led = FlushLedger(5.0)
        led.mark("batch_form", 7.0)
        assert led.stages["batch_form"] == 2.0

    def test_per_request_sum_equals_the_slo_float(self):
        """The flush-level contract lifted per request: for awkward
        floats (a submit stamp far from the flush), the noted stage
        values still fsum to the IDENTICAL ``end - ts`` wall."""
        t = _telemetry()
        end, stamps = _noted_flush(
            t, [0.3, 0.0421739214, 1e-9], stage_s=0.0137)
        for ex in t.exemplars():
            assert math.fsum(ex["stages"].values()) == ex["wall_s"]
        walls = sorted((end - ts for ts in stamps), reverse=True)
        got = sorted((e["wall_s"] for e in t.exemplars()), reverse=True)
        assert got == walls[:len(got)]

    def test_pow2_bucket(self):
        assert [_pow2_bucket(n) for n in (0, 1, 2, 3, 8, 9, 1000)] == \
            [1, 1, 2, 4, 8, 16, 1024]


# --------------------------------------------------------------------------
# Reservoir policy
# --------------------------------------------------------------------------


class TestReservoir:
    def test_violating_always_kept_newest_win(self):
        t = _telemetry(max_exemplars=3)
        for i in range(6):
            _noted_flush(t, [0.5 + i], version=i)  # all violate 0.1
        ex = [e for e in t.exemplars() if e["kind"] == "violating"]
        assert len(ex) == 3  # bounded
        assert t.kept_evicted == 3  # evictions counted, not silent
        # newest win: the survivors are the three latest versions
        assert sorted(e["catalog_version"] for e in ex) == [3, 4, 5]

    def test_shed_always_kept_with_rung_and_burn(self):
        t = _telemetry()
        t.note_shed(version=7, level="shed", burn=5.5, queue_depth=3)
        (ex,) = t.exemplars()
        assert ex["kind"] == "shed"
        assert ex["admission_level"] == "shed"
        assert ex["burn_rate"] == 5.5
        assert ex["queue_depth"] == 3
        assert ex["catalog_version"] == 7
        assert ex["stages"] == {}  # never entered a flush
        assert t.shed == 1

    def test_degraded_kept_even_within_slo(self):
        t = _telemetry()
        _noted_flush(t, [0.01], degraded=True)  # inside the 0.1 target
        (ex,) = t.exemplars()
        assert ex["kind"] == "degraded" and ex["degraded"] is True
        assert ex["violating"] is False

    def test_healthy_requests_keep_only_the_slowest_n(self):
        t = _telemetry(slow_keep=3)
        for w in (0.01, 0.05, 0.02, 0.08, 0.03, 0.001):
            _noted_flush(t, [w], stage_s=w / 2)
        ex = t.exemplars()
        assert all(e["kind"] == "slow" for e in ex)
        assert len(ex) == 3
        # worst-first, and the floor replacement kept the slowest three
        got = [round(e["wall_s"], 3) for e in ex]
        assert got == sorted(got, reverse=True)
        assert got[0] == pytest.approx(0.08, abs=1e-3)
        assert 0.001 not in [round(w, 3) for w in got]

    def test_queue_depth_is_the_submit_index(self):
        t = _telemetry()
        _noted_flush(t, [0.3, 0.2, 0.15])
        depths = sorted(e["queue_depth"] for e in t.exemplars())
        assert depths == [0, 1, 2]

    def test_rows_annotate_the_pow2_bucket(self):
        t = _telemetry()
        _noted_flush(t, [0.3, 0.2], rows=[5, 8])
        buckets = sorted(e["bucket"] for e in t.exemplars())
        assert buckets == [8, 8]

    def test_exemplars_limit_and_order(self):
        t = _telemetry()
        _noted_flush(t, [0.5, 0.4, 0.3, 0.2])
        top2 = t.exemplars(limit=2)
        assert len(top2) == 2
        assert top2[0]["wall_s"] > top2[1]["wall_s"]

    def test_snapshot_counters_and_burn(self):
        t = _telemetry()  # objective 0.9 -> budget 0.1
        _noted_flush(t, [0.5])  # violates
        for _ in range(3):
            _noted_flush(t, [0.01])
        snap = t.snapshot()
        assert snap["count"] == 4
        assert snap["violations"] == 1
        assert snap["window_fill"] == 4
        assert snap["burn_rate"] == pytest.approx((1 / 4) / 0.1)
        assert snap["p99_ms"] >= snap["p50_ms"] > 0
        # fractions sum to 1 over a non-empty window
        assert math.fsum(snap["stage_frac"].values()) == \
            pytest.approx(1.0)
        assert snap["dominant_stage"] in STAGES

    def test_window_eviction_keeps_sums_consistent(self):
        t = _telemetry(window=4)
        for w in (0.5, 0.5, 0.01, 0.01, 0.01, 0.01):
            _noted_flush(t, [w])
        snap = t.snapshot()
        assert snap["window_fill"] == 4
        # both violations rolled out of the window
        assert snap["burn_rate"] == 0.0
        assert snap["violations"] == 2  # lifetime survives the window

    def test_stage_quantiles_shape(self):
        t = _telemetry()
        for _ in range(5):
            _noted_flush(t, [0.02])
        q = t.stage_quantiles()
        assert set(q) == set(STAGES)
        assert q["gather"]["p99"] >= q["gather"]["p50"] > 0.0
        assert q["score_stage2"]["p99"] == 0.0

    def test_reset_clears_everything(self):
        t = _telemetry()
        _noted_flush(t, [0.5])
        t.note_shed(version=1)
        t.reset()
        snap = t.snapshot()
        assert snap["count"] == snap["violations"] == snap["shed"] == 0
        assert snap["exemplars"] == []

    def test_validation(self):
        with pytest.raises(ValueError):
            RequestTelemetry(0.1, objective=1.0)
        with pytest.raises(ValueError):
            RequestTelemetry(0.1, window=0)
        with pytest.raises(ValueError):
            RequestTelemetry(0.1, max_exemplars=0)
        with pytest.raises(ValueError):
            RequestTelemetry(0.1, slow_keep=0)
        with pytest.raises(ValueError):
            RequestStageCheck(_telemetry(), frac_bar=0.0)


# --------------------------------------------------------------------------
# Plane lifecycle & the zero-cost pin
# --------------------------------------------------------------------------


class TestPlaneLifecycle:
    def test_default_is_none_and_slowz_notes(self, null_obs):
        assert get_requests() is None
        doc = slowz()
        assert "enable_requests" in doc["note"]
        assert doc["exemplars"] == []

    def test_disabled_scope_is_the_shared_singleton(self, null_obs,
                                                    monkeypatch):
        """The TestNullPathZeroWork pin for this plane: with no
        telemetry installed ``request_scope`` hands out the one
        module-level null context — no allocation, and NO clock read
        (pinned by making the clock explode)."""
        import time as _time

        def _boom():  # pragma: no cover - must never run
            raise AssertionError("clock read on the disabled path")

        monkeypatch.setattr(_time, "perf_counter", _boom)
        assert request_scope(1) is _NULL_CONTEXT
        with request_scope(1):
            pass

    def test_engine_binds_none_and_allocates_no_ledger(self, null_obs):
        from large_scale_recommendation_tpu.serving import ServingEngine

        eng = ServingEngine(_model(), k=4)
        assert eng._requests is None
        # the flush path runs ledger-free end to end
        eng.submit(np.arange(4))
        assert eng.flush()

    def test_enable_requests_installs_and_disable_clears(self, null_obs):
        t = obs.enable_requests(0.2, objective=0.95, window=32,
                                max_exemplars=4, slow_keep=2)
        try:
            assert t is get_requests()
            assert t.target_s == 0.2 and t.objective == 0.95
            assert request_scope(3) is not _NULL_CONTEXT
        finally:
            obs.disable()
        assert get_requests() is None

    def test_request_scope_times_and_notes(self, null_obs):
        t = _telemetry()
        set_requests(t)
        with request_scope(version=9) as scope:
            scope.mark("gather")
        snap = t.snapshot()
        assert snap["count"] == 1
        (ex,) = snap["exemplars"]
        assert ex["catalog_version"] == 9
        assert ex["stages"]["gather"] > 0.0
        assert math.fsum(ex["stages"].values()) == ex["wall_s"]


# --------------------------------------------------------------------------
# Tracer.complete: the span-tree emission primitive
# --------------------------------------------------------------------------


class TestTracerComplete:
    def test_complete_event_shape_and_span_tree(self, null_obs):
        from large_scale_recommendation_tpu.obs.trace import Tracer

        tracer = Tracer()
        t0 = time.perf_counter() - 0.25
        parent = tracer.complete("request", t0, t0 + 0.2,
                                 cat="request", tid=42, kind="slow")
        child = tracer.complete("request/gather", t0, t0 + 0.1,
                                cat="request_stage", tid=42,
                                parent_span_id=parent)
        assert parent and child and parent != child
        ev = [e for e in tracer.events() if e.get("ph") == "X"]
        assert len(ev) == 2
        root = next(e for e in ev if e["name"] == "request")
        assert root["dur"] == pytest.approx(0.2e6)
        assert root["tid"] == 42
        assert root["args"]["kind"] == "slow"
        leaf = next(e for e in ev if e["name"] == "request/gather")
        assert leaf["args"]["parent_span_id"] == parent

    def test_complete_respects_max_events(self, null_obs):
        from large_scale_recommendation_tpu.obs.trace import Tracer

        tracer = Tracer(max_events=2)
        assert tracer.complete("a", 0.0, 1.0) is not None
        assert tracer.complete("b", 0.0, 1.0) is not None
        assert tracer.complete("c", 0.0, 1.0) is None
        assert tracer.dropped == 1

    def test_null_tracer_complete_is_none(self):
        from large_scale_recommendation_tpu.obs.trace import NullTracer

        assert NullTracer().complete("x", 0.0, 1.0) is None
        assert NullTracer().complete_tree("x", 0.0, 1.0,
                                          [("x/a", 0.5)]) is None

    def test_complete_tree_nests_exactly_at_epoch_magnitudes(self,
                                                             null_obs):
        """Sibling boundaries must be BITWISE abutting in the stored
        microsecond floats: the trace origin anchors perf_counter to
        the epoch (~1e15 us, one ulp ~0.25 us), so converting each
        child boundary from seconds independently can un-nest abutting
        siblings and fail ``validate_chrome_trace`` — the layout has
        to happen in the event's own microsecond space."""
        from large_scale_recommendation_tpu.obs.trace import (
            Tracer,
            validate_chrome_trace,
        )

        tracer = Tracer()
        t0 = time.perf_counter()
        # irrational-ish stage walls maximize rounding exposure
        stages = [("request/queue_wait", 0.001234567),
                  ("request/batch_form", 0.0007654321),
                  ("request/gather", 0.0601112131),
                  ("request/score_stage1", 0.0023456789),
                  ("request/topk_merge", 0.0009876543),
                  ("request/host_post", 0.0004321987)]
        wall = math.fsum(dt for _, dt in stages)
        for i in range(50):
            span = tracer.complete_tree(
                "request", t0 + i * 0.1, t0 + i * 0.1 + wall, stages,
                cat="request", child_cat="request_stage", tid=7000 + i)
            assert span is not None
        complete = validate_chrome_trace(
            {"traceEvents": tracer.events()})
        kids = [e for e in complete if e["cat"] == "request_stage"]
        assert len(kids) == 50 * len(stages)
        # per-tid exact abutment: child N+1 starts at the very float
        # child N's ts + dur produces
        by_tid = {}
        for e in kids:
            by_tid.setdefault(e["tid"], []).append(e)
        for evs in by_tid.values():
            evs.sort(key=lambda e: e["ts"])
            for a, b in zip(evs, evs[1:]):
                assert a["ts"] + a["dur"] == b["ts"]

    def test_exemplar_emits_perfetto_loadable_tree(self, null_obs):
        """A kept exemplar renders in the trace buffer: a parent
        ``request`` complete-event plus stage children whose durs sum
        to the parent's."""
        reg, tracer = obs.enable()
        try:
            t = _telemetry()
            set_requests(t)
            _noted_flush(t, [0.5], stage_s=0.2)
            ev = [e for e in tracer.events() if e.get("ph") == "X"]
            root = next(e for e in ev if e["name"] == "request")
            kids = [e for e in ev if e["cat"] == "request_stage"]
            assert kids
            assert sum(k["dur"] for k in kids) == \
                pytest.approx(root["dur"], rel=1e-6)
            assert all(k["args"]["parent_span_id"] ==
                       root["args"]["span_id"] for k in kids)
        finally:
            obs.disable()


# --------------------------------------------------------------------------
# Server route, health gate
# --------------------------------------------------------------------------


class TestServerAndHealth:
    def test_slowz_route_and_index(self, null_obs):
        obs.enable()
        try:
            t = obs.enable_requests(0.1, objective=0.9)
            _noted_flush(t, [0.5, 0.3])
            with ObsServer() as server:
                code, body = http_get(server.url + "/slowz")
                lcode, lbody = http_get(server.url + "/slowz?limit=1")
                bcode, _ = http_get(server.url + "/slowz?limit=junk")
                icode, ibody = http_get(server.url + "/")
        finally:
            obs.disable()
        assert code == 200
        doc = json.loads(body)
        assert doc["count"] == 2 and len(doc["exemplars"]) == 2
        assert len(json.loads(lbody)["exemplars"]) == 1
        assert bcode == 400
        assert "/slowz" in json.loads(ibody)["routes"]

    def test_slowz_without_plane_is_a_note(self, null_obs):
        obs.enable()
        try:
            with ObsServer() as server:
                code, body = http_get(server.url + "/slowz")
        finally:
            obs.disable()
        assert code == 200
        assert "enable_requests" in json.loads(body)["note"]

    def test_stage_check_needs_burn_and_domination(self, null_obs):
        t = _telemetry()
        check = RequestStageCheck(t, frac_bar=0.5)
        assert check().status == "ok"  # idle plane
        # dominant stage but inside budget: still OK (just a profile)
        _noted_flush(t, [0.01], stage_s=0.009)
        res = check()
        assert res.status == "ok"
        assert res.detail["dominant_stage"] == "gather"
        # now the SLO burns AND gather dominates: DEGRADED, culprit
        # named
        for _ in range(4):
            _noted_flush(t, [0.5], stage_s=0.45)
        res = check()
        assert res.status == "degraded"
        assert res.detail["dominant_stage"] == "gather"
        assert "gather" in res.detail["note"]
        assert res.detail["burn_rate"] > 1.0

    def test_burning_without_domination_stays_ok(self, null_obs):
        t = _telemetry()
        check = RequestStageCheck(t, frac_bar=0.9)  # bar out of reach
        for _ in range(4):
            _noted_flush(t, [0.5], stage_s=0.25)
        assert check().status == "ok"

    def test_watch_requests_flips_healthz(self, null_obs):
        mon = HealthMonitor()
        t = _telemetry()
        mon.watch_requests(t)
        assert mon.run()["status"] == "ok"
        for _ in range(4):
            _noted_flush(t, [0.5], stage_s=0.45)
        report = mon.run()
        assert report["checks"]["requests"]["status"] == "degraded"
        assert report["status"] == "degraded"


# --------------------------------------------------------------------------
# Fleet worst-first merge
# --------------------------------------------------------------------------


class TestFleet:
    def test_pod_view_merges_exemplars_worst_first(self, null_obs):
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
            FleetServer,
        )

        obs.enable()
        try:
            t = obs.enable_requests(0.1, objective=0.9)
            _noted_flush(t, [0.5, 0.01])
            t.note_shed(version=1, level="shed", burn=4.0)
            with ObsServer() as s1, ObsServer() as s2:
                # two real sockets over the one process plane: the
                # worst-first merge contract is what's under test
                view = FleetAggregator([s1.url, s2.url]).requests()
                with FleetServer(FleetAggregator([s1.url])) as fleet:
                    code, body = http_get(fleet.url + "/slowz")
                    lcode, lbody = http_get(fleet.url +
                                            "/slowz?limit=1")
        finally:
            obs.disable()
        assert len(view["targets"]) == 2
        ex = view["exemplars"]
        assert ex and all("host" in e for e in ex)
        walls = [e.get("wall_s") or 0.0 for e in ex]
        assert walls == sorted(walls, reverse=True)
        # pod stage totals sum across members, fractions re-derive
        assert view["stage_totals_s"]["gather"] > 0.0
        assert view["dominant_stage"] in STAGES
        assert code == 200
        assert json.loads(body)["exemplars"]
        assert len(json.loads(lbody)["exemplars"]) == 1

    def test_unreachable_member_is_listed_not_fatal(self, null_obs):
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
        )

        obs.enable()
        try:
            obs.enable_requests(0.1)
            with ObsServer() as s1:
                dead = "http://127.0.0.1:1"
                view = FleetAggregator([s1.url, dead],
                                       timeout_s=3.0).requests()
        finally:
            obs.disable()
        assert view["unreachable"] == ["127.0.0.1:1"]
        assert len(view["targets"]) == 1


# --------------------------------------------------------------------------
# Postmortem bundles: v8 round-trip, archived v7 synthesized
# --------------------------------------------------------------------------


class TestBundle:
    def test_v8_bundle_carries_requests_and_v7_stays_loadable(
            self, null_obs, tmp_path):
        import os

        from large_scale_recommendation_tpu.obs.recorder import (
            BUNDLE_VERSION,
            load_bundle,
            write_bundle,
        )

        obs.enable()
        obs.enable_flight_recorder(interval_s=0.05)
        try:
            t = obs.enable_requests(0.1, objective=0.9)
            _noted_flush(t, [0.5], version=5)
            path = write_bundle(str(tmp_path / "b"), trigger="manual")
            docs = load_bundle(path)
            assert BUNDLE_VERSION == 8
            assert docs["manifest"]["bundle_version"] == 8
            assert docs["requests"]["count"] == 1
            (ex,) = docs["requests"]["exemplars"]
            assert ex["catalog_version"] == 5
            # an archived version-7 bundle (pre-request-plane) stays
            # loadable with the note synthesized
            manifest_path = str(tmp_path / "b" / "manifest.json")
            with open(manifest_path) as f:
                manifest = json.load(f)
            manifest["bundle_version"] = 7
            manifest["files"] = [x for x in manifest["files"]
                                 if x != "requests.json"]
            with open(manifest_path, "w") as f:
                json.dump(manifest, f)
            os.unlink(str(tmp_path / "b" / "requests.json"))
            docs7 = load_bundle(path)
            assert docs7["requests"]["exemplars"] == []
            assert "version-7" in docs7["requests"]["note"]
        finally:
            obs.disable()

    def test_bundle_without_plane_freezes_the_note(self, null_obs,
                                                   tmp_path):
        from large_scale_recommendation_tpu.obs.recorder import (
            load_bundle,
            write_bundle,
        )

        obs.enable()
        obs.enable_flight_recorder(interval_s=0.05)
        try:
            path = write_bundle(str(tmp_path / "b"), trigger="manual")
            docs = load_bundle(path)
        finally:
            obs.disable()
        assert "not enabled" in docs["requests"]["note"]


# --------------------------------------------------------------------------
# Renderer
# --------------------------------------------------------------------------


class TestRenderer:
    def test_render_requests_local_and_fleet(self, null_obs):
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        ".."))
        from scripts.obs_report import render_requests

        t = _telemetry()
        _noted_flush(t, [0.5], rows=[5], admission_level="normal")
        out = render_requests(t.snapshot())
        assert "gather" in out and "violating" in out
        assert "dominant" in out
        fleet_doc = {
            "stage_frac": {"gather": 0.8, "host_post": 0.2},
            "stage_totals_s": {"gather": 4.0, "host_post": 1.0},
            "dominant_stage": "gather",
            "exemplars": [{"host": "h1:9100", "kind": "violating",
                           "wall_s": 0.5, "dominant_stage": "gather",
                           "catalog_version": 1, "queue_depth": 0,
                           "bucket": 8, "admission_level": None}],
            "targets": [{"host": "h1:9100", "count": 3,
                         "violations": 1, "shed": 0, "p99_ms": 500.0,
                         "dominant_stage": "gather", "note": None}],
        }
        out = render_requests(fleet_doc)
        assert "h1:9100" in out
        out = render_requests(slowz())  # absent-plane note form
        assert "enable_requests" in out


# --------------------------------------------------------------------------
# THE acceptance pin: real engine, armed admission, real socket
# --------------------------------------------------------------------------


class TestE2ESlowRequestAttribution:
    def test_slowz_names_where_the_tail_went(self, null_obs):
        """Mixed traffic against a REAL two-stage ``ServingEngine``
        with admission armed: a planted drag (attributed to the gather
        stage) makes one cohort slow, the burn walks the ladder
        through DEGRADE into SHED. ``/slowz`` over a real socket must
        hold at least one shed and one degraded exemplar, EVERY
        exemplar's stage fsum must EQUAL its measured wall, the
        slowest injected request must lead worst-first with gather
        named dominant, and the plane's violation accounting must
        agree with the engine's ``SLOTracker`` over the same window."""
        from large_scale_recommendation_tpu.obs.health import SLOTracker
        from large_scale_recommendation_tpu.serving import (
            AdmissionConfig,
            AdmissionController,
            RetrievalConfig,
            ServingEngine,
        )
        from large_scale_recommendation_tpu.serving.admission import (
            AdmissionRejectedError,
        )

        obs.enable()
        telemetry = obs.enable_requests(
            0.030, objective=0.9, window=64, max_exemplars=64,
            slow_keep=8)
        try:
            slo = SLOTracker(target_s=0.030, objective=0.9, window=64)
            adm = AdmissionController(
                slo, AdmissionConfig(min_samples=4, widen_burn=1.0,
                                     degrade_burn=2.0, shed_burn=6.0,
                                     shed_probe=0.25))
            eng = ServingEngine(
                _model(num_items=512), k=5, max_batch=64,
                retrieval=RetrievalConfig(n_clusters=None, overfetch=4))
            assert eng._requests is telemetry
            # the planted drag: 50ms attributed to gather — the
            # injected slowest request the reservoir must surface
            orig = eng._serve_rows

            def dragging(rows, stage1_only=False, ledger=None):
                time.sleep(0.05)
                if ledger is not None:
                    ledger.mark("gather")
                return orig(rows, stage1_only=stage1_only,
                            ledger=ledger)

            rng = np.random.default_rng(11)
            eng.serve([rng.integers(0, 50, 4).astype(np.int64)])
            # warm the stage1-only (degraded) executable too: compile
            # wall is not the signal, the planted drag is
            import jax.numpy as jnp

            empty_excl = (np.zeros(8, np.int32), np.zeros(8, np.int32),
                          np.full(8, np.inf, np.float32))
            eng.retriever.topk(jnp.zeros((8, RANK), jnp.float32),
                               empty_excl, k=5, stage1_only=True)
            # arm admission AFTER the warmup so the tracker and the
            # plane price the same post-warm request stream
            eng.attach_admission(adm)
            telemetry.reset()  # compile wall is not the signal
            eng._serve_rows = dragging
            shed = 0
            with ObsServer() as server:
                for _ in range(40):
                    try:
                        eng.submit(rng.integers(0, 50, 4).astype(
                            np.int64))
                        eng.flush()
                    except AdmissionRejectedError:
                        shed += 1
                code, body = http_get(server.url + "/slowz")
            slo_snap = slo.snapshot()
            eng_version = eng.version
        finally:
            obs.disable()

        assert code == 200
        doc = json.loads(body)
        ex = doc["exemplars"]
        assert ex

        # at least one shed and one degraded request were captured
        assert shed >= 1
        kinds = {e["kind"] for e in ex}
        assert "shed" in kinds, doc["kept"]
        assert any(e["degraded"] for e in ex), doc["kept"]
        assert doc["shed"] == shed

        # EVERY exemplar's stage sum reconciles exactly with its wall
        for e in ex:
            if e["kind"] == "shed":
                continue  # never entered a flush: no stages by design
            assert math.fsum(e["stages"].values()) == e["wall_s"], e

        # the slowest injected request leads worst-first with the
        # dominant stage correctly named — the drag went to gather
        flushed = [e for e in ex if e["kind"] != "shed"]
        worst = flushed[0]
        assert worst["wall_s"] >= 0.05
        assert worst["kind"] == "violating"
        assert worst["dominant_stage"] == "gather"
        assert doc["dominant_stage"] == "gather"

        # exemplar accounting agrees with the engine's SLOTracker over
        # the same window: both priced the IDENTICAL end - ts floats
        assert doc["violations"] == slo_snap["violations"]
        assert doc["window_fill"] == slo_snap["window_fill"]
        assert 1.0 - doc["violations"] / doc["window_fill"] == \
            pytest.approx(slo_snap["attainment"])
        # every flushed request violated the 30ms target under a 50ms
        # drag, so the plane's p99 must sit above the drag
        assert doc["p99_ms"] >= 50.0

        # exemplars carry the joinable annotations
        assert worst["catalog_version"] == eng_version
        assert worst["rows"] == 4 and worst["bucket"] == 4
        assert any(e["admission_level"] in ("degrade", "shed")
                   for e in ex)
