"""Golden generator for the Partitioner equivalence pins (ISSUE 7).

Run BEFORE (to capture the hand-rolled-sharding outputs) and compared
AFTER the unified-Partitioner refactor: the refactor only changes how
``NamedSharding``s are constructed — same mesh, same specs, same jitted
computations — so the outputs must match **bit for bit**.

    python tests/data/make_partitioner_golden.py   # writes partitioner_golden.npz

The workloads deliberately use only the stable public surfaces
(``make_block_mesh``, ``MeshDSGD``, ``MeshALS``, ``mesh_top_k_recommend``)
that survive the refactor unchanged, and run in the same environment as
tier-1 (8 virtual CPU devices, x64 off) so the pins replay in-suite.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "partitioner_golden.npz")


def run_workloads(mesh_factory):
    """The three mesh workloads pinned by the equivalence tests, run over
    ``mesh_factory(n_devices)``-built meshes. Returns {name: np.ndarray}.
    One copy shared by the generator and tests/test_partitioner.py so the
    pinned configs cannot drift from the goldens."""
    import numpy as np

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.models.als import ALSConfig
    from large_scale_recommendation_tpu.parallel.als_mesh import MeshALS
    from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
        MeshDSGD,
        MeshDSGDConfig,
    )
    from large_scale_recommendation_tpu.parallel.serving import (
        mesh_top_k_recommend,
    )

    out: dict = {}
    gen = SyntheticMFGenerator(num_users=120, num_items=90, rank=6,
                               noise=0.1, seed=3)
    train = gen.generate(6000)
    ru, ri, rv, _ = train.to_numpy()

    # mesh DSGD, host-blocked path
    dcfg = MeshDSGDConfig(num_factors=6, lambda_=0.01, iterations=3,
                          learning_rate=0.05, lr_schedule="constant",
                          seed=0, minibatch_size=128, init_scale=0.3)
    m = MeshDSGD(dcfg, mesh=mesh_factory(4)).fit(train)
    out["dsgd_U"], out["dsgd_V"] = np.asarray(m.U), np.asarray(m.V)

    # mesh DSGD, device-blocked path
    md = MeshDSGD(dcfg, mesh=mesh_factory(4)).fit_device(
        ru, ri, rv, 120, 90)
    out["dsgd_dev_U"] = np.asarray(md.U)
    out["dsgd_dev_V"] = np.asarray(md.V)

    # mesh ALS
    acfg = ALSConfig(num_factors=6, lambda_=0.05, iterations=3, seed=0)
    ma = MeshALS(acfg, mesh=mesh_factory(4)).fit(train)
    out["als_U"], out["als_V"] = np.asarray(ma.U), np.asarray(ma.V)

    # mesh serving over a fixed random catalog (exclusions exercised)
    rng = np.random.default_rng(7)
    U = rng.normal(size=(60, 6)).astype(np.float32)
    V = rng.normal(size=(83, 6)).astype(np.float32)
    rows, scores = mesh_top_k_recommend(
        U, V, np.arange(40, dtype=np.int32), k=7, chunk=16,
        train_u=ru[:400] % 60, train_i=ri[:400] % 83,
        mesh=mesh_factory(4))
    out["serve_rows"], out["serve_scores"] = rows, scores
    return out


def main() -> None:
    from large_scale_recommendation_tpu.utils.platform import force_cpu

    os.environ.setdefault("JAX_ENABLE_X64", "0")
    force_cpu(n_devices=8)

    import numpy as np

    from large_scale_recommendation_tpu.parallel.mesh import make_block_mesh

    arrays = run_workloads(make_block_mesh)
    np.savez(GOLDEN, **arrays)
    print(f"wrote {GOLDEN}: " + ", ".join(
        f"{k}{v.shape}" for k, v in arrays.items()))


if __name__ == "__main__":
    main()
