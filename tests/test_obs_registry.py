"""Registry contract: bucket/quantile math vs a numpy reference,
label handling, exporters, thread-safety, and the zero-cost null layer.
"""

import json
import threading

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.registry import (
    _HIST_MIN,
    Histogram,
    MetricsRegistry,
    NULL_INSTRUMENT,
    NULL_REGISTRY,
    NullRegistry,
    get_registry,
    set_registry,
)


@pytest.fixture
def reg():
    return MetricsRegistry()


class TestHistogram:
    def test_quantiles_match_numpy(self, reg):
        """Log-bucket quantile estimates vs np.percentile on a lognormal
        latency-shaped sample: the documented error bound is ~9% (half a
        2**0.25 bucket at the geometric midpoint); assert a 15% ceiling
        to keep the test robust to bucket-edge effects."""
        h = reg.histogram("lat_s")
        rng = np.random.default_rng(7)
        vals = rng.lognormal(mean=-5.0, sigma=1.5, size=20_000)
        for v in vals:
            h.observe(v)
        for q in (50, 90, 99):
            est = h.quantile(q / 100)
            ref = float(np.percentile(vals, q))
            assert abs(est - ref) / ref < 0.15, (q, est, ref)

    def test_exact_stats_ride_alongside(self, reg):
        h = reg.histogram("x")
        vals = [0.5, 1.5, 2.0, 8.0]
        for v in vals:
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(sum(vals))
        assert h.min == 0.5 and h.max == 8.0
        assert h.mean == pytest.approx(np.mean(vals))

    def test_bucket_bounds_contain_value(self):
        rng = np.random.default_rng(0)
        for v in rng.lognormal(0, 8, 200):
            idx = Histogram.bucket_index(float(v))
            lo, hi = Histogram.bucket_bounds(idx)
            if v <= _HIST_MIN:
                assert idx == 0
            else:
                assert lo <= v < hi * (1 + 1e-12), (v, lo, hi)

    def test_quantile_clamped_to_observed_extremes(self, reg):
        h = reg.histogram("one")
        h.observe(3.0)
        for q in (0.5, 0.9, 0.99):
            assert h.quantile(q) == 3.0
        assert np.isnan(reg.histogram("empty").quantile(0.5))

    def test_summary_fields(self, reg):
        h = reg.histogram("s")
        h.observe(1.0)
        s = h.summary()
        for key in ("count", "sum", "mean", "min", "max",
                    "p50", "p90", "p99"):
            assert key in s


class TestLabels:
    def test_same_labels_same_instrument(self, reg):
        assert reg.counter("c", a="1", b="2") is reg.counter(
            "c", b="2", a="1")
        assert reg.counter("c", a="1") is not reg.counter("c", a="2")
        assert reg.gauge("g") is reg.gauge("g")

    def test_name_label_does_not_collide_with_positional(self, reg):
        # instruments labeled name=... (StepTimer/ThroughputMeter shims)
        c = reg.counter("step_timer_s", name="sweep")
        c.inc()
        assert c.value == 1

    def test_types_are_namespaced_separately(self, reg):
        reg.counter("m").inc()
        reg.gauge("m").set(5)
        assert reg.counter("m").value == 1
        assert reg.gauge("m").value == 5


class TestExporters:
    def test_snapshot_is_json_safe_and_sorted(self, reg):
        reg.counter("b_total").inc(3)
        reg.gauge("a_gauge", part="0").set(1.5)
        reg.histogram("c_s").observe(0.25)
        snap = json.loads(json.dumps(reg.snapshot()))
        names = [m["name"] for m in snap["metrics"]]
        assert names == sorted(names)
        by_name = {m["name"]: m for m in snap["metrics"]}
        assert by_name["b_total"]["value"] == 3
        assert by_name["a_gauge"]["labels"] == {"part": "0"}
        assert by_name["c_s"]["count"] == 1

    def test_jsonl_append(self, reg, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg.counter("x").inc()
        reg.append_jsonl(path)
        reg.counter("x").inc()
        reg.append_jsonl(path)
        lines = open(path).read().splitlines()
        assert len(lines) == 2
        first, last = json.loads(lines[0]), json.loads(lines[-1])
        assert first["metrics"][0]["value"] == 1
        assert last["metrics"][0]["value"] == 2

    def test_prometheus_text(self, reg):
        reg.counter("req_total", code="200").inc(7)
        reg.gauge("depth").set(3)
        h = reg.histogram("lat_s", route="a")
        h.observe(0.5)
        text = reg.to_prometheus()
        assert "# TYPE req_total counter" in text
        assert 'req_total{code="200"} 7' in text
        assert "# TYPE depth gauge" in text
        assert "# TYPE lat_s summary" in text
        assert 'lat_s{route="a",quantile="0.5"}' in text
        assert 'lat_s_count{route="a"} 1' in text
        assert text.endswith("\n")

    def test_prometheus_escapes_label_values(self, reg):
        # the health gauge's `check` label carries check NAMES, and
        # watch_series defaults those to recorder series keys like
        # 'lag{partition="0"}' — unescaped, the nested quotes abort the
        # whole /metrics parse
        reg.gauge("health_check_status",
                  check='anomaly:lag{partition="0"}').set(0)
        reg.counter("weird_total", path="a\\b\nc").inc()
        text = reg.to_prometheus()
        assert ('health_check_status{check='
                '"anomaly:lag{partition=\\"0\\"}"} 0') in text
        assert 'weird_total{path="a\\\\b\\nc"} 1' in text
        # every metric line is valid exposition: name{escaped-labels} value
        import re
        body = re.compile(r'[a-zA-Z_:][a-zA-Z0-9_:]*'
                          r'(\{([a-zA-Z_][a-zA-Z0-9_]*='
                          r'"(\\.|[^"\\])*",?)*\})? \S+')
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            assert body.fullmatch(line), line


class TestThreadSafety:
    def test_concurrent_updates_are_exact(self, reg):
        c = reg.counter("n")
        h = reg.histogram("h")

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(0.01)

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000
        assert h.count == 8000

    def test_concurrent_instrument_creation(self, reg):
        out = []

        def make(i):
            out.append(reg.counter("same", k=str(i % 2)))

        threads = [threading.Thread(target=make, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len({id(c) for c in out}) == 2


class TestNullLayer:
    def test_instruments_are_shared_singletons(self):
        """The zero-allocation pin: EVERY null instrument is the one
        module-level object — handing them out costs nothing."""
        null = NullRegistry()
        assert null.counter("a") is NULL_INSTRUMENT
        assert null.gauge("b", x="1") is NULL_INSTRUMENT
        assert null.histogram("c") is NULL_INSTRUMENT
        assert not hasattr(NULL_INSTRUMENT, "__dict__")  # __slots__ = ()

    def test_mutators_record_nothing(self):
        null = NULL_REGISTRY
        null.counter("a").inc(100)
        null.gauge("b").set(5)
        null.histogram("c").observe(1.0)
        assert null.snapshot()["metrics"] == []
        assert null.to_prometheus() == ""
        assert null.names() == set()
        assert not null.enabled

    def test_null_jsonl_writes_nothing(self, tmp_path):
        path = tmp_path / "never.jsonl"
        NULL_REGISTRY.append_jsonl(str(path))
        assert not path.exists()

    def test_enable_disable_roundtrip(self, null_obs):
        # null_obs (tests/conftest.py) restores the WHOLE layer after,
        # so enabling/disabling freely here is safe even under OBS_OUT
        from large_scale_recommendation_tpu.obs.events import get_events
        from large_scale_recommendation_tpu.obs.recorder import (
            get_recorder,
        )
        from large_scale_recommendation_tpu.obs.trace import get_tracer

        reg, tracer = obs.enable()
        assert get_registry() is reg
        assert get_tracer() is tracer
        assert obs.enabled()
        rec, journal = obs.enable_flight_recorder(start=False)
        assert get_recorder() is rec and get_events() is journal
        obs.disable()  # also uninstalls the recorder/journal
        assert isinstance(get_registry(), NullRegistry)
        assert not obs.enabled()
        assert get_events() is None and get_recorder() is None
