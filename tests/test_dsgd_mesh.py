"""Mesh DSGD tests on the 8-device virtual CPU mesh.

Key property: the mesh implementation and the single-device implementation
run the SAME schedule over the SAME blocked data, so with identical seeds
they must produce (near-)identical factors — the ppermute rotation is just a
different physical realization of the stratum walk (≙ nextRatingBlock,
DSGDforMF.scala:611-619).
"""

import numpy as np
import jax
import pytest

from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
from large_scale_recommendation_tpu.parallel.mesh import (
    make_block_mesh,
    ring_backward,
)
from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
    MeshDSGD,
    MeshDSGDConfig,
    device_major_local_strata,
)
from large_scale_recommendation_tpu.data import blocking


@pytest.fixture(scope="module")
def gen():
    return SyntheticMFGenerator(num_users=200, num_items=150, rank=8,
                                noise=0.05, seed=0)


class TestRing:
    def test_ring_backward_pattern(self):
        assert ring_backward(4) == [(0, 3), (1, 0), (2, 1), (3, 2)]

    def test_mesh_creation(self):
        mesh = make_block_mesh(8)
        assert mesh.shape["blocks"] == 8

    def test_mesh_too_large_raises(self):
        with pytest.raises(ValueError):
            make_block_mesh(1000)


class TestDeviceMajorLayout:
    def test_local_indices_in_range(self):
        g = SyntheticMFGenerator(num_users=100, num_items=90, rank=4, seed=1)
        prob = blocking.block_problem(g.generate(3000), num_blocks=4, seed=0)
        ru, ri, rv, rw = device_major_local_strata(prob)
        assert ru.shape[0] == 4 and ru.shape[1] == 4
        assert ru.max() < prob.users.rows_per_block
        assert ri.max() < prob.items.rows_per_block
        # device-major cell [p, s] holds block (p, (p+s)%k): verify against
        # the stratum-major source [s, p]
        np.testing.assert_array_equal(rv[2, 3], prob.ratings.values[3, 2])


class TestMeshDSGDDevicePipeline:
    def test_fit_device_matches_single_device_fit_device(self, gen):
        """Mesh fit_device and single-device fit_device build the SAME
        on-chip blocked layout (same seed) and run the same schedule →
        factors must agree to float tolerance."""
        train = gen.generate(10000)
        ru, ri, rv, _ = train.to_numpy()
        nu, ni = 200, 150
        mesh = make_block_mesh(4)
        mcfg = MeshDSGDConfig(num_factors=8, lambda_=0.01, iterations=4,
                              learning_rate=0.05, lr_schedule="constant",
                              seed=0, minibatch_size=256, init_scale=0.3)
        mm = MeshDSGD(mcfg, mesh=mesh).fit_device(ru, ri, rv, nu, ni)

        scfg = DSGDConfig(num_factors=8, lambda_=0.01, iterations=4,
                          learning_rate=0.05, lr_schedule="constant",
                          seed=0, minibatch_size=256, init_scale=0.3)
        sm = DSGD(scfg).fit_device(ru, ri, rv, nu, ni, num_blocks=4)

        np.testing.assert_allclose(np.asarray(mm.U), np.asarray(sm.U),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(mm.V), np.asarray(sm.V),
                                   rtol=2e-3, atol=2e-4)
        # identical model surface: same predictions for the same ids
        some_u = ru[:100]
        some_i = ri[:100]
        np.testing.assert_allclose(mm.predict(some_u, some_i),
                                   sm.predict(some_u, some_i),
                                   rtol=2e-3, atol=2e-4)

    def test_fit_device_converges_on_mesh(self, gen):
        train = gen.generate(20000)
        test = gen.generate(2000)
        ru, ri, rv, _ = train.to_numpy()
        mesh = make_block_mesh(8)
        # lr 0.2/15 sweeps measured 0.0702 (noise floor 0.05); lr 0.1/10
        # is still on the bilinear-bootstrap plateau (0.30)
        cfg = MeshDSGDConfig(num_factors=8, lambda_=0.02, iterations=15,
                             learning_rate=0.2, lr_schedule="constant",
                             seed=0, minibatch_size=128, init_scale=0.2)
        m = MeshDSGD(cfg, mesh=mesh).fit_device(ru, ri, rv, 200, 150)
        assert m.rmse(test) < 0.15  # noise floor 0.05


class TestMeshDSGD:
    def test_matches_single_device(self, gen):
        """Mesh and single-device runs execute the same schedule → factors
        must agree to float tolerance."""
        train = gen.generate(10000)
        mesh = make_block_mesh(4)
        mcfg = MeshDSGDConfig(num_factors=8, lambda_=0.01, iterations=4,
                              learning_rate=0.05, lr_schedule="constant",
                              seed=0, minibatch_size=256, init_scale=0.3)
        mm = MeshDSGD(mcfg, mesh=mesh).fit(train)

        scfg = DSGDConfig(num_factors=8, lambda_=0.01, iterations=4,
                          learning_rate=0.05, lr_schedule="constant",
                          seed=0, minibatch_size=256, init_scale=0.3)
        sm = DSGD(scfg).fit(train, num_blocks=4)

        np.testing.assert_allclose(np.asarray(mm.U), np.asarray(sm.U),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(mm.V), np.asarray(sm.V),
                                   rtol=2e-3, atol=2e-4)

    def test_pallas_kernel_matches_single_device(self, gen):
        """kernel='pallas' on the mesh (per-device block sweeps through the
        VMEM-staged Pallas path inside shard_map, interpret mode on CPU)
        must match the single-device XLA run — so a measured kernel win on
        hardware needs zero plumbing on the mesh too (VERDICT r4 #4).
        Decaying schedule on purpose: exercises the runtime-scalar η."""
        train = gen.generate(10000)
        mesh = make_block_mesh(4)
        mcfg = MeshDSGDConfig(num_factors=8, lambda_=0.01, iterations=3,
                              learning_rate=0.05,
                              lr_schedule="inverse_sqrt",
                              seed=0, minibatch_size=256, init_scale=0.3,
                              kernel="pallas")
        mm = MeshDSGD(mcfg, mesh=mesh).fit(train)

        scfg = DSGDConfig(num_factors=8, lambda_=0.01, iterations=3,
                          learning_rate=0.05, lr_schedule="inverse_sqrt",
                          seed=0, minibatch_size=256, init_scale=0.3)
        sm = DSGD(scfg).fit(train, num_blocks=4)

        np.testing.assert_allclose(np.asarray(mm.U), np.asarray(sm.U),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(mm.V), np.asarray(sm.V),
                                   rtol=2e-3, atol=2e-4)

    def test_bf16_tracks_f32_at_small_lr(self, gen):
        """factor_dtype='bfloat16' on the mesh XLA route must CONVERGE
        like f32, not just run: the route upcasts once per jitted
        segment (the whole scan), so gradient accumulation is exact
        across every sweep. The regression this pins: rounding to bf16
        after every block sweep silently swallows small-lr updates
        (below bf16's ~8-bit mantissa) — measured as RMSE frozen at the
        init plateau while f32 kept converging. Small lr on purpose."""
        train = gen.generate(10000)
        test = gen.generate(2000)
        mesh = make_block_mesh(4)

        def run(dt):
            cfg = MeshDSGDConfig(num_factors=8, lambda_=0.02,
                                 iterations=12, learning_rate=0.02,
                                 lr_schedule="constant", seed=0,
                                 minibatch_size=256, init_scale=0.3,
                                 factor_dtype=dt)
            return MeshDSGD(cfg, mesh=mesh).fit(train)

        mf, mh = run("float32"), run("bfloat16")
        assert str(mh.U.dtype) == "bfloat16"
        rf, rh = mf.rmse(test), mh.rmse(test)
        # segment-cadence rounding: one bf16 round on exit — the RMSE
        # gap is quantization noise, not a convergence gap
        assert abs(rf - rh) < 0.02, (rf, rh)

    def test_convergence_8_devices(self):
        # fresh generator: the shared module fixture's RNG position depends
        # on which tests ran before (order-dependent data)
        gen = SyntheticMFGenerator(num_users=200, num_items=150, rank=8,
                                   noise=0.05, seed=42)
        train = gen.generate(15000)
        test = gen.generate(2000)
        # 200 users / 8 devices = 25 distinct user rows per block: keep the
        # minibatch at or below the block width (see test_dsgd.py note).
        cfg = MeshDSGDConfig(num_factors=8, lambda_=0.01, iterations=30,
                             learning_rate=0.1, lr_schedule="constant",
                             seed=0, minibatch_size=32, init_scale=0.3)
        model = MeshDSGD(cfg, mesh=make_block_mesh(8)).fit(train)
        rmse = model.rmse(test)
        assert rmse < 0.12, f"mesh RMSE {rmse}"

    def test_convergence_on_skewed_data(self):
        """Power-law user/item popularity (≙ ExponentialRatingGen workloads)
        must not break mesh-DSGD convergence or blow up stratum padding."""
        gen = SyntheticMFGenerator(num_users=240, num_items=160, rank=8,
                                   noise=0.05, seed=11, skew_lam=2.5)
        train = gen.generate(20000)
        test = gen.generate(2000)
        prob = blocking.block_problem(train, num_blocks=8, seed=0)
        assert prob.ratings.max_pad_ratio < 1.5, prob.ratings.max_pad_ratio
        cfg = MeshDSGDConfig(num_factors=8, lambda_=0.01, iterations=30,
                             learning_rate=0.1, lr_schedule="constant",
                             seed=0, minibatch_size=32, init_scale=0.3)
        model = MeshDSGD(cfg, mesh=make_block_mesh(8)).fit(train)
        rmse = model.rmse(test)
        assert rmse < 0.12, f"skewed mesh RMSE {rmse}"

    def test_output_sharded_over_mesh(self, gen):
        train = gen.generate(5000)
        mesh = make_block_mesh(4)
        cfg = MeshDSGDConfig(num_factors=4, iterations=2, seed=0,
                             minibatch_size=128)
        model = MeshDSGD(cfg, mesh=mesh).fit(train)
        # U stays sharded over the block axis (no implicit gather)
        assert len(model.U.sharding.device_set) == 4
