"""Tiered factor store (``store/``, ISSUE 17): the host-RAM cold tier
behind a fixed-capacity device slot pool.

The pinned invariant everything here defends: tiered training and
serving are BIT-EXACT with the untiered baseline at ANY slot capacity
that fits the concurrently pinned working set — the tier moves bytes,
never values. Covered: bit-exactness at {∞, ~2×, ~1.1×} of the
per-batch working set (evictions active at the small capacities), the
async prefetcher racing the trainer, N=2 row-disjoint concurrent
applies with eviction write-back under both threads, kill/restart with
a dirty slot pool, the mmap-backed cold tier, read-only serving
gathers, the overcommit guard (with no leaked pins), and the STORE obs
surface (/storez, bundle freeze, MonotonicGrowthCheck wiring).
"""

import json
import os
import threading

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.core.initializers import (
    PseudoRandomFactorInitializer,
)
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.store import (
    StorePrefetcher,
    TieredFactorStore,
)
from large_scale_recommendation_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_online_state,
    save_online_state,
)

RANK = 4


@pytest.fixture(autouse=True)
def _reset_store_plane():
    """Construction installs the store as the process STORE plane —
    never leak a test's store into the next test."""
    from large_scale_recommendation_tpu.obs.store import (
        get_store,
        set_store,
    )

    prev = get_store()
    yield
    set_store(prev)


def _tiered_users(cfg, slots, capacity=64, mmap_dir=None):
    # the EXACT initializer OnlineMF builds — same per-id pseudo-random
    # rows, so tiered-vs-plain diffs can only come from the tier itself
    return TieredFactorStore(
        PseudoRandomFactorInitializer(cfg.num_factors,
                                      scale=cfg.init_scale),
        capacity=capacity, slot_capacity=slots, mmap_dir=mmap_dir)


def _model(slots=None, mmap_dir=None, minibatch=32):
    cfg = OnlineMFConfig(num_factors=RANK, minibatch_size=minibatch)
    m = OnlineMF(cfg)
    if slots is not None:
        m.users = _tiered_users(m.config, slots, mmap_dir=mmap_dir)
    return m


def _batches(n_batches=8, users=100, per_batch_users=30, items=24,
             seed=0):
    """Each batch touches EXACTLY ``per_batch_users`` distinct users
    (2 ratings each) out of a universe ``slot_capacity`` can't hold —
    small pools must evict between batches yet stay exact."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        uu = rng.permutation(users)[:per_batch_users]
        u = np.repeat(uu, 2).astype(np.int64)
        i = rng.integers(0, items, u.size).astype(np.int64)
        out.append(Ratings.from_arrays(
            u, i, rng.random(u.size).astype(np.float32)))
    return out


def _train(m, batches, **kw):
    for b in batches:
        m.partial_fit(b, emit_updates=False, **kw)
    return m


def _table(m):
    """Registered user rows only — a plain table's ``full_table`` is
    its whole (pow2-capacity) array, a tiered store's is its own
    capacity; the comparable region is the first ``num_rows``."""
    return np.asarray(m.users.full_table())[: m.users.num_rows]


# --------------------------------------------------------------------------
# Bit-exactness across capacities
# --------------------------------------------------------------------------


class TestBitExactness:
    def test_tiered_matches_untiered_at_every_capacity(self):
        """∞ (pool ≥ whole table), ~2× and ~1.1× the 30-row per-batch
        working set. The small pools evict and write back constantly;
        the final tables, predictions and RMSE must still be
        byte-identical to the plain GrowableFactorTable run."""
        batches = _batches()
        probe_u, probe_i = [3, 50, 97], [1, 11, 23]
        base = _train(_model(), batches)
        U0 = _table(base)
        p0 = np.asarray(base.predict(probe_u, probe_i))
        r0 = base.rmse(batches[0])

        for slots in (128, 64, 32):
            m = _train(_model(slots=slots), batches)
            st = m.users
            assert isinstance(st, TieredFactorStore)
            assert st.num_rows == base.users.num_rows
            np.testing.assert_array_equal(_table(m), U0)
            np.testing.assert_array_equal(
                np.asarray(m.predict(probe_u, probe_i)), p0)
            assert m.rmse(batches[0]) == r0
            # pins all returned, accounting consistent
            snap = st.snapshot()
            assert snap["hot"]["pinned"] == 0
            assert st.stats.hits + st.stats.misses > 0
            if slots < 100:  # universe is 100 rows: eviction forced
                assert st.stats.evictions > 0
                assert st.stats.writebacks > 0

    def test_prefetcher_racing_trainer_stays_bit_exact(self):
        """The async worker stages each NEXT batch's ids while the
        trainer runs the current one — lookahead changes hit rate,
        never values."""
        batches = _batches()
        base = _train(_model(), batches)
        U0 = _table(base)

        m = _model(slots=32)
        pf = StorePrefetcher(m.users).start()
        try:
            for k, b in enumerate(batches):
                if k + 1 < len(batches):
                    pf.submit(np.unique(b.users))  # announce lookahead
                m.partial_fit(b, emit_updates=False)
            pf.drain()
        finally:
            pf.stop()
        np.testing.assert_array_equal(_table(m), U0)
        assert pf.submitted > 0
        assert m.users.stats.prefetched >= 0  # best-effort plane

    def test_prefetch_hits_cut_demand_misses(self):
        """Sequential control: announce a KNOWN batch, drain, THEN
        acquire — every acquire is a hit and the demand path faults
        nothing."""
        cfg = OnlineMFConfig(num_factors=RANK)
        st = _tiered_users(cfg, slots=32)
        ids = np.arange(20)
        st.ensure(ids)  # register: rows land cold, not resident
        st.prefetch(ids)
        assert st.stats.prefetched == 20
        assert st.stats.misses == 0  # prefetch is not demand traffic
        rows = st.acquire_rows(ids)
        st.release_rows(rows)
        assert st.stats.hits == 20
        assert st.stats.misses == 0
        assert st.stats.hit_rate == 1.0

    def test_prefetch_never_registers_ids(self):
        """id→row assignment is FIRST-SEEN order and belongs to the
        training path alone: a prefetcher announcing unregistered ids
        (it sees batch N+1 while batch N trains, in np.unique-sorted
        order) must drop them, or a tiered run's vocabulary would be
        a permutation of the untiered run's — per-id values equal,
        row-for-row tables NOT (the exact failure the WAL-driven
        bench first exposed)."""
        cfg = OnlineMFConfig(num_factors=RANK)
        st = _tiered_users(cfg, slots=32)
        assert st.prefetch(np.arange(50, 70)) == 0  # all unknown: no-op
        assert st.num_rows == 0
        assert st.stats.prefetched == 0
        # training then assigns rows in ITS order, unperturbed
        rows = st.acquire_rows(np.asarray([60, 55, 50]))
        st.release_rows(rows)
        r, found = st.rows_for(np.asarray([60, 55, 50]))
        assert (found > 0).all()
        np.testing.assert_array_equal(r, [0, 1, 2])
        # fresh first-seen registrations are installs, not tier misses
        assert st.stats.installs == 3
        assert st.stats.misses == 0
        assert st.stats.hit_rate == 1.0


# --------------------------------------------------------------------------
# Concurrent applies with eviction write-back
# --------------------------------------------------------------------------


class TestConcurrentEviction:
    def _streams(self, n_parts=2, n_batches=6, seed=0):
        """Row-disjoint streams: thread p's users ≡ p (mod 2), items in
        block p. 16 distinct users per batch per thread — both pinned
        sets fit a 32-slot pool together, while the 100-user universe
        forces evictions."""
        rng = np.random.default_rng(seed)
        streams = []
        for p in range(n_parts):
            bs = []
            for _ in range(n_batches):
                uu = rng.choice(50, 16, replace=False) * n_parts + p
                u = np.repeat(uu, 4).astype(np.int64)
                i = (rng.integers(0, 12, u.size) + p * 12).astype(
                    np.int64)
                bs.append(Ratings.from_arrays(
                    u, i, rng.random(u.size).astype(np.float32)))
            streams.append(bs)
        return streams

    def test_n2_disjoint_threads_match_serial_bitexact(self):
        """The Gemulla pin composed with the tier: row-disjoint applies
        commute AND the slot pool under both threads evicts/writes back
        without tearing either stratum."""
        from large_scale_recommendation_tpu.streams.parallel import (
            RowConflictGate,
        )

        streams = self._streams()
        serial = _model(slots=32)
        for bs in streams:
            for b in bs:
                serial.partial_fit(b, emit_updates=False)

        conc = _model(slots=32)
        conc.enable_concurrent_applies()
        conc.apply_gate = RowConflictGate()
        errs = []

        def consume(bs):
            try:
                for b in bs:
                    conc.partial_fit(b, emit_updates=False)
            except BaseException as e:  # surfaced below
                errs.append(e)

        threads = [threading.Thread(target=consume, args=(bs,))
                   for bs in streams]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert conc.step == serial.step
        assert conc.users.stats.evictions > 0  # the race we're pinning
        # align by id: registration order differs across interleavings
        for side in ("users", "items"):
            st, ct = getattr(serial, side), getattr(conc, side)
            ids = np.sort(st.id_array())
            np.testing.assert_array_equal(ids, np.sort(ct.id_array()))
            np.testing.assert_array_equal(st.lookup(ids),
                                          ct.lookup(ids))
        assert conc.users.snapshot()["hot"]["pinned"] == 0


# --------------------------------------------------------------------------
# Kill/restart with a dirty slot pool
# --------------------------------------------------------------------------


class TestKillRestart:
    def test_restart_with_dirty_pool_resumes_bit_exact(self, tmp_path):
        """Checkpoint mid-stream while the pool holds dirty slots, then
        'crash': a fresh process restores, re-warms the snapshot's hot
        set, and finishing the stream lands byte-identical to the
        uninterrupted run."""
        batches = _batches()
        full = _train(_model(slots=32), batches)
        U_full = _table(full)

        m = _train(_model(slots=32), batches[:5],
                   offset=(0, 5))
        assert m.users.dirty_rows().size > 0  # pool dirty at capture
        mgr = CheckpointManager(str(tmp_path))
        save_online_state(mgr, m, step=5)

        fresh = _model(slots=32)
        ck = restore_online_state(mgr, fresh)
        assert fresh.consumed_offsets == {0: 5}
        np.testing.assert_array_equal(_table(fresh), _table(m))
        # the snapshot's resident set came back hot
        assert set(fresh.users.resident_rows()) == \
            set(m.users.resident_rows())
        assert ck.meta["step"] == 5

        _train(fresh, batches[5:])
        np.testing.assert_array_equal(_table(fresh), U_full)

    def test_tiered_checkpoint_restores_into_plain_model(self, tmp_path):
        """Cross-compat both ways: the tier is a storage detail, not a
        format — a tiered snapshot restores into an untiered model (and
        the tables agree) because rows are the same first-seen order."""
        m = _train(_model(slots=32), _batches(n_batches=4))
        mgr = CheckpointManager(str(tmp_path))
        save_online_state(mgr, m, step=4)

        plain = _model()
        restore_online_state(mgr, plain)
        np.testing.assert_array_equal(_table(plain), _table(m))


# --------------------------------------------------------------------------
# Cold-tier backing, serving, guards
# --------------------------------------------------------------------------


class TestColdTierAndServing:
    def test_mmap_backed_cold_tier_is_bit_exact(self, tmp_path):
        batches = _batches(n_batches=5)
        base = _train(_model(), batches)
        m = _train(_model(slots=32, mmap_dir=str(tmp_path)), batches)
        np.testing.assert_array_equal(_table(m), _table(base))
        assert any(f.startswith("cold_") for f in os.listdir(tmp_path))
        assert m.users.snapshot()["cold"]["mmap"] is True

    def test_serve_rows_merges_hot_and_cold_readonly(self):
        """Serving gathers hot rows from the pool and cold rows from
        the host tier WITHOUT admitting them — the resident set (and
        training's working set) is untouched by a serve scan."""
        m = _train(_model(slots=32), _batches(n_batches=5))
        st = m.users
        resident_before = set(st.resident_rows())
        n = st.num_rows
        rows = np.arange(n)
        got = np.asarray(st.serve_rows(rows))
        np.testing.assert_array_equal(got,
                                      np.asarray(st.full_table())[:n])
        assert set(st.resident_rows()) == resident_before
        assert st.stats.serve_hits + st.stats.serve_misses == n
        assert st.stats.serve_misses > 0  # 100-row scan over 32 slots

    def test_overcommit_raises_with_accounting_and_no_leaked_pins(self):
        cfg = OnlineMFConfig(num_factors=RANK)
        st = _tiered_users(cfg, slots=8)
        with pytest.raises(RuntimeError, match="overcommitted"):
            st.acquire_rows(np.arange(20))
        # a raising acquire must leak no refcounts: everything it
        # pinned on the way in is unpinned on the way out
        assert st.snapshot()["hot"]["pinned"] == 0
        rows = st.acquire_rows(np.arange(8))  # pool-sized batch: fine
        st.release_rows(rows)
        assert st.snapshot()["hot"]["pinned"] == 0


# --------------------------------------------------------------------------
# STORE obs surface
# --------------------------------------------------------------------------


class TestStoreObs:
    def test_storez_route_and_index(self, null_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        obs.enable()
        m = _train(_model(slots=32), _batches(n_batches=3))
        with ObsServer() as server:
            code, body = http_get(server.url + "/storez")
            icode, ibody = http_get(server.url + "/")
        assert code == 200
        doc = json.loads(body)
        assert doc["hot"]["slot_capacity"] == 32
        assert doc["cold"]["rows"] == m.users.num_rows
        assert doc["stats"]["hits"] + doc["stats"]["misses"] > 0
        assert "/storez" in json.loads(ibody)["routes"]

    def test_storez_without_store_is_a_note(self, null_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        obs.enable()
        with ObsServer() as server:
            code, body = http_get(server.url + "/storez")
        assert code == 200
        assert "no tiered store" in json.loads(body)["note"]

    def test_bundle_freezes_store_and_monitor_watches_host_bytes(
            self, null_obs, tmp_path):
        """One v5 bundle carries store.json; the registry gauges the
        store publishes auto-sample into the recorder, and
        watch_store_memory gates tier_host_bytes growth on them."""
        from large_scale_recommendation_tpu.obs.health import (
            HealthMonitor,
        )
        from large_scale_recommendation_tpu.obs.recorder import (
            get_recorder,
            load_bundle,
            write_bundle,
        )

        obs.enable()
        obs.enable_flight_recorder(interval_s=0.05)
        rec = get_recorder()
        try:
            m = _train(_model(slots=32), _batches(n_batches=3))
            rec.sample()
            assert any(s.startswith("tier_host_bytes")
                       for s in rec.series_names())
            mon = HealthMonitor()
            mon.watch_store_memory(rec)
            report = mon.run()
            assert report["checks"]["store_memory"]["status"] == "ok"

            out = write_bundle(str(tmp_path), trigger="test")
            doc = load_bundle(out)
            assert doc["manifest"]["bundle_version"] == 7
            assert doc["store"]["hot"]["slot_capacity"] == 32
            assert doc["store"]["cold"]["rows"] == m.users.num_rows
        finally:
            obs.disable()

    def test_disable_resets_store_plane(self, null_obs):
        from large_scale_recommendation_tpu.obs.store import get_store

        obs.enable()
        _model(slots=32)
        assert get_store() is not None
        obs.disable()
        assert get_store() is None
