"""Multi-host design spike (VERDICT r2 task 5): jax.distributed bring-up,
per-host rating sharding, and the mesh-DSGD superstep loop running over a
process-spanning mesh — driven as a REAL 2-process run on localhost.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from large_scale_recommendation_tpu.parallel.distributed import (
    DistributedConfig,
    host_rating_shard,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHostShard:
    def test_shards_tile_the_dataset(self):
        """≙ partitionCustom by user (PSOfflineMF.scala:70-72): the per-host
        filters are disjoint and complete."""
        rng = np.random.default_rng(0)
        ru = rng.integers(0, 1000, 5000)
        ri = rng.integers(0, 300, 5000)
        rv = rng.normal(size=5000).astype(np.float32)
        parts = [host_rating_shard(ru, ri, rv, p, 3) for p in range(3)]
        assert sum(len(p[0]) for p in parts) == 5000
        seen = np.concatenate([np.stack([p[0], p[1]]) for p in parts], axis=1)
        assert seen.shape[1] == 5000
        # user-disjoint: a user's ratings land on exactly one host
        for p, (u, _, _) in enumerate(parts):
            assert (np.abs(u) % 3 == p).all()

    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("LSR_COORDINATOR", "1.2.3.4:555")
        monkeypatch.setenv("LSR_NUM_PROCESSES", "4")
        monkeypatch.setenv("LSR_PROCESS_ID", "2")
        cfg = DistributedConfig.from_env()
        assert cfg == DistributedConfig("1.2.3.4:555", 4, 2)

    def test_single_process_is_noop(self):
        from large_scale_recommendation_tpu.parallel.distributed import (
            initialize_distributed,
        )

        assert initialize_distributed(DistributedConfig()) is False


@pytest.mark.slow
class TestTwoProcessDemo:
    def test_two_process_cpu_demo(self, tmp_path):
        """Launch the demo as two REAL processes coordinated over localhost;
        the global 4-device mesh spans both, so the ppermute ring crosses
        the process boundary (the DCN hop of SURVEY §2.3). LSR_CKPT_DIR
        additionally exercises per-shard checkpoint save/restore across the
        2-process mesh (each process writes only its own device rows)."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env_base = {
            **os.environ,
            "LSR_COORDINATOR": f"127.0.0.1:{port}",
            "LSR_NUM_PROCESSES": "2",
            "JAX_PLATFORMS": "cpu",
            "LSR_CKPT_DIR": str(tmp_path),
        }
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "examples", "distributed_demo.py")],
                env={**env_base, "LSR_PROCESS_ID": str(p)},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=REPO,
            )
            for p in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                outs.append(out)
        finally:
            for p in procs:
                p.kill()
        assert all(p.returncode == 0 for p in procs), \
            "\n---\n".join(outs)[-4000:]
        assert "DISTRIBUTED DEMO PASS" in outs[0], outs[0][-2000:]
        for p, out in enumerate(outs):
            assert "SHARDED CKPT RESUME OK" in out, out[-2000:]
            assert "mesh-ALS" in out and "parity OK" in out, out[-2000:]
        # both processes wrote their own shard file + one manifest exists
        names = os.listdir(tmp_path)
        assert any(".shard0of2" in n for n in names), names
        assert any(".shard1of2" in n for n in names), names
        assert any(n.endswith(".manifest.json") for n in names), names


class TestGlobalDeviceBlocking:
    """global_device_blocked on the single-process virtual mesh: the
    degenerate (1-process) case must reproduce the single-device pipeline's
    layout exactly (same seeds, same math, mesh placement only)."""

    def test_matches_single_device_pipeline(self):
        import jax
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data import device_blocking as db
        from large_scale_recommendation_tpu.parallel.distributed import (
            global_device_blocked,
        )
        from large_scale_recommendation_tpu.parallel.mesh import (
            make_block_mesh,
        )

        rng = np.random.default_rng(12)
        n, nu, ni = 4096, 100, 80
        u = rng.integers(0, nu, n)
        i = rng.integers(0, ni, n)
        r = rng.normal(0, 1, n).astype(np.float32)
        w = np.ones(n, np.float32)
        mesh = make_block_mesh(4)
        g = global_device_blocked(u, i, r, w, nu, ni, mesh,
                                  minibatch_multiple=64, seed=3, rank=6,
                                  init_scale=0.2)
        p = db.device_block_problem(u, i, r, nu, ni, num_blocks=4,
                                    minibatch_multiple=64, seed=3)
        np.testing.assert_array_equal(
            np.asarray(g.ru),
            np.asarray(jnp.transpose(p.su, (1, 0, 2)) % p.rows_per_block_u))
        np.testing.assert_array_equal(
            np.asarray(g.rv), np.asarray(jnp.transpose(p.sv, (1, 0, 2))))
        np.testing.assert_array_equal(np.asarray(g.row_of_user),
                                      np.asarray(p.row_of_user))
        np.testing.assert_allclose(np.asarray(g.icu),
                                   np.asarray(jnp.transpose(p.icu, (1, 0, 2))))
        U_ref, _ = db.init_factors_device(p, 6, scale=0.2)
        np.testing.assert_allclose(np.asarray(g.U), np.asarray(U_ref),
                                   rtol=1e-6)
        # sharded placement: strata carry the device-major sharding
        assert len(g.ru.sharding.device_set) == 4

    def test_trains_through_mesh_step(self):
        """The returned arrays drive build_mesh_dsgd_step directly and
        converge — the full multi-host training shape, single process."""
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.core.updaters import (
            RegularizedSGDUpdater,
            constant_lr,
        )
        from large_scale_recommendation_tpu.ops import sgd as sgd_ops
        from large_scale_recommendation_tpu.parallel.distributed import (
            global_device_blocked,
        )
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
            build_mesh_dsgd_step,
        )
        from large_scale_recommendation_tpu.parallel.mesh import (
            make_block_mesh,
        )

        gen = SyntheticMFGenerator(num_users=200, num_items=150, rank=4,
                                   noise=0.05, seed=5)
        train, test = gen.generate(20_000), gen.generate(2_000)
        ru, ri, rv, _ = train.to_numpy()
        mesh = make_block_mesh(4)
        g = global_device_blocked(ru, ri, rv, np.ones(len(ru), np.float32),
                                  200, 150, mesh, minibatch_multiple=128,
                                  seed=0, rank=8, init_scale=0.2)
        upd = RegularizedSGDUpdater(learning_rate=0.2, lambda_=0.02,
                                    schedule=constant_lr)
        step = build_mesh_dsgd_step(mesh, upd, 128, 4, iterations=15,
                                    collision="mean", with_inv=True)
        U, V = step(g.U, g.V, g.ru, g.ri, g.rv, g.rw, g.omega_u, g.omega_v,
                    g.icu, g.icv, jnp.asarray(0, jnp.int32))
        hu, hi, hv, _ = test.to_numpy()
        hur, hir, hmask = g.holdout_rows(hu, hi)
        sse = sgd_ops.sse_rows(U, V, jnp.asarray(hur), jnp.asarray(hir),
                               jnp.asarray(hv), jnp.asarray(hmask))
        rmse = float(np.sqrt(float(sse) / hmask.sum()))
        assert rmse < 0.15  # noise floor 0.05

    def test_weight_padded_shards_match_unpadded(self):
        """Equal-length per-host shards via w=0 padding: padded global
        blocking must produce the same real content as unpadded."""
        from large_scale_recommendation_tpu.parallel.distributed import (
            global_device_blocked,
        )
        from large_scale_recommendation_tpu.parallel.mesh import (
            make_block_mesh,
        )

        rng = np.random.default_rng(9)
        n, nu, ni = 2000, 60, 50
        u = rng.integers(0, nu, n)
        i = rng.integers(0, ni, n)
        r = rng.normal(0, 1, n).astype(np.float32)
        mesh = make_block_mesh(4)
        plain = global_device_blocked(u, i, r, np.ones(n, np.float32),
                                      nu, ni, mesh, minibatch_multiple=32,
                                      seed=1)
        pad = 48
        up = np.concatenate([u, np.zeros(pad, np.int64)])
        ip = np.concatenate([i, np.zeros(pad, np.int64)])
        rp = np.concatenate([r, np.zeros(pad, np.float32)])
        wp = np.concatenate([np.ones(n, np.float32),
                             np.zeros(pad, np.float32)])
        padded = global_device_blocked(up, ip, rp, wp, nu, ni, mesh,
                                       minibatch_multiple=32, seed=1)

        def real(g):
            rw = np.asarray(g.rw) > 0
            return sorted(zip(np.asarray(g.ru)[rw].tolist(),
                              np.asarray(g.ri)[rw].tolist(),
                              np.asarray(g.rv)[rw].tolist()))

        assert real(plain) == real(padded)
        np.testing.assert_array_equal(plain.row_of_user,
                                      padded.row_of_user)
