"""Multi-host design spike (VERDICT r2 task 5): jax.distributed bring-up,
per-host rating sharding, and the mesh-DSGD superstep loop running over a
process-spanning mesh — driven as a REAL 2-process run on localhost.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from large_scale_recommendation_tpu.parallel.distributed import (
    DistributedConfig,
    host_rating_shard,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHostShard:
    def test_shards_tile_the_dataset(self):
        """≙ partitionCustom by user (PSOfflineMF.scala:70-72): the per-host
        filters are disjoint and complete."""
        rng = np.random.default_rng(0)
        ru = rng.integers(0, 1000, 5000)
        ri = rng.integers(0, 300, 5000)
        rv = rng.normal(size=5000).astype(np.float32)
        parts = [host_rating_shard(ru, ri, rv, p, 3) for p in range(3)]
        assert sum(len(p[0]) for p in parts) == 5000
        seen = np.concatenate([np.stack([p[0], p[1]]) for p in parts], axis=1)
        assert seen.shape[1] == 5000
        # user-disjoint: a user's ratings land on exactly one host
        for p, (u, _, _) in enumerate(parts):
            assert (np.abs(u) % 3 == p).all()

    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("LSR_COORDINATOR", "1.2.3.4:555")
        monkeypatch.setenv("LSR_NUM_PROCESSES", "4")
        monkeypatch.setenv("LSR_PROCESS_ID", "2")
        cfg = DistributedConfig.from_env()
        assert cfg == DistributedConfig("1.2.3.4:555", 4, 2)

    def test_single_process_is_noop(self):
        from large_scale_recommendation_tpu.parallel.distributed import (
            initialize_distributed,
        )

        assert initialize_distributed(DistributedConfig()) is False


@pytest.mark.slow
class TestTwoProcessDemo:
    def test_two_process_cpu_demo(self):
        """Launch the demo as two REAL processes coordinated over localhost;
        the global 4-device mesh spans both, so the ppermute ring crosses
        the process boundary (the DCN hop of SURVEY §2.3)."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env_base = {
            **os.environ,
            "LSR_COORDINATOR": f"127.0.0.1:{port}",
            "LSR_NUM_PROCESSES": "2",
            "JAX_PLATFORMS": "cpu",
        }
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "examples", "distributed_demo.py")],
                env={**env_base, "LSR_PROCESS_ID": str(p)},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=REPO,
            )
            for p in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                outs.append(out)
        finally:
            for p in procs:
                p.kill()
        assert all(p.returncode == 0 for p in procs), \
            "\n---\n".join(outs)[-4000:]
        assert "DISTRIBUTED DEMO PASS" in outs[0], outs[0][-2000:]
