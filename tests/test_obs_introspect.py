"""XLA introspection tests (ISSUE 9): compile-boundary capture on the
production jit geometries, the roofline join math pinned against a hand
reference, the XLA-vs-hand-model bytes cross-check for the DSGD sweep,
device-memory telemetry with the CPU graceful-absent path, profiler
capture layer routing, and the /rooflinez + /profilez endpoint routes
over a real socket."""

from __future__ import annotations

import json
import os
import warnings

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs import introspect as intro
from large_scale_recommendation_tpu.obs.introspect import (
    Introspector,
    capture_profile,
    profile_trace,
    render_key,
    roofline_rows,
)
from large_scale_recommendation_tpu.obs.registry import MetricsRegistry
from large_scale_recommendation_tpu.obs.trace import Tracer


@pytest.fixture
def live_introspection(null_obs):
    """A live obs layer (fresh registry/tracer) with an installed
    introspector, fully restored after — rides null_obs so the previous
    layer (an OBS_OUT session's, say) comes back exactly."""
    reg, tracer = obs.enable(MetricsRegistry(), Tracer())
    introspector = obs.enable_introspection(start=False)
    assert introspector.installed
    yield reg, tracer, introspector
    # null_obs's teardown restores the previous layer; disable() here
    # removes OUR hook first so layers can't stack
    obs.disable()


def _tiny_ratings(n=6000, users=300, items=120, seed=0):
    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )

    return SyntheticMFGenerator(num_users=users, num_items=items, rank=4,
                                noise=0.1, seed=seed).generate(n)


class TestRenderKey:
    def test_forms(self):
        assert render_key("serving_flush") == "serving_flush"
        assert render_key(("online_train", 512)) == "online_train/512"
        assert render_key(("train_segment", "dsgd", (300, 8))) == \
            "train_segment/dsgd/(300, 8)"

    def test_stable(self):
        key = ("train_segment", "dsgd", (300, 8), (120, 8))
        assert render_key(key) == render_key(tuple(key))


class TestCompileCapture:
    """Cost-analysis capture on every production jit geometry, CPU
    backend: keys present, flops > 0, bytes > 0."""

    def test_dsgd_segment_key(self, live_introspection):
        _, _, ins = live_introspection
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )

        DSGD(DSGDConfig(num_factors=8, iterations=2, num_blocks=2,
                        minibatch_size=512, learning_rate=0.05)
             ).fit(_tiny_ratings(), checkpoint_every=1)
        recs = [r for r in ins.records()
                if r["key"].startswith("train_segment/dsgd")]
        assert recs, [r["key"] for r in ins.records()]
        dom = max(recs, key=lambda r: r["bytes_accessed"])
        assert dom["flops"] > 0
        assert dom["bytes_accessed"] > 0
        assert dom["compile_wall_s"] > 0
        assert dom["compiles"] >= 1

    def test_als_segment_key(self, live_introspection):
        _, _, ins = live_introspection
        from large_scale_recommendation_tpu.models.als import ALS, ALSConfig

        ALS(ALSConfig(num_factors=8, iterations=2, lambda_=0.1,
                      seed=0)).fit(_tiny_ratings())
        recs = [r for r in ins.records()
                if r["key"].startswith("train_segment/als")]
        assert recs, [r["key"] for r in ins.records()]
        dom = max(recs, key=lambda r: r["bytes_accessed"])
        assert dom["flops"] > 0 and dom["bytes_accessed"] > 0

    def test_online_partial_fit_key(self, live_introspection):
        _, _, ins = live_introspection
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )

        model = OnlineMF(OnlineMFConfig(num_factors=8, minibatch_size=256))
        model.partial_fit(_tiny_ratings(2000))
        recs = [r for r in ins.records()
                if r["key"].startswith("online_train")]
        assert recs, [r["key"] for r in ins.records()]
        assert max(r["bytes_accessed"] for r in recs) > 0

    def test_serving_flush_key(self, live_introspection):
        _, _, ins = live_introspection
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data.blocking import flat_index
        from large_scale_recommendation_tpu.models.mf import MFModel
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )

        rng0 = np.random.default_rng(0)
        model = MFModel(
            U=jnp.asarray(rng0.normal(size=(300, 8)).astype(np.float32)),
            V=jnp.asarray(rng0.normal(size=(128, 8)).astype(np.float32)),
            users=flat_index(np.arange(300, dtype=np.int64)),
            items=flat_index(np.arange(128, dtype=np.int64)),
        )
        engine = ServingEngine(model, k=5, max_batch=64)
        rng = np.random.default_rng(3)
        engine.serve([rng.integers(0, 300, 8).astype(np.int64)
                      for _ in range(4)])
        recs = [r for r in ins.records()
                if r["key"].startswith("serving_flush")]
        assert recs, [r["key"] for r in ins.records()]
        assert max(r["flops"] for r in recs) > 0

    def test_stable_across_recompiles(self, live_introspection):
        """Recompiling the same geometry records the same analysis —
        cost_analysis is a function of the program, and the record
        keeps per-key totals across compiles."""
        _, tracer, ins = live_introspection
        import jax
        import jax.numpy as jnp

        x = jnp.ones((32, 32))
        results = []
        for _ in range(2):
            f = jax.jit(lambda a: jnp.tanh(a @ a.T).sum())  # fresh fn →
            with tracer.span("t", key=("recompile_pin", 32)):  # recompile
                f(x).block_until_ready()
            rec = [r for r in ins.records()
                   if r["key"] == "recompile_pin/32"]
            dom = max(rec, key=lambda r: r["bytes_accessed"])
            results.append((dom["flops"], dom["bytes_accessed"]))
        assert results[0] == results[1]
        dom = max((r for r in ins.records()
                   if r["key"] == "recompile_pin/32"),
                  key=lambda r: r["bytes_accessed"])
        assert dom["compiles"] == 2

    def test_metrics_published(self, live_introspection):
        reg, tracer, ins = live_introspection
        import jax
        import jax.numpy as jnp

        with tracer.span("t", key="metrics_pin"):
            jax.jit(lambda a: a * 2)(jnp.ones(64)).block_until_ready()
        names = reg.names()
        for name in ("compile_count", "compile_wall_s", "xla_flops",
                     "xla_bytes_accessed"):
            assert name in names, (name, sorted(names))

    def test_uninstall_restores_pristine_funnel(self, null_obs):
        import jax._src.compiler as compiler

        # force the true uninstalled state (an OBS_OUT session patches
        # suite-wide), then check install/uninstall round-trips
        prev = intro.get_introspector()
        if prev is not None:
            prev.uninstall()
        try:
            before = compiler.compile_or_get_cached
            assert not hasattr(before, "__lsr_introspector__")
            ins = Introspector()
            assert ins.install()
            assert compiler.compile_or_get_cached is not before
            # a second introspector cannot stack on the funnel
            assert Introspector().install() is False
            ins.uninstall()
            assert compiler.compile_or_get_cached is before
        finally:
            if prev is not None:
                prev.install()


class TestRooflineJoin:
    """The join math pinned against a hand-computed reference."""

    def test_pinned_reference(self):
        records = [
            {"key": "k1", "module": "jit_big", "compiles": 2,
             "compile_wall_s": 0.5, "flops": 2.0e9,
             "bytes_accessed": 4.0e8, "memory": None},
            {"key": "k1", "module": "jit_helper", "compiles": 1,
             "compile_wall_s": 0.1, "flops": 10.0,
             "bytes_accessed": 100.0, "memory": None},
            {"key": "k2", "module": "jit_cold", "compiles": 1,
             "compile_wall_s": 0.2, "flops": 5.0,
             "bytes_accessed": 50.0, "memory": None},
        ]
        # k1: 4 executions totalling 2 s, 8 iterations (2 per exec)
        walls = {"k1": {"compile_count": 1, "compile_total_s": 0.6,
                        "execute_count": 4, "execute_total_s": 2.0,
                        "execute_min_s": 0.4, "execute_max_s": 0.6,
                        "iterations": 8}}
        model_costs = {"k1": {"bytes_per_iteration": 1.0e8}}
        rows = roofline_rows(records, walls, model_costs,
                             hbm_peak_gbs=800.0, fp32_peak_tflops=50.0)
        by_key = {r["key"]: r for r in rows}
        r1 = by_key["k1"]
        # dominant module is jit_big; family sums compiles/walls
        assert r1["module"] == "jit_big"
        assert r1["compiles"] == 3
        assert r1["compile_wall_s"] == pytest.approx(0.6)
        # wall/exec = 2.0/4 = 0.5 s → 4e8 B / 0.5 s = 0.8 GB/s
        assert r1["wall_per_exec_s"] == pytest.approx(0.5)
        assert r1["achieved_gbs"] == pytest.approx(0.8)
        # 0.8 / 800 GB/s = 0.1% of HBM peak
        assert r1["pct_of_hbm_peak"] == pytest.approx(0.1)
        # 2e9 flops / 0.5 s = 4e-3 TFLOP/s → 0.008% of 50 TFLOP/s
        assert r1["achieved_tflops"] == pytest.approx(4.0e-3)
        assert r1["pct_of_fp32_peak"] == pytest.approx(0.008)
        # model: 1e8 B/iter × (8 iters / 4 execs) = 2e8 B/exec →
        # xla/model = 4e8 / 2e8 = 2.0
        assert r1["model_bytes_per_exec"] == pytest.approx(2.0e8)
        assert r1["xla_vs_model_bytes"] == pytest.approx(2.0)
        # k2 never executed: analysis present, measured columns None
        r2 = by_key["k2"]
        assert r2["xla_flops"] == 5.0
        assert r2["wall_per_exec_s"] is None
        assert r2["pct_of_hbm_peak"] is None

    def test_note_compiled_drives_same_path(self, null_obs):
        ins = Introspector(registry=null_obs)
        ins.note_compiled("fake_key", "jit_fake", flops=100.0,
                          bytes_accessed=200.0, wall_s=0.05)
        recs = ins.records()
        assert len(recs) == 1
        assert recs[0]["key"] == "fake_key"
        assert recs[0]["flops"] == 100.0
        assert ins.compile_count == 1
        assert ins.compile_wall_s == pytest.approx(0.05)

    def test_record_table_bounded(self, null_obs):
        ins = Introspector(registry=null_obs, max_records=3)
        for i in range(6):
            ins.note_compiled(f"k{i}", "jit_m", flops=1.0,
                              bytes_accessed=1.0)
        assert len(ins.records()) == 3
        assert ins.dropped == 3

    def test_tracer_key_walls_bounded(self, null_obs):
        """Compile keys embed shapes, so churning geometries mint fresh
        keys forever — the wall-aggregate table is hard-capped like
        every other obs table, overflow counted."""
        tracer = Tracer()
        tracer.max_key_walls = 3
        for i in range(6):
            with tracer.span("t", key=("churn", i)):
                pass
        assert len(tracer.key_walls()) == 3
        assert tracer.key_walls_dropped == 3
        # existing keys keep aggregating past the cap
        with tracer.span("t", key=("churn", 0)):
            pass
        assert tracer.key_walls()[("churn", 0)]["execute_count"] == 1


class TestDSGDBytesCrossCheck:
    """Acceptance: XLA's bytes-accessed for the XLA-route sweep agrees
    with ops.sgd.dsgd_bytes_per_sweep within the documented factor.

    XLA's static analysis counts each HLO's operand bytes (a gather is
    charged index+slice bytes once per op); the hand model charges 4
    full row transactions per rating — the latency-bound DRAM view.
    They agree to well within an order of magnitude on the production
    sweep geometry (measured ~0.4–2× on CPU across geometries); the
    documented acceptance band here is [1/16, 16] — a break means one
    of the two models changed meaning, which is exactly what this pin
    exists to catch (docs/OBSERVABILITY.md "Device introspection")."""

    def test_xla_route_sweep_within_documented_factor(
            self, live_introspection):
        _, _, ins = live_introspection
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )

        DSGD(DSGDConfig(num_factors=16, iterations=3, num_blocks=2,
                        minibatch_size=1024, learning_rate=0.05)
             ).fit(_tiny_ratings(20_000, users=600, items=300),
                   checkpoint_every=1)
        rows = [r for r in ins.roofline()["rows"]
                if r["key"].startswith("train_segment/dsgd")]
        assert rows
        row = max(rows, key=lambda r: r["xla_bytes_accessed"])
        ratio = row["xla_vs_model_bytes"]
        assert ratio is not None, row
        assert 1.0 / 16.0 <= ratio <= 16.0, row


class TestDeviceMemory:
    def test_cpu_graceful_absent(self, live_introspection):
        """CPU devices have no allocator stats surface: stats come back
        null, supported False, no byte gauges — and nothing raises."""
        reg, _, ins = live_introspection
        doc = ins.sample_device_memory()
        assert doc["supported"] is False
        assert len(doc["devices"]) >= 1
        assert all(d["stats"] is None for d in doc["devices"])
        assert "device_bytes_in_use" not in reg.names()
        # live-array accounting works regardless of allocator stats
        import jax.numpy as jnp

        keep = jnp.ones((64, 64), jnp.float32)
        doc = ins.sample_device_memory()
        assert doc["live_arrays"]["count"] >= 1
        assert doc["live_arrays"]["bytes"] >= keep.nbytes
        assert "float32" in doc["live_arrays"]["by_dtype"]
        assert "live_arrays_bytes" in reg.names()

    def test_bundle_carries_device_memory(self, live_introspection,
                                          tmp_path):
        from large_scale_recommendation_tpu.obs.recorder import (
            FlightRecorder,
            load_bundle,
        )

        rec = FlightRecorder(bundle_dir=str(tmp_path))
        rec.sample()
        path = rec.dump(trigger="manual")
        docs = load_bundle(path)  # validates device_memory.json shape
        assert docs["device_memory"]["supported"] is False
        assert isinstance(docs["device_memory"]["devices"], list)
        assert "live_arrays" in docs["device_memory"]

    def test_version1_bundle_still_loads(self, live_introspection,
                                         tmp_path):
        """Backward compat: an ARCHIVED incident bundle written before
        the device-introspection layer (bundle_version 1, no
        device_memory.json) must stay loadable — it is exactly the
        artifact the flight recorder exists to preserve."""
        from large_scale_recommendation_tpu.obs.recorder import (
            FlightRecorder,
            load_bundle,
        )

        rec = FlightRecorder(bundle_dir=str(tmp_path))
        rec.sample()
        path = rec.dump(trigger="manual")
        # rewrite as a faithful version-1 bundle
        os.remove(os.path.join(path, "device_memory.json"))
        mpath = os.path.join(path, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["bundle_version"] = 1
        manifest["files"] = [n for n in manifest["files"]
                             if n != "device_memory.json"]
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        docs = load_bundle(path)
        assert docs["manifest"]["bundle_version"] == 1
        assert docs["device_memory"]["devices"] == []  # synthesized note


class TestProfilerCapture:
    def test_capture_profile_writes_artifacts(self, null_obs, tmp_path):
        out = capture_profile(str(tmp_path / "prof"), seconds=0.05)
        assert out["files"], out
        assert os.path.isdir(out["dir"])
        assert intro.CAPTURE_COUNT >= 1

    def test_concurrent_capture_refused(self, null_obs, tmp_path):
        with profile_trace(str(tmp_path / "p1")):
            with pytest.raises(RuntimeError, match="already in progress"):
                with profile_trace(str(tmp_path / "p2")):
                    pass

    def test_utils_profile_shim_routes_through_capture_layer(
            self, null_obs, tmp_path):
        """Satellite: utils.metrics.profile no longer drives
        jax.profiler on its own — it routes through profile_trace (the
        shared lock + accounting) and warns about its deprecation."""
        from large_scale_recommendation_tpu.utils.metrics import profile

        before = intro.CAPTURE_COUNT
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with profile(str(tmp_path / "legacy")):
                pass
        assert intro.CAPTURE_COUNT == before + 1
        assert any(issubclass(w.category, DeprecationWarning)
                   for w in caught)
        # the None fast path stays a pure no-op: no capture, no warning
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with profile(None):
                pass
        assert intro.CAPTURE_COUNT == before + 1
        assert not caught

    def test_trip_bundle_attaches_profile(self, null_obs, tmp_path):
        from large_scale_recommendation_tpu.obs.recorder import (
            FlightRecorder,
        )

        rec = FlightRecorder(bundle_dir=str(tmp_path),
                             profile_on_trip_s=0.05)
        path = rec.dump(trigger="watchdog_trip")
        prof = os.path.join(path, "profile")
        assert os.path.isdir(prof)
        assert any(os.scandir(prof))
        # manual dumps stay capture-free (dumps are cheap by contract)
        path2 = rec.dump(trigger="manual")
        assert not os.path.isdir(os.path.join(path2, "profile"))


class TestEndpointRoutes:
    def test_rooflinez_and_profilez_over_socket(self, live_introspection,
                                                tmp_path):
        import jax
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        reg, tracer, ins = live_introspection
        with tracer.span("t", key=("endpoint_pin", 16)):
            jax.jit(lambda a: (a @ a.T).sum())(
                jnp.ones((16, 16))).block_until_ready()
        with tracer.span("t", key=("endpoint_pin", 16)) as sp:
            sp.out = jax.jit(lambda a: (a @ a.T).sum())(jnp.ones((16, 16)))
        with ObsServer(profile_dir=str(tmp_path)) as server:
            code, body = http_get(server.url + "/rooflinez")
            assert code == 200
            doc = json.loads(body)
            keys = [r["key"] for r in doc["rows"]]
            assert "endpoint_pin/16" in keys
            row = next(r for r in doc["rows"]
                       if r["key"] == "endpoint_pin/16")
            assert row["xla_flops"] > 0
            assert row["execute_count"] == 1  # first span was compile-cat
            assert row["pct_of_hbm_peak"] is not None
            # generous timeout: the capture itself is 0.05 s, but the
            # profiler's start/stop overhead scales with process state
            # (python tracer walks every thread) — in a full tier-1
            # session the round trip measurably exceeds http_get's 10 s
            # default
            code, body = http_get(server.url + "/profilez?seconds=0.05",
                                  timeout=180.0)
            assert code == 200, body
            out = json.loads(body)
            assert out["files"], out
            assert out["dir"].startswith(str(tmp_path))
            # a malformed seconds param is a CLIENT error (400), not a
            # capture-layer failure (500)
            code, body = http_get(server.url + "/profilez?seconds=abc")
            assert code == 400, (code, body)
            # the route list advertises both
            code, body = http_get(server.url + "/")
            assert "/rooflinez" in body and "/profilez" in body

    def test_rooflinez_without_introspector(self, null_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        with ObsServer() as server:
            code, body = http_get(server.url + "/rooflinez")
            assert code == 200
            assert json.loads(body)["rows"] == []


class TestRooflineRenderer:
    def test_render_roofline_table(self, null_obs):
        from scripts.obs_report import render_roofline

        ins = Introspector(registry=null_obs)
        ins.note_compiled("train_segment/dsgd/x", "jit_dsgd_train",
                          flops=1e9, bytes_accessed=5e8, wall_s=0.3)
        text = render_roofline(ins.roofline())
        assert "train_segment/dsgd/x" in text
        assert "compile key" in text and "%HBM" in text
        # empty doc renders a note, not a crash
        from large_scale_recommendation_tpu.obs.server import ObsServer

        empty = ObsServer(registry=null_obs).rooflinez()
        assert "no compile records" in render_roofline(empty)
