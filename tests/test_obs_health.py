"""The live health layer: built-in checks against synthetic
NaN/divergence/lag/staleness fixtures, SLO window math pinned to a numpy
reference, an endpoint smoke test over a real socket (``/healthz`` flips
non-200 on a tripped check), watchdog halt/rollback semantics on the
real training paths, and the null-path zero-work pin matching
``TestNullPathZeroWork``.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.obs.health import (
    CRITICAL,
    DEGRADED,
    OK,
    CheckpointStalenessCheck,
    CheckResult,
    HealthMonitor,
    PeriodicTask,
    ServingHealthCheck,
    SLOTracker,
    StreamHealthCheck,
    TrainingDivergedError,
    TrainingWatchdog,
    critical,
    degraded,
    ok,
)
from large_scale_recommendation_tpu.obs.registry import (
    NULL_INSTRUMENT,
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.server import ObsServer
from large_scale_recommendation_tpu.obs.trace import get_tracer, set_tracer


@pytest.fixture
def live_obs():
    prev_r, prev_t = get_registry(), get_tracer()
    reg, tracer = obs.enable()
    yield reg, tracer
    set_registry(prev_r)
    set_tracer(prev_t)


# null_obs comes from tests/conftest.py: ONE copy of the full-layer
# save/disable/restore-and-restart invariant, shared by every obs file


def _ratings(n=64, users=16, items=12, seed=0):
    rng = np.random.default_rng(seed)
    return Ratings.from_arrays(
        rng.integers(0, users, n).astype(np.int64),
        rng.integers(0, items, n).astype(np.int64),
        rng.random(n).astype(np.float32))


def _nan_ratings(n=8):
    return Ratings.from_arrays(
        np.arange(n, dtype=np.int64), np.arange(n, dtype=np.int64),
        np.full(n, np.nan, np.float32))


# --------------------------------------------------------------------------
# HealthMonitor aggregation
# --------------------------------------------------------------------------


class TestHealthMonitor:
    def test_worst_status_wins(self, live_obs):
        reg, _ = live_obs
        mon = HealthMonitor()
        mon.register("a", lambda: ok(x=1))
        report = mon.run()
        assert report["status"] == OK
        mon.register("b", lambda: degraded(y=2))
        assert mon.run()["status"] == DEGRADED
        mon.register("c", lambda: critical(z=3))
        report = mon.run()
        assert report["status"] == CRITICAL
        assert set(report["checks"]) == {"a", "b", "c"}
        assert report["checks"]["b"]["detail"] == {"y": 2}
        # gauges published per check + aggregate
        assert reg.gauge("health_status").value == 2
        assert reg.gauge("health_check_status", check="a").value == 0
        assert reg.gauge("health_check_status", check="c").value == 2

    def test_raising_check_is_critical_not_fatal(self, live_obs):
        mon = HealthMonitor()
        mon.register("boom", lambda: 1 / 0)
        report = mon.run()
        assert report["status"] == CRITICAL
        assert "ZeroDivisionError" in report["checks"]["boom"]["detail"][
            "error"]

    def test_non_checkresult_return_is_critical(self, live_obs):
        mon = HealthMonitor()
        mon.register("wrong", lambda: {"status": "ok"})
        assert mon.run()["status"] == CRITICAL

    def test_unregister(self, live_obs):
        mon = HealthMonitor()
        mon.register("x", lambda: critical())
        mon.unregister("x")
        assert mon.run()["status"] == OK
        assert mon.names() == []

    def test_bad_status_rejected(self):
        with pytest.raises(ValueError):
            CheckResult("fine")


# --------------------------------------------------------------------------
# SLO window math vs numpy reference
# --------------------------------------------------------------------------


class TestSLOTracker:
    def test_window_math_matches_numpy(self, live_obs):
        rng = np.random.default_rng(7)
        target, objective, window = 0.1, 0.95, 64
        slo = SLOTracker(target_s=target, objective=objective,
                         window=window, name="pin")
        lats = rng.exponential(0.06, 300)
        for v in lats:
            slo.record(float(v))
        tail = lats[-window:]
        viol_frac = float(np.mean(tail > target))
        assert slo.attainment == pytest.approx(1.0 - viol_frac)
        assert slo.burn_rate == pytest.approx(viol_frac / (1 - objective))
        assert slo.error_budget_remaining == pytest.approx(
            max(0.0, 1.0 - viol_frac / (1 - objective)))
        snap = slo.snapshot()
        assert snap["count"] == 300
        assert snap["violations"] == int(np.sum(lats > target))
        assert snap["window_fill"] == window

    def test_gauges_and_counters_published(self, live_obs):
        reg, _ = live_obs
        slo = SLOTracker(target_s=0.1, objective=0.9, window=10, name="s")
        for v in [0.05] * 8 + [0.5] * 2:
            slo.record(v)
        assert reg.counter("slo_requests_total", slo="s").value == 10
        assert reg.counter("slo_violations_total", slo="s").value == 2
        assert reg.gauge("slo_attainment", slo="s").value == \
            pytest.approx(0.8)
        assert reg.gauge("slo_burn_rate", slo="s").value == \
            pytest.approx(2.0)

    def test_nan_latency_counts_violated(self):
        slo = SLOTracker(target_s=0.1, window=4)
        slo.record(float("nan"))
        assert slo.violations == 1

    def test_serving_health_check_thresholds(self):
        slo = SLOTracker(target_s=0.1, objective=0.9, window=10)
        check = ServingHealthCheck(slo, critical_burn=2.0)
        assert check().status == OK  # idle engine is not an incident
        for v in [0.05] * 10:
            slo.record(v)
        assert check().status == OK
        for v in [0.5] * 2:  # 2/10 violated → burn 2.0 ≥ critical_burn
            slo.record(v)
        assert check().status == CRITICAL
        slo2 = SLOTracker(target_s=0.1, objective=0.9, window=20)
        for v in [0.05] * 17 + [0.5] * 3:  # burn 1.5 → over budget
            slo2.record(v)
        assert ServingHealthCheck(slo2, critical_burn=2.0)().status \
            == DEGRADED

    def test_warmup_window_never_critical(self):
        """The first (compile-carrying) flush violating a tight target
        must NOT flip a liveness-probed /healthz to 503: below the
        min-samples fill, the check caps at DEGRADED."""
        slo = SLOTracker(target_s=0.05, objective=0.99, window=512)
        check = ServingHealthCheck(slo, critical_burn=2.0)
        assert check.min_samples == 50  # ceil(1 / (0.01 * 2))
        slo.record(0.9)  # one violating compile flush: burn = 100
        res = check()
        assert res.status == DEGRADED
        assert "warming" in res.detail["note"]
        for _ in range(60):  # window filled, still violating → critical
            slo.record(0.9)
        assert check().status == CRITICAL

    def test_min_samples_capped_at_window(self):
        """A small window must not leave the check warming forever —
        CRITICAL has to stay reachable on a fully burned budget."""
        slo = SLOTracker(target_s=0.05, objective=0.99, window=16)
        check = ServingHealthCheck(slo, critical_burn=2.0)
        assert check.min_samples == 16  # capped at the window size
        for _ in range(16):  # 100% violations at full window
            slo.record(0.9)
        assert check().status == CRITICAL
        # exact-arithmetic edge: objective 0.5, burn 2 → 1/(0.5*2)=1.0;
        # one violating sample alone must not reach CRITICAL
        slo2 = SLOTracker(target_s=0.05, objective=0.5, window=8)
        check2 = ServingHealthCheck(slo2, critical_burn=2.0)
        assert check2.min_samples == 2
        slo2.record(0.9)  # burn (1/1)/0.5 = 2.0 but warming
        assert check2().status == DEGRADED

    def test_engine_records_flush_walls(self, live_obs):
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data.blocking import flat_index
        from large_scale_recommendation_tpu.models.mf import MFModel
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )

        rng = np.random.default_rng(0)
        model = MFModel(
            U=jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32)),
            V=jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32)),
            users=flat_index(np.arange(64, dtype=np.int64)),
            items=flat_index(np.arange(32, dtype=np.int64)))
        slo = SLOTracker(target_s=60.0, window=16)  # generous: must attain
        engine = ServingEngine(model, k=5, max_batch=32, slo=slo)
        engine.serve([rng.integers(0, 64, 6).astype(np.int64)
                      for _ in range(4)])
        assert slo.count > 0
        assert slo.attainment == 1.0


# --------------------------------------------------------------------------
# TrainingWatchdog: NaN, divergence window, halt/rollback
# --------------------------------------------------------------------------


class TestTrainingWatchdog:
    def test_nan_batch_halts_before_offset_stamp(self, live_obs):
        reg, _ = live_obs
        om = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        om.watchdog = TrainingWatchdog(policy="halt")
        om.partial_fit(_ratings())
        with pytest.raises(TrainingDivergedError) as ei:
            om.partial_fit(_nan_ratings(), offset=(0, 123))
        assert ei.value.reason == "non_finite_factors"
        assert not ei.value.rolled_back
        # the poisoned batch's offset was never stamped — the driver's
        # checkpoint path can't persist it
        assert 0 not in om.consumed_offsets
        assert om.watchdog.check().status == CRITICAL
        assert reg.counter("watchdog_trips_total",
                           reason="non_finite_factors").value == 1

    def test_rollback_restores_factors_and_offsets(self, live_obs,
                                                   tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            CheckpointManager,
            save_online_state,
        )

        om = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        manager = CheckpointManager(str(tmp_path))
        om.watchdog = TrainingWatchdog(policy="rollback", manager=manager)
        om.partial_fit(_ratings(), offset=(0, 50))
        save_online_state(manager, om, om.step)
        ids_ckpt = np.asarray(om.users.ids()).copy()
        rows_ckpt, _ = om.users.rows_for(ids_ckpt)
        U_ckpt = np.asarray(om.users.array)[rows_ckpt].copy()
        om.partial_fit(_ratings(seed=1), offset=(0, 60))  # past the ckpt
        with pytest.raises(TrainingDivergedError) as ei:
            om.partial_fit(_nan_ratings(), offset=(0, 70))
        assert ei.value.rolled_back
        assert om.watchdog.rollbacks == 1
        # factors AND the consumed WAL offset are back at the snapshot:
        # a restarted driver replays from offset 50, not 60/70
        assert om.consumed_offsets == {0: 50}
        # every checkpointed id's factors are back at the snapshot (ids
        # first seen AFTER the checkpoint keep their online vectors —
        # the restore can't know about them; the replayed tail retrains
        # them)
        rows_now, _ = om.users.rows_for(ids_ckpt)
        np.testing.assert_allclose(np.asarray(om.users.array)[rows_now],
                                   U_ckpt)
        active = np.asarray(om.users.array)[:om.users.num_rows]
        assert np.isfinite(active).all()  # the NaNs are gone

    def test_observe_policy_marks_but_continues(self, live_obs):
        om = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        om.watchdog = TrainingWatchdog(policy="observe")
        om.partial_fit(_nan_ratings(), offset=(0, 9))  # no raise
        assert om.watchdog.tripped
        assert om.consumed_offsets == {0: 9}  # observe does not block
        om.watchdog.reset()
        assert om.watchdog.check().status == OK

    def test_loss_divergence_window(self):
        wd = TrainingWatchdog(policy="observe", loss_window=4,
                              loss_rise_tol=0.05)
        for v in (0.5, 0.4, 0.3, 0.25):  # falling: fine
            wd.observe_loss(v)
        assert wd.check().status == OK
        for v in (0.3, 0.4, 0.55, 0.9):  # strictly rising ≥ 5%
            wd.observe_loss(v)
        assert wd.tripped and wd.reason == "loss_divergence"

    def test_loss_trending_is_degraded_not_tripped(self, live_obs):
        reg, _ = live_obs
        wd = TrainingWatchdog(policy="observe", loss_window=4,
                              loss_rise_tol=10.0)  # trip bar out of reach
        for v in (0.3, 0.3, 0.31, 0.32):  # non-decreasing window
            wd.observe_loss(v)
        assert not wd.tripped
        assert wd.check().status == DEGRADED
        # the scrapeable gauge mirrors the full severity scale
        assert reg.gauge("watchdog_state").value == 1
        for v in (0.2, 0.1, 0.05, 0.04):  # trend broken → back to ok
            wd.observe_loss(v)
        assert wd.check().status == OK
        assert reg.gauge("watchdog_state").value == 0

    def test_non_finite_loss_trips(self):
        wd = TrainingWatchdog(policy="observe")
        wd.observe_loss(float("nan"))
        assert wd.tripped and wd.reason == "non_finite_loss"

    def test_halt_policy_on_loss(self):
        wd = TrainingWatchdog(policy="halt", loss_window=3,
                              loss_rise_tol=0.0)
        wd.observe_loss(0.1)
        wd.observe_loss(0.2)
        with pytest.raises(TrainingDivergedError):
            wd.observe_loss(0.4)

    def test_dsgd_segment_guard(self, live_obs):
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )

        gen = SyntheticMFGenerator(num_users=60, num_items=30, rank=4,
                                   seed=0)
        ratings = gen.generate(2000)
        # a huge constant LR on unregularized-ish data reliably explodes
        solver = DSGD(DSGDConfig(num_factors=8, iterations=6,
                                 learning_rate=1e6,
                                 lr_schedule="constant",
                                 minibatch_size=256, lambda_=0.0))
        solver.watchdog = TrainingWatchdog(policy="halt")
        with pytest.raises(TrainingDivergedError):
            solver.fit(ratings, checkpoint_every=1)
        assert solver.watchdog.reason == "non_finite_factors"

    def test_adaptive_swap_guard(self, live_obs):
        """A diverged retrain must abort BEFORE the catalog swap: the
        serving engine keeps its pre-retrain version."""
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )

        adaptive = AdaptiveMF(AdaptiveMFConfig(
            num_factors=4, minibatch_size=64, offline_every=None))
        adaptive.watchdog = TrainingWatchdog(policy="halt")
        for s in range(3):
            adaptive.process(_ratings(seed=s))
        engine = adaptive.serving_engine(k=3, max_batch=32)
        v0 = engine.version
        # poison the HISTORY (not the online tables): the retrain fits
        # NaNs, the swap guard must refuse to install them
        adaptive._history.append((
            np.zeros(4, np.int64), np.zeros(4, np.int64),
            np.full(4, np.nan, np.float32)))
        adaptive._history_rows += 4
        with pytest.raises(TrainingDivergedError) as ei:
            adaptive.trigger_batch_training()
        assert ei.value.reason == "non_finite_retrain"
        assert engine.version == v0  # no swap reached serving

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError):
            TrainingWatchdog(policy="explode")


# --------------------------------------------------------------------------
# Stream + checkpoint checks (synthetic fixtures)
# --------------------------------------------------------------------------


class _StubDriver:
    def __init__(self):
        self.tel = {"lag_records": 0, "queue": {}}

    def telemetry(self):
        return self.tel


class TestStreamHealthCheck:
    def test_lag_thresholds(self):
        d = _StubDriver()
        check = StreamHealthCheck(d, degraded_lag=100, critical_lag=1000)
        assert check().status == OK
        d.tel["lag_records"] = 100
        assert check().status == DEGRADED
        d.tel["lag_records"] = 1000
        assert check().status == CRITICAL

    def test_dead_letter_growth_degrades_sticky(self):
        d = _StubDriver()
        d.tel["queue"] = {"dead_letter_records": 2}
        check = StreamHealthCheck(d, degraded_lag=10_000,
                                  growth_window_s=0.2)
        assert check().status == OK  # first sighting: no growth baseline
        assert check().status == OK  # stable
        d.tel["queue"] = {"dead_letter_records": 5}
        res = check()
        assert res.status == DEGRADED
        assert res.detail["dead_letter_growth"] == 3
        # STICKY: a second observer inside the window still sees the
        # degradation — the first poller must not consume the signal
        res2 = check()
        assert res2.status == DEGRADED
        assert res2.detail["dead_letter_growth"] == 3
        time.sleep(0.25)
        assert check().status == OK  # window expired, count stable

    def test_real_driver_caught_up_is_ok(self, live_obs, tmp_path):
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
            StreamingDriverConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        log = EventLog(str(tmp_path / "log"))
        ru, ri, rv, _ = _ratings(400).to_numpy()
        log.append_arrays(0, ru, ri, rv)
        model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
        driver = StreamingDriver(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400))
        check = StreamHealthCheck(driver, degraded_lag=100)
        assert check().status == DEGRADED  # 400 unconsumed records
        driver.run()
        assert check().status == OK


class TestCheckpointStaleness:
    def test_missing_then_fresh_then_stale(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        manager = CheckpointManager(str(tmp_path))
        check = CheckpointStalenessCheck(manager, degraded_after_s=60,
                                         critical_after_s=3600)
        assert check().status == DEGRADED  # none yet
        manager.save(1, {"U": np.zeros((2, 2))})
        assert check().status == OK
        # age the file artificially rather than sleeping
        path = os.path.join(str(tmp_path), "ckpt_1.npz")
        old = time.time() - 600
        os.utime(path, (old, old))
        assert check().status == DEGRADED
        older = time.time() - 7200
        os.utime(path, (older, older))
        assert check().status == CRITICAL


# --------------------------------------------------------------------------
# Endpoint smoke test: real socket
# --------------------------------------------------------------------------


class TestObsServerEndpoints:
    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_routes_and_critical_flip(self, live_obs):
        reg, tracer = live_obs
        reg.counter("smoke_total").inc(3)
        with tracer.span("smoke/span"):
            pass
        state = {"status": OK}
        mon = HealthMonitor()
        mon.register("toggle", lambda: CheckResult(state["status"]))
        with ObsServer(monitor=mon) as srv:
            code, body = self._get(srv.url + "/healthz")
            assert code == 200
            assert json.loads(body)["status"] == OK
            code, body = self._get(srv.url + "/metrics")
            assert code == 200
            assert "smoke_total 3" in body
            assert "health_check_status" in body  # the run() published
            code, body = self._get(srv.url + "/varz")
            assert code == 200
            names = {m["name"] for m in json.loads(body)["metrics"]}
            assert "smoke_total" in names
            code, body = self._get(srv.url + "/tracez")
            assert code == 200
            tz = json.loads(body)
            assert any(e["name"] == "smoke/span" for e in tz["recent"])
            code, _ = self._get(srv.url + "/nope")
            assert code == 404
            # flip the check: /healthz must go non-200
            state["status"] = CRITICAL
            code, body = self._get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["status"] == CRITICAL
        assert not srv.running

    def test_no_monitor_is_trivially_ok(self, live_obs):
        with ObsServer() as srv:
            code, body = self._get(srv.url + "/healthz")
            assert code == 200
            assert json.loads(body)["checks"] == {}

    def test_watch_renders_rates_from_varz(self, live_obs):
        import io

        from scripts.obs_report import fetch_snapshot, render_deltas

        reg, _ = live_obs
        c = reg.counter("watch_total")
        c.inc(5)
        with ObsServer() as srv:
            prev = fetch_snapshot(srv.url + "/varz")
            c.inc(10)
            cur = fetch_snapshot(srv.url + "/varz")
        table = render_deltas(prev, cur, dt=2.0, active_only=True)
        assert "watch_total" in table
        assert "5" in table  # Δ/s = 10/2
        buf = io.StringIO()  # full watch loop, one poll, against a file
        import scripts.obs_report as rep

        path = None
        try:
            import tempfile

            with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False) as f:
                json.dump(cur, f)
                path = f.name
            rep.watch(path, interval_s=0.01, count=1, out=buf)
        finally:
            if path:
                os.unlink(path)
        assert "watch_total" in buf.getvalue()


# --------------------------------------------------------------------------
# Periodic telemetry cadence
# --------------------------------------------------------------------------


class TestPeriodicExport:
    def test_periodic_task_runs_and_stops(self):
        hits = []
        task = PeriodicTask(lambda: hits.append(1), interval_s=0.02).start()
        deadline = time.time() + 5
        while len(hits) < 3 and time.time() < deadline:
            time.sleep(0.01)
        task.stop()
        assert len(hits) >= 3
        n = len(hits)
        time.sleep(0.08)
        assert len(hits) == n  # really stopped
        assert not task.running

    def test_errors_counted_not_fatal(self):
        def boom():
            raise RuntimeError("flaky probe")

        task = PeriodicTask(boom, interval_s=0.02).start()
        deadline = time.time() + 5
        while task.errors < 2 and time.time() < deadline:
            time.sleep(0.01)
        task.stop()
        assert task.errors >= 2
        assert isinstance(task.last_error, RuntimeError)

    def test_driver_telemetry_cadence_refreshes_lag_gauge(self, live_obs,
                                                          tmp_path):
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
            StreamingDriverConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        reg, _ = live_obs
        log = EventLog(str(tmp_path / "log"))
        ru, ri, rv, _ = _ratings(200).to_numpy()
        log.append_arrays(0, ru, ri, rv)
        model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
        driver = StreamingDriver(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=200))
        driver.run()
        task = driver.start_telemetry_export(interval_s=0.02)
        assert driver.start_telemetry_export() is task  # idempotent
        # append MORE records: only the cadence (no manual telemetry()
        # call) can move the lag gauge now
        log.append_arrays(0, ru, ri, rv)
        lag = reg.gauge("streams_lag_records", partition="0")
        deadline = time.time() + 5
        while lag.value != 200 and time.time() < deadline:
            time.sleep(0.01)
        driver.stop_telemetry_export()
        assert lag.value == 200
        assert not task.running


# --------------------------------------------------------------------------
# Null path: zero work when the layer is unused
# --------------------------------------------------------------------------


class TestHealthNullPathZeroWork:
    def test_hooks_default_off_everywhere(self, null_obs):
        """The disabled pin, matching TestNullPathZeroWork: no watchdog,
        no SLO, no telemetry thread unless explicitly attached — each
        hot path pays one pointer test."""
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data.blocking import flat_index
        from large_scale_recommendation_tpu.models.dsgd import DSGD
        from large_scale_recommendation_tpu.models.mf import MFModel
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )

        om = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        assert om.watchdog is None
        assert DSGD().watchdog is None
        rng = np.random.default_rng(0)
        model = MFModel(
            U=jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32)),
            V=jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32)),
            users=flat_index(np.arange(32, dtype=np.int64)),
            items=flat_index(np.arange(16, dtype=np.int64)))
        engine = ServingEngine(model, k=3, max_batch=32)
        assert engine._slo is None
        om.partial_fit(_ratings(users=32, items=16))
        engine.recommend(np.arange(4, dtype=np.int64))
        assert null_obs.names() == set()

    def test_monitor_and_slo_publish_nothing_under_null(self, null_obs):
        mon = HealthMonitor()
        mon.register("x", lambda: ok())
        report = mon.run()  # still computes the report...
        assert report["status"] == OK
        slo = SLOTracker(target_s=0.1, window=8)
        slo.record(0.05)
        assert slo._m_att is NULL_INSTRUMENT  # ...but publishes nothing
        assert slo.attainment == 1.0  # window math still works
        assert null_obs.names() == set()

    def test_driver_has_no_telemetry_thread_by_default(self, null_obs,
                                                       tmp_path):
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        model = OnlineMF(OnlineMFConfig(num_factors=4))
        driver = StreamingDriver(model, EventLog(str(tmp_path / "log")),
                                 str(tmp_path / "ckpt"))
        assert driver._telemetry_task is None
