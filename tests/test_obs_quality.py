"""Model-quality observability (``obs.quality``): the sampled ranking
metric's planted-structure pins (floor ≈ k/(n+1) for a random model,
ceiling ≈ 1 for the true factors — the eval itself must be trustworthy
before any training-side number is), catalog coverage, the reservoir
holdout's never-trained-on contract, the DSGD/ALS segment hook, and the
acceptance path — training on label-shuffled ratings mid-stream flips
``/healthz`` to 503 through the threshold-free quality anomaly checks
over a real socket.
"""

import json

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.obs.events import get_events, set_events
from large_scale_recommendation_tpu.obs.health import (
    CRITICAL,
    OK,
    HealthMonitor,
)
from large_scale_recommendation_tpu.obs.lineage import (
    get_lineage,
    set_lineage,
)
from large_scale_recommendation_tpu.obs.quality import (
    OnlineEvaluator,
    catalog_coverage,
    sampled_ranking_metrics,
)
from large_scale_recommendation_tpu.obs.recorder import (
    get_recorder,
    series_key,
    set_recorder,
)
from large_scale_recommendation_tpu.obs.registry import (
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import get_tracer, set_tracer


@pytest.fixture
def flight_obs():
    prev = (get_registry(), get_tracer(), get_events(), get_recorder(),
            get_lineage())
    reg, tracer = obs.enable()
    recorder, journal = obs.enable_flight_recorder(start=False)
    yield reg, tracer, recorder, journal
    recorder.stop()
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])
    set_lineage(prev[4])


def _planted(nu=200, ni=500, r=16, seed=0):
    """True factor tables with unit-variance scores: each user's argmax
    item is a positive the TRUE model must rank near the top and a
    random model must rank uniformly."""
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(nu, r)).astype(np.float32)
    V = rng.normal(size=(ni, r)).astype(np.float32)
    pos = np.argmax(U @ V.T, axis=1)
    return U, V, np.arange(nu), pos.astype(np.int64)


class TestSampledRankingMetrics:
    def test_planted_structure_ceiling_and_floor(self):
        """The trustworthiness pin (the ndcg=0.003-for-five-rounds
        lesson): the metric's value is interpretable because its
        extremes are KNOWN. True factors rank their own argmax positives
        ≈ perfectly; random factors score ≈ the analytic floor
        k/(num_negatives+1) — and the two are separated by an order of
        magnitude, so a near-floor score indicts the model, not the
        eval."""
        U, V, eu, ei = _planted()
        k, n_neg = 10, 100
        good = sampled_ranking_metrics(U, V, eu, ei, k=k,
                                       num_negatives=n_neg, seed=1)
        assert good["hr"] >= 0.95
        assert good["ndcg"] >= 0.9
        rng = np.random.default_rng(9)
        U_rand = rng.normal(size=U.shape).astype(np.float32)
        bad = sampled_ranking_metrics(U_rand, V, eu, ei, k=k,
                                      num_negatives=n_neg, seed=1)
        floor = k / (n_neg + 1)
        assert bad["hr"] <= 2.5 * floor  # uniform rank, sampling noise
        assert bad["hr"] >= floor / 4
        assert good["hr"] > 5 * bad["hr"]
        assert good["ndcg"] > 5 * bad["ndcg"]

    def test_train_seen_negatives_masked_out(self):
        """A train-seen item must not count as a negative: a catalog
        where the user's ONLY better-scoring item was trained on ranks
        the positive first with masking, last-ish without."""
        U = np.ones((1, 1), np.float32)
        V = np.array([[0.5], [10.0], [0.1]], np.float32)
        eu, ei = np.array([0]), np.array([0])  # positive scores 0.5
        with_mask = sampled_ranking_metrics(
            U, V, eu, ei, k=1, num_negatives=64,
            train_u=np.array([0]), train_i=np.array([1]), seed=0)
        assert with_mask["hr"] == 1.0  # only item 2 (0.1) survives
        without = sampled_ranking_metrics(U, V, eu, ei, k=1,
                                          num_negatives=64, seed=0)
        assert without["hr"] == 0.0  # item 1 (10.0) outranks it
        # masked slots shrink the VALID pool, never the sampled shape
        assert with_mask["valid_negatives"] < without["valid_negatives"]

    def test_item_mask_excludes_phantom_rows(self):
        """Phantom padding rows never enter the negative pool."""
        U = np.ones((1, 1), np.float32)
        V = np.array([[0.5], [99.0], [0.1]], np.float32)
        mask = np.array([True, False, True])  # row 1 is padding
        res = sampled_ranking_metrics(U, V, np.array([0]), np.array([0]),
                                      k=1, num_negatives=64,
                                      item_mask=mask, seed=0)
        assert res["hr"] == 1.0  # the 99.0 phantom never sampled

    def test_positive_self_collision_masked(self):
        """With a 1-item pool every sampled negative IS the positive —
        all masked, rank 0, hit."""
        U = np.ones((1, 2), np.float32)
        V = np.ones((1, 2), np.float32)
        res = sampled_ranking_metrics(U, V, np.array([0]), np.array([0]),
                                      k=5, num_negatives=16, seed=0)
        assert res["hr"] == 1.0
        assert res["valid_negatives"] == 0.0

    def test_empty_eval_set(self):
        U, V, _, _ = _planted(nu=4, ni=4, r=2)
        res = sampled_ranking_metrics(U, V, np.zeros(0, np.int64),
                                      np.zeros(0, np.int64))
        assert res["n"] == 0 and np.isnan(res["hr"])


class TestCatalogCoverage:
    def test_identical_users_cover_exactly_k(self):
        """The aggregate-diversity failure HR can't see: every user
        getting the same list covers exactly k of the catalog."""
        rng = np.random.default_rng(0)
        V = rng.normal(size=(50, 8)).astype(np.float32)
        U = np.tile(rng.normal(size=(1, 8)).astype(np.float32), (30, 1))
        cov = catalog_coverage(U, V, np.arange(30), k=10)
        assert cov == pytest.approx(10 / 50)

    def test_diverse_users_cover_more(self):
        U, V, eu, _ = _planted(nu=100, ni=50, r=16)
        cov = catalog_coverage(U, V, eu, k=10)
        assert cov > 10 / 50

    def test_item_mask_shrinks_denominator_and_pool(self):
        rng = np.random.default_rng(1)
        V = rng.normal(size=(40, 4)).astype(np.float32)
        U = rng.normal(size=(20, 4)).astype(np.float32)
        mask = np.zeros(40, bool)
        mask[:20] = True
        cov = catalog_coverage(U, V, np.arange(20), k=30, item_mask=mask)
        # ≤ 20 real items exist; every surfaced row must be a real one
        assert 0.0 < cov <= 1.0

    def test_empty_inputs_nan(self):
        U, V, _, _ = _planted(nu=4, ni=4, r=2)
        assert np.isnan(catalog_coverage(U, V, np.zeros(0, np.int64)))


def _batch(rng, Ut, Vt, n=2000, shuffle=False, noise=0.05):
    nu, ni = Ut.shape[0], Vt.shape[0]
    u = rng.integers(0, nu, n)
    i = rng.integers(0, ni, n)
    v = (Ut[u] * Vt[i]).sum(1) + rng.normal(0, noise, n)
    if shuffle:
        v = rng.permutation(v)
    return Ratings.from_arrays(u, i, v.astype(np.float32))


def _tables(nu=100, ni=40, r=6, seed=3):
    rng = np.random.default_rng(seed)
    Ut = rng.normal(size=(nu, r)).astype(np.float32) / np.sqrt(r)
    Vt = rng.normal(size=(ni, r)).astype(np.float32)
    return rng, Ut, Vt


class TestOnlineEvaluator:
    def test_split_batch_zeroes_holdout_weights_in_place_shape(self,
                                                               flight_obs):
        """The never-trained-on contract, mechanically: the returned
        batch has the SAME shape (offset stamps and padding layout
        survive) with exactly the reservoir-absorbed rows' weights
        zeroed — weight-0 is the padding contract every kernel already
        skips, so partial_fit cannot train on them."""
        rng, Ut, Vt = _tables()
        ev = OnlineEvaluator(None, holdout_fraction=0.3, seed=0)
        b = _batch(rng, Ut, Vt, n=1000)
        out = ev.split_batch(b)
        assert out.n == b.n
        zeroed = int((np.asarray(out.weights) == 0).sum())
        assert zeroed == ev.held_out_total > 0
        # the held-out values live in the reservoir, nowhere else
        assert ev.holdout_rows == ev.held_out_total

    def test_holdout_rows_never_trained(self, flight_obs):
        """End-to-end: rows the evaluator held out contribute ZERO
        training updates — the online ratings counter (real rows only)
        equals offered minus held out."""
        reg, _, _, _ = flight_obs
        rng, Ut, Vt = _tables()
        m = OnlineMF(OnlineMFConfig(num_factors=8, minibatch_size=512))
        ev = OnlineEvaluator(m, holdout_fraction=0.25, seed=0)
        offered = 0
        for _ in range(4):
            b = _batch(rng, Ut, Vt, n=1000)
            offered += 1000
            m.partial_fit(ev.split_batch(b))
        trained = reg.counter("online_ratings_total").value
        assert trained == offered - ev.held_out_total
        assert ev.held_out_total > 0

    def test_reservoir_is_bounded(self, flight_obs):
        rng, Ut, Vt = _tables()
        ev = OnlineEvaluator(None, holdout_fraction=0.5,
                             reservoir_size=64, seed=0)
        for _ in range(6):
            ev.split_batch(_batch(rng, Ut, Vt, n=500))
        assert ev.held_out_total > 64
        assert ev.holdout_rows == 64  # capped forever

    def test_evaluate_publishes_gauges_and_warms(self, flight_obs):
        reg, _, _, _ = flight_obs
        rng, Ut, Vt = _tables()
        m = OnlineMF(OnlineMFConfig(num_factors=8, minibatch_size=512))
        ev = OnlineEvaluator(m, holdout_fraction=0.2, min_eval_rows=64,
                             seed=0)
        assert ev.evaluate() is None  # empty reservoir: warming
        for _ in range(3):
            m.partial_fit(ev.split_batch(_batch(rng, Ut, Vt, n=1500)))
        metrics = ev.evaluate()
        assert metrics is not None
        assert np.isfinite(metrics["rmse"])
        assert 0.0 <= metrics["hr"] <= 1.0
        assert 0.0 < metrics["coverage"] <= 1.0
        names = {mm["name"] for mm in reg.snapshot()["metrics"]}
        for name in ("eval_rmse", "eval_ndcg_at_k", "eval_hr_at_k",
                     "eval_coverage", "eval_holdout_rows",
                     "eval_runs_total"):
            assert name in names, name
        # gauges carry the source label
        snap = {(mm["name"], tuple(sorted(mm["labels"].items())))
                for mm in reg.snapshot()["metrics"]}
        assert ("eval_rmse", (("source", "online"),)) in snap

    def test_cadence_uses_shared_periodic_machinery(self, flight_obs):
        ev = OnlineEvaluator(None, seed=0)
        ev.start(interval_s=30.0)
        try:
            assert ev.running
            task = ev._task
            ev.start(interval_s=30.0)  # idempotent: same task reused
            assert ev._task is task
        finally:
            ev.stop()
        assert not ev.running

    def test_validation(self):
        with pytest.raises(ValueError):
            OnlineEvaluator(None, holdout_fraction=0.0)
        with pytest.raises(ValueError):
            OnlineEvaluator(None, holdout_fraction=1.5)
        with pytest.raises(ValueError):
            OnlineEvaluator(None, reservoir_size=0)

    def test_snapshot_json_safe(self, flight_obs):
        ev = OnlineEvaluator(None, seed=0)
        doc = ev.snapshot()
        json.dumps(doc)
        assert doc["holdout_rows"] == 0


class TestSegmentHook:
    def test_on_segment_without_holdout_is_noop(self, flight_obs):
        ev = OnlineEvaluator(None, seed=0)
        assert ev.on_segment(np.ones((4, 2), np.float32),
                             np.ones((4, 2), np.float32)) is None
        assert ev.evaluations == 0

    def test_on_segment_scores_offline_holdout(self, flight_obs):
        """Planted tables score ≈ 0 rmse and high HR through the hook;
        the gauges land labeled with the segment kind."""
        reg, _, _, _ = flight_obs
        U, V, eu, ei = _planted(nu=64, ni=128, r=8)
        vals = (U[eu] * V[ei]).sum(1).astype(np.float32)
        ev = OnlineEvaluator(None, seed=0)
        ev.set_offline_holdout(eu, ei, vals)
        metrics = ev.on_segment(U, V, label="dsgd_segment", step=5)
        assert metrics["rmse"] == pytest.approx(0.0, abs=1e-4)
        assert metrics["hr"] >= 0.9
        snap = {(mm["name"], tuple(sorted(mm["labels"].items())))
                for mm in reg.snapshot()["metrics"]}
        assert ("eval_rmse", (("source", "dsgd_segment"),)) in snap

    def test_dsgd_calls_hook_at_segment_boundaries(self, flight_obs):
        """The integration pin: an attached evaluator fires once per
        segment during a real ``DSGD.fit``, and eval_rmse lands under
        the segment kind. Row mapping comes from re-running the
        deterministic blocking pass with fit's exact arguments."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.data import blocking
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )

        reg, _, _, _ = flight_obs
        gen = SyntheticMFGenerator(num_users=120, num_items=60, rank=4,
                                   noise=0.1, seed=0)
        train, hold = gen.generate(8_000), gen.generate(1_000)
        cfg = DSGDConfig(num_factors=8, iterations=2, num_blocks=2,
                         minibatch_size=512, learning_rate=0.05,
                         lambda_=0.01, lr_schedule="constant")
        solver = DSGD(cfg)
        # blocking is deterministic given (ratings, seed, layout knobs):
        # the same call fit() makes maps the holdout ids to rows
        problem = blocking.block_problem(
            train, num_blocks=2, seed=cfg.seed,
            minibatch_multiple=cfg.minibatch_size,
            minibatch_sort=cfg.minibatch_sort)
        hu, hi, hv, hw = hold.to_numpy()
        u_rows, u_mask = problem.users.rows_for(hu)
        i_rows, i_mask = problem.items.rows_for(hi)
        keep = (u_mask * i_mask * hw) > 0
        ev = OnlineEvaluator(None, seed=0)
        ev.set_offline_holdout(
            u_rows[keep], i_rows[keep], hv[keep],
            item_mask=problem.items.ids >= 0)
        solver.evaluator = ev
        solver.fit(train, checkpoint_every=1,
                   checkpoint_manager=None)
        assert ev.evaluations == 2  # one per segment (2 iterations / 1)
        snap = {(mm["name"], tuple(sorted(mm["labels"].items()))):
                mm for mm in reg.snapshot()["metrics"]}
        key = ("eval_rmse", (("source", "dsgd_segment"),))
        assert key in snap
        assert np.isfinite(snap[key]["value"])

    def test_als_calls_hook_at_fit_boundary(self, flight_obs):
        from large_scale_recommendation_tpu.models.als import ALS, ALSConfig

        reg, _, _, _ = flight_obs
        rng = np.random.default_rng(0)
        n = 4000
        u = rng.integers(0, 50, n)
        i = rng.integers(0, 30, n)
        v = rng.normal(3.0, 1.0, n).astype(np.float32)
        solver = ALS(ALSConfig(num_factors=4, iterations=2))
        ev = OnlineEvaluator(None, seed=0)
        solver.evaluator = ev
        model = solver.fit_device(u, i, v, 50, 30)
        assert model is not None
        assert ev.evaluations == 0  # no holdout armed: zero extra work
        # arm a row-space holdout (fit_device rows ARE the dense ids)
        ev.set_offline_holdout(u[:256], i[:256], v[:256])
        solver.fit_device(u, i, v, 50, 30)
        assert ev.evaluations == 1
        snap = {(mm["name"], tuple(sorted(mm["labels"].items())))
                for mm in reg.snapshot()["metrics"]}
        assert ("eval_rmse", (("source", "als_device_rounds"),)) in snap


class TestQualityCollapseFlipsHealthz:
    def test_label_shuffle_503s_healthz_with_no_per_model_threshold(
            self, flight_obs):
        """THE acceptance pin (ISSUE 10): train on label-shuffled
        ratings mid-stream → eval_rmse spikes off its learned baseline
        → the watch_quality AnomalyCheck goes CRITICAL → /healthz
        answers 503 over a real socket. No static per-model quality
        number appears anywhere in the wiring — the check learned this
        model's normal from the flight recorder."""
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        reg, _, rec, _ = flight_obs
        rng, Ut, Vt = _tables()
        m = OnlineMF(OnlineMFConfig(num_factors=8, minibatch_size=512,
                                    learning_rate=0.2,
                                    iterations_per_batch=2))
        ev = OnlineEvaluator(m, holdout_fraction=0.15,
                             reservoir_size=1024, min_eval_rows=32,
                             seed=0)
        monitor = HealthMonitor()
        monitor.watch_quality(rec)
        # learn the model's normal: clean planted stream to convergence
        for _ in range(40):
            m.partial_fit(ev.split_batch(_batch(rng, Ut, Vt)))
            ev.evaluate()
            rec.sample()
        with ObsServer(monitor=monitor) as server:
            code, body = http_get(server.url + "/healthz")
            assert code == 200, body
            assert json.loads(body)["status"] == OK
            # the collapse: label-shuffled ratings mid-stream
            for _ in range(4):
                m.partial_fit(ev.split_batch(
                    _batch(rng, Ut, Vt, shuffle=True)))
            ev.evaluate()
            rec.sample()
            code, body = http_get(server.url + "/healthz")
        assert code == 503, body
        report = json.loads(body)
        check = report["checks"]["quality:rmse"]
        assert check["status"] == CRITICAL
        assert check["detail"]["z"] > 6.0  # far off the learned normal