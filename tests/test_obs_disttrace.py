"""Distributed tracing (``obs.disttrace`` + the ``obs.trace`` context
plane, ISSUE 12): namespaced span/event ids (two real processes'
exports merge with zero collisions), cross-thread ``TraceContext``
propagation (the retrain lane parents back to its triggering batch),
pod trace assembly + the ``/podtracez`` route, record-id resolution to
one assembled distributed trace on a real ``StreamingDriver`` run, and
the critical-path analyzer — hand-pinned stage math, exact
reconciliation against the ``lineage_ingest_to_servable_s`` histogram
(including across a kill/restart resume), and the ``/criticalpathz``
route over a real socket.
"""

import json
import subprocess
import sys
import threading

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.disttrace import (
    STAGES,
    CriticalPathAnalyzer,
    assemble_pod_trace,
    get_disttrace,
    record_trace_id,
    resolve_record_trace,
    set_disttrace,
)
from large_scale_recommendation_tpu.obs.events import (
    EventJournal,
    get_events,
    set_events,
)
from large_scale_recommendation_tpu.obs.lineage import (
    get_lineage,
    set_lineage,
)
from large_scale_recommendation_tpu.obs.recorder import (
    get_recorder,
    set_recorder,
)
from large_scale_recommendation_tpu.obs.registry import (
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import (
    TraceContext,
    Tracer,
    get_tracer,
    process_namespace,
    set_tracer,
    validate_chrome_trace,
)


@pytest.fixture
def causal_obs():
    """Live registry/tracer + lineage + critical-path analyzer, the
    previous layer restored after (an OBS_OUT session runs its own
    suite-wide instances)."""
    prev = (get_registry(), get_tracer(), get_events(), get_recorder(),
            get_lineage(), get_disttrace())
    reg, tracer = obs.enable()
    obs.enable_lineage(capacity=64)
    analyzer = obs.enable_disttrace(capacity=32)
    yield reg, tracer, analyzer
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])
    set_lineage(prev[4])
    set_disttrace(prev[5])


def _fill_log(log, n_batches=3, n=500, partition=0, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n_batches):
        log.append_arrays(partition, rng.integers(0, 100, n),
                          rng.integers(0, 50, n),
                          rng.random(n).astype(np.float32))


def _driver(tmp_path, log, sub="ckpt", **cfg):
    from large_scale_recommendation_tpu.models.online import (
        OnlineMF,
        OnlineMFConfig,
    )
    from large_scale_recommendation_tpu.streams.driver import (
        StreamingDriver,
        StreamingDriverConfig,
    )

    model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
    return StreamingDriver(
        model, log, str(tmp_path / sub),
        config=StreamingDriverConfig(batch_records=400, **cfg))


# --------------------------------------------------------------------------
# Trace identity: namespaced ids, deterministic record trace ids
# --------------------------------------------------------------------------


class TestTraceIdentity:
    def test_record_trace_id_is_deterministic(self):
        """The cross-process propagation mechanism: the id is a pure
        function of the record's durable identity — any process
        derives it with no side channel."""
        assert record_trace_id(0, 42) == "wal-p0-o42"
        assert record_trace_id(3, 7) == record_trace_id(3, 7)
        assert record_trace_id(0, 1) != record_trace_id(1, 1)

    def test_span_and_event_ids_are_namespaced(self, causal_obs):
        _, tracer, _ = causal_obs
        ns = process_namespace()
        journal = EventJournal(capacity=8)
        with tracer.span("work") as sp:
            ev = journal.emit("thing")
        assert sp.id.startswith(ns + ":")
        assert ev["id"].startswith(ns + ":")
        assert ev["id"].rsplit(":", 1)[1] == str(ev["seq"])
        # the event's span correlation token is the namespaced span id
        assert ev["span_id"] == sp.id

    def test_two_real_processes_merge_with_zero_collisions(
            self, causal_obs, tmp_path):
        """The satellite pin: a SECOND real process's exports (spans
        AND event records) merge with this process's with zero id
        collisions, and the merged trace validates."""
        _, tracer, _ = causal_obs
        with tracer.span("local/outer"):
            with tracer.span("local/inner"):
                pass
        journal = EventJournal(capacity=8)
        journal.emit("local.event")

        script = r"""
import json, sys
from large_scale_recommendation_tpu import obs
reg, tracer = obs.enable()
from large_scale_recommendation_tpu.obs.events import EventJournal
journal = EventJournal(capacity=8)
with tracer.span("remote/outer"):
    with tracer.span("remote/inner"):
        journal.emit("remote.event")
print(json.dumps({"trace": tracer.chrome_trace(),
                  "events": journal.events()}))
"""
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=120,
                             env=None)
        assert out.returncode == 0, out.stderr[-2000:]
        remote = json.loads(out.stdout.strip().splitlines()[-1])

        local_doc = tracer.chrome_trace()
        local_ids = {e["args"]["span_id"]
                     for e in local_doc["traceEvents"]}
        remote_ids = {e["args"]["span_id"]
                      for e in remote["trace"]["traceEvents"]}
        assert local_ids and remote_ids
        assert not (local_ids & remote_ids)  # zero span-id collisions
        local_ev = {e["id"] for e in journal.events()}
        remote_ev = {e["id"] for e in remote["events"]}
        assert local_ev and remote_ev
        assert not (local_ev & remote_ev)  # zero event-id collisions
        merged = assemble_pod_trace([("local", local_doc),
                                     ("remote", remote["trace"])])
        validate_chrome_trace(merged)
        names = {e["name"] for e in merged["traceEvents"]}
        assert {"local/outer", "remote/outer",
                "process_name"} <= names


# --------------------------------------------------------------------------
# TraceContext propagation
# --------------------------------------------------------------------------


class TestTraceContext:
    def test_capture_and_reenter_on_another_thread(self, causal_obs):
        """The retrain-lane contract in miniature: a context captured
        inside a span, re-entered on another thread, parents that
        thread's top-level span back to the capturing span and carries
        the trace id."""
        _, tracer, _ = causal_obs
        done = threading.Event()

        def work(ctx):
            with tracer.activate(ctx):
                with tracer.span("thread/work"):
                    pass
            done.set()

        with tracer.activate(TraceContext(trace_id="trace-1")):
            with tracer.span("main/batch") as batch:
                t = threading.Thread(
                    target=work, args=(tracer.capture_context(),))
                t.start()
                t.join()
        assert done.wait(1)
        by_name = {e["name"]: e for e in tracer.events()}
        worked = by_name["thread/work"]
        assert worked["args"]["parent_span_id"] == batch.id
        assert worked["args"]["trace_id"] == "trace-1"
        assert worked["tid"] != by_name["main/batch"]["tid"]

    def test_activate_none_is_noop(self, causal_obs):
        _, tracer, _ = causal_obs
        with tracer.activate(None):
            with tracer.span("plain"):
                pass
        (ev,) = [e for e in tracer.events() if e["name"] == "plain"]
        assert "trace_id" not in ev["args"]
        assert "parent_span_id" not in ev["args"]

    def test_null_tracer_context_surface(self):
        from large_scale_recommendation_tpu.obs.trace import NULL_TRACER

        assert NULL_TRACER.capture_context() is None
        assert NULL_TRACER.current_context() is None
        with NULL_TRACER.activate(TraceContext(trace_id="x")) as got:
            assert got is None

    def test_instant_carries_active_trace_id(self, causal_obs):
        _, tracer, _ = causal_obs
        with tracer.activate(TraceContext(trace_id="t-9")):
            tracer.instant("mark", note=1)
        (ev,) = [e for e in tracer.events() if e["name"] == "mark"]
        assert ev["args"]["trace_id"] == "t-9"

    def test_retrain_thread_parents_to_triggering_batch(
            self, causal_obs):
        """The satellite pin: an ``AdaptiveMF`` background retrain's
        span resolves to the triggering batch's span in the EXPORTED
        trace (before this PR the retrain lane parented to nothing)."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )

        _, tracer, _ = causal_obs
        adaptive = AdaptiveMF(AdaptiveMFConfig(
            num_factors=4, offline_every=2, offline_iterations=1,
            background=True))
        gen = SyntheticMFGenerator(num_users=60, num_items=30, rank=2,
                                   noise=0.1, seed=0)
        batch_span_id = None
        with tracer.span("stream/ingest_batch", partition=0) as sp:
            adaptive.process(gen.generate(256))
            adaptive.process(gen.generate(256))  # triggers the retrain
            batch_span_id = sp.id
        adaptive.flush()
        retrains = [e for e in tracer.events()
                    if e["name"] == "adaptive/retrain"]
        assert retrains, [e["name"] for e in tracer.events()]
        assert retrains[-1]["args"]["parent_span_id"] == batch_span_id


# --------------------------------------------------------------------------
# Pod assembly + the validator's merged-trace semantics
# --------------------------------------------------------------------------


class TestAssembly:
    def _doc(self, pid, tid, ts, name="w", span_id="x:1"):
        return {"traceEvents": [
            {"name": name, "cat": "span", "ph": "X", "ts": ts,
             "dur": 10.0, "pid": pid, "tid": tid,
             "args": {"span_id": span_id}}]}

    def test_pid_remap_and_metadata(self):
        merged = assemble_pod_trace([
            ("host-a", self._doc(7, 1, 0.0, span_id="a:1")),
            ("host-b", self._doc(7, 1, 5.0, span_id="b:1")),
        ])
        events = merged["traceEvents"]
        meta = [e for e in events if e["ph"] == "M"]
        assert [m["args"]["name"] for m in meta] == ["host-a", "host-b"]
        pids = {e["pid"] for e in events if e["ph"] == "X"}
        assert pids == {0, 1}  # synthetic, collision-free
        assert merged["podSources"] == ["host-a", "host-b"]

    def test_merged_colliding_pids_validate(self):
        """Two processes with the SAME os pid/tid and OVERLAPPING
        (non-nesting) intervals: unmergeable before the (pid, tid)
        nesting fix — now each source is its own group."""
        merged = assemble_pod_trace([
            ("a", self._doc(7, 1, 0.0)),
            ("b", self._doc(7, 1, 5.0)),  # overlaps, doesn't nest
        ])
        validate_chrome_trace(merged)  # must not raise

    def test_partial_overlap_on_one_thread_still_rejected(self):
        doc = {"traceEvents": [
            {"name": "a", "cat": "s", "ph": "X", "ts": 0.0, "dur": 10.0,
             "pid": 1, "tid": 1, "args": {}},
            {"name": "b", "cat": "s", "ph": "X", "ts": 5.0, "dur": 10.0,
             "pid": 1, "tid": 1, "args": {}},
        ]}
        with pytest.raises(ValueError, match="overlap"):
            validate_chrome_trace(doc)

    def test_resolution_pins_swap_and_flush_to_the_applying_process(
            self):
        """Catalog versions are a PER-PROCESS counter: two consumer
        processes both mint version 3. Resolution must join the swap
        to the INGESTING process and the flush to the SWAPPING one —
        without the pid constraint, process A's record chained through
        process B's unrelated same-numbered flush (review-caught)."""
        def ev(name, pid, ts, dur=None, **args):
            e = {"name": name, "cat": "s", "ph": "X" if dur is not None
                 else "i", "ts": ts, "pid": pid, "tid": 1, "args": args}
            if dur is not None:
                e["dur"] = dur
            return e

        doc = {"traceEvents": [
            ev("wal/append", 0, 0.0, 5.0, partition=0, start_offset=0,
               end_offset=100),
            ev("stream/ingest_batch", 1, 10.0, 5.0, partition=0,
               start_offset=0, end_offset=100),
            ev("online/partial_fit", 1, 11.0, 2.0),
            # the DECOY: another process's same-numbered, EARLIER swap
            ev("lineage/swap_watermark", 2, 12.0, None, partition=0,
               watermark=500, version=3),
            ev("serving/flush", 2, 13.0, 1.0, catalog_version=3),
            # the real chain on the ingesting process
            ev("lineage/swap_watermark", 1, 20.0, None, partition=0,
               watermark=100, version=3),
            ev("serving/flush", 1, 21.0, 1.0, catalog_version=3),
        ]}
        chain = resolve_record_trace(doc, 0, 50)
        assert chain["complete"], chain
        hops = {h["hop"]: h for h in chain["hops"]}
        assert hops["catalog_swap"]["pid"] == 1
        assert hops["servable_flush"]["pid"] == 1

    def test_metadata_phase_validates(self):
        doc = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "p0"}}]}
        assert validate_chrome_trace(doc) == []


# --------------------------------------------------------------------------
# Critical-path analyzer: hand-pinned stage math
# --------------------------------------------------------------------------


class TestCriticalPathAnalyzer:
    def test_stage_decomposition_hand_pinned(self, causal_obs):
        reg, _, _ = causal_obs
        ana = CriticalPathAnalyzer(registry=reg)
        ana.note_append(400, partition=0, t=100.0)
        ana.note_dequeue(400, partition=0, t=101.5)
        ana.note_applied(400, partition=0, t=101.75)
        sample = ana.note_swap(9, partition=0, watermark=400, t=102.0)
        assert sample["offset"] == 399
        assert sample["queue_wait_s"] == pytest.approx(1.5)
        assert sample["train_apply_s"] == pytest.approx(0.25)
        assert sample["swap_lag_s"] == pytest.approx(0.25)
        # the stage sum IS the total by construction
        assert sample["total_s"] == pytest.approx(2.0)
        assert sample["flush_wait_s"] is None
        ana.note_serve(9, t=102.5)
        (done,) = ana.samples()
        assert done["flush_wait_s"] == pytest.approx(0.5)
        # gauges published for the recorder to keep history of
        names = {(m["name"], tuple(sorted(m["labels"].items())))
                 for m in reg.snapshot()["metrics"]}
        for stage in STAGES:
            assert ("critical_path_s", (("stage", stage),)) in names
        assert ("critical_path_total_s", ()) in names

    def test_one_sample_per_version_partition(self, causal_obs):
        reg, _, _ = causal_obs
        ana = CriticalPathAnalyzer(registry=reg)
        ana.note_applied(100, t=10.0)
        assert ana.note_swap(1, watermark=100, t=11.0) is not None
        assert ana.note_swap(1, watermark=100, t=12.0) is None  # dup
        assert ana.note_swap(2, watermark=100, t=12.0) is not None
        assert ana.samples_total == 2

    def test_no_covered_mark_no_sample(self, causal_obs):
        reg, _, _ = causal_obs
        ana = CriticalPathAnalyzer(registry=reg)
        assert ana.note_swap(1, watermark=50, t=1.0) is None  # no marks
        ana.note_applied(100, t=10.0)
        assert ana.note_swap(2, watermark=50, t=11.0) is None  # behind
        assert ana.note_swap(3, watermark=None) is None

    def test_missing_append_mark_degrades_gracefully(self, causal_obs):
        """A cross-process producer without an in-process append mark:
        queue_wait unknown (None), total measured from apply start."""
        reg, _, _ = causal_obs
        ana = CriticalPathAnalyzer(registry=reg)
        ana.note_dequeue(200, t=50.0)
        ana.note_applied(200, t=50.5)
        s = ana.note_swap(4, watermark=200, t=51.0)
        assert s["queue_wait_s"] is None
        assert s["train_apply_s"] == pytest.approx(0.5)
        assert s["total_s"] == pytest.approx(1.0)

    def test_capacity_bound_holds(self, causal_obs):
        reg, _, _ = causal_obs
        ana = CriticalPathAnalyzer(capacity=4, registry=reg)
        ana.note_applied(10, t=1.0)
        for v in range(10):
            ana.note_swap(v, watermark=10, t=2.0)
        assert len(ana) == 4
        assert ana.samples_total == 10
        snap = ana.snapshot()
        assert snap["stages"]["swap_lag"]["count"] == 4

    def test_snapshot_shape(self, causal_obs):
        _, _, ana = causal_obs
        snap = ana.snapshot()
        assert set(snap) >= {"time", "stages", "samples",
                             "samples_total", "capacity", "marks"}
        assert set(snap["stages"]) == set(STAGES) | {"total"}


# --------------------------------------------------------------------------
# The acceptance paths: real driver run, reconciliation, resume
# --------------------------------------------------------------------------


class TestDriverAcceptance:
    def _hist(self, reg):
        for m in reg.snapshot()["metrics"]:
            if m["name"] == "lineage_ingest_to_servable_s":
                return m
        return None

    def test_record_resolves_to_one_assembled_trace(self, causal_obs,
                                                    tmp_path):
        """The first acceptance half: on a real driver run, a sampled
        rating's record id resolves to ONE assembled distributed trace
        spanning WAL append → ingest batch → partial_fit → catalog
        swap → first servable flush."""
        from large_scale_recommendation_tpu.streams.log import EventLog

        _, tracer, _ = causal_obs
        log = EventLog(str(tmp_path / "log"), fsync=False)
        _fill_log(log)
        driver = _driver(tmp_path, log)
        engine = driver.serving_engine(k=3, max_batch=32)
        driver.run()
        driver.refresh_serving()
        engine.recommend(np.arange(5, dtype=np.int64))

        doc = assemble_pod_trace([("p0", tracer.chrome_trace())])
        validate_chrome_trace(doc)
        chain = resolve_record_trace(doc, 0, driver.consumed_offset - 1)
        assert chain["complete"], chain
        assert chain["found"] == ["wal_append", "ingest_batch",
                                  "partial_fit", "catalog_swap",
                                  "servable_flush"]
        # every hop is joinable by its namespaced span id (instants
        # outside spans carry None — the swap marker is one)
        ingest = [h for h in chain["hops"]
                  if h["hop"] == "ingest_batch"][0]
        assert str(ingest["span_id"]).startswith(process_namespace())
        # the trace-side decomposition covers every stage
        assert set(chain["stages"]) == set(STAGES)
        assert all(v >= 0 for v in chain["stages"].values())

    def test_critical_path_reconciles_with_lineage_histogram(
            self, causal_obs, tmp_path):
        """The satellite-3 pin: per-stage sums behave (total == stage
        sum) and the ``swap_lag`` stage reconciles against the
        ``lineage_ingest_to_servable_s`` sample — EXACTLY, because the
        two planes share their clock reads — including across a
        kill/restart resume."""
        from large_scale_recommendation_tpu.streams.log import EventLog

        reg, _, analyzer = causal_obs
        log = EventLog(str(tmp_path / "log"), fsync=False)
        _fill_log(log, n_batches=3)
        driver = _driver(tmp_path, log)
        engine = driver.serving_engine(k=3, max_batch=32)
        driver.run()
        driver.refresh_serving()
        engine.recommend(np.arange(4, dtype=np.int64))

        def check():
            samples = analyzer.samples()
            assert samples
            hist = self._hist(reg)
            assert hist is not None
            # one histogram observation per completed sample
            assert hist["count"] == len(samples)
            lags = [s["swap_lag_s"] for s in samples]
            assert np.mean(lags) == pytest.approx(hist["mean"],
                                                  rel=1e-6, abs=1e-6)
            for s in samples:
                parts = [v for v in (s["queue_wait_s"],
                                     s["train_apply_s"],
                                     s["swap_lag_s"]) if v is not None]
                assert sum(parts) == pytest.approx(s["total_s"],
                                                   abs=1e-9)
            # the builds that actually served priced their flush_wait
            # (a bind build superseded by a refresh before ever serving
            # legitimately never completes the stage)
            assert any(s["flush_wait_s"] is not None for s in samples)

        check()
        n_before = len(analyzer.samples())

        # kill/restart: a fresh driver + model resumes from the
        # checkpoint, ingests more, refreshes — the new samples must
        # keep reconciling
        _fill_log(log, n_batches=2, seed=1)
        driver2 = _driver(tmp_path, log)
        assert driver2.resume()
        engine2 = driver2.serving_engine(k=3, max_batch=32)
        driver2.run()
        driver2.refresh_serving()
        engine2.recommend(np.arange(4, dtype=np.int64))
        assert len(analyzer.samples()) > n_before
        check()


# --------------------------------------------------------------------------
# Endpoints: /criticalpathz and the pod /podtracez over real sockets
# --------------------------------------------------------------------------


class TestEndpoints:
    def test_criticalpathz_route(self, causal_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        _, _, ana = causal_obs
        ana.note_applied(10, t=1.0)
        ana.note_swap(1, watermark=10, t=1.5)
        with ObsServer() as server:
            code, body = http_get(server.url + "/criticalpathz")
            assert code == 200
            doc = json.loads(body)
            assert doc["samples_total"] == 1
            assert doc["stages"]["swap_lag"]["count"] == 1
            code, body = http_get(server.url + "/")
            assert "/criticalpathz" in json.loads(body)["routes"]

    def test_criticalpathz_without_analyzer(self, null_obs):
        from large_scale_recommendation_tpu.obs.server import ObsServer

        doc = ObsServer().criticalpathz()
        assert "note" in doc and doc["samples"] == []

    def test_tracez_limit_param(self, causal_obs):
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        _, tracer, _ = causal_obs
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        with ObsServer(tracez_limit=2) as server:
            code, body = http_get(server.url + "/tracez")
            assert len(json.loads(body)["recent"]) == 2
            code, body = http_get(server.url + "/tracez?limit=0")
            assert len(json.loads(body)["recent"]) == 5
            code, _ = http_get(server.url + "/tracez?limit=junk")
            assert code == 400
            # a negative limit is a client error, NOT a request for
            # the whole 200k-event buffer
            code, _ = http_get(server.url + "/tracez?limit=-1")
            assert code == 400

    def test_podtracez_merges_two_live_servers(self, causal_obs):
        """The pod route over REAL sockets: two ObsServers with
        separate tracers (standing in for two processes) merge into
        one validated timeline with both sources present."""
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
            FleetServer,
        )
        from large_scale_recommendation_tpu.obs.server import (
            ObsServer,
            http_get,
        )

        t1, t2 = Tracer(), Tracer()
        with t1.span("proc1/work"):
            pass
        with t2.span("proc2/work"):
            pass
        s1 = ObsServer(tracer=t1).start()
        s2 = ObsServer(tracer=t2).start()
        try:
            fleet = FleetServer(
                FleetAggregator([s1.url, s2.url])).start()
            try:
                code, body = http_get(fleet.url + "/podtracez")
                assert code == 200
                doc = json.loads(body)
                validate_chrome_trace(doc)
                names = {e["name"] for e in doc["traceEvents"]}
                assert {"proc1/work", "proc2/work"} <= names
                assert len(doc["podSources"]) == 2
                assert doc["unreachable"] == []
                code, body = http_get(fleet.url + "/")
                assert "/podtracez" in json.loads(body)["routes"]
            finally:
                fleet.stop()
        finally:
            s1.stop()
            s2.stop()

    def test_podtracez_skips_unreachable_target(self, causal_obs):
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
        )
        from large_scale_recommendation_tpu.obs.server import ObsServer

        t1 = Tracer()
        with t1.span("alive/work"):
            pass
        s1 = ObsServer(tracer=t1).start()
        try:
            agg = FleetAggregator(
                [s1.url, "http://127.0.0.1:9"], timeout_s=2.0)
            doc = agg.pod_trace()
            assert len(doc["podSources"]) == 1
            assert len(doc["unreachable"]) == 1
        finally:
            s1.stop()

    def test_report_renders_critical_path(self, causal_obs, capsys):
        sys.path.insert(0, "scripts")
        from obs_report import main as report_main

        _, _, ana = causal_obs
        ana.note_append(10, t=1.0)
        ana.note_dequeue(10, t=2.0)
        ana.note_applied(10, t=2.5)
        ana.note_swap(1, watermark=10, t=3.0)
        ana.note_serve(1, t=3.25)
        import json as _json
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            _json.dump(ana.snapshot(), f)
            path = f.name
        assert report_main(["--critical-path", path]) == 0
        out = capsys.readouterr().out
        assert "queue_wait" in out and "flush_wait" in out
        assert "total" in out
