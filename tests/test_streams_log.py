"""EventLog: offsets, segment roll, torn-tail recovery, retention.

The durability invariants the streaming recovery contract
(docs/STREAMING.md) rests on: acked offsets survive reopen, a torn tail
from a crash mid-write is truncated (never renumbered), and retention
refuses to lie about what is replayable.
"""

import os

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.streams.log import (
    HEADER_SIZE,
    RECORD_SIZE,
    EventLog,
    LogTruncatedError,
)


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return Ratings.from_arrays(rng.integers(0, 100, n),
                               rng.integers(0, 50, n),
                               rng.random(n).astype(np.float32))


class TestAppendRead:
    def test_roundtrip_offsets(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        b = _batch(100)
        assert log.append(0, b) == (0, 100)
        assert log.append(0, _batch(50, seed=1)) == (100, 150)
        out, nxt = log.read(0, 0, 100)
        assert nxt == 100
        np.testing.assert_array_equal(out.users, np.asarray(b.users))
        np.testing.assert_array_equal(out.ratings, np.asarray(b.ratings))
        # mid-stream read honors the requested range exactly
        out2, nxt2 = log.read(0, 90, 20)
        assert (nxt2, out2.n) == (110, 20)
        np.testing.assert_array_equal(out2.users[:10],
                                      np.asarray(b.users)[90:])

    def test_padding_rows_are_dropped(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        padded = _batch(10).pad_to(32)  # 22 weight-0 padding rows
        assert log.append(0, padded) == (0, 10)

    def test_read_at_end_is_empty(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        log.append(0, _batch(5))
        out, nxt = log.read(0, 5, 100)
        assert (out.n, nxt) == (0, 5)

    def test_multi_partition_independent_offsets(self, tmp_path):
        log = EventLog(str(tmp_path), num_partitions=3, fsync=False)
        assert log.append(1, _batch(10)) == (0, 10)
        assert log.append(2, _batch(20, seed=1)) == (0, 20)
        assert log.append(1, _batch(5, seed=2)) == (10, 15)
        assert log.end_offset(0) == 0
        assert log.lag({1: 10}) == 25  # 5 on p1 + 20 on p2 (p0 empty)


class TestSegments:
    def test_roll_and_cross_segment_read(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=64, fsync=False)
        b = _batch(300)
        log.append(0, b)
        part = log._parts[0]
        assert [s[0] for s in part.segments] == [0, 64, 128, 192, 256]
        out, nxt = log.read(0, 50, 200)  # spans 4 segments
        assert nxt == 250
        np.testing.assert_array_equal(out.users,
                                      np.asarray(b.users)[50:250])

    def test_reopen_resumes_offsets(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=64, fsync=False)
        log.append(0, _batch(100))
        log.close()
        log2 = EventLog(str(tmp_path), segment_records=64, fsync=False)
        assert log2.end_offset(0) == 100
        assert log2.append(0, _batch(10, seed=3)) == (100, 110)
        out, _ = log2.read(0, 0, 110)
        assert out.n == 110

    def test_geometry_mismatch_refused(self, tmp_path):
        EventLog(str(tmp_path), num_partitions=2, fsync=False).close()
        with pytest.raises(ValueError, match="renumber"):
            EventLog(str(tmp_path), num_partitions=4, fsync=False)

    def test_reopen_with_smaller_segment_records(self, tmp_path):
        # segment_records may shrink across opens, leaving the active
        # segment OVER-full; the next append must seal it and roll
        # (regression: negative room corrupted the segment counts and
        # made acked offsets unreadable)
        log = EventLog(str(tmp_path), segment_records=256, fsync=False)
        b = _batch(200)
        log.append(0, b)
        log.close()
        log2 = EventLog(str(tmp_path), segment_records=16, fsync=False)
        b2 = _batch(40, seed=1)
        assert log2.append(0, b2) == (200, 240)
        assert [tuple(s) for s in log2._parts[0].segments] == [
            (0, 200), (200, 16), (216, 16), (232, 8)]
        out, nxt = log2.read(0, 190, 30)  # spans the over-full boundary
        assert (out.n, nxt) == (30, 220)
        np.testing.assert_array_equal(out.users[:10],
                                      np.asarray(b.users)[190:])
        np.testing.assert_array_equal(out.users[10:],
                                      np.asarray(b2.users)[:20])


class TestCrashRecovery:
    def test_torn_tail_truncated_on_reopen(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        log.append(0, _batch(20))
        log.close()
        seg = os.path.join(str(tmp_path), "p0", f"seg_{0:020d}.log")
        with open(seg, "ab") as f:  # crash mid-append: 7 stray bytes
            f.write(b"\x01" * 7)
        log2 = EventLog(str(tmp_path), fsync=False)
        assert log2.end_offset(0) == 20  # unacked tail discarded
        assert log2.append(0, _batch(5, seed=1)) == (20, 25)
        out, _ = log2.read(0, 0, 25)
        assert out.n == 25

    def test_torn_whole_records_survive(self, tmp_path):
        # a torn tail is only the PARTIAL trailing record; complete
        # records before it are intact bytes and must survive
        log = EventLog(str(tmp_path), fsync=False)
        log.append(0, _batch(20))
        log.close()
        seg = os.path.join(str(tmp_path), "p0", f"seg_{0:020d}.log")
        assert os.path.getsize(seg) == HEADER_SIZE + 20 * RECORD_SIZE
        log2 = EventLog(str(tmp_path), fsync=False)
        out, _ = log2.read(0, 0, 20)
        assert out.n == 20

    def test_headerless_shell_segment_recovers(self, tmp_path):
        # crash between segment create and header write leaves a short
        # file; reopen must rewrite it as an empty segment, not die
        log = EventLog(str(tmp_path), segment_records=8, fsync=False)
        log.append(0, _batch(8))  # fills segment 0
        log.close()
        shell = os.path.join(str(tmp_path), "p0", f"seg_{8:020d}.log")
        with open(shell, "wb") as f:
            f.write(b"LS")  # truncated header
        log2 = EventLog(str(tmp_path), segment_records=8, fsync=False)
        assert log2.end_offset(0) == 8
        assert log2.append(0, _batch(3, seed=2)) == (8, 11)


class TestCrossInstance:
    def test_reader_instance_sees_writer_appends(self, tmp_path):
        # the multi-process topology: a tailer's EventLog instance must
        # observe appends made through a DIFFERENT instance (regression:
        # segment state was only scanned at open, so a separate-instance
        # tailer froze at its open-time end while reporting lag 0)
        writer = EventLog(str(tmp_path), segment_records=16, fsync=False)
        writer.append(0, _batch(4))
        reader = EventLog(str(tmp_path), segment_records=16, fsync=False)
        assert reader.end_offset(0) == 4
        writer.append(0, _batch(40, seed=1))  # grows tail AND rolls
        assert reader.end_offset(0) == 44
        assert reader.lag({0: 4}) == 40
        out, nxt = reader.read(0, 4, 100)
        assert (out.n, nxt) == (40, 44)

    def test_reader_instance_sees_foreign_retention(self, tmp_path):
        writer = EventLog(str(tmp_path), segment_records=16, fsync=False)
        writer.append(0, _batch(40))
        reader = EventLog(str(tmp_path), segment_records=16, fsync=False)
        writer.truncate_before(0, 32)
        with pytest.raises(LogTruncatedError):  # not FileNotFoundError
            reader.read(0, 0, 8)
        assert reader.start_offset(0) == 32


class TestRetention:
    def test_truncate_before_frees_segments(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=32, fsync=False)
        log.append(0, _batch(100))
        floor = log.truncate_before(0, 70)  # segments [0,32),[32,64) go
        assert floor == 64
        assert log.start_offset(0) == 64
        out, nxt = log.read(0, 64, 100)
        assert (out.n, nxt) == (36, 100)

    def test_read_below_floor_raises(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=32, fsync=False)
        log.append(0, _batch(100))
        log.truncate_before(0, 64)
        with pytest.raises(LogTruncatedError):
            log.read(0, 10, 5)

    def test_active_segment_survives(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=32, fsync=False)
        log.append(0, _batch(40))  # 32 sealed + 8 active
        log.truncate_before(0, 10 ** 9)  # beyond the end
        assert log.start_offset(0) == 32  # active tail never deleted
        assert log.append(0, _batch(4, seed=1)) == (40, 44)

    def test_concurrent_tail_read_and_truncate(self, tmp_path):
        # the driver's built-in race (truncate_log=True): the consumer
        # thread truncates on every checkpoint while the feeder thread
        # reads the tail — reads must return complete, correct data or
        # raise, never silently hand back uninitialized buffer rows
        import threading

        log = EventLog(str(tmp_path), segment_records=32, fsync=False)
        n = 4096
        idx = np.arange(n)
        log.append_arrays(0, idx % 997, idx % 991,
                          idx.astype(np.float32))  # rating == offset
        consumed = [0]
        errors = []

        def reader():
            try:
                off = 0
                while off < n:
                    out, nxt = log.read(0, off, 100)
                    np.testing.assert_array_equal(
                        np.asarray(out.ratings),
                        np.arange(off, nxt, dtype=np.float32))
                    off = nxt
                    consumed[0] = off
            except Exception as exc:  # surfaced to the main thread
                errors.append(exc)
                consumed[0] = n

        t = threading.Thread(target=reader)
        t.start()
        while consumed[0] < n:  # truncate as fast as the reader commits
            log.truncate_before(0, consumed[0])
        t.join(timeout=30)
        assert not errors
        assert consumed[0] == n

    def test_concurrent_append_and_tail_read(self, tmp_path):
        # same-instance producer + tailer: a read at the end triggers
        # refresh(), which max-bumps the active count from the flushed
        # file size while the appender is between flush and bookkeeping
        # (regression: += on top of that bump double-counted, inflating
        # the in-memory count past the file — tail reads then died with
        # short-read errors)
        import threading
        import time

        log = EventLog(str(tmp_path), segment_records=64, fsync=False)
        n = 3000
        errors = []

        def writer():
            try:
                for k in range(0, n, 50):
                    idx = np.arange(k, k + 50)
                    log.append_arrays(0, idx % 997, idx % 991,
                                      idx.astype(np.float32))
            except Exception as exc:  # surfaced to the main thread
                errors.append(exc)

        t = threading.Thread(target=writer)
        t.start()
        off = 0
        deadline = time.monotonic() + 30
        while off < n and time.monotonic() < deadline:
            out, nxt = log.read(0, off, 75)
            np.testing.assert_array_equal(
                np.asarray(out.ratings),
                np.arange(off, nxt, dtype=np.float32))
            off = nxt
        t.join(timeout=30)
        assert not errors
        assert off == n
