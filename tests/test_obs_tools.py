"""The terminal-side observability tooling: the bench regression gate
(``scripts/bench_regress.py`` — wrapper/raw/salvage loading, threshold
verdicts, exit codes) and the live-watch delta math in
``scripts/obs_report.py``.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.bench_regress import (  # noqa: E402
    compare,
    flatten_result,
    load_result,
    main as regress_main,
)
from scripts.obs_report import snapshot_deltas  # noqa: E402


def _bench_doc(value=1000.0, extra=None):
    return {"metric": "ratings/s test", "value": value, "unit": "ratings/s",
            "vs_baseline": 1.0, "extra": extra or {}}


def _wrapper(parsed=None, tail=""):
    return {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": tail,
            "parsed": parsed}


class TestLoading:
    def test_raw_bench_line(self, tmp_path):
        p = tmp_path / "raw.json"
        p.write_text(json.dumps(_bench_doc(
            2000.0, {"serving_users_per_s": 42.5, "pipeline": "device"})))
        flat, caveat = load_result(str(p))
        assert flat == {"value": 2000.0, "serving_users_per_s": 42.5}
        assert caveat is None

    def test_wrapper_with_parsed(self, tmp_path):
        p = tmp_path / "w.json"
        p.write_text(json.dumps(_wrapper(parsed=_bench_doc(
            3000.0, {"online_ratings_per_s": 7.0}))))
        flat, _ = load_result(str(p))
        assert flat["value"] == 3000.0
        assert flat["online_ratings_per_s"] == 7.0

    def test_truncated_tail_salvage(self, tmp_path):
        """A front-truncated tail (the real r05 shape) still yields its
        numeric pairs — array elements (no preceding key) don't match."""
        tail = ('_per_s\": 123.4, \"rmse_curve\": [0.27, 0.26], '
                '\"serving_users_per_s\": 25837.8}}')
        p = tmp_path / "t.json"
        p.write_text(json.dumps(_wrapper(parsed=None, tail=tail)))
        flat, _ = load_result(str(p))
        assert flat["serving_users_per_s"] == 25837.8
        assert 0.26 not in flat.values()  # curve entries not salvaged

    def test_error_field_is_caveat(self, tmp_path):
        doc = _bench_doc(1.0)
        doc["error"] = "CPU fallback run"
        p = tmp_path / "e.json"
        p.write_text(json.dumps(_wrapper(parsed=doc)))
        _, caveat = load_result(str(p))
        assert "CPU fallback" in caveat

    def test_flat_baseline_dict(self):
        flat = flatten_result({"serving_users_per_s": 10.0, "note": "x"})
        assert flat == {"serving_users_per_s": 10.0}


class TestCompare:
    def test_verdicts(self):
        base = {"a": 100.0, "b": 100.0, "c": 100.0}
        cur = {"a": 95.0, "b": 60.0}
        rows = compare(base, cur, {"a": 10.0, "b": 10.0, "c": 10.0})
        by_key = {r["key"]: r for r in rows}
        assert by_key["a"]["verdict"] == "ok"  # -5% within 10%
        assert by_key["b"]["verdict"] == "REGRESSION"  # -40%
        assert by_key["c"]["verdict"] == "missing"

    def test_improvement_is_ok(self):
        rows = compare({"a": 100.0}, {"a": 300.0}, {"a": 10.0})
        assert rows[0]["verdict"] == "ok"

    def test_lower_is_better_keys(self):
        # *_wall_s is auto-flagged lower-better: growth is the regression
        rows = compare({"dsgd_train_wall_s": 2.0},
                       {"dsgd_train_wall_s": 3.0},
                       {"dsgd_train_wall_s": 10.0})
        assert rows[0]["verdict"] == "REGRESSION"
        rows = compare({"dsgd_train_wall_s": 2.0},
                       {"dsgd_train_wall_s": 1.0},
                       {"dsgd_train_wall_s": 10.0})
        assert rows[0]["verdict"] == "ok"

    def test_higher_is_better_keys_explicit(self):
        """Throughputs and achieved bandwidth (the ISSUE-6 gate keys) are
        EXPLICITLY higher-is-better: a drop regresses, growth never does —
        even for keys that also contain a lower-better substring."""
        from scripts.bench_regress import is_lower_better

        for key in ("effective_hbm_gbs", "pct_of_hbm_peak",
                    "online_ratings_per_s", "als_rank32_rows_per_s",
                    "serving_users_per_s", "train_hbm_gbs",
                    "kernel_pallas_loop_effective_hbm_gbs"):
            assert not is_lower_better(key, set()), key
            rows = compare({key: 100.0}, {key: 60.0}, {key: 10.0})
            assert rows[0]["verdict"] == "REGRESSION", key
            rows = compare({key: 100.0}, {key: 300.0}, {key: 10.0})
            assert rows[0]["verdict"] == "ok", key
        # the explicit rule wins over an accidental DEFAULT_LOWER
        # substring collision ("time_to_" is lower-better, but a rate
        # named around it must stay higher-better)
        assert not is_lower_better("time_to_target_ratings_per_s", set())
        # an explicit --lower flag still wins over everything
        assert is_lower_better("effective_hbm_gbs",
                               {"effective_hbm_gbs"})

    def test_hbm_gate_keys_in_default_watch_set(self):
        """The ISSUE-6 bandwidth keys are gated by DEFAULT (no flags)."""
        from scripts.bench_regress import DEFAULT_KEYS

        assert "effective_hbm_gbs" in DEFAULT_KEYS
        assert "pct_of_hbm_peak" in DEFAULT_KEYS

    def test_compile_gate_keys_in_default_watch_set(self):
        """The ISSUE-9 compile-time keys are gated by DEFAULT: a
        compile-count explosion or a compile-wall blowup trips the gate
        with no flags."""
        from scripts.bench_regress import DEFAULT_KEYS

        for key in ("compile_wall_s", "xla_compile_wall_s",
                    "compile_count"):
            assert key in DEFAULT_KEYS, key

    def test_compile_keys_lower_is_better(self):
        """Compile time/count regress when they GROW — lower-is-better
        (compile_wall_s via the _wall_s pattern, compile_count via its
        own DEFAULT_LOWER entry)."""
        from scripts.bench_regress import is_lower_better

        for key in ("compile_wall_s", "xla_compile_wall_s",
                    "compile_count"):
            assert is_lower_better(key, set()), key
            rows = compare({key: 10.0}, {key: 20.0}, {key: 15.0})
            assert rows[0]["verdict"] == "REGRESSION", key
            rows = compare({key: 10.0}, {key: 5.0}, {key: 15.0})
            assert rows[0]["verdict"] == "ok", key


class TestGateEndToEnd:
    def _write(self, tmp_path, name, value, extra=None):
        p = tmp_path / name
        p.write_text(json.dumps(_wrapper(parsed=_bench_doc(value, extra))))
        return str(p)

    def test_ok_exit_zero(self, tmp_path, capsys):
        b = self._write(tmp_path, "b.json", 1000.0,
                        {"serving_users_per_s": 50.0})
        c = self._write(tmp_path, "c.json", 980.0,
                        {"serving_users_per_s": 51.0})
        rc = regress_main(["--baseline", b, "--current", c])
        assert rc == 0
        out = capsys.readouterr().out
        assert "no regressions" in out

    def test_regression_exit_one_and_table(self, tmp_path, capsys):
        b = self._write(tmp_path, "b.json", 1000.0)
        c = self._write(tmp_path, "c.json", 500.0)
        rc = regress_main(["--baseline", b, "--current", c,
                           "--key", "value=20"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "value" in out

    def test_report_file_written(self, tmp_path):
        b = self._write(tmp_path, "b.json", 1000.0)
        c = self._write(tmp_path, "c.json", 990.0)
        report = tmp_path / "report.txt"
        rc = regress_main(["--baseline", b, "--current", c,
                           "--report", str(report)])
        assert rc == 0
        assert "baseline" in report.read_text()

    def test_missing_key_fails_only_strict(self, tmp_path):
        b = self._write(tmp_path, "b.json", 1000.0,
                        {"serving_users_per_s": 50.0})
        c = self._write(tmp_path, "c.json", 1000.0)  # extra key gone
        args = ["--baseline", b, "--current", c,
                "--key", "value=30", "--key", "serving_users_per_s=30"]
        assert regress_main(args) == 0
        assert regress_main(args + ["--strict"]) == 1

    def test_multichip_family_gates_pad_and_throughput(self, tmp_path,
                                                       capsys):
        """--family multichip (ISSUE 7): MULTICHIP_r*.json rounds gate
        through the same loader with pad ratio LOWER-is-better and
        sharded throughput HIGHER-is-better."""
        base = {"n_devices": 16, "max_pad_ratio": 1.10, "layout_mb": 600.0,
                "train_ratings_per_s": 500_000.0, "als_rows_per_s": 9000.0}
        # a pad-ratio blowup alone must trip the gate
        cur = dict(base, max_pad_ratio=1.60)
        b, c = tmp_path / "MULTICHIP_r01.json", tmp_path / "MULTICHIP_r02.json"
        b.write_text(json.dumps(base))
        c.write_text(json.dumps(cur))
        rc = regress_main(["--family", "multichip",
                           "--baseline", str(b), "--current", str(c)])
        assert rc == 1
        assert "max_pad_ratio" in capsys.readouterr().out
        # a throughput collapse must trip it too
        c.write_text(json.dumps(dict(base, train_ratings_per_s=100_000.0)))
        assert regress_main(["--family", "multichip",
                             "--baseline", str(b),
                             "--current", str(c)]) == 1
        # better pad ratio AND faster training is never a regression
        c.write_text(json.dumps(dict(base, max_pad_ratio=1.02,
                                     train_ratings_per_s=900_000.0)))
        assert regress_main(["--family", "multichip",
                             "--baseline", str(b),
                             "--current", str(c)]) == 0

    def test_multichip_direction_rules(self):
        """Pad/layout keys are lower-is-better; the sharded throughput
        keys stay higher-is-better; all are in the default watch set."""
        from scripts.bench_regress import (
            MULTICHIP_KEYS,
            is_lower_better,
        )

        for key in ("max_pad_ratio", "layout_mb", "layout_bytes"):
            assert is_lower_better(key, set()), key
        for key in ("train_ratings_per_s", "als_rows_per_s"):
            assert not is_lower_better(key, set()), key
        for key in ("train_ratings_per_s", "als_rows_per_s",
                    "max_pad_ratio", "layout_mb"):
            assert key in MULTICHIP_KEYS

    def test_multichip_find_rounds_and_legacy_wrappers(self, tmp_path):
        """find_rounds(prefix=) orders MULTICHIP rounds; the committed
        legacy wrapper shape ({n_devices, rc, ok, tail}) still loads
        (empty metrics -> 'missing' verdicts, never a crash)."""
        from scripts.bench_regress import find_rounds

        for n in (2, 1, 10):
            (tmp_path / f"MULTICHIP_r{n:02d}.json").write_text("{}")
        (tmp_path / "BENCH_r01.json").write_text("{}")
        rounds = find_rounds(str(tmp_path), prefix="MULTICHIP")
        assert [os.path.basename(p) for p in rounds] == [
            "MULTICHIP_r01.json", "MULTICHIP_r02.json",
            "MULTICHIP_r10.json"]
        legacy = tmp_path / "MULTICHIP_r00.json"
        legacy.write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
             "tail": ""}))
        flat, caveat = load_result(str(legacy))
        assert flat == {} and caveat is None
        rows = compare(flat, {"max_pad_ratio": 1.2}, {"max_pad_ratio": 10.0})
        assert rows[0]["verdict"] == "missing"

    def test_multichip_wrapper_tail_salvage(self, tmp_path):
        """A future driver wrapper whose tail holds the pod_dryrun JSON
        line salvages the numeric fields through the shared loader."""
        tail = ('{"n_devices": 16, "max_pad_ratio": 1.104, '
                '"train_ratings_per_s": 421337, "two_process": '
                '{"ok": true, "wall_s": 38.2}}')
        p = tmp_path / "MULTICHIP_r03.json"
        p.write_text(json.dumps({"n": 16, "rc": 0, "tail": tail,
                                 "parsed": None}))
        flat, _ = load_result(str(p))
        assert flat["max_pad_ratio"] == 1.104
        assert flat["train_ratings_per_s"] == 421337

    def test_real_rounds_parse(self):
        """Every committed *successful* BENCH_r*.json loads into a
        non-empty flat metric dict — the gate can always read the
        repo's own rounds (a crashed round, rc != 0 with a traceback
        tail, legitimately yields nothing and must not blow up)."""
        from scripts.bench_regress import find_rounds

        rounds = find_rounds()
        assert len(rounds) >= 2
        parsed_any = 0
        for path in rounds:
            with open(path) as f:
                rc = json.load(f).get("rc")
            flat, _ = load_result(path)  # must never raise
            if rc == 0:
                assert flat, f"no numeric keys salvaged from {path}"
                parsed_any += 1
        assert parsed_any >= 2  # enough healthy rounds to actually gate


class TestQualityLineageRenderers:
    def test_render_lineage_snapshot(self):
        from scripts.obs_report import render_lineage

        doc = {"time": 100.0, "swaps": 3, "evicted": 0,
               "records": [
                   {"catalog_version": 1, "wall_time": 90.0,
                    "wal_offset_watermark": 500, "train_step": 4,
                    "retrain_id": None, "source": "stream_refresh",
                    "seq": 1}],
               "freshness": {"servable_watermark": 500,
                             "servable_swap_age_s": 10.0,
                             "latest_ingest_offset": 700,
                             "ingest_ahead": True,
                             "unservable_age_s": 6.0}}
        out = render_lineage(doc)
        assert "stream_refresh" in out
        assert "500" in out
        assert "INGEST AHEAD" in out

    def test_render_lineage_accepts_bundle_file_shape(self):
        from scripts.obs_report import render_lineage

        bundle_doc = {"lineage": {"records": [], "swaps": 0,
                                  "freshness": {}},
                      "quality": [], "data_quality": []}
        assert "no provenance records" in render_lineage(bundle_doc)

    def test_render_quality_series_and_bundle_shapes(self):
        from scripts.obs_report import render_quality

        series_doc = {"series": {
            'eval_rmse{source="online"}': {
                "points": [[1, 0.5], [2, 0.4]], "n": 2},
            "online_batch_s:p50": {"points": [[1, 0.1]], "n": 1}}}
        out = render_quality(series_doc)
        assert "eval_rmse" in out
        assert "online_batch_s" not in out  # non-quality series filtered
        bundle_doc = {"lineage": {"records": []},
                      "quality": [{"name": "eval_rmse",
                                   "labels": {"source": "online"},
                                   "type": "gauge", "value": 0.42}],
                      "data_quality": []}
        out = render_quality(bundle_doc)
        assert "0.42" in out

    def test_cli_modes(self, tmp_path, capsys):
        import json as _json

        from scripts.obs_report import main as report_main

        p = tmp_path / "lineage.json"
        p.write_text(_json.dumps({"records": [], "swaps": 0,
                                  "freshness": {}}))
        assert report_main(["--lineage", str(p)]) == 0
        assert "catalog lineage" in capsys.readouterr().out
        q = tmp_path / "series.json"
        q.write_text(_json.dumps({"series": {}}))
        assert report_main(["--quality", str(q)]) == 0
        assert "model-quality" in capsys.readouterr().out
        b = tmp_path / "budget.json"
        b.write_text(_json.dumps({"note": "rollout budget not enabled",
                                  "cohorts": {}}))
        assert report_main(["--budget", str(b)]) == 0
        assert "rollout error budget" in capsys.readouterr().out

    def test_render_budget_snapshot_and_fleet_shapes(self):
        from scripts.obs_report import render_budget

        # the local /budgetz shape: cohorts keyed by version string
        doc = {"target_s": 0.1, "objective": 0.9,
               "burn_rates": {"primary": 0.5, "fast": 4.0, "slow": 0.5},
               "cohorts": {"7": {"served": 40, "shed": 0,
                                 "shed_frac": 0.0, "attainment": 1.0,
                                 "burn_rate_fast": 0.0, "p99_ms": 10.0,
                                 "error_budget_remaining": 1.0},
                           "9": {"served": 40, "shed": 3,
                                 "shed_frac": 0.07, "attainment": 0.0,
                                 "burn_rate_fast": 10.0, "p99_ms": 200.0,
                                 "error_budget_remaining": 0.0}},
               "verdicts": {
                   "pending_rollbacks": {"9": {"reason": "burn cliff",
                                               "time": 100.0}},
                   "history": [{"time": 100.0, "canary_version": 9,
                                "incumbent_version": 7,
                                "verdict": "ROLLBACK",
                                "reason": "burn cliff"}]}}
        out = render_budget(doc)
        assert "PENDING ROLLBACK v9" in out
        assert "burn cliff" in out
        assert "fast=4" in out
        # the fleet pod-aggregate shape: a merged, sorted row list
        fleet = {"objective": 0.9,
                 "cohorts": [{"version": 9, "served": 80, "shed": 6,
                              "shed_frac": 0.07, "attainment": 0.0,
                              "burn_rate_fast_max": 10.0,
                              "p99_ms_max": 200.0,
                              "error_budget_remaining_min": 0.0,
                              "hosts": 2}],
                 "pending_rollbacks": {"9": [{"host": "a:1",
                                              "reason": "burn cliff"}]},
                 "targets": [{"host": "a:1", "evaluations": 3,
                              "pending_rollbacks": ["9"],
                              "note": None}]}
        out = render_budget(fleet)
        assert "a:1" in out and "PENDING ROLLBACK v9" in out
        # the absent-plane note renders, never crashes
        assert "enable_budget" in render_budget(
            {"note": "rollout budget not enabled (obs.enable_budget)",
             "cohorts": {}})


class TestWatchDeltas:
    def _snap(self, t, counter=0.0, gauge=0.0, hist_count=0):
        return {"time": t, "metrics": [
            {"name": "c_total", "type": "counter", "labels": {},
             "value": counter},
            {"name": "g", "type": "gauge", "labels": {"x": "1"},
             "value": gauge},
            {"name": "h_s", "type": "histogram", "labels": {},
             "count": hist_count, "sum": 1.0, "mean": 0.1, "min": 0.1,
             "max": 0.1, "p50": 0.1, "p90": 0.1, "p99": 0.1},
        ]}

    def test_counter_and_histogram_rates(self):
        rows = snapshot_deltas(self._snap(0, counter=10, hist_count=4),
                               self._snap(2, counter=30, gauge=7.0,
                                          hist_count=10), dt=2.0)
        by = {r["name"]: r for r in rows}
        assert by["c_total"]["delta"] == 20
        assert by["c_total"]["rate"] == 10.0
        assert by["h_s"]["delta"] == 6
        assert by["h_s"]["rate"] == 3.0
        assert by["h_s"]["p99"] == 0.1
        # gauges: value + change (no rate) — the delta is what keeps a
        # moving lag/SLO gauge visible in --watch's active-only view
        assert by["g"]["value"] == 7.0
        assert by["g"]["delta"] == 7.0
        assert "rate" not in by["g"]

    def test_watch_active_view_keeps_moving_gauges(self):
        from scripts.obs_report import render_deltas

        prev = self._snap(0, gauge=3.0)
        cur = self._snap(1, gauge=9.0)
        table = render_deltas(prev, cur, dt=1.0, active_only=True)
        assert "g" in table.splitlines()[2]  # the gauge row survived
        stale = render_deltas(cur, cur, dt=1.0, active_only=True)
        assert "(no activity)" in stale  # unchanged gauge drops out

    def test_new_instrument_counts_from_zero(self):
        prev = {"time": 0, "metrics": []}
        rows = snapshot_deltas(prev, self._snap(1, counter=5), dt=1.0)
        by = {r["name"]: r for r in rows}
        assert by["c_total"]["delta"] == 5


class TestServingFamily:
    """``--family serving`` (ISSUE 8): SERVING_r*.json traffic-sim
    rounds gate with p99 latencies LOWER-is-better and throughput /
    QPS-at-SLO / recall higher-is-better — the unit twins of the
    multichip family's tests above."""

    BASE = {"fast_users_per_s": 900.0, "exact_users_per_s": 300.0,
            "fast_vs_exact": 3.0, "qps_at_slo": 60.0,
            "recall_at_10": 0.97, "p99_ms": 120.0,
            "overload_fast_p99_ms": 250.0}

    def _round(self, tmp_path, name, **over):
        extra = dict(self.BASE, **over)
        value = extra.pop("value", extra["fast_users_per_s"])
        p = tmp_path / name
        p.write_text(json.dumps(  # the real serving_bench line shape
            {"metric": "two-stage serving users/s", "value": value,
             "unit": "users/s", "vs_baseline": extra["fast_vs_exact"],
             "extra": extra}))
        return str(p)

    def test_p99_blowup_alone_trips(self, tmp_path, capsys):
        b = self._round(tmp_path, "SERVING_r01.json")
        c = self._round(tmp_path, "SERVING_r02.json", p99_ms=400.0)
        rc = regress_main(["--family", "serving",
                           "--baseline", b, "--current", c])
        assert rc == 1
        assert "p99_ms" in capsys.readouterr().out

    def test_recall_drop_trips_tight(self, tmp_path):
        """Recall is deterministic (same code + seed ⇒ same index):
        its threshold is tight — a 7% drop is a retrieval-math change."""
        b = self._round(tmp_path, "SERVING_r01.json")
        c = self._round(tmp_path, "SERVING_r02.json", recall_at_10=0.90)
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c]) == 1

    def test_throughput_collapse_trips(self, tmp_path):
        b = self._round(tmp_path, "SERVING_r01.json")
        c = self._round(tmp_path, "SERVING_r02.json",
                        fast_users_per_s=400.0, value=400.0)
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c]) == 1

    def test_across_the_board_improvement_never_trips(self, tmp_path):
        b = self._round(tmp_path, "SERVING_r01.json")
        c = self._round(tmp_path, "SERVING_r02.json",
                        fast_users_per_s=2000.0, value=2000.0,
                        p99_ms=40.0, overload_fast_p99_ms=90.0,
                        qps_at_slo=200.0, recall_at_10=0.999,
                        fast_vs_exact=6.0)
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c]) == 0

    def test_serving_direction_rules(self):
        from scripts.bench_regress import SERVING_KEYS, is_lower_better

        for key in ("p99_ms", "p50_ms", "overload_fast_p99_ms",
                    "overload_exact_p99_ms"):
            assert is_lower_better(key, set()), key
        for key in ("fast_users_per_s", "exact_users_per_s",
                    "fast_vs_exact", "qps_at_slo", "recall_at_10"):
            assert not is_lower_better(key, set()), key
        for key in ("fast_users_per_s", "qps_at_slo", "recall_at_10",
                    "p99_ms", "overload_fast_p99_ms"):
            assert key in SERVING_KEYS

    def test_serving_find_rounds(self, tmp_path):
        from scripts.bench_regress import find_rounds

        for n in (3, 1):
            (tmp_path / f"SERVING_r{n:02d}.json").write_text("{}")
        (tmp_path / "BENCH_r01.json").write_text("{}")
        rounds = find_rounds(str(tmp_path), prefix="SERVING")
        assert [os.path.basename(p) for p in rounds] == [
            "SERVING_r01.json", "SERVING_r03.json"]


class TestQualityFamily:
    """``--family quality`` (ISSUE 10): the model-quality keys ride
    inside the BENCH rounds — implicit ranking/coverage and the eval_*
    family gate higher-is-better, eval_rmse lower — following the
    PR 7/8 family pattern (direction + watch-set unit twins)."""

    BASE = {"als_implicit_ndcg": 0.45, "als_implicit_hr10": 0.62,
            "als_implicit_coverage": 0.30, "rmse_final": 0.85}

    def _round(self, tmp_path, name, **over):
        extra = dict(self.BASE, **over)
        p = tmp_path / name
        p.write_text(json.dumps(  # the real bench line shape
            {"metric": "ratings/s", "value": 1000.0,
             "unit": "ratings/s", "extra": extra}))
        return str(p)

    def test_ndcg_collapse_alone_trips(self, tmp_path, capsys):
        """The ndcg=0.003 scenario the family exists for: a ranking
        collapse trips the gate even with throughput untouched."""
        b = self._round(tmp_path, "BENCH_r01.json")
        c = self._round(tmp_path, "BENCH_r02.json",
                        als_implicit_ndcg=0.003, als_implicit_hr10=0.007)
        rc = regress_main(["--family", "quality",
                           "--baseline", b, "--current", c])
        assert rc == 1
        assert "als_implicit_ndcg" in capsys.readouterr().out

    def test_rmse_blowup_trips_lower_is_better(self, tmp_path):
        b = self._round(tmp_path, "BENCH_r01.json")
        c = self._round(tmp_path, "BENCH_r02.json", rmse_final=2.0)
        assert regress_main(["--family", "quality",
                             "--baseline", b, "--current", c]) == 1

    def test_coverage_collapse_trips(self, tmp_path):
        b = self._round(tmp_path, "BENCH_r01.json")
        c = self._round(tmp_path, "BENCH_r02.json",
                        als_implicit_coverage=0.05)
        assert regress_main(["--family", "quality",
                             "--baseline", b, "--current", c]) == 1

    def test_across_the_board_improvement_never_trips(self, tmp_path):
        b = self._round(tmp_path, "BENCH_r01.json")
        c = self._round(tmp_path, "BENCH_r02.json",
                        als_implicit_ndcg=0.9, als_implicit_hr10=0.95,
                        als_implicit_coverage=0.6, rmse_final=0.4)
        assert regress_main(["--family", "quality",
                             "--baseline", b, "--current", c]) == 0

    def test_quality_direction_rules(self):
        """Direction rules cover BOTH the bench-borne keys and the
        evaluator's eval_* family (watchable via --key on
        quality-bearing rounds)."""
        from scripts.bench_regress import QUALITY_KEYS, is_lower_better

        for key in ("als_implicit_ndcg", "als_implicit_hr10",
                    "als_implicit_coverage", "eval_ndcg_at_k",
                    "eval_hr_at_k", "eval_coverage"):
            assert not is_lower_better(key, set()), key
        for key in ("eval_rmse", "rmse_final", "lineage_staleness_s"):
            assert is_lower_better(key, set()), key
        for key in self.BASE:
            assert key in QUALITY_KEYS, key

    def test_quality_family_reads_bench_rounds(self):
        """The family maps onto the BENCH prefix and watches ONLY keys
        a bench round can actually carry — a default watch key no
        round contains would be permanent 'missing' noise and an
        unconditional --strict failure."""
        from scripts.bench_regress import QUALITY_KEYS, FAMILIES

        prefix, keys = FAMILIES["quality"]
        assert prefix == "BENCH"
        assert keys is not FAMILIES["bench"][1]
        assert not any(k.startswith("eval_") for k in QUALITY_KEYS)


class TestCriticalPathDirection:
    """ISSUE 12: the ingest→servable critical-path keys gate
    LOWER-is-better — the PR 7/8-pattern direction/watch-set unit
    twins for ``critical_path_total_s`` and the per-stage keys."""

    def test_critical_path_keys_lower_is_better(self):
        from scripts.bench_regress import is_lower_better

        for key in ("critical_path_total_s", "critical_path_s",
                    "critical_path_swap_lag_s"):
            assert is_lower_better(key, set()), key
        rows = compare({"critical_path_total_s": 1.0},
                       {"critical_path_total_s": 2.0},
                       {"critical_path_total_s": 30.0})
        assert rows[0]["verdict"] == "REGRESSION"
        rows = compare({"critical_path_total_s": 1.0},
                       {"critical_path_total_s": 0.5},
                       {"critical_path_total_s": 30.0})
        assert rows[0]["verdict"] == "ok"

    def test_no_higher_pattern_collision(self):
        """A critical-path wall must never match a higher-is-better
        pattern (DEFAULT_HIGHER wins over DEFAULT_LOWER, so a
        collision would silently flip the gate's direction)."""
        from scripts.bench_regress import DEFAULT_HIGHER

        for key in ("critical_path_total_s", "critical_path_s"):
            assert not any(pat in key for pat in DEFAULT_HIGHER), key


class TestIngestFamily:
    """``--family ingest`` (ISSUE 13): INGEST_r*.json parallel-ingest
    rounds gate with rates and scaling efficiency higher-is-better and
    recovery wall / duplicate window LOWER-is-better — the PR 7/8
    pattern direction/no-collision unit twins."""

    BASE = {"ingest_n1_ratings_per_s": 1_000_000.0,
            "ingest_n4_ratings_per_s": 3_000_000.0,
            "scaling_eff_n4": 0.75,
            "recovery_s": 2.0,
            "duplicate_window_batches_max": 4.0}

    def _round(self, tmp_path, name, **over):
        extra = dict(self.BASE, **over)
        value = extra.pop("value", extra["ingest_n4_ratings_per_s"])
        p = tmp_path / name
        p.write_text(json.dumps(  # the real streams_bench line shape
            {"metric": "parallel ingest ratings/s", "value": value,
             "unit": "ratings/s", "vs_baseline": 3.0, "extra": extra}))
        return str(p)

    def test_scaling_efficiency_drop_trips(self, tmp_path, capsys):
        b = self._round(tmp_path, "INGEST_r01.json")
        c = self._round(tmp_path, "INGEST_r02.json", scaling_eff_n4=0.3)
        rc = regress_main(["--family", "ingest",
                           "--baseline", b, "--current", c])
        assert rc == 1
        assert "scaling_eff_n4" in capsys.readouterr().out

    def test_recovery_blowup_trips(self, tmp_path):
        b = self._round(tmp_path, "INGEST_r01.json")
        c = self._round(tmp_path, "INGEST_r02.json", recovery_s=10.0)
        assert regress_main(["--family", "ingest",
                             "--baseline", b, "--current", c]) == 1

    def test_duplicate_window_growth_trips_tight(self, tmp_path):
        """The duplicate window is bounded by the barrier cadence —
        near-deterministic, so its threshold is tight: +1 batch on a
        4-batch window is a 25% regression."""
        b = self._round(tmp_path, "INGEST_r01.json")
        c = self._round(tmp_path, "INGEST_r02.json",
                        duplicate_window_batches_max=5.0)
        assert regress_main(["--family", "ingest",
                             "--baseline", b, "--current", c]) == 1

    def test_throughput_collapse_trips(self, tmp_path):
        b = self._round(tmp_path, "INGEST_r01.json")
        c = self._round(tmp_path, "INGEST_r02.json",
                        ingest_n4_ratings_per_s=1_000_000.0,
                        value=1_000_000.0)
        assert regress_main(["--family", "ingest",
                             "--baseline", b, "--current", c]) == 1

    def test_across_the_board_improvement_never_trips(self, tmp_path):
        b = self._round(tmp_path, "INGEST_r01.json")
        c = self._round(tmp_path, "INGEST_r02.json",
                        ingest_n1_ratings_per_s=1_500_000.0,
                        ingest_n4_ratings_per_s=5_000_000.0,
                        value=5_000_000.0, scaling_eff_n4=0.85,
                        recovery_s=0.5,
                        duplicate_window_batches_max=1.0)
        assert regress_main(["--family", "ingest",
                             "--baseline", b, "--current", c]) == 0

    def test_ingest_direction_rules(self):
        from scripts.bench_regress import INGEST_KEYS, is_lower_better

        for key in ("recovery_s", "duplicate_window_batches_max"):
            assert is_lower_better(key, set()), key
        for key in ("ingest_n1_ratings_per_s", "ingest_n4_ratings_per_s",
                    "scaling_eff_n4", "scaling_eff_n2"):
            assert not is_lower_better(key, set()), key
        assert set(self.BASE) | {"value"} == set(INGEST_KEYS)

    def test_no_higher_pattern_collision(self):
        """The lower-is-better ingest keys must never match a
        higher-is-better pattern (DEFAULT_HIGHER wins, so a collision
        would silently flip the gate's direction) — and vice versa."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in ("recovery_s", "duplicate_window_batches_max"):
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for key in ("scaling_eff_n4", "ingest_n4_ratings_per_s"):
            assert not any(pat in key for pat in DEFAULT_LOWER), key

    def test_contention_direction_rules(self):
        """The ISSUE-14 concurrency keys: a rising Amdahl serial
        fraction or per-rung lock-wait total is a serialization
        regression — LOWER is better, at every N suffix the bench
        emits."""
        from scripts.bench_regress import is_lower_better

        for key in ("serial_fraction_n2", "serial_fraction_n8",
                    "lock_wait_s_total_n2", "lock_wait_s_total_n4"):
            assert is_lower_better(key, set()), key

    def test_contention_no_direction_collision(self):
        """serial_fraction/lock_wait must not match any
        higher-is-better pattern (which would win and flip the
        direction), and no existing higher-is-better ingest key may
        match the new lower patterns."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in ("serial_fraction_n4", "lock_wait_s_total_n4"):
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for key in ("ingest_n4_ratings_per_s", "scaling_eff_n4",
                    "qps_at_slo", "effective_hbm_gbs"):
            assert not any(pat in key
                           for pat in ("serial_fraction", "lock_wait")), key
        assert "serial_fraction" in DEFAULT_LOWER
        assert "lock_wait" in DEFAULT_LOWER

    def test_serial_fraction_rise_trips_via_key(self, tmp_path):
        """The watch-via---key contract the CI step uses on rounds that
        carry the contention extras (the committed pre-ISSUE-14 round
        doesn't, so the keys stay out of the family default set)."""
        b = self._round(tmp_path, "INGEST_r01.json",
                        serial_fraction_n4=0.10)
        c = self._round(tmp_path, "INGEST_r02.json",
                        serial_fraction_n4=0.40)
        assert regress_main(["--family", "ingest",
                             "--baseline", b, "--current", c,
                             "--key", "serial_fraction_n4=50"]) == 1
        # an IMPROVED (dropping) serial fraction never trips
        assert regress_main(["--family", "ingest",
                             "--baseline", c, "--current", b,
                             "--key", "serial_fraction_n4=50"]) == 0


class TestRankShardDirection:
    """ISSUE 16: the rank-sharded 2-D mesh keys pod_dryrun emits into
    the MULTICHIP rounds — throughput higher-is-better, per-device
    factor+catalog bytes (and the ratio vs model=1) LOWER-is-better.
    Watched via --key, NOT in MULTICHIP_KEYS: rounds before r07 lack
    the keys, and a default watch key the baseline can't contain is
    permanent "missing" noise (the PR 10/13 lesson)."""

    def test_rank_shard_direction_rules(self):
        from scripts.bench_regress import is_lower_better

        for key in ("rank_shard_bytes_per_device",
                    "rank_shard_bytes_per_device_m1",
                    "rank_shard_bytes_ratio_vs_m1"):
            assert is_lower_better(key, set()), key
        for key in ("rank_sharded_ratings_per_s",
                    "rank_sharded_8x2_ratings_per_s"):
            assert not is_lower_better(key, set()), key

    def test_rank_shard_no_direction_collision(self):
        """The bytes keys must not match any higher-is-better pattern
        (DEFAULT_HIGHER wins over DEFAULT_LOWER, so a collision would
        silently flip the gate's direction), and the throughput keys
        must not match the new lower pattern — 'rank_shard_bytes' is
        NOT a substring of 'rank_sharded_*'."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in ("rank_shard_bytes_per_device",
                    "rank_shard_bytes_ratio_vs_m1"):
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for key in ("rank_sharded_ratings_per_s",
                    "rank_sharded_8x2_ratings_per_s"):
            assert not any(pat in key for pat in DEFAULT_LOWER), key
        assert "rank_shard_bytes" in DEFAULT_LOWER
        assert "rank_sharded" in DEFAULT_HIGHER

    def test_rank_shard_keys_not_in_family_watch_set(self):
        """The PR 10/13 lesson: new keys gate via --key until every
        committed round in the diff window carries them."""
        from scripts.bench_regress import MULTICHIP_KEYS

        for key in MULTICHIP_KEYS:
            assert "rank_shard" not in key, key

    def _round(self, tmp_path, name, **over):
        base = {"n_devices": 16, "train_ratings_per_s": 450_000.0,
                "als_rows_per_s": 2_600.0, "max_pad_ratio": 1.104,
                "layout_mb": 144.0,
                "rank_sharded_ratings_per_s": 320_000.0,
                "rank_shard_bytes_per_device": 2_031_616.0,
                "rank_shard_bytes_ratio_vs_m1": 0.256}
        base.update(over)
        p = tmp_path / name
        p.write_text(json.dumps(base))
        return str(p)

    def test_footprint_growth_trips_via_key(self, tmp_path):
        b = self._round(tmp_path, "MULTICHIP_r07.json")
        c = self._round(tmp_path, "MULTICHIP_r08.json",
                        rank_shard_bytes_per_device=4_000_000.0)
        assert regress_main(["--family", "multichip",
                             "--baseline", b, "--current", c,
                             "--key", "rank_shard_bytes_per_device=20"
                             ]) == 1
        # SHRINKING per-device bytes is the improvement direction
        assert regress_main(["--family", "multichip",
                             "--baseline", c, "--current", b,
                             "--key", "rank_shard_bytes_per_device=20"
                             ]) == 0

    def test_rank_sharded_throughput_collapse_trips_via_key(self, tmp_path):
        b = self._round(tmp_path, "MULTICHIP_r07.json")
        c = self._round(tmp_path, "MULTICHIP_r08.json",
                        rank_sharded_ratings_per_s=100_000.0)
        assert regress_main(["--family", "multichip",
                             "--baseline", b, "--current", c,
                             "--key", "rank_sharded_ratings_per_s=30"
                             ]) == 1


class TestTierFamily:
    """``--family tier`` (ISSUE 17): TIERED_r*.json tiered-store
    rounds gate with the tiered ingest rate / hit rate / fraction-of-
    HBM higher-is-better and prefetch stall / eviction count
    LOWER-is-better — the direction/no-collision/not-in-family twins
    the ingest and rank-shard families carry."""

    BASE = {"tier_hit_rate": 0.93,
            "tiered_vs_hbm_frac": 0.78,
            "tier_prefetch_wait_s": 0.4,
            "tier_evictions": 900.0}

    def _round(self, tmp_path, name, **over):
        extra = dict(self.BASE, **over)
        value = extra.pop("value", 400_000.0)
        p = tmp_path / name
        p.write_text(json.dumps(  # the real streams_bench line shape
            {"metric": "tiered ingest ratings/s", "value": value,
             "unit": "ratings/s", "vs_baseline": 1.0, "extra": extra}))
        return str(p)

    def test_hit_rate_drop_trips_tight(self, tmp_path, capsys):
        """Same Zipfian trace + same slot budget → the hit rate is
        near-deterministic, so its threshold is tight (10%)."""
        b = self._round(tmp_path, "TIERED_r01.json")
        c = self._round(tmp_path, "TIERED_r02.json", tier_hit_rate=0.70)
        rc = regress_main(["--family", "tier",
                           "--baseline", b, "--current", c])
        assert rc == 1
        assert "tier_hit_rate" in capsys.readouterr().out

    def test_prefetch_stall_blowup_trips(self, tmp_path):
        b = self._round(tmp_path, "TIERED_r01.json")
        c = self._round(tmp_path, "TIERED_r02.json",
                        tier_prefetch_wait_s=2.5)
        assert regress_main(["--family", "tier",
                             "--baseline", b, "--current", c]) == 1

    def test_eviction_blowup_trips(self, tmp_path):
        b = self._round(tmp_path, "TIERED_r01.json")
        c = self._round(tmp_path, "TIERED_r02.json",
                        tier_evictions=2_000.0)
        assert regress_main(["--family", "tier",
                             "--baseline", b, "--current", c]) == 1

    def test_throughput_collapse_trips(self, tmp_path):
        b = self._round(tmp_path, "TIERED_r01.json")
        c = self._round(tmp_path, "TIERED_r02.json",
                        value=200_000.0, tiered_vs_hbm_frac=0.4)
        assert regress_main(["--family", "tier",
                             "--baseline", b, "--current", c]) == 1

    def test_across_the_board_improvement_never_trips(self, tmp_path):
        b = self._round(tmp_path, "TIERED_r01.json")
        c = self._round(tmp_path, "TIERED_r02.json",
                        value=600_000.0, tier_hit_rate=0.98,
                        tiered_vs_hbm_frac=0.95,
                        tier_prefetch_wait_s=0.05, tier_evictions=100.0)
        assert regress_main(["--family", "tier",
                             "--baseline", b, "--current", c]) == 0

    def test_tier_direction_rules(self):
        from scripts.bench_regress import TIER_KEYS, is_lower_better

        for key in ("tier_prefetch_wait_s", "tier_evictions",
                    "tier_evictions_total"):
            assert is_lower_better(key, set()), key
        for key in ("tier_hit_rate", "tiered_vs_hbm_frac",
                    "tiered_ratings_per_s"):
            assert not is_lower_better(key, set()), key
        assert set(self.BASE) | {"value"} == set(TIER_KEYS)

    def test_tier_no_direction_collision(self):
        """tier_prefetch_wait_s must not match the _per_s HIGHER
        pattern ("_pre" != "_per" — DEFAULT_HIGHER wins, so a
        collision would silently flip the gate's direction), and the
        higher-is-better tier keys must not match any lower pattern."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in ("tier_prefetch_wait_s", "tier_evictions"):
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for key in ("tier_hit_rate", "tiered_vs_hbm_frac",
                    "tiered_ratings_per_s"):
            assert not any(pat in key for pat in DEFAULT_LOWER), key
        assert "prefetch_wait" in DEFAULT_LOWER
        assert "tier_evictions" in DEFAULT_LOWER
        assert "tier_hit_rate" in DEFAULT_HIGHER

    def test_tier_keys_not_in_other_families(self):
        """The tier watch set is its own family — tier keys must not
        leak into the bench/ingest default sets (the PR 10/13 lesson:
        a default watch key a family's committed rounds can't contain
        is permanent "missing" noise)."""
        from scripts.bench_regress import (
            DEFAULT_KEYS,
            FAMILIES,
            INGEST_KEYS,
            TIER_KEYS,
        )

        for key in list(DEFAULT_KEYS) + list(INGEST_KEYS):
            assert "tier" not in key, key
        prefix, keys = FAMILIES["tier"]
        assert prefix == "TIERED"
        assert keys is TIER_KEYS


class TestTransferDirections:
    """Transfer-plane keys (ISSUE 18): ``retrace`` /
    ``implicit_transfers`` / ``transfer_wait`` joined DEFAULT_LOWER —
    the direction/no-collision/not-in-family twins the tier and ingest
    families carry. CI watches these via explicit ``--key`` only:
    committed rounds predating ISSUE 18 lack the keys, and a default
    watch key the baseline can't contain is permanent "missing" noise
    (the PR 10/13 lesson)."""

    TRANSFER_KEYS = ("retrace_total", "implicit_transfers_total",
                     "transfer_wait_s_total")

    def test_transfer_direction_rules(self):
        from scripts.bench_regress import is_lower_better

        for key in self.TRANSFER_KEYS + ("retraces_steady",
                                         "transfer_wait_s"):
            assert is_lower_better(key, set()), key

    def test_transfer_no_direction_collision(self):
        """None of the transfer keys may match a HIGHER pattern
        (DEFAULT_HIGHER wins, so a collision silently flips the gate's
        direction). In particular "transfer_wait" vs the _per_s HIGHER
        rule: "wait" != "_per_s", pinned here like tier's "_pre"."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in self.TRANSFER_KEYS:
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for pat in ("retrace", "implicit_transfers", "transfer_wait"):
            assert pat in DEFAULT_LOWER

    def test_transfer_keys_not_in_family_watch_sets(self):
        """Explicit --key only — no family default set may carry a
        transfer key."""
        from scripts.bench_regress import FAMILIES

        for fam, (_, keys) in FAMILIES.items():
            for key in keys:
                for pat in ("retrace", "implicit_transfer",
                            "transfer_wait"):
                    assert pat not in key, (fam, key)

    def test_retrace_blowup_trips_via_key(self, tmp_path):
        """A steady-state retrace regression on a round that carries
        the key trips through the LOWER direction rule."""
        for name, retraces in (("TIERED_r01.json", 1.0),
                               ("TIERED_r02.json", 8.0)):
            (tmp_path / name).write_text(json.dumps(
                {"metric": "tiered ingest ratings/s", "value": 400_000.0,
                 "unit": "ratings/s",
                 "extra": {"tier_hit_rate": 0.93,
                           "tiered_vs_hbm_frac": 0.78,
                           "tier_prefetch_wait_s": 0.4,
                           "tier_evictions": 900.0,
                           "retrace_total": retraces,
                           "implicit_transfers_total": 0.0}}))
        b = str(tmp_path / "TIERED_r01.json")
        c = str(tmp_path / "TIERED_r02.json")
        assert regress_main(["--family", "tier",
                             "--baseline", b, "--current", c,
                             "--key", "retrace_total=50"]) == 1
        # the improvement direction (fewer retraces) never trips
        assert regress_main(["--family", "tier",
                             "--baseline", c, "--current", b,
                             "--key", "retrace_total=50"]) == 0


class TestRolloutDirections:
    """Rollout-budget keys (ISSUE 19): ``burn_rate`` /
    ``verdict_latency`` joined DEFAULT_LOWER and
    ``error_budget_remaining`` DEFAULT_HIGHER — the direction /
    no-collision / not-in-family twins the transfer and rank-shard
    entries carry. CI watches these via explicit ``--key`` only:
    SERVING_r01 predates the plane, and a default watch key the
    baseline can't contain is permanent "missing" noise (the
    PR 10/13 lesson)."""

    LOWER_KEYS = ("slo_burn_rate_fast", "slo_burn_rate_slow",
                  "verdict_latency_batches")

    def test_rollout_direction_rules(self):
        from scripts.bench_regress import is_lower_better

        for key in self.LOWER_KEYS:
            assert is_lower_better(key, set()), key
        assert not is_lower_better("error_budget_remaining", set())

    def test_rollout_no_direction_collision(self):
        """The burn/verdict keys must not match a HIGHER pattern
        (DEFAULT_HIGHER wins, so a collision silently flips the gate's
        direction), and error_budget_remaining must not match a LOWER
        pattern — in particular "_rmse" does not occur in it."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in self.LOWER_KEYS:
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        assert not any(pat in "error_budget_remaining"
                       for pat in DEFAULT_LOWER)
        for pat in ("burn_rate", "verdict_latency"):
            assert pat in DEFAULT_LOWER
        assert "error_budget_remaining" in DEFAULT_HIGHER

    def test_rollout_keys_not_in_family_watch_sets(self):
        """Explicit --key only — no family default set may carry a
        rollout key."""
        from scripts.bench_regress import FAMILIES

        for fam, (_, keys) in FAMILIES.items():
            for key in keys:
                for pat in ("burn_rate", "verdict_latency",
                            "error_budget"):
                    assert pat not in key, (fam, key)

    def test_burn_rate_blowup_trips_via_key(self, tmp_path):
        """A fast-burn regression on a round that carries the key
        trips through the LOWER direction rule; the remaining-budget
        key gates through the HIGHER rule."""
        for name, burn, remaining in (("SERVING_r01.json", 0.5, 0.95),
                                      ("SERVING_r02.json", 4.0, 0.20)):
            (tmp_path / name).write_text(json.dumps(
                {"metric": "serving users/s", "value": 300.0,
                 "unit": "users/s",
                 "extra": {"qps_at_slo": 12.0, "p99_ms": 80.0,
                           "recall_at_10": 0.99, "shed_frac": 0.0,
                           "slo_burn_rate_fast": burn,
                           "error_budget_remaining": remaining}}))
        b = str(tmp_path / "SERVING_r01.json")
        c = str(tmp_path / "SERVING_r02.json")
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c,
                             "--key", "slo_burn_rate_fast=50"]) == 1
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c,
                             "--key", "error_budget_remaining=50"]) == 1
        # the improvement direction (less burn, more budget) never
        # trips
        assert regress_main(["--family", "serving",
                             "--baseline", c, "--current", b,
                             "--key", "slo_burn_rate_fast=50",
                             "--key", "error_budget_remaining=50"]) == 0


class TestRequestStageDirections:
    """Request-plane keys (ISSUE 20): ``request_stage`` /
    ``queue_wait`` joined DEFAULT_LOWER — the direction /
    no-collision / not-in-family twins the rollout entries carry. CI
    watches these via explicit ``--key`` only: committed rounds
    predating the plane lack the keys, and a default watch key the
    baseline can't contain is permanent "missing" noise (the
    PR 10/13 lesson)."""

    LOWER_KEYS = ("request_stage_gather_s_p99",
                  "request_stage_score_stage1_s_p50",
                  "queue_wait_s_p99")

    def test_request_stage_direction_rules(self):
        from scripts.bench_regress import is_lower_better

        for key in self.LOWER_KEYS:
            assert is_lower_better(key, set()), key

    def test_request_stage_no_direction_collision(self):
        """A stage wall must not match a HIGHER pattern
        (DEFAULT_HIGHER wins, so a collision silently flips the
        gate's direction)."""
        from scripts.bench_regress import DEFAULT_HIGHER, DEFAULT_LOWER

        for key in self.LOWER_KEYS:
            assert not any(pat in key for pat in DEFAULT_HIGHER), key
        for pat in ("request_stage", "queue_wait"):
            assert pat in DEFAULT_LOWER

    def test_request_stage_keys_not_in_family_watch_sets(self):
        """Explicit --key only — no family default set may carry a
        request-plane key."""
        from scripts.bench_regress import FAMILIES

        for fam, (_, keys) in FAMILIES.items():
            for key in keys:
                for pat in ("request_stage", "queue_wait"):
                    assert pat not in key, (fam, key)

    def test_stage_p99_blowup_trips_via_key(self, tmp_path):
        """A gather-stage p99 regression on a round that carries the
        key trips through the LOWER direction rule."""
        for name, p99 in (("SERVING_r02.json", 0.002),
                          ("SERVING_r03.json", 0.080)):
            (tmp_path / name).write_text(json.dumps(
                {"metric": "serving users/s", "value": 300.0,
                 "unit": "users/s",
                 "extra": {"qps_at_slo": 12.0, "p99_ms": 80.0,
                           "recall_at_10": 0.99, "shed_frac": 0.0,
                           "request_stage_gather_s_p99": p99,
                           "queue_wait_s_p99": p99}}))
        b = str(tmp_path / "SERVING_r02.json")
        c = str(tmp_path / "SERVING_r03.json")
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c,
                             "--key", "request_stage_gather_s_p99=50"
                             ]) == 1
        assert regress_main(["--family", "serving",
                             "--baseline", b, "--current", c,
                             "--key", "queue_wait_s_p99=50"]) == 1
        # the improvement direction (faster stages) never trips
        assert regress_main(["--family", "serving",
                             "--baseline", c, "--current", b,
                             "--key", "request_stage_gather_s_p99=50",
                             "--key", "queue_wait_s_p99=50"]) == 0
