"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; every sharding/collective
code path is exercised on XLA's host-platform virtual devices instead
(SURVEY §4: multi-device tests via xla_force_host_platform_device_count).
``force_cpu`` must run before anything initializes a jax backend — env vars
alone are not enough where a site hook pins the ``jax_platforms`` config
(see utils/platform.py), so it updates the config too.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "0")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(n_devices=8)
