"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; every sharding/collective
code path is exercised on XLA's host-platform virtual devices instead
(SURVEY §4: multi-device tests via xla_force_host_platform_device_count).
``force_cpu`` must run before anything initializes a jax backend — env vars
alone are not enough where a site hook pins the ``jax_platforms`` config
(see utils/platform.py), so it updates the config too.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "0")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(n_devices=8)

# OBS_OUT=<dir>: run the whole suite with the observability layer live
# and dump the session's metrics JSONL + Prometheus snapshot + Chrome
# trace there at exit — the artifact the CI workflow uploads for every
# tier-1 run. Unset (the default, local runs): the null layer stays
# installed and instrumentation costs nothing.
_OBS_OUT = os.environ.get("OBS_OUT")
_OBS_REG = _OBS_TRACER = None
if _OBS_OUT:
    from large_scale_recommendation_tpu import obs as _obs  # noqa: E402

    _OBS_REG, _OBS_TRACER = _obs.enable()


def pytest_sessionfinish(session, exitstatus):
    if not _OBS_OUT:
        return
    os.makedirs(_OBS_OUT, exist_ok=True)
    _OBS_REG.append_jsonl(os.path.join(_OBS_OUT, "tier1_metrics.jsonl"))
    with open(os.path.join(_OBS_OUT, "tier1_metrics.prom"), "w") as f:
        f.write(_OBS_REG.to_prometheus())
    _OBS_TRACER.to_chrome_trace(os.path.join(_OBS_OUT, "tier1_trace.json"))
