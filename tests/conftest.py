"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; every sharding/collective
code path is exercised on XLA's host-platform virtual devices instead
(SURVEY §4: multi-device tests via xla_force_host_platform_device_count).
``force_cpu`` must run before anything initializes a jax backend — env vars
alone are not enough where a site hook pins the ``jax_platforms`` config
(see utils/platform.py), so it updates the config too.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "0")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from large_scale_recommendation_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(n_devices=8)

# OBS_OUT=<dir>: run the whole suite with the observability layer live
# and dump the session's metrics JSONL + Prometheus snapshot + Chrome
# trace there at exit — the artifact the CI workflow uploads for every
# tier-1 run. The endpoint server also runs for the whole session, and
# sessionfinish fetches /healthz + /metrics over the REAL socket (the
# .prom artifact is the served body, proving the scrape surface end to
# end); the /healthz report lands in tier1_healthz.json, which the CI
# workflow gates on (job fails if status == "critical"). The FLIGHT
# RECORDER also runs for the whole session (1 s cadence, bounded
# memory), so sessionfinish can freeze a full postmortem bundle
# (tier1_bundle/) — on a health-gate failure, the uploaded artifact
# carries the lead-up series/events/spans, not just the final verdict.
# Unset (the default, local runs): the null layer stays installed and
# instrumentation costs nothing.
_OBS_OUT = os.environ.get("OBS_OUT")
_OBS_REG = _OBS_TRACER = _OBS_SERVER = _OBS_RECORDER = None
if _OBS_OUT:
    from large_scale_recommendation_tpu import obs as _obs  # noqa: E402
    from large_scale_recommendation_tpu.obs import health as _health  # noqa: E402
    from large_scale_recommendation_tpu.obs.server import ObsServer  # noqa: E402

    _OBS_REG, _OBS_TRACER = _obs.enable()
    _OBS_RECORDER, _OBS_JOURNAL = _obs.enable_flight_recorder(
        interval_s=1.0, bundle_dir=os.path.join(_OBS_OUT, "postmortem"))
    # XLA introspection for the whole session: every compile the suite
    # pays is captured at the funnel (cost analysis + wall, attributed
    # to the enclosing compile key), the device-memory sampler feeds
    # the recorder, and sessionfinish renders the joined roofline as a
    # tier-1 artifact (tier1_roofline.json/.txt)
    _OBS_INTROSPECTOR = _obs.enable_introspection(interval_s=1.0)
    # catalog lineage for the whole session: every engine the suite
    # builds stamps its swaps, and sessionfinish freezes the journal +
    # the quality-plane series into tier1_quality.json
    _OBS_LINEAGE = _obs.enable_lineage()
    # critical-path attribution for the whole session: drivers/engines
    # the suite builds stamp their ingest→servable stages
    # (critical_path_s{stage} gauges ride the same recorder)
    _OBS_DISTTRACE = _obs.enable_disttrace()
    # concurrency plane for the whole session: every model/engine/
    # driver lock the suite constructs binds its instrumented form,
    # the thread sampler feeds contention_* gauges into the recorder,
    # and sessionfinish freezes tier1_contention.json
    # (max_threads raised: a whole tier-1 session churns through many
    # short-lived driver/server threads; the table is still bounded)
    _OBS_CONTENTION = _obs.enable_contention(interval_s=1.0,
                                             max_threads=512)
    # transfer plane for the whole session: every deliberate
    # device<->host crossing the suite drives lands in the per-site
    # ledger, and the hot jitted fns are watched for retraces. Guard
    # stays OFF: a tier-1 session legitimately runs eager paths the
    # hot-loop disallow contract does not cover
    _OBS_TRANSFERS = _obs.enable_transfers(guard="off")
    _OBS_MONITOR = _health.HealthMonitor()

    def _session_check():
        # the layer itself is the subject: a live registry and a trace
        # buffer that isn't silently dropping spans
        if not _OBS_REG.enabled:
            return _health.critical(note="registry not live")
        if _OBS_TRACER.dropped:
            return _health.degraded(dropped_spans=_OBS_TRACER.dropped)
        return _health.ok(metric_names=len(_OBS_REG.names()))

    _OBS_MONITOR.register("obs_session", _session_check)
    _OBS_SERVER = ObsServer(registry=_OBS_REG, tracer=_OBS_TRACER,
                            monitor=_OBS_MONITOR).start()


import pytest  # noqa: E402


@pytest.fixture
def null_obs():
    """The fully-disabled obs layer installed for one test, with the
    ENTIRE previous layer restored after — registry, tracer, event
    journal, AND flight recorder (an OBS_OUT session runs one
    suite-wide; its sampler is restarted if it was live). ONE copy,
    shared by every obs test file: the restore invariant is non-trivial
    and must not drift between copies."""
    from large_scale_recommendation_tpu import obs
    from large_scale_recommendation_tpu.obs.contention import (
        get_contention,
        set_contention,
    )
    from large_scale_recommendation_tpu.obs.disttrace import (
        get_disttrace,
        set_disttrace,
    )
    from large_scale_recommendation_tpu.obs.events import (
        get_events,
        set_events,
    )
    from large_scale_recommendation_tpu.obs.introspect import (
        get_introspector,
        set_introspector,
    )
    from large_scale_recommendation_tpu.obs.lineage import (
        get_lineage,
        set_lineage,
    )
    from large_scale_recommendation_tpu.obs.recorder import (
        get_recorder,
        set_recorder,
    )
    from large_scale_recommendation_tpu.obs.registry import (
        get_registry,
        set_registry,
    )
    from large_scale_recommendation_tpu.obs.store import (
        get_store,
        set_store,
    )
    from large_scale_recommendation_tpu.obs.trace import (
        get_tracer,
        set_tracer,
    )
    from large_scale_recommendation_tpu.obs.budget import (
        get_budget,
        set_budget,
    )
    from large_scale_recommendation_tpu.obs.requests import (
        get_requests,
        set_requests,
    )
    from large_scale_recommendation_tpu.obs.transfers import (
        get_transfers,
        set_transfers,
    )

    prev_r, prev_t = get_registry(), get_tracer()
    prev_j, prev_rec = get_events(), get_recorder()
    prev_ins, prev_lin = get_introspector(), get_lineage()
    prev_dt = get_disttrace()
    prev_ct = get_contention()
    prev_tf = get_transfers()
    prev_store = get_store()
    prev_budget = get_budget()
    prev_requests = get_requests()
    was_running = prev_rec is not None and prev_rec.running
    ins_was_running = prev_ins is not None and prev_ins.running
    ct_was_running = prev_ct is not None and prev_ct.running
    obs.disable()  # closes the introspector too: compile funnel unpatched
    yield get_registry()
    set_registry(prev_r)
    set_tracer(prev_t)
    set_events(prev_j)
    set_recorder(prev_rec)
    set_lineage(prev_lin)
    set_disttrace(prev_dt)
    set_contention(prev_ct)
    if ct_was_running:  # an OBS_OUT session runs one suite-wide
        prev_ct.start()
    set_introspector(prev_ins)
    if prev_ins is not None:  # an OBS_OUT session runs one suite-wide
        prev_ins.install()
        if ins_was_running:
            prev_ins.start()
    if was_running:
        prev_rec.start()
    set_transfers(prev_tf)
    set_store(prev_store)  # a test-built TieredFactorStore must not leak
    set_budget(prev_budget)
    set_requests(prev_requests)


def pytest_sessionfinish(session, exitstatus):
    if not _OBS_OUT:
        return
    import json

    from large_scale_recommendation_tpu.obs.server import http_get

    os.makedirs(_OBS_OUT, exist_ok=True)
    # graftlint finding counts stamped into the SAME registry the
    # metrics artifacts freeze below (ISSUE 15): the trajectory of
    # suppressed/baselined static-analysis debt ships with every tier-1
    # round — a rising lint_baselined_total is debt accruing even while
    # the --strict CI gate stays green
    try:
        from tools.graftlint import run_lint as _graftlint

        _lint = _graftlint()  # pure-AST, sub-second, no jax touched
        _OBS_REG.gauge("lint_findings_total").set(len(_lint.findings))
        for _rule, _n in _lint.per_rule().items():
            _OBS_REG.gauge("lint_findings", rule=_rule).set(_n)
        _OBS_REG.gauge("lint_baselined_total").set(len(_lint.baselined))
        _OBS_REG.gauge("lint_suppressed_total").set(len(_lint.suppressed))
        with open(os.path.join(_OBS_OUT, "tier1_lint.json"), "w") as f:
            json.dump(_lint.to_dict(), f, indent=2)
    except Exception as e:  # artifact-only: never fail the session
        with open(os.path.join(_OBS_OUT, "tier1_lint_error.txt"),
                  "w") as f:
            f.write(repr(e))
    _OBS_REG.append_jsonl(os.path.join(_OBS_OUT, "tier1_metrics.jsonl"))
    _OBS_TRACER.to_chrome_trace(os.path.join(_OBS_OUT, "tier1_trace.json"))
    # the session's per-kernel roofline: every compile key the suite
    # exercised, XLA cost analysis joined with measured execute walls
    try:
        from scripts.obs_report import render_roofline

        _roofline = _OBS_INTROSPECTOR.roofline()
        with open(os.path.join(_OBS_OUT, "tier1_roofline.json"), "w") as f:
            json.dump(_roofline, f, indent=2)
        with open(os.path.join(_OBS_OUT, "tier1_roofline.txt"), "w") as f:
            f.write(render_roofline(_roofline) + "\n")
    except Exception as e:  # artifact-only: never fail the session on it
        with open(os.path.join(_OBS_OUT, "tier1_roofline_error.txt"),
                  "w") as f:
            f.write(repr(e))
    # the model-quality plane's artifact (ISSUE 10): the session's
    # lineage journal + every eval_*/dataq_*/lineage_* series the
    # suite's flight recorder captured, next to the roofline/bundle
    try:
        from large_scale_recommendation_tpu.obs.lineage import get_lineage

        _lin = get_lineage()  # tests swap journals; freeze the current
        _series = _OBS_RECORDER.snapshot()
        _quality_doc = {
            "lineage": (_lin.snapshot() if _lin is not None
                        else {"note": "no lineage journal",
                              "records": []}),
            "series": {k: v for k, v in _series["series"].items()
                       if k.startswith(("eval_", "dataq_", "lineage_"))},
        }
        with open(os.path.join(_OBS_OUT, "tier1_quality.json"), "w") as f:
            json.dump(_quality_doc, f, indent=2)
    except Exception as e:
        with open(os.path.join(_OBS_OUT, "tier1_quality_error.txt"),
                  "w") as f:
            f.write(repr(e))
    # the concurrency plane's artifact (ISSUE 14): the suite-long
    # saturation window — lock table + thread utilization — next to
    # the roofline/quality artifacts
    try:
        from large_scale_recommendation_tpu.obs.contention import (
            SaturationAnalyzer,
        )

        with open(os.path.join(_OBS_OUT, "tier1_contention.json"),
                  "w") as f:
            json.dump(SaturationAnalyzer(_OBS_CONTENTION).snapshot(), f,
                      indent=2, default=repr)
    except Exception as e:
        with open(os.path.join(_OBS_OUT, "tier1_contention_error.txt"),
                  "w") as f:
            f.write(repr(e))
    # the transfer plane's artifact (ISSUE 18): the suite-long per-site
    # device<->host ledger plus retrace attribution — which sites moved
    # how many bytes at what effective rate across the whole tier-1 run
    try:
        from large_scale_recommendation_tpu.obs.transfers import (
            get_transfers as _get_tf,
        )

        _tf = _get_tf()  # tests swap ledgers; freeze the current one
        with open(os.path.join(_OBS_OUT, "tier1_transfers.json"),
                  "w") as f:
            json.dump(_tf.snapshot() if _tf is not None
                      else {"note": "no transfer ledger", "sites": {}},
                      f, indent=2)
    except Exception as e:
        with open(os.path.join(_OBS_OUT, "tier1_transfers_error.txt"),
                  "w") as f:
            f.write(repr(e))
    # scrape the session's endpoint server for real: the artifacts below
    # came over the socket, not from in-process calls (http_get turns a
    # dead-server connection failure into a synthetic 599, so both
    # artifacts always exist and the CI gate shows WHAT broke)
    code, prom = http_get(_OBS_SERVER.url + "/metrics")
    if code != 200:  # fall back so the artifact always exists
        prom = _OBS_REG.to_prometheus()
    with open(os.path.join(_OBS_OUT, "tier1_metrics.prom"), "w") as f:
        f.write(prom)
    code, body = http_get(_OBS_SERVER.url + "/healthz")
    try:
        report = json.loads(body)
    except ValueError:
        report = {"status": "critical",
                  "error": "unparseable /healthz body",
                  "body": body[:500]}
    report["http_status"] = code
    with open(os.path.join(_OBS_OUT, "tier1_healthz.json"), "w") as f:
        json.dump(report, f, indent=2)
    _OBS_SERVER.stop()
    # freeze the session's flight-recorder state as a bundle: on a
    # health-gate failure this is the postmortem CI ships — series
    # lead-up, event tail, span tail, final health/registry snapshots
    _OBS_RECORDER.stop()
    try:
        _OBS_RECORDER.sample()  # one last point so the bundle is current
        _OBS_RECORDER.dump(
            trigger="session_end", detail={"exitstatus": int(exitstatus),
                                           "healthz": report.get("status")},
            directory=os.path.join(_OBS_OUT, "tier1_bundle"),
            health_report=report)
    except Exception as e:  # the suite's verdict must not die on its
        with open(os.path.join(_OBS_OUT,  # own black box
                               "tier1_bundle_error.txt"), "w") as f:
            f.write(repr(e))
