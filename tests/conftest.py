"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; every sharding/collective
code path is exercised on XLA's host-platform virtual devices instead
(SURVEY §4: multi-device tests via xla_force_host_platform_device_count).
This must run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
