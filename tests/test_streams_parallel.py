"""Parallel ingest (``streams/parallel.py``, ISSUE 13): the row-conflict
gate, concurrent-apply bit-parity with the serial path, the
cross-partition checkpoint barrier, multi-consumer kill/restart recovery
with per-partition zero-loss/bounded-duplication and lineage +
critical-path reconciliation at N > 1, the N=4 starved-feed arrival-skew
pin, per-partition lag gauges for ALL N partitions, and delta-swap
coalescing (engine defer/flush parity, one version bump per refresh).
"""

import threading
import time

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.streams import (
    EventLog,
    ParallelIngestRunner,
    RowConflictGate,
    StreamingDriverConfig,
    append_routed,
    route_partition,
)
from large_scale_recommendation_tpu.utils.checkpoint import (
    CheckpointManager,
)


def _online(rank=4, minibatch=64):
    return OnlineMF(OnlineMFConfig(num_factors=rank,
                                   minibatch_size=minibatch))


def _fill_strata(log, n, n_batches, batch=300, seed=0, users=30,
                 items=12, per_partition=None):
    """Stratum-routed fill: partition p's users ≡ p (mod n) and its
    items live in block p — fully row-disjoint streams."""
    rng = np.random.default_rng(seed)
    for p in range(n):
        b = n_batches if per_partition is None else per_partition[p]
        for _ in range(b):
            u = rng.integers(0, users, batch) * n + p
            i = rng.integers(0, items, batch) + p * items
            log.append_arrays(p, u, i, rng.random(batch).astype(np.float32))


def _runner(tmp_path, log, model=None, sub="ckpt", **cfg):
    model = model or _online()
    return model, ParallelIngestRunner(
        model, log, str(tmp_path / sub),
        config=StreamingDriverConfig(batch_records=300, **cfg))


# --------------------------------------------------------------------------
# Routing + gate
# --------------------------------------------------------------------------


class TestRouting:
    def test_route_partition_is_user_stable(self):
        parts = route_partition([0, 1, 5, 9, 1, 5], 4)
        assert parts.tolist() == [0, 1, 1, 1, 1, 1]
        # same user always lands in the same partition
        assert route_partition([7], 4) == route_partition([7], 4)

    def test_append_routed_splits_by_user(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), num_partitions=3,
                       fsync=False)
        users = np.arange(12)
        n = append_routed(log, users, users, np.ones(12, np.float32))
        assert n == 12
        for p in range(3):
            batch, _ = log.read(p, 0, 100)
            ru = batch.to_numpy()[0]
            assert (route_partition(ru, 3) == p).all()


class TestRowConflictGate:
    def test_disjoint_grants_overlap(self):
        gate = RowConflictGate()
        t1 = gate.acquire([1, 2], [10])
        t2 = gate.acquire([3], [11, 12])  # disjoint: no wait
        assert gate.grants == 2 and gate.waits == 0
        assert gate.in_flight() == (3, 3)
        gate.release(t1)
        gate.release(t2)
        assert gate.in_flight() == (0, 0)

    def test_collision_blocks_until_release(self):
        gate = RowConflictGate()
        t1 = gate.acquire([1], [10])
        order = []

        def contender():
            t = gate.acquire([2], [10])  # shares item 10 → must wait
            order.append("acquired")
            gate.release(t)

        th = threading.Thread(target=contender)
        th.start()
        time.sleep(0.05)
        assert order == []  # still blocked on the in-flight claim
        order.append("releasing")
        gate.release(t1)
        th.join(timeout=5)
        assert order == ["releasing", "acquired"]
        assert gate.waits == 1

    def test_user_collision_also_blocks(self):
        gate = RowConflictGate()
        t1 = gate.acquire([5], [1])
        done = threading.Event()

        def contender():
            gate.release(gate.acquire([5], [2]))
            done.set()

        th = threading.Thread(target=contender)
        th.start()
        assert not done.wait(0.05)
        gate.release(t1)
        th.join(timeout=5)
        assert done.is_set()


# --------------------------------------------------------------------------
# Concurrent applies: bit-parity with the serial path
# --------------------------------------------------------------------------


class TestConcurrentApply:
    def _batches(self, n_parts=4, n_batches=3, batch=200, seed=0):
        """Row-disjoint batch streams, one per 'consumer'."""
        from large_scale_recommendation_tpu.core.types import Ratings

        rng = np.random.default_rng(seed)
        streams = []
        for p in range(n_parts):
            bs = []
            for _ in range(n_batches):
                u = rng.integers(0, 20, batch) * n_parts + p
                i = rng.integers(0, 10, batch) + p * 10
                bs.append(Ratings.from_arrays(
                    u, i, rng.random(batch).astype(np.float32)))
            streams.append(bs)
        return streams

    def test_disjoint_threads_match_serial_bitexact(self):
        """The Gemulla pin: row-disjoint applies commute, so N threads
        interleaving them must produce EXACTLY the serial tables."""
        streams = self._batches()

        serial = _online()
        for bs in streams:
            for b in bs:
                serial.partial_fit(b, emit_updates=False)

        conc = _online()
        conc.enable_concurrent_applies()
        conc.apply_gate = RowConflictGate()
        errs = []

        def consume(bs):
            try:
                for b in bs:
                    conc.partial_fit(b, emit_updates=False)
            except BaseException as e:  # surfaced below
                errs.append(e)

        threads = [threading.Thread(target=consume, args=(bs,))
                   for bs in streams]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert conc.step == serial.step
        # align rows by id (registration order differs across
        # interleavings) and compare factors exactly
        for side in ("users", "items"):
            st = getattr(serial, side)
            ct = getattr(conc, side)
            ids = np.sort(st.id_array())
            np.testing.assert_array_equal(ids, np.sort(ct.id_array()))
            np.testing.assert_array_equal(st.lookup(ids), ct.lookup(ids))

    def test_emit_updates_id_alignment(self):
        """Concurrent-path updates-only output pairs each id with ITS
        vector (rows are first-seen ordered, ids sorted — the mapping
        must re-align them)."""
        from large_scale_recommendation_tpu.core.types import Ratings

        m = _online()
        m.enable_concurrent_applies()
        # register ids out of sorted order so row order != id order
        b = Ratings.from_arrays([9, 3, 7], [20, 5, 11],
                                [1.0, 2.0, 3.0])
        out = m.partial_fit(b)
        ids, vecs = out.user_arrays
        assert ids.tolist() == [3, 7, 9]
        for ident, vec in zip(ids.tolist(), vecs):
            np.testing.assert_array_equal(vec, m.users.lookup([ident])[0])
        ids_i, vecs_i = out.item_arrays
        assert ids_i.tolist() == [5, 11, 20]
        for ident, vec in zip(ids_i.tolist(), vecs_i):
            np.testing.assert_array_equal(vec, m.items.lookup([ident])[0])

    def test_colliding_batches_serialize_and_stay_finite(self):
        """Two batches sharing an item id: the gate serializes them
        (waits > 0) and both apply."""
        from large_scale_recommendation_tpu.core.types import Ratings

        m = _online()
        m.enable_concurrent_applies()
        m.apply_gate = RowConflictGate()
        b1 = Ratings.from_arrays([1], [7], [1.0])
        b2 = Ratings.from_arrays([2], [7], [2.0])  # same item row

        barrier = threading.Barrier(2)

        def apply(b):
            barrier.wait()
            m.partial_fit(b, emit_updates=False)

        ts = [threading.Thread(target=apply, args=(b,))
              for b in (b1, b2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert m.step == 2
        assert np.isfinite(m.items.lookup([7])).all()

    def test_offset_stamp_only_after_commit(self):
        from large_scale_recommendation_tpu.core.types import Ratings

        m = _online()
        m.enable_concurrent_applies()
        m.partial_fit(Ratings.from_arrays([1], [2], [1.0]),
                      offset=(3, 17), emit_updates=False)
        assert m.consumed_offsets == {3: 17}
        # empty batch still advances the stream position
        m.partial_fit(Ratings.from_arrays([0], [0], [1.0],
                                          weights=[0.0]),
                      offset=(3, 20), emit_updates=False)
        assert m.consumed_offsets == {3: 20}


# --------------------------------------------------------------------------
# Runner: catch-up, barrier, resume
# --------------------------------------------------------------------------


class TestRunnerCatchUp:
    def test_drains_all_partitions_with_barrier(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), num_partitions=4,
                       fsync=False)
        _fill_strata(log, 4, 3)
        model, runner = _runner(tmp_path, log, checkpoint_every=2)
        assert not runner.resume()
        applied = runner.run()
        assert applied == 12
        tele = runner.telemetry()
        assert all(v == 0 for v in tele["lag_records"].values())
        assert model.consumed_offsets == {p: 900 for p in range(4)}
        assert runner.checkpoints_written >= 1
        # ONE atomic snapshot carries every partition's offset
        ck = CheckpointManager(str(tmp_path / "ckpt")).restore()
        assert ck.meta["offsets"] == {str(p): 900 for p in range(4)}

    def test_resume_restores_every_partition(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), num_partitions=3,
                       fsync=False)
        _fill_strata(log, 3, 2)
        _, r1 = _runner(tmp_path, log)
        r1.run()
        _fill_strata(log, 3, 1, seed=9)
        m2, r2 = _runner(tmp_path, log)
        assert r2.resume()
        assert m2.consumed_offsets == {p: 600 for p in range(3)}
        assert r2.run() == 3  # only the new tail replays
        assert m2.consumed_offsets == {p: 900 for p in range(3)}

    def test_single_partition_runner_stays_serial(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), fsync=False)
        _fill_strata(log, 1, 3)
        model, runner = _runner(tmp_path, log)
        assert runner.gate is None
        assert not model.concurrent_applies  # N=1: the plain hot path
        assert runner.run() == 3

    def test_consumer_fault_stops_all_and_raises(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), num_partitions=2,
                       fsync=False)
        _fill_strata(log, 2, 50)

        class Boom(RuntimeError):
            pass

        def explode(batch):
            if batch.partition == 1:
                raise Boom()

        model, runner = _runner(tmp_path, log)
        runner.on_batch = explode
        with pytest.raises(Boom):
            runner.run()
        # no final barrier on a crashed run beyond what cadence wrote
        assert model.consumed_offsets.get(0, 0) < 50 * 300

    def test_barrier_holds_while_stamps_frozen(self, tmp_path):
        """The frozen-offset interaction: while a (simulated) background
        retrain buffers batches without advancing the stamps, the
        barrier must hold — and one covering snapshot lands once the
        stamps catch up."""
        log = EventLog(str(tmp_path / "log"), num_partitions=2,
                       fsync=False)
        _fill_strata(log, 2, 3)
        model, runner = _runner(tmp_path, log, checkpoint_every=1)
        real_fit = model.partial_fit
        # deterministic freeze: partition 0's first two batches apply
        # WITHOUT advancing their stamp (the buffered-during-retrain
        # shape), its third batch stamps and unblocks the barrier
        frozen_p0 = [2]
        lock = threading.Lock()

        def fit(batch, offset=None, emit_updates=False, **kw):
            with lock:
                if (offset is not None and offset[0] == 0
                        and frozen_p0[0] > 0):
                    frozen_p0[0] -= 1
                    offset = None
            return real_fit(batch, offset=offset,
                            emit_updates=emit_updates, **kw)

        model.partial_fit = fit
        runner.run()
        assert runner.barriers_held >= 1
        assert runner.checkpoints_written >= 1
        # the final snapshot covers everything both partitions applied
        ck = CheckpointManager(str(tmp_path / "ckpt")).restore()
        assert ck.meta["offsets"] == {"0": 900, "1": 900}


class TestAdaptiveParallel:
    def test_background_retrain_holds_barrier_then_covers(self,
                                                          tmp_path):
        """AdaptiveMF at N consumers: applies serialize on the model's
        lock, a background retrain freezes the stamps (the barrier
        HOLDS), the retrain swap reaches serving, and the final barrier
        snapshot covers every partition — from which a fresh adaptive
        model rebuilds its full multi-partition history."""
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )

        def adaptive():
            return AdaptiveMF(AdaptiveMFConfig(
                num_factors=4, minibatch_size=64, offline_every=5,
                offline_iterations=2, background=True))

        n = 2
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 6)
        model = adaptive()
        runner = ParallelIngestRunner(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=300,
                                         checkpoint_every=2))
        assert model.concurrent_applies  # serialized-process mode armed
        engine = runner.serving_engine(k=3, max_batch=32)
        v0 = engine.version
        applied = runner.run()
        model.flush()  # absorb any in-flight background retrain
        runner.maybe_checkpoint()
        assert applied == 12
        assert model.retrain_count >= 1
        assert engine.version != v0, "retrain swap never reached serving"
        ck = CheckpointManager(str(tmp_path / "ckpt")).restore()
        assert set(ck.meta["offsets"]) == {"0", "1"}
        m2 = adaptive()
        r2 = ParallelIngestRunner(
            m2, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=300))
        assert r2.resume()
        assert m2._history_rows == sum(
            int(v) for v in ck.meta["offsets"].values())


# --------------------------------------------------------------------------
# Kill/restart at N>1: per-partition zero loss, bounded duplication,
# lineage + critical-path reconciliation (extends the PR 12 pin)
# --------------------------------------------------------------------------


@pytest.fixture
def causal_obs():
    from large_scale_recommendation_tpu.obs.disttrace import (
        get_disttrace,
        set_disttrace,
    )
    from large_scale_recommendation_tpu.obs.events import (
        get_events,
        set_events,
    )
    from large_scale_recommendation_tpu.obs.lineage import (
        get_lineage,
        set_lineage,
    )
    from large_scale_recommendation_tpu.obs.recorder import (
        get_recorder,
        set_recorder,
    )
    from large_scale_recommendation_tpu.obs.registry import (
        get_registry,
        set_registry,
    )
    from large_scale_recommendation_tpu.obs.trace import (
        get_tracer,
        set_tracer,
    )

    prev = (get_registry(), get_tracer(), get_events(), get_recorder(),
            get_lineage(), get_disttrace())
    reg, tracer = obs.enable()
    obs.enable_lineage(capacity=64)
    analyzer = obs.enable_disttrace(capacity=64)
    yield reg, tracer, analyzer
    set_registry(prev[0])
    set_tracer(prev[1])
    set_events(prev[2])
    set_recorder(prev[3])
    set_lineage(prev[4])
    set_disttrace(prev[5])


class _Crash(RuntimeError):
    pass


class TestKillRestartMultiConsumer:
    N = 3

    def test_per_partition_zero_loss_bounded_duplication(
            self, tmp_path, causal_obs):
        """The satellite-4 pin: kill mid-stream with partitions at
        DIFFERENT offsets, restart, and account for every partition's
        records exactly — zero loss, per-partition duplicate window ≤
        checkpoint_every batches — then reconcile the post-resume
        lineage watermarks and critical-path samples (the PR 12
        reconciliation, now at N > 1)."""
        reg, _, analyzer = causal_obs
        n, batch, ck_every = self.N, 300, 2
        per_partition = [4 + p for p in range(n)]  # uneven offsets
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 0, batch=batch,
                     per_partition=per_partition)
        applied: list[tuple[int, int, int]] = []
        lock = threading.Lock()

        def record_and_crash(b):
            with lock:
                applied.append((b.partition, b.start_offset,
                                b.end_offset))
                if len(applied) == 6:
                    raise _Crash()

        m1, r1 = _runner(tmp_path, log, checkpoint_every=ck_every)
        r1.on_batch = record_and_crash
        with pytest.raises(_Crash):
            r1.run()
        frontier = r1.applied_frontier()

        m2, r2 = _runner(tmp_path, log, checkpoint_every=ck_every)
        r2.on_batch = lambda b: applied.append(
            (b.partition, b.start_offset, b.end_offset))
        assert r2.resume()
        restored = dict(m2.consumed_offsets)
        # the duplicate window at the kill instant, per partition
        for p in range(n):
            dup = frontier.get(p, 0) - restored.get(p, 0)
            assert 0 <= dup <= ck_every * batch, (p, dup)
        engine = r2.serving_engine(k=3, max_batch=32)
        r2.run()
        r2.refresh_serving()
        engine.recommend(np.arange(4, dtype=np.int64))

        # per-partition zero loss + bounded duplication
        for p in range(n):
            total = per_partition[p] * batch
            covered = np.zeros(total, np.int32)
            for part, lo, hi in applied:
                if part == p:
                    covered[lo:hi] += 1
            assert (covered >= 1).all(), f"lost records in p{p}"
            assert (covered > 1).sum() <= ck_every * batch, \
                f"p{p} replayed more than the barrier window"
            assert m2.consumed_offsets[p] == total

        # post-resume lineage watermarks: every partition's servable
        # frontier reached its consumed offset
        fresh = obs.get_lineage().freshness()
        for p in range(n):
            assert fresh["partitions"][p]["servable_watermark"] == \
                m2.consumed_offsets[p]
            assert not fresh["partitions"][p]["ingest_ahead"]

        # critical-path samples resolve PER PARTITION and reconcile
        # exactly against the lineage freshness histogram (the PR 12
        # contract, now with N partitions contributing samples)
        samples = analyzer.samples()
        assert {s["partition"] for s in samples} == set(range(n))
        hist = next(m for m in reg.snapshot()["metrics"]
                    if m["name"] == "lineage_ingest_to_servable_s")
        assert hist["count"] == len(samples)
        lags = [s["swap_lag_s"] for s in samples]
        assert np.mean(lags) == pytest.approx(hist["mean"], rel=1e-6,
                                              abs=1e-6)
        for s in samples:
            parts = [v for v in (s["queue_wait_s"], s["train_apply_s"],
                                 s["swap_lag_s"]) if v is not None]
            assert sum(parts) == pytest.approx(s["total_s"], abs=1e-9)
        assert any(s["flush_wait_s"] is not None for s in samples)


# --------------------------------------------------------------------------
# The N=4 starved-feed skew pin + per-partition gauges
# --------------------------------------------------------------------------


class TestParallelObservability:
    def test_starved_partition_flips_skew_at_n4(self, tmp_path,
                                                causal_obs):
        """The satellite-3 pin: ONE inspector shared across N=4
        consumers sees all partitions' arrival rates, and a partition
        trickling at ~1/20 of its peers flips the skew check to
        DEGRADED. (A per-consumer inspector would read skew 1.0
        forever — it never sees the starving sibling.)"""
        from large_scale_recommendation_tpu.obs.dataquality import (
            DataQualityInspector,
        )
        from large_scale_recommendation_tpu.obs.health import DEGRADED

        reg, _, _ = causal_obs
        n = 4
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        rng = np.random.default_rng(0)
        for p in range(n):
            per = 15 if p == 2 else 300  # partition 2 starves
            for _ in range(3):
                u = rng.integers(0, 30, per) * n + p
                i = rng.integers(0, 12, per) + p * 12
                log.append_arrays(p, u, i,
                                  rng.random(per).astype(np.float32))
        # duplicates priced at the workload's baseline (dense
        # small-vocab synthetic stream runs ~30% NATURAL duplicate
        # keys — the PR 10 class_policy lesson): the verdict this test
        # pins must come from the SKEW, not the duplicate class
        inspector = DataQualityInspector(
            skew_threshold=10.0,
            class_policy={"duplicate_key": (0.9, 1.0)},
            registry=reg)
        model = _online()
        runner = ParallelIngestRunner(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=300),
            inspector=inspector)
        runner.run()
        assert inspector.last_skew >= 10.0
        status, detail = inspector.status()
        assert status == DEGRADED
        assert detail.get("skewed") is True

    def test_lag_gauges_published_for_all_partitions(self, tmp_path,
                                                     causal_obs):
        """The satellite-3 fix: a single driver only publishes its own
        partition's ``streams_lag_records``; the runner's telemetry
        publishes ALL N."""
        reg, _, _ = causal_obs
        n = 4
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 2)
        model = _online()
        runner = ParallelIngestRunner(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=300))
        runner.run()
        runner.telemetry()
        lag_labels = {
            m["labels"].get("partition")
            for m in reg.snapshot()["metrics"]
            if m["name"] == "streams_lag_records"
        }
        assert lag_labels >= {str(p) for p in range(n)}


# --------------------------------------------------------------------------
# Delta-swap coalescing
# --------------------------------------------------------------------------


class TestSwapCoalescing:
    def _engine(self, n_users=40, n_items=30, rank=4):
        import jax.numpy as jnp

        from large_scale_recommendation_tpu.data.blocking import (
            flat_index,
        )
        from large_scale_recommendation_tpu.models.mf import MFModel
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )

        rng = np.random.default_rng(0)
        model = MFModel(
            U=jnp.asarray(rng.normal(size=(n_users, rank))
                          .astype(np.float32)),
            V=jnp.asarray(rng.normal(size=(n_items, rank))
                          .astype(np.float32)),
            users=flat_index(np.arange(n_users, dtype=np.int64)),
            items=flat_index(np.arange(n_items, dtype=np.int64)),
        )
        return ServingEngine(model, k=3, max_batch=32)

    def test_deferred_flush_equals_eager_bitexact(self):
        """N deferred deltas + one flush ≡ the same deltas applied
        eagerly, bit-for-bit — with exactly ONE version bump."""
        rng = np.random.default_rng(1)
        a, b = self._engine(), self._engine()
        deltas = []
        for start in (0, 10, 20):
            rows = np.arange(start, start + 5, dtype=np.int64)
            vals = rng.normal(size=(5, 4)).astype(np.float32)
            deltas.append((rows, vals))

        for rows, vals in deltas:  # eager: one swap per delta
            a.apply_delta(item_rows=rows, V_rows=vals)
        v_before = b.version
        versions_seen = []
        b.on_refresh = versions_seen.append
        for rows, vals in deltas:  # deferred: buffered, no swap
            b.apply_delta(item_rows=rows, V_rows=vals, defer=True)
            assert b.version == v_before
        assert b.pending_delta_rows == 15
        b.flush_deltas()
        assert b.pending_delta_rows == 0
        assert len(versions_seen) == 1  # ONE bump for three deltas
        assert b.stats["delta_flushes"] == 1
        np.testing.assert_array_equal(np.asarray(a.model.V),
                                      np.asarray(b.model.V))
        ids_a, sc_a = a.recommend(np.arange(6, dtype=np.int64))
        ids_b, sc_b = b.recommend(np.arange(6, dtype=np.int64))
        np.testing.assert_array_equal(ids_a, ids_b)
        np.testing.assert_array_equal(sc_a, sc_b)

    def test_newest_deferred_value_wins_per_row(self):
        eager, deferred = self._engine(), self._engine()
        rows = np.asarray([3], dtype=np.int64)
        v1 = np.ones((1, 4), np.float32)
        v2 = np.full((1, 4), 2.0, np.float32)
        eager.apply_delta(item_rows=rows, V_rows=v1)
        eager.apply_delta(item_rows=rows, V_rows=v2)
        deferred.apply_delta(item_rows=rows, V_rows=v1, defer=True)
        deferred.apply_delta(item_rows=rows, V_rows=v2, defer=True)
        assert deferred.pending_delta_rows == 1  # newest value wins
        deferred.flush_deltas()
        np.testing.assert_array_equal(np.asarray(eager.model.V),
                                      np.asarray(deferred.model.V))

    def test_defer_vocab_growth_raises_at_defer_time(self):
        e = self._engine(n_items=30)
        with pytest.raises(ValueError, match="vocab grew"):
            e.apply_delta(item_rows=np.asarray([30]),
                          V_rows=np.zeros((1, 4), np.float32),
                          defer=True)

    def test_rejected_defer_leaves_nothing_pending(self):
        """A defer with a valid item side but an out-of-bound user side
        must buffer NEITHER half — a torn half-delta flushed later
        would break the eager-equivalence contract."""
        e = self._engine(n_users=40, n_items=30)
        with pytest.raises(ValueError, match="vocab grew"):
            e.apply_delta(item_rows=np.asarray([3]),
                          V_rows=np.ones((1, 4), np.float32),
                          user_rows=np.asarray([40]),
                          U_rows=np.ones((1, 4), np.float32),
                          defer=True)
        assert e.pending_delta_rows == 0
        assert e.stats["deferred_delta_rows"] == 0

    def test_full_refresh_supersedes_pending_deltas(self):
        """A full refresh() clears anything still deferred: a later
        flush must NOT scatter stale pre-refresh vectors over the
        fresher catalog."""
        e = self._engine()
        rows = np.asarray([3], dtype=np.int64)
        e.apply_delta(item_rows=rows,
                      V_rows=np.full((1, 4), 9.0, np.float32),
                      defer=True)
        assert e.pending_delta_rows == 1
        e.refresh()  # rebuild from the bound model's CURRENT state
        assert e.pending_delta_rows == 0
        fresh_row = np.asarray(e.model.V)[3].copy()
        e.flush_deltas()  # no-op: nothing pending survives the rebuild
        np.testing.assert_array_equal(np.asarray(e.model.V)[3],
                                      fresh_row)

    def test_flush_with_nothing_pending_is_a_noop(self):
        e = self._engine()
        v = e.version
        assert e.flush_deltas() == v
        assert e.stats["delta_flushes"] == 0

    def test_runner_refresh_is_one_swap_for_n_consumers(self, tmp_path):
        """N consumers' dirty rows ship as ONE catalog version bump per
        refresh — the anti-thrash pin."""
        n = 3
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 2)
        model, runner = _runner(tmp_path, log)
        runner.run()
        engine = runner.serving_engine(k=3, max_batch=32)
        versions_at_bind = len(runner.catalog_versions)
        _fill_strata(log, n, 2, seed=7)
        runner.run()
        runner.refresh_serving()
        # one refresh = one new version, though all N partitions
        # contributed dirty rows
        assert len(runner.catalog_versions) == versions_at_bind + 1
        assert engine.stats["delta_flushes"] == 1
        assert engine.stats["delta_swaps"] == 1

    def test_concurrent_refresh_requests_coalesce(self, tmp_path):
        n = 2
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 2)
        model, runner = _runner(tmp_path, log)
        runner.run()
        runner.serving_engine(k=3, max_batch=32)
        # hold the refresh mid-flight and fire more requests at it
        release = threading.Event()
        real = runner._do_refresh

        def slow(delta):
            release.wait(5)
            real(delta)

        runner._do_refresh = slow
        t = threading.Thread(target=runner.refresh_serving)
        t.start()
        time.sleep(0.05)
        for _ in range(3):
            runner.refresh_serving()  # absorbed, returns immediately
        assert runner.refreshes_coalesced == 3
        release.set()
        t.join(timeout=10)
        assert not runner._refreshing

    def test_midship_vocab_growth_falls_back_to_full_refresh(
            self, tmp_path):
        """The delta=None TOCTOU: the geometry check passes, then a
        concurrent apply grows the vocab before the delta ships — the
        engine's bound check fires mid-delta and delta=None must FALL
        BACK to a full rebuild, not crash the refreshing thread.
        delta=True keeps the assertion semantics."""
        n = 2
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 2)
        model, runner = _runner(tmp_path, log)
        runner.run()
        engine = runner.serving_engine(k=3, max_batch=32)
        _fill_strata(log, n, 1, seed=5)
        runner.run()
        real = engine.apply_delta
        calls = [0]

        def grown_midship(*a, **kw):
            calls[0] += 1
            raise ValueError("delta row 999 outside catalog of 10 rows "
                             "— vocab grew; use refresh()")

        engine.apply_delta = grown_midship
        refreshes_before = engine.stats["refreshes"]
        runner.refresh_serving(delta=None)  # falls back, no raise
        assert calls[0] >= 1
        assert engine.stats["refreshes"] == refreshes_before + 1
        # delta=True asserts instead of falling back
        _fill_strata(log, n, 1, seed=6)
        runner.run()
        with pytest.raises(ValueError, match="vocab grew"):
            runner.refresh_serving(delta=True)
        engine.apply_delta = real

    def test_stop_before_run_wins(self, tmp_path):
        """A stop delivered before the consume loop starts must make
        the next run exit immediately (the runner's stop() racing a
        consumer thread that hadn't entered run() yet used to be
        erased by run()'s unconditional clear — a follow-mode loop then
        tailed forever). The consumed stop does not leak: the run
        after it drains normally."""
        from large_scale_recommendation_tpu.streams import (
            StreamingDriver,
        )

        log = EventLog(str(tmp_path / "log"), fsync=False)
        _fill_strata(log, 1, 3)
        drv = StreamingDriver(
            _online(), log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=300))
        drv.stop()
        assert drv.run(follow=True) == 0  # would hang before the fix
        assert drv.run() == 3  # pending stop consumed, next run drains

    def test_delta_matches_full_refresh(self, tmp_path):
        """Runner delta shipping ≡ full rebuild, bit-for-bit on the
        same engine: a full refresh immediately after a delta refresh
        must change NOTHING (the delta missed no dirty row)."""
        n = 2
        log = EventLog(str(tmp_path / "log"), num_partitions=n,
                       fsync=False)
        _fill_strata(log, n, 2)
        model, runner = _runner(tmp_path, log)
        runner.run()
        engine = runner.serving_engine(k=3, max_batch=32)
        _fill_strata(log, n, 1, seed=5)
        runner.run()
        runner.refresh_serving(delta=True)
        V_delta = np.asarray(engine.model.V).copy()
        U_delta = np.asarray(engine.model.U).copy()
        runner.refresh_serving(delta=False)  # authoritative rebuild
        np.testing.assert_array_equal(V_delta,
                                      np.asarray(engine.model.V))
        np.testing.assert_array_equal(U_delta,
                                      np.asarray(engine.model.U))
