"""StreamingDriver: catch-up, checkpoint cadence, crash/resume recovery.

The acceptance pin (ISSUE 2): after a simulated crash the driver resumes
from the checkpointed WAL offset with ZERO lost ratings, at most ONE
duplicated micro-batch (checkpoint_every=1), and the serving engine
observes a fresh catalog version after the post-restart retrain.
"""

import os

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.adaptive import (
    AdaptiveMF,
    AdaptiveMFConfig,
)
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.streams import (
    EventLog,
    GeneratorSource,
    StreamingDriver,
    StreamingDriverConfig,
    pump_to_log,
)
from large_scale_recommendation_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_online_state,
    save_online_state,
)


def _filled_log(path, n_batches=6, batch=400, seed=0, users=60, items=40):
    log = EventLog(path, fsync=False)
    gen = SyntheticMFGenerator(num_users=users, num_items=items, rank=4,
                               seed=seed)
    pump_to_log(GeneratorSource(gen, batch, num_batches=n_batches), log)
    return log


def _online(rank=4):
    return OnlineMF(OnlineMFConfig(num_factors=rank, minibatch_size=64))


class TestCatchUp:
    def test_drains_log_and_checkpoints(self, tmp_path):
        log = _filled_log(str(tmp_path / "log"))
        drv = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                              config=StreamingDriverConfig(
                                  batch_records=500))
        assert not drv.resume()  # fresh directory
        applied = drv.run()
        assert applied == 5  # ceil(2400 / 500)
        tele = drv.telemetry()
        assert tele["records_processed"] == 2400
        assert tele["lag_records"] == 0
        assert tele["consumed_offset"] == 2400
        assert drv.checkpoints_written == applied  # checkpoint_every=1
        assert drv.manager.latest_step() is not None

    def test_resume_continues_without_reapply(self, tmp_path):
        log = _filled_log(str(tmp_path / "log"), n_batches=4)
        cfg = StreamingDriverConfig(batch_records=400)
        d1 = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                             config=cfg)
        d1.run()
        # new data lands; a NEW driver (fresh process) resumes
        gen = SyntheticMFGenerator(num_users=60, num_items=40, rank=4,
                                   seed=9)
        pump_to_log(GeneratorSource(gen, 400, num_batches=2), log)
        d2 = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                             config=cfg)
        assert d2.resume()
        assert d2.consumed_offset == 1600  # clean shutdown: no replay
        assert d2.run() == 2
        assert d2.consumed_offset == 2400

    def test_checkpoint_every_n(self, tmp_path):
        log = _filled_log(str(tmp_path / "log"), n_batches=6, batch=400)
        drv = StreamingDriver(
            _online(), log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400,
                                         checkpoint_every=4))
        drv.run()
        # 6 batches → one cadence checkpoint at 4 + the final flush
        assert drv.checkpoints_written == 2

    def test_retention_chases_checkpoint(self, tmp_path):
        log = EventLog(str(tmp_path / "log"), segment_records=256,
                       fsync=False)
        gen = SyntheticMFGenerator(num_users=60, num_items=40, rank=4,
                                   seed=1)
        pump_to_log(GeneratorSource(gen, 256, num_batches=5), log)
        drv = StreamingDriver(
            _online(), log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=256,
                                         truncate_log=True))
        drv.run()
        assert log.start_offset(0) == 1024  # all but the active segment
        assert log.end_offset(0) == 1280

    def test_lag_is_per_partition(self, tmp_path):
        # another partition's backlog is NOT this driver's lag
        # (regression: telemetry used EventLog.lag, which charges every
        # unconsumed partition from its floor)
        log = EventLog(str(tmp_path / "log"), num_partitions=2,
                       fsync=False)
        gen = SyntheticMFGenerator(num_users=60, num_items=40, rank=4,
                                   seed=2)
        pump_to_log(GeneratorSource(gen, 400, num_batches=2), log,
                    partition=0)
        pump_to_log(GeneratorSource(gen, 400, num_batches=3), log,
                    partition=1)
        drv = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                              partition=0,
                              config=StreamingDriverConfig(
                                  batch_records=400))
        drv.run()
        tele = drv.telemetry()
        assert tele["consumed_offset"] == 800
        assert tele["lag_records"] == 0  # p1's 1200 backlog isn't ours
        assert log.lag({0: 800}) == 1200  # whole-log view still sees it


class _Crash(RuntimeError):
    pass


class TestCrashRecovery:
    def test_kill_restart_no_loss_bounded_duplication(self, tmp_path):
        """The recovery acceptance pin, pure-online form: crash the
        driver mid-stream AFTER applying a batch but BEFORE its
        checkpoint lands (the worst at-least-once window), restart from
        the checkpoint, and account for every record exactly."""
        total = 6 * 400
        log = _filled_log(str(tmp_path / "log"), n_batches=6)
        applied: list[tuple[int, int]] = []

        def crash_at_3(batch):
            applied.append((batch.start_offset, batch.end_offset))
            if len(applied) == 3:
                raise _Crash()

        d1 = StreamingDriver(
            _online(), log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400),
            on_batch=crash_at_3)
        with pytest.raises(_Crash):
            d1.run()
        assert len(applied) == 3  # batch 3 applied, checkpoint lost

        # restart: fresh model + driver, as a new process would
        d2 = StreamingDriver(
            _online(), log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400),
            on_batch=lambda b: applied.append(
                (b.start_offset, b.end_offset)))
        assert d2.resume()
        assert d2.consumed_offset == 800  # batch 3's ckpt never landed
        d2.run()

        # zero loss: the union of applied ranges covers [0, total)
        covered = np.zeros(total, np.int32)
        for lo, hi in applied:
            covered[lo:hi] += 1
        assert (covered >= 1).all(), "lost ratings"
        # bounded duplication: exactly the one unacked micro-batch
        dup_ranges = [(lo, hi) for lo, hi in applied
                      if (covered[lo:hi] > 1).any()]
        assert (covered > 1).sum() <= 400, "more than one batch replayed"
        assert sorted(set(dup_ranges)) == [(800, 1200)]
        assert d2.consumed_offset == total
        assert d2.telemetry()["lag_records"] == 0

    def test_adaptive_crash_resume_fresh_catalog_version(self, tmp_path):
        """Adaptive form: the post-restart retrain must reach serving —
        a fresh catalog version on the engine, observed via the swap
        hook."""
        log = _filled_log(str(tmp_path / "log"), n_batches=8, batch=300)

        def adaptive():
            return AdaptiveMF(AdaptiveMFConfig(
                num_factors=4, minibatch_size=64, offline_every=3,
                offline_iterations=2))

        hits = [0]

        def crash_at_4(batch):
            hits[0] += 1
            if hits[0] == 4:
                raise _Crash()

        d1 = StreamingDriver(adaptive(), log, str(tmp_path / "ckpt"),
                             config=StreamingDriverConfig(
                                 batch_records=300),
                             on_batch=crash_at_4)
        with pytest.raises(_Crash):
            d1.run()

        m2 = adaptive()
        d2 = StreamingDriver(m2, log, str(tmp_path / "ckpt"),
                             config=StreamingDriverConfig(
                                 batch_records=300))
        assert d2.resume()
        assert d2.consumed_offset == 900  # 3 checkpointed batches
        # retrain history rebuilt from the log below the restored offset
        # — the post-restart retrain must not fit from the tail alone
        assert m2._history_rows == 900
        assert d2.resume()  # idempotent: the refill resets, no dup rows
        assert m2._history_rows == 900
        engine = d2.serving_engine(k=3)
        v0 = engine.version
        d2.run()  # replays batch 4 + the tail; offline_every=3 retrains
        assert m2.retrain_count >= 1
        assert engine.version != v0, "retrain swap never reached serving"
        # the swap was OBSERVED through the hook, not just polled
        assert d2.catalog_versions[0] == v0
        assert engine.version in d2.catalog_versions[1:]
        assert d2.consumed_offset == 2400

    def test_crash_does_not_checkpoint_failed_batch(self, tmp_path):
        # the offset persisted after a crash must be ≤ the last APPLIED
        # batch — never the in-flight one (maybe-lost otherwise)
        log = _filled_log(str(tmp_path / "log"), n_batches=3)
        mgr_dir = str(tmp_path / "ckpt")

        def crash_immediately(batch):
            raise _Crash()

        d1 = StreamingDriver(_online(), log, mgr_dir,
                             config=StreamingDriverConfig(
                                 batch_records=400),
                             on_batch=crash_immediately)
        with pytest.raises(_Crash):
            d1.run()
        assert CheckpointManager(mgr_dir).latest_step() is None

    def test_early_stop_surfaces_feeder_fault(self, tmp_path):
        # run(max_batches=N) exits the consume loop before the feeder's
        # end-of-stream re-raise — a feeder fault (tail read dying) must
        # still surface from run(), not be silently swallowed
        import time

        log = _filled_log(str(tmp_path / "log"), n_batches=3)
        calls = [0]
        real_read = log.read

        def read(partition, start, n):
            calls[0] += 1
            if calls[0] > 1:
                raise RuntimeError("tail io fault")
            return real_read(partition, start, n)

        log.read = read
        drv = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                              config=StreamingDriverConfig(
                                  batch_records=400))

        def hold_until_feeder_faults(batch):
            # deterministic: don't let the consumer exit (which stops
            # the tail source) before the feeder reaches its fault
            deadline = time.monotonic() + 30
            while (drv._source._error is None
                   and time.monotonic() < deadline):
                time.sleep(0.005)

        drv.on_batch = hold_until_feeder_faults
        with pytest.raises(RuntimeError, match="tail io fault"):
            drv.run(max_batches=1)
        # the one applied batch was checkpointed before the fault
        assert drv.checkpoints_written == 1

    def test_checkpoint_held_while_offset_stamp_frozen(self, tmp_path):
        # background-retrain window: the model buffers batches WITHOUT
        # advancing its offset stamp (AdaptiveMF background=True); the
        # driver must hold checkpoints — each would just re-persist the
        # pre-retrain offset — and write ONE as soon as the stamp
        # catches up past the batch (post-swap)
        log = _filled_log(str(tmp_path / "log"), n_batches=3)
        model = _online()
        real_fit = model.partial_fit
        frozen = [True]  # first two batches: simulate the buffer window

        def fit(batch, offset=None, emit_updates=False):
            return real_fit(
                batch, offset=None if frozen[0] else offset,
                emit_updates=emit_updates)

        model.partial_fit = fit

        seen = [0]

        def unfreeze_after_2(batch):
            seen[0] += 1
            if seen[0] >= 2:
                frozen[0] = False

        drv = StreamingDriver(model, log, str(tmp_path / "ckpt"),
                              config=StreamingDriverConfig(
                                  batch_records=400),
                              on_batch=unfreeze_after_2)
        drv.run()
        # batches 1-2 held (stamp frozen at 0), batch 3 stamps 1200 and
        # writes the single covering checkpoint
        assert drv.checkpoints_written == 1
        assert drv.consumed_offset == 1200
        d2 = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                             config=StreamingDriverConfig(
                                 batch_records=400))
        assert d2.resume()
        assert d2.consumed_offset == 1200


class TestOfflineStateRoundtrip:
    def test_offsets_persist_with_factors(self, tmp_path):
        m = _online()
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=4,
                                   seed=3)
        m.partial_fit(gen.generate(200), offset=(0, 200))
        m.partial_fit(gen.generate(100), offset=(0, 300))
        m.partial_fit(gen.generate(50), offset=(2, 50))
        mgr = CheckpointManager(str(tmp_path))
        save_online_state(mgr, m, step=m.step)

        m2 = _online()
        ck = restore_online_state(mgr, m2)
        assert m2.consumed_offsets == {0: 300, 2: 50}
        assert ck.meta["kind"] == "online_state"
        np.testing.assert_array_equal(
            np.asarray(m2.users.array)[:m2.users.num_rows],
            np.asarray(m.users.array)[:m.users.num_rows])

    def test_empty_batch_still_advances_offset(self, tmp_path):
        from large_scale_recommendation_tpu.core.types import Ratings

        m = _online()
        empty = Ratings.from_arrays([0], [0], [1.0],
                                    weights=[0.0])  # all padding
        m.partial_fit(empty, offset=(0, 7))
        assert m.consumed_offsets == {0: 7}

    def test_serving_refresh_for_pure_online(self, tmp_path):
        log = _filled_log(str(tmp_path / "log"), n_batches=2)
        drv = StreamingDriver(_online(), log, str(tmp_path / "ckpt"),
                              config=StreamingDriverConfig(
                                  batch_records=400))
        drv.run()
        engine = drv.serving_engine(k=3)
        v0 = engine.version
        gen = SyntheticMFGenerator(num_users=60, num_items=40, rank=4,
                                   seed=5)
        pump_to_log(GeneratorSource(gen, 400, num_batches=1), log)
        drv.run()
        drv.refresh_serving()
        assert engine.version != v0
        assert drv.catalog_versions[-1] == engine.version
