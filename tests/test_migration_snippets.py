"""Every python code fence in docs/MIGRATION.md executes for real.

The README vouches that the migration guide's snippets run against the
actual APIs; this test makes that claim CI-enforced — a rename that
breaks a snippet fails here, not in a migrating user's editor. Fences
execute in order in one shared namespace seeded with the free variables
the guide's prose assumes (train, users, items, a stream, events).
"""

import os
import re

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_migration_guide_snippets_execute():
    with open(os.path.join(REPO, "docs", "MIGRATION.md")) as f:
        doc = f.read()
    blocks = re.findall(r"```python\n(.*?)```", doc, re.DOTALL)
    assert len(blocks) >= 4, "guide lost its snippets?"

    from large_scale_recommendation_tpu.core.generators import (
        SyntheticMFGenerator,
    )
    from large_scale_recommendation_tpu.core.types import Ratings

    gen = SyntheticMFGenerator(num_users=300, num_items=150, rank=4,
                               noise=0.1, seed=1)
    train = gen.generate(20000)
    ru, ri, rv, _ = train.to_numpy()
    users = np.array([0, 3, 7])
    items = np.array([1, 4, 9])
    stream_of_rating_batches = [
        Ratings.from_arrays(ru[j:j + 2000], ri[j:j + 2000], rv[j:j + 2000])
        for j in range(0, 8000, 2000)
    ]
    ev = list(zip(ru[:2000].tolist(), ri[:2000].tolist(),
                  rv[:2000].tolist()))
    ns = {
        "train": train,
        "users": users,
        "items": items,
        "stream_of_rating_batches": stream_of_rating_batches,
        "early_events": ev[:1000],
        "later_events": ev[1000:],
        "handle": lambda u: None,
    }
    for j, block in enumerate(blocks):
        # the guide's snippets use illustrative sizes; shrink the slow
        # knobs so the whole guide runs in CI time
        block = (block.replace("iterations=10", "iterations=3")
                 .replace("iterations=5", "iterations=2")
                 .replace("num_factors=32", "num_factors=8"))
        try:
            exec(compile(block, f"MIGRATION.md[block {j}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - failure reporting
            raise AssertionError(
                f"MIGRATION.md block {j} failed: {e}\n---\n{block}") from e
    # the doc's flow actually produced artifacts
    assert "model" in ns and ns["model"].rmse(gen.generate(1000)) < 1.0
