"""ROLLOUT plane (``obs/budget.py``, ISSUE 19): error budgets,
per-catalog-version attribution, canary verdicts.

The acceptance pin everything here defends: a REAL two-``ServingEngine``
run over a REAL socket with a deliberately poisoned catalog version
shipped to one engine only — the attribution ledger pins the regression
to that version, the verdict engine returns ROLLBACK within the sample
budget and stamps it into lineage, the incumbent's error budget is
untouched, and ``/healthz`` is DEGRADED exactly while the ROLLBACK is
un-acted-on. Covered: the multi-window ``SLOTracker`` extension
(fast/slow burn pair, primary window bit-compatible), cohort math,
the verdict state machine (warming HOLD → hard ROLLBACK → PROMOTE
exoneration → sample-budget fail-safe), ``RolloutCheck`` +
``HealthMonitor.watch_rollout``, lineage verdict stamps, ``/budgetz``
over a real ``ObsServer``, fleet merge-by-version (worst-host windowed
readings), postmortem bundles (v7 write/load, archived v6 synthesized),
and the zero-cost disabled path.
"""

import json

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.obs.budget import (
    HOLD,
    PROMOTE,
    ROLLBACK,
    CanaryVerdictEngine,
    RolloutBudget,
    RolloutCheck,
    budgetz,
    get_budget,
    serve_scope,
    set_budget,
)
from large_scale_recommendation_tpu.obs.health import (
    HealthMonitor,
    SLOTracker,
)
from large_scale_recommendation_tpu.obs.server import ObsServer, http_get
from large_scale_recommendation_tpu.obs.transfers import _NULL_CONTEXT

RANK = 8


@pytest.fixture(autouse=True)
def _reset_planes():
    """Tests install budgets (and via enable_budget the registry stays
    whatever null_obs set) — never leak the plane into the next test."""
    prev = get_budget()
    yield
    set_budget(prev)


def _small_budget(**kw):
    kw.setdefault("objective", 0.9)
    kw.setdefault("fast_window", 8)
    kw.setdefault("slow_window", 64)
    kw.setdefault("min_samples", 8)
    kw.setdefault("sample_budget", 32)
    return RolloutBudget(0.1, **kw)


def _model(num_users=50, num_items=256, seed=20, poisoned=False):
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    V = rng.normal(size=(num_items, RANK)).astype(np.float32)
    if poisoned:
        # the poison: item factors row-shuffled — identical serving
        # cost, garbage answers (the regression is in WHAT it serves)
        V = V[rng.permutation(num_items)]
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, RANK)).astype(np.float32)),
        V=jnp.asarray(V),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)))


# --------------------------------------------------------------------------
# Multi-window SLOTracker: the fast/slow pair, primary pinned elsewhere
# --------------------------------------------------------------------------


class TestMultiWindowSLO:
    def test_burn_rates_fast_catches_cliff_slow_remembers(self, null_obs):
        slo = SLOTracker(0.1, objective=0.9, window=64,
                         windows={"fast": 4, "slow": 64})
        for _ in range(60):
            slo.record(0.01)
        assert slo.burn_rates() == {"primary": 0.0, "fast": 0.0,
                                    "slow": 0.0}
        for _ in range(4):  # a cliff: the fast window saturates
            slo.record(0.5)
        rates = slo.burn_rates()
        assert rates["fast"] == pytest.approx(10.0)  # 100% viol / 10%
        assert rates["slow"] == pytest.approx((4 / 64) / 0.1)
        assert rates["primary"] == rates["slow"]
        for _ in range(4):  # recovery: fast forgives, slow remembers
            slo.record(0.01)
        rates = slo.burn_rates()
        assert rates["fast"] == 0.0
        assert rates["slow"] > 0.0

    def test_snapshot_windows_subdict_only_with_extras(self, null_obs):
        plain = SLOTracker(0.1, objective=0.9, window=8)
        assert "windows" not in plain.snapshot()
        multi = SLOTracker(0.1, objective=0.9, window=8,
                           windows={"fast": 4})
        multi.record(0.5)
        snap = multi.snapshot()
        assert snap["windows"]["fast"]["size"] == 4
        assert snap["windows"]["fast"]["fill"] == 1
        # burn reads over the FILL, the same semantic the primary
        # window is pinned to: 1 violation / 1 recorded = 100% / 10%
        assert snap["windows"]["fast"]["burn_rate"] == pytest.approx(10.0)

    def test_extra_burn_gauges_publish_per_window(self, null_obs):
        from large_scale_recommendation_tpu.obs.registry import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        slo = SLOTracker(0.1, objective=0.9, window=16, name="svc",
                         registry=reg, windows={"fast": 4, "slow": 16})
        for lat in [0.01] * 15 + [0.5]:
            slo.record(lat)
        by_window = {
            m["labels"]["window"]: m["value"]
            for m in reg.snapshot()["metrics"]
            if m["name"] == "slo_burn_rate"
            and "window" in m["labels"]}  # the primary gauge has none
        assert by_window["fast"] == pytest.approx((1 / 4) / 0.1)
        assert by_window["slow"] == pytest.approx((1 / 16) / 0.1)
        # the primary (unlabelled) burn gauge publishes alongside
        (primary,) = [m["value"] for m in reg.snapshot()["metrics"]
                      if m["name"] == "slo_burn_rate"
                      and "window" not in m["labels"]]
        assert primary == pytest.approx((1 / 16) / 0.1)


# --------------------------------------------------------------------------
# Cohort attribution math
# --------------------------------------------------------------------------


class TestCohortLedger:
    def test_outcomes_key_by_version(self, null_obs):
        b = _small_budget()
        b.note_results(7, [0.01, 0.02, 0.5], degraded=1)
        b.note_result(9, 0.03)
        b.note_shed(7, n=2)
        b.note_eval(7, {"shadow_recall": 0.98, "nan": float("nan"),
                        "label": "x"})
        b.note_extra(7, staleness_s=1.5)
        c7 = b.cohort(7)
        assert c7["served"] == 3 and c7["violations"] == 1
        assert c7["degraded"] == 1 and c7["shed"] == 2
        assert c7["shed_frac"] == pytest.approx(2 / 5)
        assert c7["evals"] == {"shadow_recall": 0.98}  # finite scalars
        assert c7["extras"] == {"staleness_s": 1.5}
        assert c7["burn_rate_fast"] == pytest.approx((1 / 3) / 0.1)
        c9 = b.cohort(9)
        assert c9["served"] == 1 and c9["violations"] == 0
        assert c9["error_budget_remaining"] == 1.0
        assert b.cohort(11) is None
        assert b.versions() == [7, 9]

    def test_service_level_slo_sees_every_cohort(self, null_obs):
        b = _small_budget()
        b.note_result(1, 0.01)
        b.note_result(2, 0.5)
        assert b.slo.snapshot()["count"] == 2
        assert b.snapshot()["burn_rates"]["fast"] > 0.0

    def test_version_table_bounded_oldest_evicts(self, null_obs):
        b = _small_budget(max_versions=2)
        for v in (1, 2, 3):
            b.note_result(v, 0.01)
        assert b.versions() == [2, 3]
        assert b.evicted == 1
        assert b.snapshot()["evicted"] == 1

    def test_serve_scope_times_into_the_cohort(self, null_obs):
        b = _small_budget()
        with b.serve_scope(5):
            pass
        assert b.cohort(5)["served"] == 1

    def test_validation(self, null_obs):
        with pytest.raises(ValueError, match="max_versions"):
            RolloutBudget(0.1, max_versions=0)
        with pytest.raises(ValueError, match="fast_window"):
            RolloutBudget(0.1, fast_window=64, slow_window=8)
        with pytest.raises(ValueError, match="min_samples"):
            CanaryVerdictEngine(_small_budget(), min_samples=0)
        with pytest.raises(ValueError, match="sample_budget"):
            CanaryVerdictEngine(_small_budget(), min_samples=8,
                                sample_budget=4)


# --------------------------------------------------------------------------
# The verdict state machine
# --------------------------------------------------------------------------


class TestVerdictEngine:
    def test_warming_holds_then_clean_promotes(self, null_obs):
        b = _small_budget()
        b.note_results(1, [0.01] * 20)
        rec = b.verdicts.evaluate(2, 1)  # canary never served
        assert rec["verdict"] == HOLD and "warming" in rec["reason"]
        b.note_results(2, [0.01] * 8)
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == PROMOTE
        assert b.verdicts.pending() == {}

    def test_missing_incumbent_holds(self, null_obs):
        b = _small_budget()
        b.note_results(2, [0.01] * 8)
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == HOLD
        assert "no incumbent" in rec["reason"]

    def test_burn_cliff_rolls_back_and_names_the_version(self, null_obs):
        b = _small_budget()
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)  # every canary request violates
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == ROLLBACK
        assert "burn_rate_fast" in rec["reason"]
        assert rec["canary_version"] == 2
        assert 2 in b.verdicts.pending()

    def test_eval_regression_rolls_back_with_direction(self, null_obs):
        b = _small_budget()
        b.note_results(1, [0.01] * 8)
        b.note_results(2, [0.01] * 8)  # latency identical
        b.note_eval(1, {"shadow_recall": 0.99, "eval_rmse": 1.0})
        b.note_eval(2, {"shadow_recall": 0.50, "eval_rmse": 1.0})
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == ROLLBACK
        assert "shadow_recall" in rec["reason"]
        # lower-better keys read the other way: a DROPPING rmse is an
        # improvement, never a signal
        b2 = _small_budget()
        b2.note_results(1, [0.01] * 8)
        b2.note_results(2, [0.01] * 8)
        b2.note_eval(1, {"eval_rmse": 1.0})
        b2.note_eval(2, {"eval_rmse": 0.5})
        assert b2.verdicts.evaluate(2, 1)["verdict"] == PROMOTE

    def test_soft_signal_holds_then_sample_budget_fails_safe(
            self, null_obs):
        b = _small_budget(min_samples=8, sample_budget=16,
                          eval_tol=0.10)
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.01] * 8)
        # 7% worse: above the soft bar (5%), below the hard bar (10%)
        b.note_eval(1, {"shadow_recall": 1.00})
        b.note_eval(2, {"shadow_recall": 0.93})
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == HOLD
        b.note_results(2, [0.01] * 8)  # sample budget now spent
        rec = b.verdicts.evaluate(2, 1)
        assert rec["verdict"] == ROLLBACK
        assert "sample budget exhausted" in rec["reason"]

    def test_promote_exonerates_a_pending_rollback(self, null_obs):
        # a small latency reservoir so the recovery can age the cliff
        # out of the p99 read, not just out of the fast burn window
        b = _small_budget(lat_reservoir=8)
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)
        assert b.verdicts.evaluate(2, 1)["verdict"] == ROLLBACK
        # the canary recovers: fast window and reservoir forget
        b.note_results(2, [0.01] * 8)
        b.note_results(1, [0.01] * 8)
        assert b.verdicts.evaluate(2, 1)["verdict"] == PROMOTE
        assert b.verdicts.pending() == {}

    def test_mark_rolled_back_clears_pending(self, null_obs):
        b = _small_budget()
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)
        b.verdicts.evaluate(2, 1)
        assert b.verdicts.mark_rolled_back(2) is True
        assert b.verdicts.pending() == {}
        assert b.verdicts.mark_rolled_back(2) is False  # idempotent

    def test_snapshot_history_and_counters(self, null_obs):
        from large_scale_recommendation_tpu.obs.registry import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        b = RolloutBudget(0.1, objective=0.9, fast_window=8,
                          slow_window=64, min_samples=8,
                          sample_budget=32, registry=reg)
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)
        b.verdicts.evaluate(2, 1)
        snap = b.verdicts.snapshot()
        assert snap["evaluations"] == 1
        assert list(snap["pending_rollbacks"]) == ["2"]
        assert snap["history"][-1]["verdict"] == ROLLBACK
        assert snap["config"]["min_samples"] == 8
        metrics = {(m["name"], tuple(sorted(m["labels"].items()))):
                   m["value"] for m in reg.snapshot()["metrics"]}
        assert metrics[("rollout_verdicts_total",
                        (("verdict", ROLLBACK),))] == 1
        assert metrics[("rollout_pending_rollbacks", ())] == 1
        assert metrics[("rollout_served_total", ())] == 28

    def test_verdicts_stamp_lineage(self, null_obs):
        journal = obs.enable_lineage(capacity=16)
        try:
            b = _small_budget()
            b.note_results(1, [0.01] * 20)
            b.note_results(2, [0.5] * 8)
            b.verdicts.evaluate(2, 1)
            rec = journal.resolve(2)
            assert rec["verdict"] == ROLLBACK
            assert "burn_rate_fast" in rec["verdict_reason"]
            assert "rolled_back" not in rec
            b.verdicts.mark_rolled_back(2)
            assert journal.resolve(2)["rolled_back"] is True
        finally:
            obs.disable()


# --------------------------------------------------------------------------
# Plane lifecycle + the zero-cost disabled path
# --------------------------------------------------------------------------


class TestPlaneLifecycle:
    def test_default_is_none_and_budgetz_notes(self, null_obs):
        assert get_budget() is None
        doc = budgetz()
        assert "enable_budget" in doc["note"] and doc["cohorts"] == {}

    def test_disabled_scope_is_the_shared_singleton(self, null_obs,
                                                    monkeypatch):
        """The TestNullPathZeroWork pin for this plane: with no budget
        installed ``serve_scope`` hands out the one module-level null
        context — no allocation, and NO clock read (pinned by making
        the clock explode)."""
        import time as _time

        def _boom():  # pragma: no cover - must never run
            raise AssertionError("clock read on the disabled path")

        monkeypatch.setattr(_time, "perf_counter", _boom)
        assert serve_scope(1) is _NULL_CONTEXT
        with serve_scope(1):
            pass

    def test_engine_binds_none_when_plane_off(self, null_obs):
        from large_scale_recommendation_tpu.serving import ServingEngine

        assert ServingEngine(_model(), k=4)._budget is None

    def test_enable_budget_installs_and_disable_clears(self, null_obs):
        b = obs.enable_budget(0.1, objective=0.95, fast_window=4,
                              slow_window=16, min_samples=4)
        try:
            assert b is get_budget()
            assert b.objective == 0.95
            assert b.verdicts.min_samples == 4
            assert serve_scope(3) is not _NULL_CONTEXT
        finally:
            obs.disable()
        assert get_budget() is None


# --------------------------------------------------------------------------
# Server route, health gate
# --------------------------------------------------------------------------


class TestServerAndHealth:
    def test_budgetz_route_and_index(self, null_obs):
        obs.enable()
        try:
            b = obs.enable_budget(0.1, objective=0.9)
            b.note_result(3, 0.01)
            with ObsServer() as server:
                code, body = http_get(server.url + "/budgetz")
                icode, ibody = http_get(server.url + "/")
        finally:
            obs.disable()
        assert code == 200
        doc = json.loads(body)
        assert doc["cohorts"]["3"]["served"] == 1
        assert "/budgetz" in json.loads(ibody)["routes"]

    def test_budgetz_without_plane_is_a_note(self, null_obs):
        obs.enable()
        try:
            with ObsServer() as server:
                code, body = http_get(server.url + "/budgetz")
        finally:
            obs.disable()
        assert code == 200
        assert "enable_budget" in json.loads(body)["note"]

    def test_rollout_check_degraded_exactly_while_pending(self, null_obs):
        b = _small_budget()
        check = RolloutCheck(b)
        assert check().status == "ok"
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)
        b.verdicts.evaluate(2, 1)
        res = check()
        assert res.status == "degraded"
        assert "un-acted-on" in res.detail["note"]
        b.verdicts.mark_rolled_back(2)
        assert check().status == "ok"

    def test_watch_rollout_flips_healthz(self, null_obs):
        mon = HealthMonitor()
        b = _small_budget()
        mon.watch_rollout(b)
        assert mon.run()["status"] == "ok"
        b.note_results(1, [0.01] * 20)
        b.note_results(2, [0.5] * 8)
        b.verdicts.evaluate(2, 1)
        report = mon.run()
        assert report["checks"]["rollout"]["status"] == "degraded"
        assert report["status"] == "degraded"


# --------------------------------------------------------------------------
# Fleet merge-by-version
# --------------------------------------------------------------------------


class TestFleet:
    def test_pod_view_merges_cohorts_by_version(self, null_obs):
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
            FleetServer,
        )

        obs.enable()
        try:
            b = obs.enable_budget(0.1, objective=0.9, fast_window=8,
                                  slow_window=64, min_samples=4,
                                  sample_budget=16)
            b.note_results(1, [0.01] * 6)
            b.note_results(2, [0.5] * 4)
            b.note_shed(2, n=1)
            b.verdicts.evaluate(2, 1)
            with ObsServer() as s1, ObsServer() as s2:
                # two real sockets over the one process budget: the
                # merge-by-version contract is what's under test
                view = FleetAggregator([s1.url, s2.url]).budget()
                with FleetServer(FleetAggregator([s1.url])) as fleet:
                    code, body = http_get(fleet.url + "/budgetz")
        finally:
            obs.disable()
        (r2,) = [r for r in view["cohorts"] if r["version"] == 2]
        assert r2["hosts"] == 2
        assert r2["served"] == 8  # summed across members
        assert r2["shed"] == 2
        # the windowed readings keep the WORST host, never averaged
        assert r2["burn_rate_fast_max"] == pytest.approx(10.0)
        # every canary request violated: the slow window (burn over
        # fill, the pinned SLOTracker semantic) is fully burned
        assert r2["error_budget_remaining_min"] == 0.0
        assert r2["attainment"] == 0.0
        assert view["pending_rollbacks"]["2"][0]["reason"]
        assert len(view["pending_rollbacks"]["2"]) == 2  # one per host
        assert code == 200
        assert json.loads(body)["cohorts"][0]["version"] == 1

    def test_unreachable_member_is_listed_not_fatal(self, null_obs):
        from large_scale_recommendation_tpu.obs.fleet import (
            FleetAggregator,
        )

        obs.enable()
        try:
            obs.enable_budget(0.1)
            with ObsServer() as s1:
                dead = "http://127.0.0.1:1"
                view = FleetAggregator([s1.url, dead],
                                       timeout_s=3.0).budget()
        finally:
            obs.disable()
        assert view["unreachable"] == ["127.0.0.1:1"]
        assert len(view["targets"]) == 1


# --------------------------------------------------------------------------
# Postmortem bundles: v7 round-trip, archived v6 synthesized
# --------------------------------------------------------------------------


class TestBundle:
    def test_v7_bundle_carries_budget_and_v6_stays_loadable(
            self, null_obs, tmp_path):
        import os

        from large_scale_recommendation_tpu.obs.recorder import (
            BUNDLE_VERSION,
            load_bundle,
            write_bundle,
        )

        obs.enable()
        obs.enable_flight_recorder(interval_s=0.05)
        try:
            b = obs.enable_budget(0.1, objective=0.9)
            b.note_result(5, 0.02)
            path = write_bundle(str(tmp_path / "b"), trigger="manual")
            docs = load_bundle(path)
            # the plane landed in bundle v7; later planes keep
            # bumping the version, so pin the floor, not the value
            assert BUNDLE_VERSION >= 7
            assert docs["manifest"]["bundle_version"] == BUNDLE_VERSION
            assert docs["budget"]["cohorts"]["5"]["served"] == 1
            # an archived version-6 bundle (pre-rollout-plane) stays
            # loadable with the note synthesized
            manifest_path = str(tmp_path / "b" / "manifest.json")
            with open(manifest_path) as f:
                manifest = json.load(f)
            manifest["bundle_version"] = 6
            manifest["files"] = [x for x in manifest["files"]
                                 if x != "budget.json"]
            with open(manifest_path, "w") as f:
                json.dump(manifest, f)
            os.unlink(str(tmp_path / "b" / "budget.json"))
            docs6 = load_bundle(path)
            assert docs6["budget"]["cohorts"] == {}
            assert "version-6" in docs6["budget"]["note"]
        finally:
            obs.disable()

    def test_bundle_without_plane_freezes_the_note(self, null_obs,
                                                   tmp_path):
        from large_scale_recommendation_tpu.obs.recorder import (
            load_bundle,
            write_bundle,
        )

        obs.enable()
        obs.enable_flight_recorder(interval_s=0.05)
        try:
            path = write_bundle(str(tmp_path / "b"), trigger="manual")
            docs = load_bundle(path)
        finally:
            obs.disable()
        assert "not enabled" in docs["budget"]["note"]


# --------------------------------------------------------------------------
# THE acceptance pin: poisoned canary, two engines, real socket
# --------------------------------------------------------------------------


class TestE2EPoisonedCanary:
    def test_poisoned_version_attributed_rolled_back_incumbent_untouched(
            self, null_obs):
        """One deliberately poisoned catalog version ships to one
        engine only. The ledger attributes the regression to THAT
        version, the verdict engine returns ROLLBACK within the sample
        budget and stamps it into lineage, the incumbent's budget is
        untouched, ``/healthz`` is DEGRADED while the ROLLBACK is
        un-acted-on and green after the rollback lands."""
        from large_scale_recommendation_tpu.serving import (
            ServingEngine,
            recall_at_k,
        )

        obs.enable()
        journal = obs.enable_lineage(capacity=32)
        # a generous latency target: on a CPU test host only the
        # PLANTED poison may trip a signal, never scheduler noise
        budget = obs.enable_budget(
            30.0, objective=0.9, fast_window=8, slow_window=64,
            min_samples=8, sample_budget=64)
        mon = HealthMonitor()
        mon.watch_rollout(budget)
        try:
            # engines bind the plane at construction — incumbent serves
            # the healthy catalog, the canary the poisoned one
            incumbent = ServingEngine(_model(), k=5, max_batch=64)
            canary = ServingEngine(_model(poisoned=True), k=5,
                                   max_batch=64)
            inc_ver, can_ver = incumbent.version, canary.version
            assert inc_ver != can_ver
            rng = np.random.default_rng(11)
            verdicts = []
            with ObsServer(monitor=mon) as server:
                for _ in range(4):
                    reqs = [rng.integers(0, 50, 4).astype(np.int64)
                            for _ in range(4)]
                    inc_res = incumbent.serve(reqs)
                    can_res = canary.serve(reqs)
                    shadow = float(np.mean(
                        [recall_at_k(c[0], i[0])
                         for c, i in zip(can_res, inc_res)]))
                    budget.note_eval(inc_ver, {"shadow_recall": 1.0})
                    budget.note_eval(can_ver, {"shadow_recall": shadow})
                    verdicts.append(
                        budget.verdicts.evaluate(can_ver, inc_ver))
                    if verdicts[-1]["verdict"] == ROLLBACK:
                        break
                # the engine seam attributed every request to the
                # version that served it
                code, body = http_get(server.url + "/budgetz")
                hcode, hbody = http_get(server.url + "/healthz")
                # the operator acts; the page clears
                assert budget.verdicts.mark_rolled_back(can_ver)
                gcode, gbody = http_get(server.url + "/healthz")
        finally:
            obs.disable()

        # ROLLBACK within the sample budget, from the warming HOLD
        assert verdicts[0]["verdict"] == HOLD
        assert verdicts[-1]["verdict"] == ROLLBACK
        assert "shadow_recall" in verdicts[-1]["reason"]
        served = sum(v["canary"]["served"] for v in verdicts
                     if v["canary"] is not None)
        assert served <= budget.verdicts.sample_budget

        # the socket view attributes the regression to the poisoned
        # version and only that version
        assert code == 200
        doc = json.loads(body)
        can_row = doc["cohorts"][str(can_ver)]
        inc_row = doc["cohorts"][str(inc_ver)]
        assert can_row["evals"]["shadow_recall"] < 0.5
        assert inc_row["evals"]["shadow_recall"] == 1.0
        assert can_row["served"] == inc_row["served"] > 0
        # the incumbent's error budget is untouched
        assert inc_row["violations"] == 0
        assert inc_row["error_budget_remaining"] == 1.0

        # lineage carries the verdict, then the act
        rec = journal.resolve(can_ver)
        assert rec["verdict"] == ROLLBACK
        assert "shadow_recall" in rec["verdict_reason"]
        assert rec["rolled_back"] is True
        # the incumbent's provenance record carries no rollback stamp
        inc_rec = journal.resolve(inc_ver)
        assert inc_rec is None or inc_rec.get("verdict") != ROLLBACK

        # /healthz: DEGRADED while the ROLLBACK was un-acted-on,
        # green after the rollback landed
        assert json.loads(hbody)["status"] == "degraded"
        assert json.loads(hbody)["checks"]["rollout"]["status"] == \
            "degraded"
        assert json.loads(gbody)["status"] == "ok"
