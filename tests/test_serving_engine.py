"""Serving-engine tests: micro-batching, versioned refresh, bf16 parity.

The engine's contract is MFModel.recommend's, delivered at sustained
throughput: every test here pins engine output against the per-call
surfaces, plus the two properties the per-call path lacks — a bounded
compiled-executable family across mixed request sizes, and catalog
versioning that makes a retrain swap visible to serving.
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.als import ALS, ALSConfig
from large_scale_recommendation_tpu.parallel.mesh import make_block_mesh
from large_scale_recommendation_tpu.serving.engine import ServingEngine


@pytest.fixture(scope="module")
def fitted():
    gen = SyntheticMFGenerator(num_users=60, num_items=41, rank=4,
                               noise=0.05, seed=6)
    train = gen.generate(6000)
    model = ALS(ALSConfig(num_factors=6, lambda_=0.05,
                          iterations=4)).fit(train)
    return model, train


def test_engine_matches_model_recommend(fitted):
    """id-space parity with the per-call path, unknown ids included."""
    model, train = fitted
    mesh = make_block_mesh(4)
    eng = ServingEngine(model, k=6, mesh=mesh, train=train)
    uids = np.array([0, 5, 11, 99999])
    i1, s1, m1 = eng.recommend(uids, return_mask=True)
    i0, s0, m0 = model.recommend(uids, k=6, train=train, mesh=mesh,
                                 return_mask=True)
    np.testing.assert_array_equal(m1, m0)
    np.testing.assert_array_equal(i1, i0)
    np.testing.assert_allclose(s1, s0, rtol=1e-6, atol=1e-7)


def test_serve_packs_requests_and_keeps_per_request_results(fitted):
    """The micro-batcher coalesces small requests into shared buckets;
    each request still gets exactly its own per-call answer."""
    model, train = fitted
    eng = ServingEngine(model, k=5, mesh=make_block_mesh(4), train=train,
                        max_batch=256)
    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, 60, int(rng.integers(1, 50))).astype(np.int64)
            for _ in range(25)]
    results = eng.serve(reqs)
    assert len(results) == len(reqs)
    for r, (ids, scores) in zip(reqs, results):
        ids0, scores0 = model.recommend(r, k=5, train=train)
        np.testing.assert_array_equal(ids, ids0)
        np.testing.assert_allclose(scores, scores0, rtol=1e-6, atol=1e-7)
    # far fewer kernel calls than requests: rows packed into buckets
    assert eng.stats["microbatches"] < len(reqs)
    assert eng.stats["requests"] == len(reqs)


def test_mixed_sizes_compile_bounded_by_bucket_family(fitted):
    """The acceptance pin: across many mixed-size requests the compiled
    executable count is O(#buckets) (the pow2 family), NOT O(#requests)
    — asserted via the jitted step's own compile-cache instrumentation."""
    model, _ = fitted
    # dedicated mesh: the weak-keyed step cache is per-mesh, so this
    # engine's executable count starts from zero
    mesh = make_block_mesh(2)
    eng = ServingEngine(model, k=4, mesh=mesh, max_batch=128)
    rng = np.random.default_rng(1)
    sizes = rng.integers(1, 200, 60)  # 60 requests, ~40 distinct sizes
    for n in sizes:
        eng.recommend(rng.integers(0, 60, int(n)).astype(np.int64))
    # bucket family for max_batch=128, min_bucket=8: {8,16,32,64,128}
    assert eng.bucket_family == (8, 16, 32, 64, 128)
    assert eng.executable_variants <= len(eng.bucket_family), eng.stats
    assert set(eng.stats["buckets"]) <= set(eng.bucket_family)
    assert eng.stats["requests"] == 60


def test_recommend_and_serve_align_past_prequeued_submits(fitted):
    """recommend()/serve() after a dangling submit() return THEIR OWN
    results (review-found regression: flush()[0] returned the
    pre-queued request's answer)."""
    model, _ = fitted
    eng = ServingEngine(model, k=4, mesh=make_block_mesh(2))
    r0 = np.array([1, 2, 3])
    r1 = np.array([7, 8])
    eng.submit(r0)
    ids, scores = eng.recommend(r1)
    ids1, scores1 = model.recommend(r1, k=4)
    assert ids.shape == (2, 4)
    np.testing.assert_array_equal(ids, ids1)

    eng.submit(r0)
    results = eng.serve([r1, r0])
    assert len(results) == 2
    np.testing.assert_array_equal(results[0][0], ids1)


def test_bucket_policy_validation_and_family(fitted):
    """min_bucket flows into the bucket family (review-found: the
    family ignored floors below 8) and invalid policies raise."""
    model, _ = fitted
    eng = ServingEngine(model, k=4, mesh=make_block_mesh(2),
                        min_bucket=4, max_batch=64)
    assert eng.bucket_family == (4, 8, 16, 32, 64)
    eng.recommend(np.arange(3))
    assert set(eng.stats["buckets"]) <= set(eng.bucket_family)
    with pytest.raises(ValueError):
        ServingEngine(model, mesh=make_block_mesh(2), min_bucket=5)
    with pytest.raises(ValueError):
        ServingEngine(model, mesh=make_block_mesh(2), max_batch=100)
    with pytest.raises(ValueError):
        ServingEngine(model, mesh=make_block_mesh(2), min_bucket=32,
                      max_batch=16)


def test_bf16_catalog_parity(fitted):
    """bf16 catalog: identical top-K id sets on a seeded model, scores
    within bf16 tolerance of f32 (f32 accumulation bounds the drift)."""
    model, train = fitted
    mesh = make_block_mesh(4)
    f32 = ServingEngine(model, k=6, mesh=mesh, train=train)
    bf16 = ServingEngine(model, k=6, mesh=mesh, train=train,
                         dtype="bfloat16")
    assert bf16._catalog.dtype == "bfloat16"
    uids = np.arange(60)
    ids32, s32 = f32.recommend(uids)
    ids16, s16 = bf16.recommend(uids)
    for row32, row16 in zip(ids32, ids16):
        assert set(row32.tolist()) == set(row16.tolist())
    np.testing.assert_allclose(s16, s32, rtol=2e-2, atol=2e-2)


def test_stale_catalog_regression_model_path(fitted):
    """Mutating model.U/V then recommend(mesh=...) serves FRESH factors
    (the advisor-flagged stale-cache bug: the per-mesh catalog cache was
    never invalidated)."""
    model, train = fitted
    mesh = make_block_mesh(4)
    uids = np.arange(10)
    before = model.recommend(uids, k=5, mesh=mesh)
    # "retrain": new factor arrays on the SAME model object
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    model.U = jnp.asarray(
        rng.normal(size=model.U.shape).astype(np.float32))
    model.V = jnp.asarray(
        rng.normal(size=model.V.shape).astype(np.float32))
    after = model.recommend(uids, k=5, mesh=mesh)
    fresh = model.recommend(uids, k=5)  # non-mesh path is always fresh
    np.testing.assert_array_equal(after[0], fresh[0])
    np.testing.assert_allclose(after[1], fresh[1], rtol=1e-6, atol=1e-7)
    assert not np.array_equal(before[0], after[0]) or not np.allclose(
        before[1], after[1])


def test_engine_refresh_is_rebind_not_recompile(fitted):
    """refresh() with same-geometry factors: new catalog version, same
    compiled executables (the O(1) retrain-swap contract)."""
    model, _ = fitted
    mesh = make_block_mesh(2)
    eng = ServingEngine(model, k=4, mesh=mesh)
    uids = np.arange(20)
    eng.recommend(uids)
    variants = eng.executable_variants
    v0 = eng.version

    import dataclasses

    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    model2 = dataclasses.replace(
        model,
        U=jnp.asarray(rng.normal(size=model.U.shape).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=model.V.shape).astype(np.float32)))
    assert eng.refresh(model2) != v0
    ids, scores = eng.recommend(uids)
    ids0, scores0 = model2.recommend(uids, k=4)
    np.testing.assert_array_equal(ids, ids0)
    np.testing.assert_allclose(scores, scores0, rtol=1e-6, atol=1e-7)
    assert eng.executable_variants == variants  # zero new compiles


def test_concurrent_recommend_threads_get_their_own_results(fitted):
    """recommend() is submit+flush under ONE lock acquisition: parallel
    callers never drain each other's tickets (review-found regression:
    a racing flush returned [] to the loser and misrouted its result)."""
    import threading

    model, _ = fitted
    eng = ServingEngine(model, k=4, mesh=make_block_mesh(2))
    uid_sets = [np.arange(i, i + 6) for i in range(8)]
    expected = [model.recommend(u, k=4)[0] for u in uid_sets]
    errors = []

    def worker(i):
        try:
            for _ in range(10):
                ids, _ = eng.recommend(uid_sets[i])
                np.testing.assert_array_equal(ids, expected[i])
        except Exception as e:  # surfaced after join
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(uid_sets))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_step_cache_is_lru_bounded():
    """The per-mesh executable cache evicts at the cap — a service
    sweeping many distinct k values cannot accumulate compiled
    executables forever (the bound the old lru_cache(32) provided)."""
    from large_scale_recommendation_tpu.parallel.serving import (
        _STEP_CACHE_ATTR,
        _STEP_CACHE_CAP,
        _mesh_topk_step,
    )

    mesh = make_block_mesh(2)
    for k in range(1, _STEP_CACHE_CAP + 10):
        _mesh_topk_step(mesh, k, k, 64)
    per_mesh = getattr(mesh, _STEP_CACHE_ATTR)
    assert len(per_mesh) == _STEP_CACHE_CAP
    # most-recent keys survive, oldest were evicted
    assert (_STEP_CACHE_CAP + 9, _STEP_CACHE_CAP + 9, 64, False) in per_mesh
    assert (1, 1, 64, False) not in per_mesh


def test_concurrent_refresh_never_tears_a_flush(fitted):
    """A refresh landing from another thread (the AdaptiveMF swap path)
    must not rebind the catalog mid-flush: every served result equals
    EXACTLY one model's answer — never a cross-version mix."""
    import dataclasses
    import threading

    import jax.numpy as jnp

    model, _ = fitted
    rng = np.random.default_rng(5)
    other = dataclasses.replace(
        model,
        U=jnp.asarray(rng.normal(size=model.U.shape).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=model.V.shape).astype(np.float32)))
    mesh = make_block_mesh(2)
    eng = ServingEngine(model, k=4, mesh=mesh, max_batch=64)
    uids = np.arange(30)
    answers = {
        m.recommend(uids, k=4)[0].tobytes(): name
        for m, name in ((model, "a"), (other, "b"))
    }
    stop = threading.Event()

    def flip():
        flip_to = other
        while not stop.is_set():
            eng.refresh(flip_to)
            flip_to = model if flip_to is other else other

    t = threading.Thread(target=flip, daemon=True)
    t.start()
    try:
        for _ in range(30):
            ids, _ = eng.recommend(uids)
            assert ids.tobytes() in answers, "cross-version result"
    finally:
        stop.set()
        t.join()


def test_adaptive_swap_auto_refreshes_engine():
    """AdaptiveMF.serving_engine: the retrain swap refreshes the live
    engine's catalog — serving tracks the adaptive model's swaps with no
    manual choreography."""
    from large_scale_recommendation_tpu.models.adaptive import (
        AdaptiveMF,
        AdaptiveMFConfig,
    )

    gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3,
                               noise=0.05, seed=2)
    adaptive = AdaptiveMF(AdaptiveMFConfig(
        num_factors=4, learning_rate=0.05, minibatch_size=64,
        offline_every=None, offline_algorithm="als",
        offline_iterations=3))
    for _ in range(3):
        adaptive.process(gen.generate(300))
    eng = adaptive.serving_engine(k=5, mesh=make_block_mesh(2))
    v0 = eng.version
    adaptive.trigger_batch_training()  # sync retrain + swap
    assert adaptive.retrain_count == 1
    assert eng.version != v0  # the swap reached the engine
    uids = np.arange(10)
    ids, scores = eng.recommend(uids)
    ids0, scores0 = adaptive.to_model().recommend(uids, k=5)
    np.testing.assert_array_equal(ids, ids0)
    np.testing.assert_allclose(scores, scores0, rtol=1e-6, atol=1e-7)
