"""The unified logical-axis Partitioner (ISSUE 7): rules-table
resolution on 1/8/16-device meshes, sharding equality with the
hand-rolled constructions it replaced, placement/checkpoint wiring, and
the equivalence pins — unified-layer mesh DSGD / mesh ALS / mesh
serving must reproduce the PRE-refactor outputs **bit for bit** on the
same mesh (goldens captured at the hand-rolled-sharding commit by
``tests/data/make_partitioner_golden.py``).
"""

import os
import sys

import numpy as np
import pytest

import jax
from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec as P

from large_scale_recommendation_tpu.parallel.mesh import (
    BLOCK_AXIS,
    make_block_mesh,
    ring_backward,
)
from large_scale_recommendation_tpu.parallel.partitioner import (
    DATA_AXIS,
    DEFAULT_RULES,
    MODEL_AXIS,
    Partitioner,
    as_partitioner,
    make_data_model_mesh,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.data.make_partitioner_golden import (  # noqa: E402
    GOLDEN,
    run_workloads,
)

LOGICAL_AXES = [name for name, _ in DEFAULT_RULES]


class TestRulesTable:
    """Every logical axis must resolve on every mesh shape the stack
    runs on: 1 device (laptop), 8 (the conftest virtual mesh / one TPU
    VM), 16 (pod-shaped — abstract here; scripts/pod_dryrun.py resolves
    the same table over 16 REAL virtual devices and test_pod_scale pins
    its JSON contract)."""

    @pytest.mark.parametrize("n_dev", [1, 4, 8])
    def test_all_axes_resolve_on_real_meshes(self, n_dev):
        for part in (Partitioner(num_devices=n_dev),
                     Partitioner(mesh=make_block_mesh(n_dev))):
            assert part.num_blocks == n_dev
            for name in LOGICAL_AXES:
                part.spec(name)       # must not raise
                part.sharding(name)   # must build a NamedSharding
            assert part.spec("users", "rank") == part.spec("items", "rank")

    def test_all_axes_resolve_on_16_device_abstract_mesh(self):
        part = Partitioner(mesh=AbstractMesh(((DATA_AXIS, 16),
                                              (MODEL_AXIS, 1))))
        assert part.num_blocks == 16
        for name in LOGICAL_AXES:
            part.spec(name)
        assert part.spec("users", "rank") == P(DATA_AXIS, MODEL_AXIS)
        assert part.spec("ratings") == P(DATA_AXIS)
        assert part.spec("queries") == P(None)
        assert len(part.ring_backward()) == 16

    def test_data_model_mesh_shape(self):
        part = Partitioner(num_devices=8)
        assert tuple(part.mesh.axis_names) == (DATA_AXIS, MODEL_AXIS)
        assert dict(part.mesh.shape) == {DATA_AXIS: 8, MODEL_AXIS: 1}
        assert part.data_axis == DATA_AXIS
        assert part.model_axis == MODEL_AXIS
        assert part.model_parallel == 1

    def test_legacy_blocks_mesh_adopts_its_axis_as_data(self):
        mesh = make_block_mesh(4)
        part = Partitioner(mesh=mesh)
        assert part.data_axis == BLOCK_AXIS
        assert part.model_axis is None
        # 'rank' maps to the (absent) model axis -> unsharded dim
        assert part.spec("users", "rank") == P(BLOCK_AXIS, None)

    def test_unknown_logical_axis_raises(self):
        part = Partitioner(num_devices=4)
        with pytest.raises(KeyError, match="unknown logical axis"):
            part.spec("wombats")

    def test_ring_matches_legacy_helper(self):
        part = Partitioner(num_devices=8)
        assert list(part.ring_backward()) == ring_backward(8)

    def test_model_parallel_guard(self):
        part = Partitioner(mesh=AbstractMesh(((DATA_AXIS, 4),
                                              (MODEL_AXIS, 2))))
        assert part.model_parallel == 2
        with pytest.raises(NotImplementedError, match="rank"):
            part.require_no_model_parallel("mesh DSGD")

    def test_model_parallel_must_divide_devices(self):
        with pytest.raises(ValueError, match="does not divide"):
            make_data_model_mesh(num_devices=8, model_parallel=3)


class TestShardingEquality:
    """The partitioner must hand back EXACTLY the shardings the
    hand-rolled code constructed — equality of layouts, not just of
    results."""

    def test_matches_hand_rolled_on_legacy_mesh(self):
        mesh = make_block_mesh(4)
        part = Partitioner(mesh=mesh)
        hand = NamedSharding(mesh, P(BLOCK_AXIS))
        assert part.sharding("users", "rank").is_equivalent_to(hand, 2)
        assert part.sharding("items", "rank").is_equivalent_to(hand, 2)
        assert part.sharding("ratings").is_equivalent_to(hand, 3)
        assert part.sharding("users").is_equivalent_to(hand, 1)
        assert part.replicated().is_equivalent_to(
            NamedSharding(mesh, P()), 2)

    def test_size1_model_axis_is_layout_noop(self):
        part = Partitioner(num_devices=4)
        flat = NamedSharding(part.mesh, P(DATA_AXIS))
        assert part.sharding("users", "rank").is_equivalent_to(flat, 2)

    def test_as_partitioner_identity_and_hash(self):
        mesh = make_block_mesh(4)
        p1, p2 = as_partitioner(mesh), as_partitioner(mesh)
        assert p1 == p2 and hash(p1) == hash(p2)
        assert as_partitioner(p1) is p1
        assert p1 != Partitioner(mesh=make_block_mesh(8))


class TestPlacement:
    def test_shard_places_with_rules_sharding(self):
        part = Partitioner(num_devices=4)
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        arr = part.shard(x, "users", "rank")
        assert arr.sharding.is_equivalent_to(
            part.sharding("users", "rank"), 2)
        np.testing.assert_array_equal(np.asarray(arr), x)

    def test_place_single_process_equals_shard(self):
        part = Partitioner(num_devices=4)
        x = np.arange(16, dtype=np.float32)
        np.testing.assert_array_equal(
            np.asarray(part.place(x, "ratings")),
            np.asarray(part.shard(x, "ratings")))

    def test_make_global_array_roundtrips(self):
        part = Partitioner(num_devices=4)
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        arr = part.make_global_array(x, "items", "rank")
        np.testing.assert_array_equal(np.asarray(arr), x)
        assert arr.sharding.is_equivalent_to(
            part.sharding("items", "rank"), 2)

    def test_constrain_under_jit(self):
        part = Partitioner(num_devices=4)
        x = np.arange(16, dtype=np.float32).reshape(8, 2)

        @jax.jit
        def f(a):
            return part.constrain(a * 2.0, "users", "rank")

        out = f(x)
        np.testing.assert_allclose(np.asarray(out), x * 2.0)
        assert out.sharding.is_equivalent_to(
            part.sharding("users", "rank"), 2)


class TestCheckpointWiring:
    """restore_segment_state_sharded(partitioner=...) re-shards via the
    rules table — the resume path training actually runs under."""

    def test_partitioner_restore_roundtrip(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        part = Partitioner(num_devices=4)
        U = part.shard(np.arange(32, dtype=np.float32).reshape(8, 4),
                       "users", "rank")
        V = part.shard(-np.arange(16, dtype=np.float32).reshape(8, 2),
                       "items", "rank")
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(3, {"U": U, "V": V}, {"kind": "t"})
        U2, V2, done = restore_segment_state_sharded(
            mgr, "t", np.zeros((8, 4), np.float32),
            np.zeros((8, 2), np.float32), partitioner=part)
        assert done == 3
        np.testing.assert_array_equal(np.asarray(U2), np.asarray(U))
        np.testing.assert_array_equal(np.asarray(V2), np.asarray(V))
        assert U2.sharding.is_equivalent_to(
            part.sharding("users", "rank"), 2)

    def test_sharding_and_partitioner_are_exclusive(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
            restore_segment_state_sharded,
        )

        part = Partitioner(num_devices=4)
        mgr = ShardedCheckpointManager(str(tmp_path))
        with pytest.raises(ValueError, match="not both"):
            restore_segment_state_sharded(
                mgr, "t", np.zeros((8, 2)), np.zeros((8, 2)),
                sharding=part.replicated(), partitioner=part)


@pytest.fixture(scope="module")
def golden():
    return dict(np.load(GOLDEN))


@pytest.fixture(scope="module")
def unified_outputs():
    """The pinned workloads run over BOTH mesh spellings the unified
    layer accepts (module-scoped: each run trains mesh DSGD twice, mesh
    ALS once and serves once)."""
    return {
        "legacy": run_workloads(make_block_mesh),
        "partitioner": run_workloads(
            lambda n: Partitioner(num_devices=n)),
    }


class TestPreRefactorEquivalence:
    """The acceptance pins: the unified layer reproduces the
    hand-rolled-sharding outputs bit for bit — same mesh (the legacy 1D
    ring) AND the partitioner's own ('data', 'model') mesh."""

    @pytest.mark.parametrize("spelling", ["legacy", "partitioner"])
    @pytest.mark.parametrize("key", [
        "dsgd_U", "dsgd_V",            # mesh DSGD, host-blocked
        "dsgd_dev_U", "dsgd_dev_V",    # mesh DSGD, device-blocked
        "als_U", "als_V",              # mesh ALS
        "serve_rows", "serve_scores",  # mesh serving
    ])
    def test_bit_for_bit_vs_prerefactor_golden(self, golden,
                                               unified_outputs,
                                               spelling, key):
        np.testing.assert_array_equal(
            unified_outputs[spelling][key], golden[key],
            err_msg=f"{key} over the {spelling} mesh diverged from the "
                    "pre-refactor hand-rolled-sharding output")

    def test_both_spellings_agree_bitwise(self, unified_outputs):
        for key, v in unified_outputs["legacy"].items():
            np.testing.assert_array_equal(
                v, unified_outputs["partitioner"][key], err_msg=key)


class TestSolverSurfaces:
    def test_serving_engine_accepts_partitioner(self):
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.als import ALS, ALSConfig
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )

        train = SyntheticMFGenerator(num_users=40, num_items=30, rank=4,
                                     noise=0.05, seed=5).generate(3000)
        model = ALS(ALSConfig(num_factors=4, lambda_=0.05,
                              iterations=3)).fit(train)
        part = Partitioner(num_devices=4)
        eng = ServingEngine(model, k=5, mesh=part, max_batch=16,
                            min_bucket=4)
        ids_e, scores_e = eng.recommend(np.arange(8))
        ids_m, scores_m = model.recommend(np.arange(8), k=5)
        np.testing.assert_allclose(scores_e, scores_m, rtol=1e-5)
        np.testing.assert_array_equal(ids_e, ids_m)

    def test_model_recommend_accepts_partitioner(self):
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.als import ALS, ALSConfig

        train = SyntheticMFGenerator(num_users=30, num_items=25, rank=4,
                                     noise=0.05, seed=6).generate(2000)
        model = ALS(ALSConfig(num_factors=4, lambda_=0.05,
                              iterations=3)).fit(train)
        part = Partitioner(num_devices=4)
        i1, s1 = model.recommend(np.arange(6), k=4, mesh=part)
        i2, s2 = model.recommend(np.arange(6), k=4)
        np.testing.assert_allclose(s1, s2, rtol=1e-5)
        np.testing.assert_array_equal(i1, i2)
        # the catalog cache keys on the interned Mesh: a raw-mesh caller
        # shares the partitioner caller's build
        assert part.mesh in model.__dict__["_serving_catalogs"]

    def test_package_public_surface(self):
        import large_scale_recommendation_tpu.parallel as par

        for name in ("Partitioner", "as_partitioner", "DEFAULT_RULES",
                     "DistributedConfig", "initialize_distributed",
                     "host_rating_shard", "make_global_array",
                     "global_device_blocked", "make_block_mesh",
                     "MeshDSGD", "MeshALS", "shard_catalog",
                     "mesh_top_k_recommend"):
            assert getattr(par, name) is not None
        assert "Partitioner" in par.__all__
        with pytest.raises(AttributeError):
            par.no_such_symbol


class TestShardingFunnel:
    """Pins for the ISSUE-15 sharding-funnel fixes: the legacy surfaces
    (``mesh.make_block_mesh``/``mesh.replicated``/
    ``distributed.make_global_array``) now construct THROUGH
    ``parallel/partitioner.py`` (graftlint rule ``sharding-funnel``) and
    must keep producing the exact pre-funnel objects."""

    def test_make_block_mesh_delegates_unchanged(self):
        from large_scale_recommendation_tpu.parallel.mesh import (
            select_devices,
        )
        from large_scale_recommendation_tpu.parallel.partitioner import (
            make_legacy_block_mesh,
        )

        mesh = make_block_mesh(4)
        assert mesh.axis_names == (BLOCK_AXIS,)
        assert list(mesh.devices.flat) == select_devices(4)
        assert mesh == make_legacy_block_mesh(4)

    def test_replicated_equals_hand_rolled(self):
        from large_scale_recommendation_tpu.parallel.mesh import (
            replicated,
        )

        mesh = make_block_mesh(4)
        assert replicated(mesh) == NamedSharding(mesh, P())
        mesh2 = make_data_model_mesh(4)
        assert replicated(mesh2) == NamedSharding(mesh2, P())

    def test_replicated_works_on_any_mesh(self):
        """The compatibility surface must accept meshes the rules table
        cannot adopt (no inferable data axis) — an empty spec is valid
        on every mesh, exactly as before the funnel refactor."""
        from jax.sharding import Mesh

        from large_scale_recommendation_tpu.parallel.mesh import (
            replicated,
            select_devices,
        )

        weird = Mesh(np.asarray(select_devices(4)).reshape(2, 2),
                     ("x", "y"))
        assert replicated(weird) == NamedSharding(weird, P())

    def test_raw_sharding_equals_hand_rolled(self):
        from large_scale_recommendation_tpu.parallel.partitioner import (
            raw_sharding,
        )

        mesh = make_block_mesh(4)
        spec = P(BLOCK_AXIS)
        assert raw_sharding(mesh, spec) == NamedSharding(mesh, spec)

    def test_make_global_array_routes_through_funnel(self):
        from large_scale_recommendation_tpu.parallel.distributed import (
            make_global_array,
        )

        mesh = make_block_mesh(4)
        data = np.arange(32, dtype=np.float32).reshape(8, 4)
        arr = make_global_array(data, mesh, P(BLOCK_AXIS))
        assert arr.sharding == NamedSharding(mesh, P(BLOCK_AXIS))
        np.testing.assert_array_equal(np.asarray(arr), data)

    def test_package_is_funnel_clean(self):
        """The mechanical form of the invariant: graftlint's
        sharding-funnel rule finds nothing in the production package."""
        from tools.graftlint import run_lint

        res = run_lint(rules=["sharding-funnel"])
        assert res.findings == [], [f.path for f in res.findings]


@pytest.mark.slow
class TestTwoProcessSmoke:
    """The 2-process jax.distributed local-cluster smoke (satellite):
    subprocesses on CPU via the pod_dryrun harness function; SKIPPED
    (not failed) where the jaxlib lacks cross-process CPU collectives."""

    def test_two_process_pass(self):
        from scripts.pod_dryrun import run_two_process_pass

        out = run_two_process_pass(timeout_s=420.0)
        if out.get("skipped"):
            pytest.skip(out.get("reason", "2-process pass unsupported"))
        assert out.get("ok"), out
        assert out["n_processes"] == 2
