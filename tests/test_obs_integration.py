"""End-to-end observability: the instrumented runtime tiers populate the
documented metric names, the null path does zero registry/tracer work,
and one demo-shaped run produces all three artifacts (Prometheus text,
metrics JSONL, Chrome trace) with a schema-valid, compile/execute-
distinguishable trace.
"""

import json

import numpy as np
import pytest

from large_scale_recommendation_tpu import obs
from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.online import (
    OnlineMF,
    OnlineMFConfig,
)
from large_scale_recommendation_tpu.obs.registry import (
    NULL_INSTRUMENT,
    get_registry,
    set_registry,
)
from large_scale_recommendation_tpu.obs.trace import (
    get_tracer,
    set_tracer,
    validate_chrome_trace,
)
from large_scale_recommendation_tpu.serving.engine import ServingEngine
from large_scale_recommendation_tpu.streams.driver import (
    StreamingDriver,
    StreamingDriverConfig,
)
from large_scale_recommendation_tpu.streams.log import EventLog


@pytest.fixture
def live_obs():
    """A fresh registry+tracer installed for the test, with whatever was
    installed before (usually the nulls) restored after."""
    prev_r, prev_t = get_registry(), get_tracer()
    reg, tracer = obs.enable()
    yield reg, tracer
    set_registry(prev_r)
    set_tracer(prev_t)


# null_obs comes from tests/conftest.py: ONE copy of the full-layer
# save/disable/restore-and-restart invariant, shared by every obs file


def _tiny_model(num_users=300, num_items=128, rank=8, seed=0):
    import jax.numpy as jnp

    from large_scale_recommendation_tpu.data.blocking import flat_index
    from large_scale_recommendation_tpu.models.mf import MFModel

    rng = np.random.default_rng(seed)
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, rank)).astype(np.float32)),
        V=jnp.asarray(rng.normal(size=(num_items, rank)).astype(np.float32)),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)),
    )


def _fill_log(log, n_batches=3, batch=400, seed=0):
    gen = SyntheticMFGenerator(num_users=200, num_items=64, rank=4,
                               seed=seed)
    for _ in range(n_batches):
        ru, ri, rv, _ = gen.generate(batch).to_numpy()
        log.append_arrays(0, ru, ri, rv)
    return n_batches * batch


class TestServingEngineMetrics:
    # the documented serving metric catalog (docs/OBSERVABILITY.md) —
    # the end-to-end pin that instrumentation stays wired through the
    # engine's submit/flush/refresh paths
    EXPECTED = {
        "serving_queue_wait_s", "serving_batch_assembly_s",
        "serving_flush_s", "serving_score_s", "serving_bucket_occupancy",
        "serving_requests_total", "serving_rows_total",
        "serving_microbatches_total", "serving_catalog_swaps_total",
        "serving_catalog_version",
    }

    def test_serve_populates_expected_names(self, live_obs):
        reg, _ = live_obs
        engine = ServingEngine(_tiny_model(), k=5, max_batch=64)
        rng = np.random.default_rng(1)
        engine.serve([rng.integers(0, 300, 12).astype(np.int64)
                      for _ in range(10)])
        missing = self.EXPECTED - reg.names()
        assert not missing, f"unpopulated metrics: {missing}"
        assert reg.counter("serving_requests_total").value == 10
        assert reg.counter("serving_rows_total").value == 120
        assert reg.histogram("serving_queue_wait_s").count == 10
        # per-pow2-bucket labels on the score histograms
        buckets = {dict(h.labels)["bucket"]
                   for h in reg.find("serving_score_s")}
        assert buckets  # at least one bucket exercised
        assert all(int(b) & (int(b) - 1) == 0 for b in buckets)

    def test_refresh_counts_catalog_swap_with_version_label(self,
                                                            live_obs):
        reg, _ = live_obs
        engine = ServingEngine(_tiny_model(), k=5, max_batch=64)
        v0 = engine.version
        v1 = engine.refresh(_tiny_model(seed=9))
        assert v1 != v0
        versions = {dict(c.labels)["version"]
                    for c in reg.find("serving_catalog_swaps_total")}
        assert {str(v0), str(v1)} <= versions
        assert reg.gauge("serving_catalog_version").value == v1


class TestStreamingDriverMetrics:
    EXPECTED = {
        "streams_batches_total", "streams_records_total",
        "streams_checkpoint_s", "streams_lag_records",
        "online_batch_s", "online_batches_total", "online_ratings_total",
    }

    def test_run_populates_expected_names(self, live_obs, tmp_path):
        reg, _ = live_obs
        log = EventLog(str(tmp_path / "log"))
        n = _fill_log(log)
        model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
        driver = StreamingDriver(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400))
        applied = driver.run()
        tel = driver.telemetry()  # publishes the lag + queue gauges
        assert tel["lag_records"] == 0
        missing = self.EXPECTED - reg.names()
        assert not missing, f"unpopulated metrics: {missing}"
        part = {"partition": "0"}
        (batches,) = [c for c in reg.find("streams_batches_total")
                      if dict(c.labels) == part]
        assert batches.value == applied
        (records,) = [c for c in reg.find("streams_records_total")
                      if dict(c.labels) == part]
        assert records.value == n
        assert reg.histogram("streams_checkpoint_s",
                             partition="0").count == applied
        (lag,) = [g for g in reg.find("streams_lag_records")
                  if dict(g.labels) == part]
        assert lag.value == 0
        # queue-stat gauges mirrored from IngestStats via telemetry()
        assert "streams_queue_enqueued_records" in reg.names()


class TestNullPathZeroWork:
    def test_engine_binds_null_singletons(self, null_obs):
        """The disabled-hot-path pin: with the null layer installed the
        engine's instrument handles ARE the shared no-op singletons, the
        obs gate is off (no clock reads, no stamp list), and nothing is
        recorded anywhere."""
        engine = ServingEngine(_tiny_model(), k=5, max_batch=64)
        assert engine._obs_on is False
        assert engine._m_flush is NULL_INSTRUMENT
        assert engine._m_qwait is NULL_INSTRUMENT
        assert engine._m_requests is NULL_INSTRUMENT
        assert not engine._trace.enabled
        rng = np.random.default_rng(2)
        out = engine.serve([rng.integers(0, 300, 8).astype(np.int64)
                            for _ in range(5)])
        assert len(out) == 5
        assert engine._pending_t == []  # no queue-wait stamps kept
        assert null_obs.snapshot()["metrics"] == []
        assert null_obs.to_prometheus() == ""

    def test_tiered_store_binds_null(self, null_obs):
        """The STORE plane extension of the zero-cost pin: with the
        null layer installed the tiered store's instruments ARE the
        shared no-op singletons, `_obs_on` is off (no per-acquire gauge
        writes), and a full acquire/release/evict cycle records
        nothing anywhere."""
        from large_scale_recommendation_tpu.core.initializers import (
            PseudoRandomFactorInitializer,
        )
        from large_scale_recommendation_tpu.obs.store import set_store
        from large_scale_recommendation_tpu.store import (
            TieredFactorStore,
        )

        store = TieredFactorStore(PseudoRandomFactorInitializer(4),
                                  capacity=32, slot_capacity=8)
        try:
            assert store._obs_on is False
            assert store._m_hit_rate is NULL_INSTRUMENT
            assert store._m_wait is NULL_INSTRUMENT
            assert store._m_evictions is NULL_INSTRUMENT
            assert store._m_host_bytes is NULL_INSTRUMENT
            for lo in (0, 8):  # second window evicts the first
                rows = store.acquire_rows(np.arange(lo, lo + 8))
                store.release_rows(rows)
            assert store.stats.evictions > 0  # host counters still on
            assert null_obs.names() == set()
            assert null_obs.snapshot()["metrics"] == []
        finally:
            set_store(None)

    def test_driver_and_online_bind_null(self, null_obs, tmp_path):
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, n_batches=1)
        model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
        driver = StreamingDriver(
            model, log, str(tmp_path / "ckpt"),
            config=StreamingDriverConfig(batch_records=400))
        assert driver._obs_on is False
        assert driver._m_ckpt is NULL_INSTRUMENT
        assert model._obs_on is False
        driver.run()
        assert driver.telemetry()["lag_records"] == 0
        assert null_obs.names() == set()

    def test_flight_recorder_and_events_default_off_everywhere(
            self, null_obs, tmp_path):
        """The flight-recorder extension of the zero-cost pin: with
        nothing installed, get_events()/get_recorder() are None (not
        null objects), every emitting component binds that None — one
        pointer test per hook — and no sampler thread, journal ring, or
        bundle machinery exists anywhere."""
        from large_scale_recommendation_tpu.obs.events import (
            get_events,
            set_events,
        )
        from large_scale_recommendation_tpu.obs.recorder import (
            get_recorder,
            set_recorder,
        )

        # force the true disabled state (an OBS_OUT session conftest may
        # have a journal/recorder installed for the whole suite)
        prev_j, prev_r = get_events(), get_recorder()
        set_events(None)
        set_recorder(None)
        try:
            self._assert_null_everywhere(null_obs, tmp_path)
        finally:
            set_events(prev_j)
            set_recorder(prev_r)

    def _assert_null_everywhere(self, null_obs, tmp_path):
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )
        from large_scale_recommendation_tpu.models.dsgd import DSGD
        from large_scale_recommendation_tpu.obs.events import get_events
        from large_scale_recommendation_tpu.obs.health import (
            TrainingWatchdog,
        )
        from large_scale_recommendation_tpu.obs.recorder import (
            get_recorder,
        )
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )
        from large_scale_recommendation_tpu.streams.sources import (
            IngestQueue,
        )

        assert get_events() is None
        assert get_recorder() is None
        engine = ServingEngine(_tiny_model(), k=3, max_batch=32)
        assert engine._events is None
        model = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=64))
        assert model._events is None
        assert DSGD()._events is None
        assert AdaptiveMF(AdaptiveMFConfig(num_factors=4))._events is None
        assert IngestQueue()._events is None
        log = EventLog(str(tmp_path / "log"))
        assert log._parts[0]._events is None
        driver = StreamingDriver(model, log, str(tmp_path / "ckpt"))
        assert driver._events is None
        # the uninstrumented hot paths still run clean end to end,
        # recording nothing anywhere
        _fill_log(log, n_batches=1)
        driver.run()
        wd = TrainingWatchdog(policy="observe")
        wd.observe_loss(float("nan"))  # trip: no journal, no bundle
        assert wd.tripped and wd.last_bundle is None
        assert null_obs.names() == set()

    def test_model_plane_default_off_everywhere(self, null_obs,
                                                tmp_path):
        """The ISSUE-10 extension of the zero-cost pin: with nothing
        enabled, get_lineage() is None and every stamping/joining site
        binds that None — the engine's swap/flush hooks, the driver's
        ingest watermark, the adaptive install — and a driver built
        without an inspector/evaluator carries None hooks: one pointer
        test per batch, no reservoir, no window deques, no journal."""
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )
        from large_scale_recommendation_tpu.obs.lineage import (
            get_lineage,
            set_lineage,
        )
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
        )

        prev = get_lineage()
        set_lineage(None)  # an OBS_OUT session runs one suite-wide
        try:
            assert get_lineage() is None
            engine = ServingEngine(_tiny_model(), k=3, max_batch=32)
            assert engine._lineage is None
            model = OnlineMF(OnlineMFConfig(num_factors=4,
                                            minibatch_size=64))
            log = EventLog(str(tmp_path / "log"))
            driver = StreamingDriver(model, log, str(tmp_path / "ckpt"))
            assert driver._lineage is None
            assert driver.inspector is None
            assert driver.evaluator is None
            adaptive = AdaptiveMF(AdaptiveMFConfig(num_factors=4))
            assert adaptive._lineage is None
            # the offline trainers' quality hook defaults off too
            from large_scale_recommendation_tpu.models.als import ALS
            from large_scale_recommendation_tpu.models.dsgd import DSGD

            assert DSGD().evaluator is None
            assert ALS().evaluator is None
            # the whole null stream path still runs clean, recording
            # nothing anywhere
            _fill_log(log, n_batches=1)
            driver.serving_engine(k=3, max_batch=32)
            driver.run()
            driver.refresh_serving()
            assert null_obs.names() == set()
        finally:
            set_lineage(prev)

    def test_disttrace_default_off_everywhere(self, null_obs, tmp_path):
        """The ISSUE-12 extension of the zero-cost pin: with nothing
        enabled, get_disttrace() is None and every stamping site binds
        that None — the WAL append, the driver marks, the engine
        serve-note, the adaptive swap-note — and the default-off
        tracer means NO context stamps anywhere: batches carry
        ctx=None, capture_context() is None, and no wal/ingest spans,
        clock reads or registry names appear."""
        from large_scale_recommendation_tpu.models.adaptive import (
            AdaptiveMF,
            AdaptiveMFConfig,
        )
        from large_scale_recommendation_tpu.obs.disttrace import (
            get_disttrace,
            set_disttrace,
        )
        from large_scale_recommendation_tpu.obs.trace import get_tracer
        from large_scale_recommendation_tpu.serving.engine import (
            ServingEngine,
        )
        from large_scale_recommendation_tpu.streams.sources import (
            LogTailSource,
        )

        prev = get_disttrace()
        set_disttrace(None)  # an OBS_OUT session runs one suite-wide
        try:
            assert get_disttrace() is None
            assert get_tracer().capture_context() is None
            log = EventLog(str(tmp_path / "log"))
            assert log._disttrace is None
            _fill_log(log, n_batches=1)
            # default-off tracer ⇒ no per-batch context mints
            for batch in LogTailSource(log, batch_records=128):
                assert batch.ctx is None
                break
            engine = ServingEngine(_tiny_model(), k=3, max_batch=32)
            assert engine._disttrace is None
            model = OnlineMF(OnlineMFConfig(num_factors=4,
                                            minibatch_size=64))
            driver = StreamingDriver(model, log, str(tmp_path / "ckpt"))
            assert driver._disttrace is None
            assert AdaptiveMF(
                AdaptiveMFConfig(num_factors=4))._disttrace is None
            # the whole null stream path still runs clean end to end
            eng = driver.serving_engine(k=3, max_batch=32)
            driver.run()
            driver.refresh_serving()
            eng.recommend(np.arange(3, dtype=np.int64))
            assert null_obs.names() == set()
        finally:
            set_disttrace(prev)

    def test_introspection_default_off_and_funnel_unpatched(
            self, null_obs):
        """The ISSUE-9 extension of the zero-cost pin: with nothing
        enabled, get_introspector() is None (producer hooks bind that
        None — TrainSegmentTimer.finish, the bundle writer, the
        /rooflinez route) and the jax compile funnel is the PRISTINE
        function — no wrapper, no per-compile work of any kind. An
        OBS_OUT session patches suite-wide, so the installed hook (if
        any) is parked for the duration of the check and restored."""
        import jax._src.compiler as compiler

        from large_scale_recommendation_tpu.obs.introspect import (
            get_introspector,
        )
        from large_scale_recommendation_tpu.obs.server import ObsServer

        assert get_introspector() is None  # null_obs cleared it
        suite_ins = None
        current = compiler.compile_or_get_cached
        if hasattr(current, "__lsr_introspector__"):
            suite_ins = current.__lsr_introspector__
            suite_ins.uninstall()
        try:
            assert not hasattr(compiler.compile_or_get_cached,
                               "__lsr_introspector__")
            # the disabled-route answer carries no introspector either
            assert ObsServer().rooflinez()["rows"] == []
        finally:
            if suite_ins is not None:
                suite_ins.install()


class TestLegacyShimMigration:
    """utils.metrics helpers keep their surfaces but mirror into the
    registry when one is live (satellite: the pre-obs timing logic is
    deprecated in favor of the registry)."""

    def test_step_timer_mirrors_histogram(self, live_obs):
        reg, _ = live_obs
        from large_scale_recommendation_tpu.utils import metrics as M

        t = M.StepTimer("sweep")
        with t.time():
            pass
        assert t.count == 1  # original surface intact
        assert reg.histogram("step_timer_s", name="sweep").count == 1

    def test_throughput_meter_mirrors_counters(self, live_obs):
        reg, _ = live_obs
        from large_scale_recommendation_tpu.utils import metrics as M

        m = M.ThroughputMeter(name="serve")
        m.record(1000, 2.0)
        assert m.rate == 500.0
        assert reg.counter("meter_elements_total", name="serve").value \
            == 1000
        assert reg.counter("meter_seconds_total", name="serve").value \
            == 2.0

    def test_ingest_stats_publish(self, live_obs):
        reg, _ = live_obs
        from large_scale_recommendation_tpu.utils.metrics import IngestStats

        s = IngestStats(enqueued_records=42, depth=3)
        s.publish(partition="1")
        assert reg.gauge("ingest_enqueued_records",
                         partition="1").value == 42
        assert reg.gauge("ingest_depth", partition="1").value == 3
        assert s.snapshot()["enqueued_records"] == 42  # surface intact

    def test_metrics_log_counts_events(self, live_obs):
        reg, _ = live_obs
        from large_scale_recommendation_tpu.utils.metrics import MetricsLog

        log = MetricsLog(log_to=None)
        log.log("epoch", rmse=0.1)
        log.log("epoch", rmse=0.05)
        assert len(log.of("epoch")) == 2
        assert reg.counter("metrics_log_events_total",
                           event="epoch").value == 2

    def test_shims_are_noop_when_disabled(self, null_obs):
        from large_scale_recommendation_tpu.utils import metrics as M

        t = M.StepTimer("x")
        with t.time():
            pass
        m = M.ThroughputMeter()
        m.record(10, 1.0)
        M.IngestStats().publish()
        assert null_obs.names() == set()


class TestEndToEndArtifacts:
    def test_train_serve_stream_dump_all_three_artifacts(self, live_obs,
                                                         tmp_path):
        """The acceptance demo in test form: one run produces a
        Prometheus snapshot, a metrics JSONL, and a Chrome trace whose
        schema validates — with compile and execute spans
        distinguishable."""
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )

        reg, tracer = live_obs
        # train: 2 one-iteration segments → the first carries the
        # compile (span cat "compile"), the second is steady ("execute")
        gen = SyntheticMFGenerator(num_users=120, num_items=60, rank=4,
                                   seed=3)
        ratings = gen.generate(4000)
        solver = DSGD(DSGDConfig(num_factors=8, iterations=2,
                                 minibatch_size=512, num_blocks=2,
                                 learning_rate=0.05))
        model = solver.fit(ratings, checkpoint_every=1)
        assert reg.histogram("train_segment_s", model="dsgd").count == 2
        steady = reg.gauge("train_throughput_ratings_per_s",
                           model="dsgd", phase="steady")
        assert steady.value > 0

        # serve + stream
        engine = ServingEngine(model, k=5, max_batch=64)
        rng = np.random.default_rng(4)
        engine.serve([rng.integers(0, 120, 9).astype(np.int64)
                      for _ in range(6)])
        log = EventLog(str(tmp_path / "log"))
        _fill_log(log, n_batches=2)
        om = OnlineMF(OnlineMFConfig(num_factors=4, minibatch_size=128))
        StreamingDriver(om, log, str(tmp_path / "ckpt"),
                        config=StreamingDriverConfig(
                            batch_records=400)).run()

        # artifact 1: Prometheus text
        prom = reg.to_prometheus()
        assert "serving_flush_s" in prom
        assert "train_segment_s" in prom
        assert "streams_batches_total" in prom

        # artifact 2: metrics JSONL
        jsonl = str(tmp_path / "metrics.jsonl")
        reg.append_jsonl(jsonl)
        snap = json.loads(open(jsonl).read().splitlines()[-1])
        names = {m["name"] for m in snap["metrics"]}
        assert {"serving_flush_s", "train_segment_s",
                "online_batch_s"} <= names

        # artifact 3: Chrome trace, schema-validated from disk
        trace_path = str(tmp_path / "trace.json")
        tracer.to_chrome_trace(trace_path)
        doc = json.load(open(trace_path))
        events = validate_chrome_trace(doc)
        cats = {e["cat"] for e in events}
        assert "compile" in cats and "execute" in cats, cats
        train_spans = [e for e in events if e["name"] == "train/dsgd"]
        assert [e["cat"] for e in train_spans] == ["compile", "execute"]
