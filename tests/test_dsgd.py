"""Single-device DSGD: oracle parity + convergence integration tests.

Oracle: a NumPy transcription of the reference inner loop
(DSGDforMF.scala:398-417) run in the same minibatch grouping; convergence:
planted low-rank model must reach low RMSE (SURVEY §4 test plan).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.core.updaters import (
    SGDUpdater,
    RegularizedSGDUpdater,
)
from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
from large_scale_recommendation_tpu.ops import sgd as sgd_ops


class TestKernelOracle:
    def test_minibatch_update_matches_numpy(self):
        rng = np.random.default_rng(0)
        n_rows, k, b = 20, 6, 8
        U = rng.normal(size=(n_rows, k)).astype(np.float32)
        V = rng.normal(size=(n_rows, k)).astype(np.float32)
        ur = rng.integers(0, n_rows, b)
        ir = rng.integers(0, n_rows, b)
        vals = rng.normal(size=b).astype(np.float32)
        w = np.ones(b, dtype=np.float32)
        omega = np.ones(n_rows, dtype=np.float32) * 2.0
        upd = RegularizedSGDUpdater(learning_rate=0.05, lambda_=0.3,
                                    schedule=lambda lr, t: lr)

        Un, Vn = sgd_ops.sgd_minibatch_update(
            jnp.array(U), jnp.array(V), jnp.array(ur), jnp.array(ir),
            jnp.array(vals), jnp.array(w), jnp.array(omega), jnp.array(omega),
            upd, 1, collision="sum")

        # NumPy oracle: additive deltas from OLD factors, accumulated
        eU, eV = U.copy(), V.copy()
        for i in range(b):
            u, v = U[ur[i]], V[ir[i]]
            e = vals[i] - u @ v
            eU[ur[i]] += -0.05 * (0.3 / 2.0 * u - e * v)
            eV[ir[i]] += -0.05 * (0.3 / 2.0 * v - e * u)
        np.testing.assert_allclose(np.asarray(Un), eU, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(Vn), eV, rtol=1e-4, atol=1e-5)

    def test_padding_rows_untouched(self):
        """Weight-0 entries must leave factors bit-identical."""
        rng = np.random.default_rng(1)
        U = rng.normal(size=(10, 4)).astype(np.float32)
        V = rng.normal(size=(10, 4)).astype(np.float32)
        ur = np.zeros(8, dtype=np.int32)  # padding points at row 0
        w = np.zeros(8, dtype=np.float32)
        upd = RegularizedSGDUpdater(0.1, 1.0)
        Un, Vn = sgd_ops.sgd_minibatch_update(
            jnp.array(U), jnp.array(V), jnp.array(ur), jnp.array(ur),
            jnp.zeros(8, jnp.float32), jnp.array(w),
            jnp.ones(10), jnp.ones(10), upd, 1)
        np.testing.assert_array_equal(np.asarray(Un), U)
        np.testing.assert_array_equal(np.asarray(Vn), V)

    def test_batchsize1_matches_sequential_reference_semantics(self):
        """minibatch=1 chains updates exactly like the reference's
        sequential loop (DSGDforMF.scala:398-417)."""
        rng = np.random.default_rng(2)
        n_rows, k, e = 6, 3, 12
        U = rng.normal(size=(n_rows, k)).astype(np.float32)
        V = rng.normal(size=(n_rows, k)).astype(np.float32)
        ur = rng.integers(0, n_rows, e).astype(np.int32)
        ir = rng.integers(0, n_rows, e).astype(np.int32)
        vals = rng.normal(size=e).astype(np.float32)
        lam, lr = 0.2, 0.05
        omega = np.full(n_rows, 2.0, dtype=np.float32)
        upd = RegularizedSGDUpdater(lr, lam, schedule=lambda b, t: b)

        Un, Vn = sgd_ops.sgd_block_sweep(
            jnp.array(U), jnp.array(V), jnp.array(ur), jnp.array(ir),
            jnp.array(vals), jnp.ones(e, jnp.float32),
            jnp.array(omega), jnp.array(omega), upd, 1, minibatch=1)

        eU, eV = U.copy(), V.copy()
        for i in range(e):
            u, v = eU[ur[i]].copy(), eV[ir[i]].copy()
            err = vals[i] - u @ v
            eU[ur[i]] = u - lr * (lam / 2.0 * u - err * v)
            eV[ir[i]] = v - lr * (lam / 2.0 * v - err * u)
        np.testing.assert_allclose(np.asarray(Un), eU, rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(Vn), eV, rtol=1e-3, atol=1e-5)


class TestDSGDConvergence:
    @pytest.mark.parametrize("num_blocks", [1, 4])
    def test_planted_model_convergence(self, num_blocks):
        gen = SyntheticMFGenerator(num_users=300, num_items=200, rank=8,
                                   noise=0.05, seed=0)
        train = gen.generate(20000)
        test = gen.generate(2000)
        # minibatch sized ≲ rows_per_block (users/k): a block only holds
        # rows_per_block distinct users, so larger minibatches force row
        # collisions whose mean-mode averaging slows convergence (at real
        # scale blocks are 10⁴-10⁵ rows wide and this is moot).
        cfg = DSGDConfig(
            num_factors=8, lambda_=0.01, iterations=20,
            learning_rate=0.1, lr_schedule="constant",
            seed=0, minibatch_size=256 // num_blocks, init_scale=0.3,
        )
        solver = DSGD(cfg)
        model = solver.fit(train, num_blocks=num_blocks)
        rmse = model.rmse(test)
        # planted noise floor is 0.05; < 0.1 means convergence to the floor
        assert rmse < 0.1, f"RMSE {rmse} too high (blocks={num_blocks})"

    def test_risk_decreases(self):
        gen = SyntheticMFGenerator(num_users=100, num_items=80, rank=4,
                                   noise=0.1, seed=1)
        train = gen.generate(5000)
        cfg = DSGDConfig(num_factors=4, lambda_=0.01, iterations=0, seed=0,
                         learning_rate=0.05, minibatch_size=256,
                         init_scale=0.3)
        m0 = DSGD(cfg).fit(train, num_blocks=2)
        risk0 = m0.empirical_risk(train, 0.01)
        cfg10 = DSGDConfig(num_factors=4, lambda_=0.01, iterations=10, seed=0,
                           learning_rate=0.05, minibatch_size=256,
                           init_scale=0.3)
        m1 = DSGD(cfg10).fit(train, num_blocks=2)
        risk1 = m1.empirical_risk(train, 0.01)
        assert risk1 < risk0

    def test_determinism_with_seed(self):
        """≙ the reference's seeded determinism contract
        (DSGDforMF.scala:319-323,553-557)."""
        gen = SyntheticMFGenerator(num_users=50, num_items=40, rank=4, seed=2)
        train = gen.generate(2000)
        cfg = DSGDConfig(num_factors=4, iterations=3, seed=5,
                         minibatch_size=128)
        a = DSGD(cfg).fit(train, num_blocks=2)
        b = DSGD(cfg).fit(train, num_blocks=2)
        np.testing.assert_array_equal(np.asarray(a.U), np.asarray(b.U))
        np.testing.assert_array_equal(np.asarray(a.V), np.asarray(b.V))

    def test_pluggable_updater_seam(self):
        """Injecting core SGDUpdater (unregularized,
        FactorUpdater.scala:35-53) through the DSGD driver."""
        gen = SyntheticMFGenerator(num_users=50, num_items=40, rank=4, seed=3)
        train = gen.generate(3000)
        cfg = DSGDConfig(num_factors=4, iterations=5, seed=0,
                         minibatch_size=128, init_scale=0.3)
        solver = DSGD(cfg, updater=SGDUpdater(learning_rate=0.02))
        model = solver.fit(train, num_blocks=2)
        assert model.rmse(train) < 1.0

    def test_predict_unseen_scores_zero(self):
        gen = SyntheticMFGenerator(num_users=30, num_items=30, rank=4, seed=4)
        model = DSGD(DSGDConfig(num_factors=4, iterations=2,
                                minibatch_size=64)).fit(gen.generate(500))
        scores = model.predict(np.array([0, 99999]), np.array([0, 0]))
        assert scores[1] == 0.0

    def test_predict_return_mask_exposes_join_drop(self):
        """The reference's predict silently drops unseen pairs
        (MatrixFactorization.scala:250-265); return_mask=True surfaces that
        join-drop set so 'model says 0' ≠ 'never seen'."""
        gen = SyntheticMFGenerator(num_users=30, num_items=30, rank=4, seed=4)
        model = DSGD(DSGDConfig(num_factors=4, iterations=2,
                                minibatch_size=64)).fit(gen.generate(500))
        u = np.array([0, 99999, 0])
        i = np.array([0, 0, 99999])
        scores, seen = model.predict(u, i, return_mask=True)
        assert seen.dtype == bool
        np.testing.assert_array_equal(seen, [True, False, False])
        assert scores[1] == 0.0 and scores[2] == 0.0
        # default call unchanged
        np.testing.assert_array_equal(model.predict(u, i), scores)

    def test_unfitted_predict_raises(self):
        with pytest.raises(RuntimeError):
            DSGD().predict(np.array([1]), np.array([1]))


class TestModelExport:
    def test_factor_vectors_roundtrip(self):
        gen = SyntheticMFGenerator(num_users=20, num_items=15, rank=4, seed=5)
        model = DSGD(DSGDConfig(num_factors=4, iterations=1,
                                minibatch_size=64)).fit(gen.generate(300))
        fvs = list(model.user_factors())
        ids = sorted(fv.id for fv in fvs)
        ru, _, _, _ = gen.generate(0).to_numpy()  # not used; check vs index
        assert ids == sorted(i for i in model.users.ids if i >= 0)
        assert all(fv.factors.shape == (4,) for fv in fvs)


class TestPrecomputedCollisions:
    """Precomputed minibatch collision scales (data.blocking.
    minibatch_inv_counts) must be the SAME math as the runtime counters —
    they only move the counting from the kernel hot path to blocking time."""

    def test_precompute_matches_runtime(self):
        gen = SyntheticMFGenerator(num_users=50, num_items=40, rank=4,
                                   noise=0.1, seed=0)
        # small tables + mb > rows_per_block → plenty of collisions
        train = gen.generate(8000)
        base = dict(num_factors=4, lambda_=0.05, iterations=4,
                    learning_rate=0.1, lr_schedule="constant", seed=0,
                    minibatch_size=128, init_scale=0.3)
        on = DSGD(DSGDConfig(precompute_collisions=True, **base)).fit(
            train, num_blocks=2)
        off = DSGD(DSGDConfig(precompute_collisions=False, **base)).fit(
            train, num_blocks=2)
        np.testing.assert_allclose(np.asarray(on.U), np.asarray(off.U),
                                   rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(on.V), np.asarray(off.V),
                                   rtol=2e-5, atol=1e-6)

    def test_mesh_precompute_matches_runtime(self):
        from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
            MeshDSGD,
            MeshDSGDConfig,
        )

        gen = SyntheticMFGenerator(num_users=64, num_items=48, rank=4,
                                   noise=0.1, seed=1)
        train = gen.generate(6000)
        base = dict(num_factors=4, lambda_=0.05, iterations=3,
                    learning_rate=0.1, lr_schedule="constant", seed=0,
                    minibatch_size=64, init_scale=0.3)
        on = MeshDSGD(MeshDSGDConfig(precompute_collisions=True,
                                     **base)).fit(train)
        off = MeshDSGD(MeshDSGDConfig(precompute_collisions=False,
                                      **base)).fit(train)
        np.testing.assert_allclose(np.asarray(on.U), np.asarray(off.U),
                                   rtol=2e-5, atol=1e-6)

    def test_inv_counts_values(self):
        from large_scale_recommendation_tpu.data import blocking as blk

        gen = SyntheticMFGenerator(num_users=10, num_items=8, rank=2, seed=2)
        train = gen.generate(500)
        prob = blk.block_problem(train, num_blocks=1, seed=0,
                                 minibatch_multiple=64)
        icu, icv = blk.minibatch_inv_counts(prob.ratings, 64)
        flat_rows = prob.ratings.u_rows.reshape(-1)
        flat_w = prob.ratings.weights.reshape(-1)
        flat_icu = icu.reshape(-1)
        # brute-force check every chunk
        for a in range(0, len(flat_rows), 64):
            rows = flat_rows[a:a + 64]
            w = flat_w[a:a + 64]
            for j in range(64):
                if w[j] == 0:
                    assert flat_icu[a + j] == 1.0
                else:
                    c = int(((rows == rows[j]) & (w > 0)).sum())
                    np.testing.assert_allclose(flat_icu[a + j], 1.0 / c,
                                               rtol=1e-6)
