"""Sources + ingest queue: offset stamping, backpressure policies,
poison quarantine, feeder-fault surfacing."""

import threading
import time

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.streams import (
    CSVSource,
    EventLog,
    GeneratorSource,
    IngestQueue,
    LogTailSource,
    LogTruncatedError,
    QueuedSource,
    StreamBatch,
    pump_to_log,
    split_poison,
)


def _sbatch(n, start=0, partition=0, seed=0):
    rng = np.random.default_rng(seed)
    return StreamBatch(
        ratings=Ratings.from_arrays(rng.integers(0, 40, n),
                                    rng.integers(0, 30, n),
                                    rng.random(n).astype(np.float32)),
        partition=partition, start_offset=start, end_offset=start + n)


class TestSources:
    def test_generator_source_offset_stamps(self):
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3,
                                   seed=0)
        batches = list(GeneratorSource(gen, batch_records=100,
                                       num_batches=4))
        assert [(b.start_offset, b.end_offset) for b in batches] == [
            (0, 100), (100, 200), (200, 300), (300, 400)]
        assert all(b.n == 100 for b in batches)

    def test_log_tail_source_stamps_log_offsets(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3,
                                   seed=1)
        n = pump_to_log(GeneratorSource(gen, 128, num_batches=3), log)
        assert n == 384
        batches = list(LogTailSource(log, batch_records=150))
        assert [(b.start_offset, b.end_offset) for b in batches] == [
            (0, 150), (150, 300), (300, 384)]
        # mid-stream start offset: the resume path
        tail = list(LogTailSource(log, start_offset=300,
                                  batch_records=150))
        assert [(b.start_offset, b.end_offset) for b in tail] == [
            (300, 384)]

    def test_log_tail_follow_sees_late_appends(self, tmp_path):
        log = EventLog(str(tmp_path), fsync=False)
        log.append_arrays(0, [1], [2], [3.0])
        src = LogTailSource(log, batch_records=10, follow=True,
                            poll_interval_s=0.005)
        got = []

        def consume():
            for b in src:
                got.append(b)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            time.sleep(0.005)
        log.append_arrays(0, [4], [5], [6.0])  # lands AFTER the tail
        while len(got) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        src.stop()
        t.join(timeout=5)
        assert [b.end_offset for b in got] == [1, 2]

    def test_csv_source(self, tmp_path):
        path = tmp_path / "u.data"
        rows = [(u, u % 7, float(u % 5) + 1) for u in range(25)]
        path.write_text("".join(f"{u}\t{i}\t{r}\t0\n" for u, i, r in rows))
        batches = list(CSVSource(str(path), batch_records=10))
        assert [(b.start_offset, b.end_offset) for b in batches] == [
            (0, 10), (10, 20), (20, 25)]
        np.testing.assert_array_equal(
            np.asarray(batches[0].ratings.users), np.arange(10))


class TestIngestQueue:
    def test_fifo_and_close_drain(self):
        q = IngestQueue(capacity=4)
        for k in range(3):
            assert q.put(_sbatch(10, start=k * 10))
        q.close()
        got = []
        while (b := q.get()) is not None:
            got.append(b.start_offset)
        assert got == [0, 10, 20]
        assert q.get(timeout=0.01) is None

    def test_block_policy_backpressures_without_loss(self):
        q = IngestQueue(capacity=2, policy="block")
        produced = 40
        consumed = []

        def producer():
            for k in range(produced):
                q.put(_sbatch(5, start=k * 5))
            q.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while (b := q.get(timeout=5)) is not None:
            consumed.append(b.start_offset)
            time.sleep(0.001)  # slower than the producer
        t.join(timeout=5)
        assert consumed == [k * 5 for k in range(produced)]  # zero loss
        assert q.stats.depth_high_water <= 2  # bound held
        assert q.stats.blocked_puts > 0  # backpressure engaged

    def test_drop_policy_sheds_and_counts(self):
        q = IngestQueue(capacity=2, policy="drop")
        results = [q.put(_sbatch(10, start=k * 10)) for k in range(5)]
        assert results == [True, True, False, False, False]
        assert q.stats.dropped_batches == 3
        assert q.stats.dropped_records == 30

    def test_drop_policy_counts_real_rows_not_offset_span(self):
        # a quarantined batch keeps its full [start, end) stamp but
        # holds fewer real rows — drop accounting counts the rows
        # actually lost (matching the dead_letter policy), so rows
        # already in the dead-letter buffer are not double-counted
        q = IngestQueue(capacity=1, policy="drop")
        assert q.put(_sbatch(10, start=0))
        shed = StreamBatch(ratings=_sbatch(6, seed=1).ratings.pad_to(16),
                           partition=0, start_offset=10, end_offset=20)
        assert not q.put(shed)
        assert q.stats.dropped_batches == 1
        assert q.stats.dropped_records == 6  # not shed.n == 10

    def test_dead_letter_policy_is_recoverable(self):
        q = IngestQueue(capacity=1, policy="dead_letter")
        assert q.put(_sbatch(10, start=0))
        assert not q.put(_sbatch(7, start=10, seed=1))
        assert q.stats.dead_letter_batches == 1
        assert q.stats.dead_letter_records == 7
        assert q.stats.dropped_batches == 0  # quarantined ≠ lost
        u, i, r = q.dead_letters.records()
        assert len(u) == 7

    def test_invalid_policy_refused(self):
        with pytest.raises(ValueError, match="policy"):
            IngestQueue(policy="explode")

    def test_dead_letter_buffer_bound_holds_for_oversized_chunk(self):
        # one shed chunk larger than the whole buffer must be trimmed
        # to the newest `capacity` records, not retained whole
        from large_scale_recommendation_tpu.streams.sources import (
            DeadLetterBuffer,
        )

        buf = DeadLetterBuffer(capacity=100)
        idx = np.arange(300)
        buf.put(idx, idx, idx.astype(np.float32))
        assert len(buf) == 100
        assert buf.total == 300  # lifetime counter still sees all
        u, _, _ = buf.records()
        np.testing.assert_array_equal(u, np.arange(200, 300))

    def test_early_exit_consumer_sees_feeder_fault_via_finish(self):
        # a consumer that breaks out of batches() early (the driver's
        # max_batches path) never reaches the end-of-stream re-raise;
        # finish() must surface the feeder's fault instead
        def faulty():
            yield _sbatch(10, start=0)
            yield _sbatch(10, start=10)
            raise RuntimeError("boom")

        qs = QueuedSource(faulty(), capacity=4)
        it = qs.batches()
        assert next(it).start_offset == 0
        # capacity 4 > 2 batches: the feeder never blocks, so it always
        # runs through to its fault — wait for it so the test is
        # deterministic (finish() only surfaces faults the feeder HIT;
        # stopping a healthy feeder early is not a fault)
        qs._thread.join(timeout=30)
        with pytest.raises(RuntimeError, match="boom"):
            qs.finish()


class TestPoisonQuarantine:
    def test_split_poison_mask(self):
        users = np.array([1, -1, 2, 3])
        items = np.array([1, 2, -5, 3])
        vals = np.array([1.0, 1.0, 1.0, np.nan], np.float32)
        np.testing.assert_array_equal(
            split_poison(users, items, vals), [True, False, False, False])

    def test_quarantine_preserves_offsets_and_feeds_clean(self):
        bad = StreamBatch(
            ratings=Ratings.from_arrays(
                [1, -1, 2, 3], [1, 2, 3, 4],
                np.array([1.0, 1.0, np.nan, 1.0], np.float32)),
            partition=0, start_offset=100, end_offset=104)
        qs = QueuedSource([bad])
        out = list(qs)
        assert len(out) == 1
        # the batch still covers its full range — poison rows are
        # consumed into quarantine, not lost and not re-readable
        assert (out[0].start_offset, out[0].end_offset) == (100, 104)
        np.testing.assert_array_equal(np.asarray(out[0].ratings.users),
                                      [1, 3])
        assert qs.stats.poison_records == 2
        u, i, r = qs.dead_letters.records()
        assert sorted(u.tolist()) == [-1, 2]

    def test_driver_survives_poison(self, tmp_path):
        # end-to-end: a poisoned log region must not kill the driver OR
        # corrupt the model (no NaN reaches the tables)
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams import (
            StreamingDriver,
            StreamingDriverConfig,
        )

        log = EventLog(str(tmp_path / "log"), fsync=False)
        gen = SyntheticMFGenerator(num_users=30, num_items=20, rank=3,
                                   seed=2)
        pump_to_log(GeneratorSource(gen, 100, num_batches=2), log)
        log.append_arrays(0, [5, 6], [1, 2],
                          [np.nan, np.inf])  # poison region
        pump_to_log(GeneratorSource(gen, 100, num_batches=1), log)

        m = OnlineMF(OnlineMFConfig(num_factors=3, minibatch_size=64))
        drv = StreamingDriver(m, log, str(tmp_path / "ckpt"),
                              config=StreamingDriverConfig(
                                  batch_records=100))
        drv.run()
        assert drv.consumed_offset == 302  # poison counted as consumed
        assert np.isfinite(np.asarray(m.users.array)).all()
        assert drv.telemetry()["queue"]["poison_records"] == 2


class TestFeederFaults:
    def test_runtime_fault_surfaces_on_consumer(self, tmp_path):
        log = EventLog(str(tmp_path), segment_records=16, fsync=False)
        rng = np.random.default_rng(0)
        log.append_arrays(0, rng.integers(0, 9, 64),
                          rng.integers(0, 9, 64), rng.random(64))
        log.truncate_before(0, 48)
        qs = QueuedSource(LogTailSource(log, start_offset=0,
                                        batch_records=16))
        with pytest.raises(LogTruncatedError):
            list(qs)
