"""Estimator/transformer chaining (≙ the reference's FlinkML Predictor
pipeline surface, MatrixFactorization.scala:58 + ParameterMap ++).
"""

import numpy as np
import pytest

from large_scale_recommendation_tpu.core.generators import SyntheticMFGenerator
from large_scale_recommendation_tpu.core.types import Ratings
from large_scale_recommendation_tpu.models.als import ALS, ALSConfig
from large_scale_recommendation_tpu.models.dsgd import DSGD, DSGDConfig
from large_scale_recommendation_tpu.models.pipeline import (
    IdCompactor,
    MeanCenterer,
    Pipeline,
)


def _sparse_id_workload(seed=0, n=12000, mean=3.5):
    """Planted structure with SPARSE raw ids (MovieLens-style) and a
    large value offset — the exact shape the pipeline stages exist for."""
    gen = SyntheticMFGenerator(num_users=120, num_items=80, rank=5,
                               noise=0.05, seed=seed)
    train, test = gen.generate(n), gen.generate(n // 4)

    def sparsify(r):
        ru, ri, rv, rw = r.to_numpy()
        return Ratings.from_arrays(ru * 7 + 13, ri * 11 + 5,
                                   rv + mean, rw)

    return sparsify(train), sparsify(test)


class TestStages:
    def test_id_compactor_roundtrip_and_unseen(self):
        train, _ = _sparse_id_workload()
        fc = IdCompactor().fit(train)
        ru, ri, _, _ = train.to_numpy()
        du, di = fc.map_ids(ru, ri)
        assert du.min() == 0 and du.max() == fc.num_users - 1
        assert (du >= 0).all() and (di >= 0).all()
        # determinism: same raw id -> same dense id
        assert (fc.map_ids(ru[:1], ri[:1])[0] == du[0]).all()
        # unseen ids -> -1
        u_bad, i_bad = fc.map_ids([999_999], [999_999])
        assert u_bad[0] == -1 and i_bad[0] == -1
        out = fc.transform(train)
        assert out.n == train.n

    def test_mean_centerer_inverts(self):
        train, _ = _sparse_id_workload()
        fm = MeanCenterer().fit(train)
        centered = fm.transform(train)
        _, _, cv, cw = centered.to_numpy()
        assert abs(float((cv * cw).sum() / cw.sum())) < 1e-4
        np.testing.assert_allclose(fm.adjust_scores(cv),
                                   train.to_numpy()[2], rtol=1e-5)


class TestPipeline:
    def test_chain_equals_manual_composition(self):
        """Pipeline(IdCompactor, MeanCenterer, ALS) == hand-rolled
        compact+center+fit, including score un-centering at predict."""
        train, test = _sparse_id_workload()
        cfg = ALSConfig(num_factors=8, lambda_=0.05, iterations=6, seed=0)
        pm = Pipeline(IdCompactor(), MeanCenterer(), ALS(cfg)).fit(train)

        # manual twin
        fc = IdCompactor().fit(train)
        fm = MeanCenterer().fit(fc.transform(train))
        manual = ALS(cfg).fit(fm.transform(fc.transform(train)))
        ru, ri, rv, _ = test.to_numpy()
        du, di = fc.map_ids(ru, ri)
        want = np.asarray(manual.predict(du, di)) + fm.mean
        got = pm.predict(ru, ri)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

        # and the chain actually learns: well under the predict-mean floor
        rv_std = float(np.std(rv))
        assert pm.rmse(test) < 0.5 * rv_std

    def test_unseen_pairs_predict_the_mean(self):
        train, _ = _sparse_id_workload()
        pm = Pipeline(IdCompactor(), MeanCenterer(),
                      DSGD(DSGDConfig(num_factors=6, iterations=4,
                                      learning_rate=0.1,
                                      lr_schedule="constant",
                                      seed=0))).fit(train)
        s = pm.predict([424242], [777777])
        np.testing.assert_allclose(s, pm.fitted_stages[1].mean, rtol=1e-6)

    def test_fit_time_overrides_merge_into_final_config(self):
        """fit(**overrides) ≙ fit(training, parameterMap) — later wins,
        estimator instance untouched, unknown keys refuse."""
        train, _ = _sparse_id_workload()
        est = ALS(ALSConfig(num_factors=4, iterations=1, seed=0))
        pipe = Pipeline(IdCompactor(), MeanCenterer(), est)
        pm = pipe.fit(train, iterations=5, num_factors=8)
        assert est.config.iterations == 1  # caller's instance unmodified
        assert pm.model.rank == 8
        with pytest.raises(ValueError):
            pipe.fit(train, not_a_field=3)

    def test_rejects_stageless_and_fitless(self):
        with pytest.raises(ValueError):
            Pipeline()
        with pytest.raises(TypeError):
            Pipeline(IdCompactor(), object())


class TestReviewRegressions:
    def test_compactor_threads_weights(self):
        """Non-unit weights survive compaction — a dropped weight column
        silently un-weights every downstream loss."""
        tr, _ = _sparse_id_workload()
        ru, ri, rv, _ = tr.to_numpy()
        w = np.full(tr.n, 2.0, np.float32)
        w[: tr.n // 2] = 0.5
        weighted = Ratings.from_arrays(ru, ri, rv, w)
        out = IdCompactor().fit(weighted).transform(weighted)
        np.testing.assert_array_equal(out.to_numpy()[3], w)
        # and MeanCenterer then computes the WEIGHTED mean
        fm = MeanCenterer().fit(out)
        assert abs(fm.mean - float((rv * w).sum() / w.sum())) < 1e-5

    def test_injected_updater_survives_overrides(self):
        from large_scale_recommendation_tpu.core.updaters import (
            SGDUpdater,
        )

        tr, _ = _sparse_id_workload(n=4000)
        custom = SGDUpdater(learning_rate=0.05)
        est = DSGD(DSGDConfig(num_factors=4, iterations=1, seed=0),
                   updater=custom)
        pipe = Pipeline(IdCompactor(), MeanCenterer(), est)
        # spy via identity: the fitted chain must use the SAME object
        pm = pipe.fit(tr, iterations=2)
        assert pm is not None
        # rebuild preserved the injected updater (identity, not equality)
        # — reconstruct the rebuild logic's observable effect instead of
        # poking internals: a default-updater estimator rebuilt with a new
        # lr must NOT carry the old lr
        est2 = DSGD(DSGDConfig(num_factors=4, iterations=1,
                               learning_rate=0.001, seed=0))
        pm2 = Pipeline(IdCompactor(), MeanCenterer(), est2).fit(
            tr, learning_rate=0.3, lr_schedule="constant", iterations=4)
        est3 = DSGD(DSGDConfig(num_factors=4, iterations=1,
                               learning_rate=0.001, seed=0))
        pm3 = Pipeline(IdCompactor(), MeanCenterer(), est3).fit(
            tr, iterations=4)
        # the lr override must actually change training (0.3 learns,
        # 0.001 is a crawl)
        assert pm2.rmse(tr) < pm3.rmse(tr) - 0.05
