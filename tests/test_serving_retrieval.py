"""Two-stage quantized retrieval: round-trip bounds, recall pins,
delta-swap ≡ full-rebuild equivalence, per-request catalog versions.

The fast path's contract has three legs, each pinned here: (a) int8
per-row quantization is bounded (error ≤ scale/2 per element), (b) the
two-stage engine's recall@k against the exact path meets the ≥0.95 @
overfetch-4 acceptance (flat mode on an unstructured catalog — the
hardest case — and clustered mode on a structured one — the case IVF
routing exists for), and (c) a delta swap installs ONLY touched rows
yet lands bit-equivalent to a full rebuild, on the sharded f32 catalog,
the int8 catalog, and through ``ServingEngine.apply_delta`` +
``StreamingDriver.refresh_serving``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from large_scale_recommendation_tpu.data.blocking import flat_index
from large_scale_recommendation_tpu.models.mf import MFModel
from large_scale_recommendation_tpu.serving import (
    RecResult,
    RetrievalConfig,
    ServingEngine,
    build_quantized_catalog,
    quantize_rows,
    recall_at_k,
)
from large_scale_recommendation_tpu.serving.retrieval import (
    dequantize_rows,
)


def random_model(num_users, num_items, rank, seed=0, structured=False,
                 n_centers=16):
    rng = np.random.default_rng(seed)
    if structured:
        centers = rng.normal(size=(n_centers, rank)) * 2.0
        V = (centers[rng.integers(0, n_centers, num_items)]
             + 0.3 * rng.normal(size=(num_items, rank)))
    else:
        V = rng.normal(size=(num_items, rank))
    return MFModel(
        U=jnp.asarray(rng.normal(size=(num_users, rank)).astype(
            np.float32)),
        V=jnp.asarray(V.astype(np.float32)),
        users=flat_index(np.arange(num_users, dtype=np.int64)),
        items=flat_index(np.arange(num_items, dtype=np.int64)))


class TestQuantization:
    def test_roundtrip_error_bounded_per_row(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(64, 16)).astype(np.float32)
        X[5] *= 1e4  # large-magnitude row: scale adapts per row
        X[9] = 0.0  # all-zero row: scale 1, exact round-trip
        q, s = quantize_rows(X)
        q, s = np.asarray(q), np.asarray(s)
        assert q.dtype == np.int8
        assert np.abs(q).max() <= 127
        deq = np.asarray(dequantize_rows(jnp.asarray(q), jnp.asarray(s)))
        # symmetric rounding: error ≤ scale/2 per element, every row
        bound = s[:, None] / 2 + 1e-6
        assert (np.abs(deq - X) <= bound).all()
        np.testing.assert_array_equal(deq[9], 0.0)

    def test_scale_is_rowmax_over_127(self):
        X = np.array([[1.0, -254.0], [0.0, 0.5]], np.float32)
        _, s = quantize_rows(X)
        np.testing.assert_allclose(np.asarray(s), [2.0, 0.5 / 127],
                                   rtol=1e-6)


class TestTwoStageRecall:
    def test_flat_recall_pin_at_overfetch_4(self):
        """The acceptance pin: recall@10 ≥ 0.95 at overfetch 4, flat
        int8 stage 1, UNSTRUCTURED catalog (quantization is the only
        approximation — the hardest honest case for stage 1)."""
        model = random_model(300, 2048, 16, seed=1)
        exact = ServingEngine(model, k=10)
        fast = ServingEngine(model, k=10,
                             retrieval=RetrievalConfig(overfetch=4))
        uids = np.arange(300)
        ie, se = exact.recommend(uids)
        ia, sa = fast.recommend(uids)
        assert recall_at_k(ia, ie) >= 0.95

        # stage 2 rescored EXACTLY: every returned (id, score) matches
        # the exact path's score for that id (approximation only picks
        # WHICH items are considered, never what they score)
        exact_scores = {(q, int(i)): se[q, j]
                        for q in range(len(uids))
                        for j, i in enumerate(ie[q])}
        checked = 0
        for q in range(len(uids)):
            for j, i in enumerate(ia[q]):
                key = (q, int(i))
                if key in exact_scores:
                    np.testing.assert_allclose(
                        sa[q, j], exact_scores[key], rtol=1e-4,
                        atol=1e-4)
                    checked += 1
        assert checked > 1000  # the overlap is nearly everything

    def test_clustered_recall_pin_on_structured_catalog(self):
        """Clustered MIPS stage 1 on a catalog WITH cluster structure
        (the regime IVF routing exists for — real embedding catalogs
        cluster): recall@10 ≥ 0.95 probing 12 of 32 cells."""
        model = random_model(256, 4096, 16, seed=2, structured=True)
        exact = ServingEngine(model, k=10)
        fast = ServingEngine(model, k=10, retrieval=RetrievalConfig(
            overfetch=4, n_clusters=32, n_probe=12, kmeans_sample=4096))
        uids = np.arange(256)
        ie, _ = exact.recommend(uids)
        ia, _ = fast.recommend(uids)
        assert recall_at_k(ia, ie) >= 0.95

    def test_engine_contract_conventions(self):
        """The recommend conventions hold on the fast path: unknown
        users → -1/0.0 rows, int64 ids, return_mask, and results are
        RecResult tuples carrying the catalog version."""
        model = random_model(50, 256, 8, seed=3)
        eng = ServingEngine(model, k=5, retrieval="two_stage")
        res = eng.recommend(np.array([1, 2, 99999]), return_mask=True)
        ids, scores, mask = res
        assert isinstance(res, RecResult)
        assert res.catalog_version == eng.version
        assert res.degraded is False
        assert ids.dtype == np.int64
        np.testing.assert_array_equal(mask, [True, True, False])
        np.testing.assert_array_equal(ids[2], -1)
        np.testing.assert_array_equal(scores[2], 0.0)

    def test_train_exclusions_apply_exactly(self):
        """Excluded (train-seen) pairs never surface from the fast path
        — the membership test's semantics match the exact scatter-min."""
        model = random_model(40, 128, 8, seed=4)
        rng = np.random.default_rng(5)
        tu = rng.integers(0, 40, 300).astype(np.int64)
        ti = rng.integers(0, 128, 300).astype(np.int64)
        eng = ServingEngine(model, k=10, train=(tu, ti),
                            retrieval=RetrievalConfig(overfetch=8))
        uids = np.arange(40)
        ids, scores = eng.recommend(uids)
        excluded = set(zip(tu.tolist(), ti.tolist()))
        for q in range(40):
            for i, s in zip(ids[q], scores[q]):
                if i >= 0:
                    assert (q, int(i)) not in excluded

    def test_clustered_slabs_partition_every_row(self):
        """Every catalog row lives at exactly one slab/overflow
        position, and the capacity cap bounds every cluster."""
        rng = np.random.default_rng(6)
        V = rng.normal(size=(1000, 8)).astype(np.float32)
        cat = build_quantized_catalog(V, config=RetrievalConfig(
            n_clusters=8, kmeans_sample=1000, slab_slack=1.5))
        assert cat.clustered
        pos = cat.pos_of_row
        assert len(np.unique(pos)) == 1000  # injective placement
        C, m, _ = cat.slab_q.shape
        rows = np.concatenate([np.asarray(cat.slab_rows).ravel(),
                               np.asarray(cat.ovf_rows)])
        real = rows[rows < 1000]
        assert sorted(real.tolist()) == list(range(1000))
        stats = cat.stats
        assert stats["max_cluster"] <= stats["capacity_cap"] == m


class TestDeltaSwaps:
    def _patched(self, V1, rows, seed=7):
        rng = np.random.default_rng(seed)
        V2 = V1.copy()
        V2[rows] = rng.normal(size=(len(rows), V1.shape[1])).astype(
            np.float32)
        return V2

    def test_sharded_catalog_delta_bit_equals_rebuild(self):
        from large_scale_recommendation_tpu.parallel.serving import (
            shard_catalog,
        )

        rng = np.random.default_rng(8)
        V1 = rng.normal(size=(100, 8)).astype(np.float32)
        rows = np.array([0, 3, 50, 99])
        V2 = self._patched(V1, rows)
        mask = np.ones(100, bool)
        mask[17] = False
        cat1 = shard_catalog(jnp.asarray(V1), item_mask=mask)
        rebuilt = shard_catalog(jnp.asarray(V2), item_mask=mask)
        delta = cat1.apply_delta(rows, V2[rows])
        np.testing.assert_array_equal(np.asarray(delta.V_sh),
                                      np.asarray(rebuilt.V_sh))
        np.testing.assert_array_equal(np.asarray(delta.w_sh),
                                      np.asarray(rebuilt.w_sh))
        assert delta.version != cat1.version
        assert delta.rows_per_shard == cat1.rows_per_shard

    def test_quantized_flat_delta_bit_equals_rebuild(self):
        rng = np.random.default_rng(9)
        V1 = rng.normal(size=(64, 8)).astype(np.float32)
        rows = np.array([1, 7, 63])
        V2 = self._patched(V1, rows)
        cat1 = build_quantized_catalog(jnp.asarray(V1))
        rebuilt = build_quantized_catalog(jnp.asarray(V2))
        delta = cat1.apply_delta(rows, V2[rows], version=rebuilt.version)
        np.testing.assert_array_equal(np.asarray(delta.q),
                                      np.asarray(rebuilt.q))
        np.testing.assert_array_equal(np.asarray(delta.scale),
                                      np.asarray(rebuilt.scale))
        assert delta.version == rebuilt.version

    def test_quantized_clustered_delta_requantizes_dirty_rows(self):
        """Clustered delta keeps each row's cluster slot but its slab
        content must equal a fresh per-row quantization of the new
        factors (re-clustering is a full-rebuild concern)."""
        rng = np.random.default_rng(10)
        V1 = rng.normal(size=(500, 8)).astype(np.float32)
        rows = np.arange(0, 500, 37)
        V2 = self._patched(V1, rows)
        cat = build_quantized_catalog(jnp.asarray(V1),
                                      config=RetrievalConfig(
                                          n_clusters=8,
                                          kmeans_sample=500))
        delta = cat.apply_delta(rows, V2[rows], version=999)
        q2, s2 = quantize_rows(jnp.asarray(V2))
        C, m, r = delta.slab_q.shape
        flat_q = np.concatenate([np.asarray(delta.slab_q).reshape(-1, r),
                                 np.asarray(delta.ovf_q)])
        flat_s = np.concatenate([np.asarray(delta.slab_scale).ravel(),
                                 np.asarray(delta.ovf_scale)])
        pos = cat.pos_of_row
        np.testing.assert_array_equal(flat_q[pos], np.asarray(q2))
        np.testing.assert_array_equal(flat_s[pos], np.asarray(s2))
        assert delta.version == 999

    @pytest.mark.parametrize("retrieval", [None, "flat"])
    def test_engine_delta_equals_full_refresh(self, retrieval):
        """The end contract: an engine that took a DELTA serves results
        bit-identical to an engine fully rebuilt from the patched model
        — exact mesh path and flat fast path both (clustered would
        re-cluster on rebuild; its slab equivalence is pinned above).
        Zero new compiles: a delta never changes a shape."""
        cfg = (None if retrieval is None
               else RetrievalConfig(overfetch=4))
        model_a = random_model(60, 256, 8, seed=11)
        model_b = random_model(60, 256, 8, seed=11)
        rng = np.random.default_rng(12)
        item_rows = np.array([0, 17, 200, 255])
        user_rows = np.array([3, 59])
        V_new = rng.normal(size=(4, 8)).astype(np.float32)
        U_new = rng.normal(size=(2, 8)).astype(np.float32)

        eng_a = ServingEngine(model_a, k=6, retrieval=cfg)
        uids = np.arange(60)
        eng_a.recommend(uids)  # warm
        variants = eng_a.executable_variants
        v0 = eng_a.version
        versions_seen = []
        eng_a.on_refresh = versions_seen.append
        v1 = eng_a.apply_delta(item_rows=item_rows, V_rows=V_new,
                               user_rows=user_rows, U_rows=U_new)
        assert v1 != v0 and versions_seen == [v1]
        assert eng_a.stats["delta_swaps"] == 1
        assert eng_a.executable_variants == variants  # no new compiles

        # full-rebuild reference: patch model_b wholesale, fresh engine
        model_b.V = jnp.asarray(model_b.V).at[
            jnp.asarray(item_rows)].set(jnp.asarray(V_new))
        model_b.U = jnp.asarray(model_b.U).at[
            jnp.asarray(user_rows)].set(jnp.asarray(U_new))
        eng_b = ServingEngine(model_b, k=6, retrieval=cfg)
        ra = eng_a.recommend(uids)
        rb = eng_b.recommend(uids)
        np.testing.assert_array_equal(ra[0], rb[0])
        np.testing.assert_array_equal(ra[1], rb[1])
        # per-request version moved with the delta (the mid-flight-swap
        # detection satellite): results carry the post-delta token
        assert ra.catalog_version == v1

    def test_engine_delta_rejects_vocab_growth(self):
        model = random_model(20, 64, 4, seed=13)
        eng = ServingEngine(model, k=4)
        with pytest.raises(ValueError, match="vocab grew"):
            eng.apply_delta(item_rows=np.array([64]),
                            V_rows=np.zeros((1, 4), np.float32))
        with pytest.raises(ValueError, match="vocab grew"):
            eng.apply_delta(user_rows=np.array([20]),
                            U_rows=np.zeros((1, 4), np.float32))


class TestDriverDeltaShipping:
    def test_refresh_serving_ships_delta_and_matches_full(self, tmp_path):
        """The streaming wire: batches applied through the driver mark
        dirty ids; ``refresh_serving()`` ships ONLY those rows and the
        engine then serves exactly what a full re-snapshot refresh
        would."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
            StreamingDriverConfig,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        gen = SyntheticMFGenerator(num_users=40, num_items=30, rank=3,
                                   noise=0.05, seed=14)
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        learning_rate=0.05,
                                        minibatch_size=64))
        log = EventLog(str(tmp_path / "wal"))
        # seed the vocab, then attach the engine (so later batches only
        # touch KNOWN ids — the geometry-stable delta regime)
        model.partial_fit(gen.generate(800))
        driver = StreamingDriver(model, log, str(tmp_path / "ckpt"),
                                 config=StreamingDriverConfig(
                                     batch_records=200))
        engine = driver.serving_engine(k=5)
        v0 = engine.version
        log.append(0, gen.generate(400))
        driver.run()
        tel = driver.telemetry()
        assert tel["dirty_users"] > 0 and tel["dirty_items"] > 0
        driver.refresh_serving(delta=True)  # asserts the delta path ran
        assert engine.stats["delta_swaps"] == 1
        assert engine.version != v0
        assert driver.telemetry()["dirty_users"] == 0
        # the delta-refreshed engine answers exactly like the live model
        uids = np.arange(40)
        ids_d, scores_d = engine.recommend(uids)
        ids_f, scores_f = model.to_model().recommend(uids, k=5)
        np.testing.assert_array_equal(ids_d, ids_f)
        np.testing.assert_allclose(scores_d, scores_f, rtol=1e-6,
                                   atol=1e-7)

    def test_refresh_serving_falls_back_on_vocab_growth(self, tmp_path):
        """New ids since the engine's snapshot change the geometry: auto
        mode silently takes the full-refresh path; delta=True raises."""
        from large_scale_recommendation_tpu.core.generators import (
            SyntheticMFGenerator,
        )
        from large_scale_recommendation_tpu.models.online import (
            OnlineMF,
            OnlineMFConfig,
        )
        from large_scale_recommendation_tpu.streams.driver import (
            StreamingDriver,
        )
        from large_scale_recommendation_tpu.streams.log import EventLog

        gen = SyntheticMFGenerator(num_users=20, num_items=15, rank=3,
                                   noise=0.05, seed=15)
        model = OnlineMF(OnlineMFConfig(num_factors=4,
                                        minibatch_size=64))
        model.partial_fit(gen.generate(200))
        log = EventLog(str(tmp_path / "wal"))
        driver = StreamingDriver(model, log, str(tmp_path / "ckpt"))
        engine = driver.serving_engine(k=4)
        # grow the vocab directly on the model (new user/item ids)
        bigger = SyntheticMFGenerator(num_users=40, num_items=30, rank=3,
                                      noise=0.05, seed=16)
        log.append(0, bigger.generate(300))
        driver.run()
        with pytest.raises(ValueError, match="geometry"):
            driver.refresh_serving(delta=True)
        v0 = engine.version
        driver.refresh_serving()  # auto: falls back to full refresh
        assert engine.version != v0
        assert engine.stats["delta_swaps"] == 0
