"""Virtual-mesh evidence past 8 devices (VERDICT r4 #7).

The 8-device conftest mesh cannot catch k-scaling pathologies (pad-ratio
blowup at high k, per-shard minibatch divisibility, high-k layout
memory), so the pod-shaped pass runs in a SUBPROCESS with its own
16-device XLA flag — the same isolation trick the 2-process demo test
uses. ``scripts/pod_dryrun.py`` holds the actual workload (shared with
standalone runs); this test pins its JSON contract.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@pytest.mark.slow
class TestPodShapedMesh:
    def test_pod_dryrun_16_devices(self):
        """dryrun_multichip(16) + partitioner rules resolution at 16
        devices + the pod-shaped (10:1 vocab, rank 128, k=16) at-scale
        pass + the 2-process local cluster: green run, bounded pad
        ratio, minibatch divisibility, sub-data-std train risk, and the
        MULTICHIP JSON contract (pad-ratio / layout-bytes / throughput
        fields) the --family multichip regression gate consumes.

        The final stdout line must parse as JSON even with stderr
        merged in (the stderr-flush-before-final-line hardening bench.py
        and pallas_probe.py already carry), so run with 2>&1."""
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "pod_dryrun.py"),
             "16"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO, timeout=1800,
        )
        assert proc.returncode == 0, proc.stdout[-3000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["n_devices"] == 16
        # the script asserts the hard bounds; re-pin the headline ones
        # here so a contract drift in the script cannot silently pass
        assert out["max_pad_ratio"] < 2.0
        assert out["train_rmse_after_4_sweeps"] < out["data_std"]
        # the MULTICHIP trajectory contract: every key the multichip
        # regress family watches, plus the 16-device rules coverage
        from scripts.bench_regress import MULTICHIP_KEYS

        for key in MULTICHIP_KEYS:
            assert key in out, key
        assert out["train_ratings_per_s"] > 0
        assert out["layout_bytes"] > 0
        assert out["partitioner_axes_resolved"] >= 5
        # the 2-process local-cluster pass ran (or skipped loudly)
        two = out["two_process"]
        assert two.get("ok") or two.get("skipped"), two
        if two.get("ok"):
            # the pod-observability half (ISSUE 9): process 0 merged
            # both processes' /metrics+/healthz through obs.fleet over
            # real sockets and the aggregate passed its asserts
            assert two.get("fleet_ok"), two
            # the distributed-tracing half (ISSUE 12): the merged pod
            # trace validated and a sampled record resolved to one
            # assembled trace across the process boundary
            assert two.get("trace_ok"), two
