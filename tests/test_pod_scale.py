"""Virtual-mesh evidence past 8 devices (VERDICT r4 #7).

The 8-device conftest mesh cannot catch k-scaling pathologies (pad-ratio
blowup at high k, per-shard minibatch divisibility, high-k layout
memory), so the pod-shaped pass runs in a SUBPROCESS with its own
16-device XLA flag — the same isolation trick the 2-process demo test
uses. ``scripts/pod_dryrun.py`` holds the actual workload (shared with
standalone runs); this test pins its JSON contract.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestPodShapedMesh:
    def test_pod_dryrun_16_devices(self):
        """dryrun_multichip(16) + the pod-shaped (10:1 vocab, rank 128,
        k=16) at-scale pass: green run, bounded pad ratio, minibatch
        divisibility, sub-data-std train risk."""
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "pod_dryrun.py"),
             "16"],
            env=env, capture_output=True, text=True, cwd=REPO,
            timeout=1800,
        )
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["n_devices"] == 16
        # the script asserts the hard bounds; re-pin the headline ones
        # here so a contract drift in the script cannot silently pass
        assert out["max_pad_ratio"] < 2.0
        assert out["train_rmse_after_4_sweeps"] < out["data_std"]
