"""Rank-sharded factors end-to-end (ISSUE 16): the ``'rank' → 'model'``
rule at ``model_parallel ∈ {2, 4}`` must reproduce the model=1
computation — mesh DSGD to fp reduction tolerance, explicit mesh ALS
bit-compatibly, and serving (mesh top-k + the two-stage retriever) with
IDENTICAL top-k ids — while dividing per-device factor/catalog bytes.

Parity compares EQUAL data-axis sizes: blocking pads tables per k
(= devices / model_parallel), so the m=2 run on 8 devices (k=4) pins
against a 1-D mesh of 4 devices, and m=4 (k=2) against 2 devices —
same padded shapes, same serpentine deal, same minibatch order; the
ONLY delta is the rank split and its psum.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from large_scale_recommendation_tpu.core.generators import (
    SyntheticMFGenerator,
)
from large_scale_recommendation_tpu.models.als import ALSConfig
from large_scale_recommendation_tpu.ops import sgd as sgd_ops
from large_scale_recommendation_tpu.parallel.als_mesh import MeshALS
from large_scale_recommendation_tpu.parallel.dsgd_mesh import (
    MeshDSGD,
    MeshDSGDConfig,
)
from large_scale_recommendation_tpu.parallel.partitioner import Partitioner
from large_scale_recommendation_tpu.parallel.serving import (
    mesh_top_k_recommend,
    shard_catalog,
)
from large_scale_recommendation_tpu.serving.retrieval import (
    RetrievalConfig,
    TwoStageRetriever,
    build_quantized_catalog,
)

NU, NI = 96, 64


@pytest.fixture(scope="module")
def ratings():
    return SyntheticMFGenerator(num_users=NU, num_items=NI, rank=4,
                                noise=0.1, seed=0).generate(6000)


def _dsgd_cfg(rank=8, iters=3):
    return MeshDSGDConfig(num_factors=rank, lambda_=0.01, iterations=iters,
                          learning_rate=0.05, lr_schedule="constant",
                          seed=0, minibatch_size=64, init_scale=0.3)


def _fit_dsgd(part, ratings, rank=8, iters=3):
    ru, ri, rv, _ = ratings.to_numpy()
    m = MeshDSGD(_dsgd_cfg(rank, iters), partitioner=part).fit_device(
        ru, ri, rv, NU, NI)
    jax.block_until_ready((m.U, m.V))
    return m


class TestMeshDSGDParity:
    @pytest.mark.parametrize("m", [2, 4])
    def test_rank_sharded_matches_model1_equal_k(self, ratings, m):
        """Same seed, same blocked layout (equal k) ⇒ same factors up
        to the psum's reduction-order fp tolerance (measured ~3e-08).
        The prediction dot is the ONE reduced term; everything row-space
        runs unchanged on rank slices."""
        base = _fit_dsgd(Partitioner(num_devices=8 // m), ratings)
        shd = _fit_dsgd(Partitioner(num_devices=8, model_parallel=m),
                        ratings)
        np.testing.assert_allclose(np.asarray(shd.U), np.asarray(base.U),
                                   atol=1e-5, rtol=0)
        np.testing.assert_allclose(np.asarray(shd.V), np.asarray(base.V),
                                   atol=1e-5, rtol=0)

    def test_factors_sharded_over_model_axis(self, ratings):
        part = Partitioner(num_devices=8, model_parallel=2)
        model = _fit_dsgd(part, ratings)
        spec = model.U.sharding.spec
        assert tuple(spec) == ("data", "model"), spec
        # each device holds rank/m columns of its row block
        shard = model.U.addressable_shards[0]
        assert shard.data.shape[1] == 8 // 2

    def test_rank_not_divisible_fails_loudly(self, ratings):
        ru, ri, rv, _ = ratings.to_numpy()
        part = Partitioner(num_devices=8, model_parallel=4)
        with pytest.raises(ValueError, match="divisible"):
            MeshDSGD(_dsgd_cfg(rank=6), partitioner=part).fit_device(
                ru, ri, rv, NU, NI)

    def test_pallas_kernel_refuses_model_parallel(self, ratings):
        import dataclasses

        ru, ri, rv, _ = ratings.to_numpy()
        part = Partitioner(num_devices=8, model_parallel=2)
        cfg = dataclasses.replace(_dsgd_cfg(), kernel="pallas")
        with pytest.raises(NotImplementedError, match="model"):
            MeshDSGD(cfg, partitioner=part).fit_device(ru, ri, rv, NU, NI)


class TestMeshALSParity:
    def _fit(self, part, ratings, implicit=False):
        cfg = ALSConfig(num_factors=8, lambda_=0.1, iterations=2, seed=0,
                        implicit_alpha=40.0 if implicit else None)
        m = MeshALS(cfg, partitioner=part).fit(ratings)
        jax.block_until_ready((m.U, m.V))
        return m

    @pytest.mark.parametrize("m", [2, 4])
    def test_explicit_bit_compatible_equal_k(self, ratings, m):
        """ALS solves per row on the all-gathered full-rank table: the
        gather concatenates contiguous column slices bit-identically,
        so the rank-sharded solve IS the model=1 solve (measured
        max|dU| = 0.0); each device then keeps only its rank slice."""
        base = self._fit(Partitioner(num_devices=8 // m), ratings)
        shd = self._fit(Partitioner(num_devices=8, model_parallel=m),
                        ratings)
        np.testing.assert_array_equal(np.asarray(shd.U),
                                      np.asarray(base.U))
        np.testing.assert_array_equal(np.asarray(shd.V),
                                      np.asarray(base.V))

    def test_implicit_bit_compatible_equal_k(self, ratings):
        """The implicit path's rank-sharded Gram (row-chunked partial
        einsum + psum over 'model') must reproduce model=1 bit-for-bit
        — including NaN propagation where the baseline NaNs (this
        environment's pre-existing implicit failure), so equality is
        pinned, never finiteness."""
        base = self._fit(Partitioner(num_devices=4), ratings,
                         implicit=True)
        shd = self._fit(Partitioner(num_devices=8, model_parallel=2),
                        ratings, implicit=True)
        np.testing.assert_array_equal(np.asarray(shd.U),
                                      np.asarray(base.U))

    def test_rank_not_divisible_fails_loudly(self, ratings):
        part = Partitioner(num_devices=8, model_parallel=4)
        cfg = ALSConfig(num_factors=6, lambda_=0.1, iterations=1, seed=0)
        with pytest.raises(ValueError, match="divisible"):
            MeshALS(cfg, partitioner=part).fit(ratings)


class TestMeshServingParity:
    @pytest.mark.parametrize("m", [2, 4])
    def test_topk_ids_identical_equal_k(self, m):
        rng = np.random.default_rng(1)
        U = rng.normal(size=(40, 8)).astype(np.float32)
        V = rng.normal(size=(64, 8)).astype(np.float32)
        rows = np.arange(40, dtype=np.int32)
        base_part = Partitioner(num_devices=8 // m)
        shd_part = Partitioner(num_devices=8, model_parallel=m)
        ids_b, sc_b = mesh_top_k_recommend(
            U, V, rows, k=10, catalog=shard_catalog(V, base_part))
        ids_s, sc_s = mesh_top_k_recommend(
            U, V, rows, k=10, catalog=shard_catalog(V, shd_part))
        np.testing.assert_array_equal(np.asarray(ids_s),
                                      np.asarray(ids_b))
        np.testing.assert_allclose(np.asarray(sc_s), np.asarray(sc_b),
                                   atol=1e-5, rtol=0)

    def test_shard_catalog_rank_not_divisible_fails(self):
        V = np.zeros((64, 6), np.float32)
        part = Partitioner(num_devices=8, model_parallel=4)
        with pytest.raises(ValueError, match="divisible"):
            shard_catalog(V, part)


EMPTY_EXCL = (np.zeros(8, np.int32), np.zeros(8, np.int32),
              np.full(8, np.inf, np.float32))


class TestTwoStageRetrieverRankSharded:
    def _tables(self, seed=2, rank=16):
        rng = np.random.default_rng(seed)
        V = rng.normal(size=(512, rank)).astype(np.float32)
        Q = rng.normal(size=(32, rank)).astype(np.float32)
        return V, Q

    @pytest.mark.parametrize("m", [2, 4])
    @pytest.mark.parametrize("clustered", [False, True])
    def test_topk_ids_identical(self, m, clustered):
        """Stage-1 int8 codes are computed from FULL rows before the
        column split (scales identical at any m) and int8 partial dots
        psum exactly in int32 — same candidates, same exact-rescore,
        same ids at every model size."""
        V, Q = self._tables()
        cfg = RetrievalConfig(n_clusters=8 if clustered else None,
                              kmeans_iters=2)
        base = TwoStageRetriever(V, config=cfg)
        shd = TwoStageRetriever(
            V, config=cfg,
            partitioner=Partitioner(num_devices=8, model_parallel=m))
        sc_b, ids_b = base.topk(Q, EMPTY_EXCL, k=10)
        sc_s, ids_s = shd.topk(Q, EMPTY_EXCL, k=10)
        np.testing.assert_array_equal(np.asarray(ids_s),
                                      np.asarray(ids_b))
        np.testing.assert_allclose(np.asarray(sc_s), np.asarray(sc_b),
                                   atol=1e-5, rtol=0)

    def test_apply_delta_requantizes_sharded(self):
        V, Q = self._tables()
        cfg = RetrievalConfig(n_clusters=None)
        base = TwoStageRetriever(V, config=cfg)
        shd = TwoStageRetriever(
            V, config=cfg,
            partitioner=Partitioner(num_devices=8, model_parallel=2))
        rows = np.array([3, 100, 511], np.int32)
        vals = np.random.default_rng(5).normal(
            size=(3, V.shape[1])).astype(np.float32)
        base.apply_delta(rows, vals, version=1)
        shd.apply_delta(rows, vals, version=1)
        _, ids_b = base.topk(Q, EMPTY_EXCL, k=10)
        _, ids_s = shd.topk(Q, EMPTY_EXCL, k=10)
        np.testing.assert_array_equal(np.asarray(ids_s),
                                      np.asarray(ids_b))

    def test_per_device_bytes_shrink(self):
        """The footprint claim: int8 codes + f32 rescore rows divide by
        m, only per-row scales/weights replicate — per-device bytes at
        m=4 land well under half of replicated (the ≤ ~30% acceptance
        is pinned at rank 128 in the MULTICHIP round; this guards the
        mechanism at test scale)."""
        V, _ = self._tables(rank=32)
        cfg = RetrievalConfig(n_clusters=None)
        base = TwoStageRetriever(V, config=cfg)
        shd = TwoStageRetriever(
            V, config=cfg,
            partitioner=Partitioner(num_devices=8, model_parallel=4))
        assert shd.nbytes_per_device() < 0.5 * base.nbytes_per_device()

    def test_build_quantized_catalog_rank_not_divisible(self):
        V = np.zeros((64, 6), np.float32)
        part = Partitioner(num_devices=8, model_parallel=4)
        with pytest.raises(ValueError, match="divisible"):
            build_quantized_catalog(V, partitioner=part)


class TestRankShardedCheckpoint:
    def _manager(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            ShardedCheckpointManager,
        )

        return ShardedCheckpointManager(str(tmp_path))

    def test_round_trip_model2(self, tmp_path):
        from large_scale_recommendation_tpu.utils.checkpoint import (
            restore_segment_state_sharded,
        )

        rng = np.random.default_rng(0)
        U = rng.normal(size=(32, 8)).astype(np.float32)
        V = rng.normal(size=(24, 8)).astype(np.float32)
        part = Partitioner(num_devices=8, model_parallel=2)
        mgr = self._manager(tmp_path)
        mgr.save(5, {"U": part.shard(jnp.asarray(U), "users", "rank"),
                     "V": part.shard(jnp.asarray(V), "items", "rank")},
                 {"kind": "mesh"})
        U2, V2, done = restore_segment_state_sharded(
            mgr, "mesh", np.zeros_like(U), np.zeros_like(V),
            partitioner=part)
        assert done == 5
        np.testing.assert_array_equal(np.asarray(U2), U)
        np.testing.assert_array_equal(np.asarray(V2), V)
        assert U2.sharding == part.sharding("users", "rank")

    @pytest.mark.parametrize("m_save,m_load", [(2, 1), (2, 4), (1, 2)])
    def test_changed_model_size_resume_reshards(self, tmp_path,
                                                m_save, m_load):
        """Resume across a CHANGED model size: the 2-D overlap fill
        reassembles each device's slice from whichever saved pieces
        cover it — including old row-only (pre-rank-sharding) files
        restored onto a 2-D layout."""
        from large_scale_recommendation_tpu.utils.checkpoint import (
            restore_segment_state_sharded,
        )

        rng = np.random.default_rng(1)
        U = rng.normal(size=(32, 8)).astype(np.float32)
        V = rng.normal(size=(24, 8)).astype(np.float32)
        saver = Partitioner(num_devices=8, model_parallel=m_save)
        loader = Partitioner(num_devices=8, model_parallel=m_load)
        mgr = self._manager(tmp_path)
        mgr.save(3, {"U": saver.shard(jnp.asarray(U), "users", "rank"),
                     "V": saver.shard(jnp.asarray(V), "items", "rank")},
                 {"kind": "mesh"})
        U2, V2, done = restore_segment_state_sharded(
            mgr, "mesh", np.zeros_like(U), np.zeros_like(V),
            partitioner=loader)
        assert done == 3
        np.testing.assert_array_equal(np.asarray(U2), U)
        np.testing.assert_array_equal(np.asarray(V2), V)

    def test_missing_columns_fail_loudly(self, tmp_path):
        """A snapshot whose pieces do not cover a requested region must
        error on the fill-AREA check — never silently misplace rows."""
        rng = np.random.default_rng(2)
        U = rng.normal(size=(32, 8)).astype(np.float32)
        part = Partitioner(num_devices=8, model_parallel=2)
        mgr = self._manager(tmp_path)
        mgr.save(1, {"U": part.shard(jnp.asarray(U), "users", "rank")},
                 {"kind": "mesh"})
        # doctor the shard file: drop the second column group's pieces
        name = [n for n in os.listdir(tmp_path) if n.endswith(".npz")][0]
        path = os.path.join(str(tmp_path), name)
        with np.load(path) as z:
            payload = {k: z[k] for k in z.files}
        keep = payload["U__cstarts"] == 0
        n_keep = int(keep.sum())
        doctored = {"U__starts": payload["U__starts"][keep],
                    "U__lens": payload["U__lens"][keep],
                    "U__cstarts": payload["U__cstarts"][keep],
                    "U__clens": payload["U__clens"][keep]}
        kept_idx = [j for j, k_ in enumerate(keep) if k_]
        for newj, oldj in enumerate(kept_idx):
            doctored[f"U__p{newj}"] = payload[f"U__p{oldj}"]
        assert n_keep < len(keep)  # the doctoring removed something
        np.savez(path, **doctored)
        with pytest.raises(ValueError, match="missing rows"):
            mgr.restore_array(1, "U", part.sharding("users", "rank"),
                              (32, 8), np.float32)

    def test_fit_device_resume_at_model2(self, ratings, tmp_path):
        """End-to-end through the mesh DSGD superstep loop: 2 sweeps +
        checkpoint, resume for the remaining 2 ⇒ identical factors to
        an unbroken 4-sweep fit at the same model size."""
        ru, ri, rv, _ = ratings.to_numpy()
        part = Partitioner(num_devices=8, model_parallel=2)
        mgr = self._manager(tmp_path)
        MeshDSGD(_dsgd_cfg(iters=2), partitioner=part).fit_device(
            ru, ri, rv, NU, NI, checkpoint_manager=mgr,
            checkpoint_every=2)
        resumed = MeshDSGD(_dsgd_cfg(iters=4),
                           partitioner=part).fit_device(
            ru, ri, rv, NU, NI, checkpoint_manager=mgr,
            checkpoint_every=2, resume=True)
        straight = _fit_dsgd(part, ratings, iters=4)
        np.testing.assert_allclose(np.asarray(resumed.U),
                                   np.asarray(straight.U),
                                   atol=1e-6, rtol=0)


class TestRooflineModelSize:
    def test_bytes_per_sweep_divides_by_model_size(self):
        full = sgd_ops.dsgd_bytes_per_sweep(1000, 64, kernel="xla")
        quarter = sgd_ops.dsgd_bytes_per_sweep(1000, 64, kernel="xla",
                                               model_size=4)
        # the 16-byte COO term is per rating, not per factor column
        assert quarter == 1000 * (4 * 16 * 4 + 16)
        assert quarter < full

    def test_bytes_per_sweep_validates_model_size(self):
        with pytest.raises(ValueError, match="model_size"):
            sgd_ops.dsgd_bytes_per_sweep(1000, 64, model_size=0)
        with pytest.raises(ValueError, match="divisible|divide"):
            sgd_ops.dsgd_bytes_per_sweep(1000, 63, model_size=4)
        with pytest.raises(ValueError, match="pallas"):
            sgd_ops.dsgd_bytes_per_sweep(1000, 64, kernel="pallas",
                                         model_size=2)

    def test_collective_bytes_formula(self):
        assert sgd_ops.dsgd_collective_bytes_per_sweep(1000, 64, 1) == 0
        # psum of one f32 per rating: 2·(m−1)/m bytes on the wire per
        # reduced element (ring all-reduce), m=4 ⇒ 1.5 × 4 B × nnz
        assert sgd_ops.dsgd_collective_bytes_per_sweep(1000, 64, 4) == \
            int(1000 * 4 * 2 * 3 / 4)

    def test_roofline_rows_carry_collective_term(self):
        """The interconnect term is its OWN roofline key — wire traffic
        never hides inside the HBM number."""
        from large_scale_recommendation_tpu.obs.introspect import (
            roofline_rows,
        )

        records = [{"key": "train_segment/dsgd", "module": "jit_step",
                    "compiles": 1, "compile_wall_s": 0.1,
                    "flops": 1e6, "bytes_accessed": 1e4}]
        walls = {"train_segment/dsgd":
                 {"execute_count": 2, "execute_total_s": 0.5,
                  "iterations": 8}}
        model_costs = {"train_segment/dsgd": {
            "bytes_per_iteration": 100.0,
            "collective_bytes_per_iteration": 48.0}}
        (row,) = roofline_rows(records, walls, model_costs)
        assert row["model_bytes_per_exec"] == 100.0 * 4
        assert row["model_collective_bytes_per_exec"] == 48.0 * 4
        # replicated kernels (no registered collective term) stay None
        (row1,) = roofline_rows(
            records, walls,
            {"train_segment/dsgd": {"bytes_per_iteration": 100.0}})
        assert row1["model_collective_bytes_per_exec"] is None
