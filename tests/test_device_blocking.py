"""On-device blocking pipeline: layout invariants, parity with the host
pass's semantics, and end-to-end convergence through the DSGD kernel.

The device path (data/device_blocking.py) must produce a layout satisfying
the same contract as the host path (data/blocking.py) — disjoint strata,
balanced blocks, correct omegas and collision scales — without being
bit-identical (different seeded permutations).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from large_scale_recommendation_tpu.data import blocking, device_blocking
from large_scale_recommendation_tpu.ops import sgd as sgd_ops
from large_scale_recommendation_tpu.core.updaters import (
    RegularizedSGDUpdater,
    constant_lr,
)


def _toy(n=4000, nu=300, ni=200, seed=0, skew=None):
    rng = np.random.default_rng(seed)
    if skew is None:
        u = rng.integers(0, nu, n)
        i = rng.integers(0, ni, n)
    else:
        u = np.minimum((-np.log1p(-rng.random(n) * (1 - np.exp(-skew)))
                        / skew * nu).astype(np.int64), nu - 1)
        i = np.minimum((-np.log1p(-rng.random(n) * (1 - np.exp(-skew)))
                        / skew * ni).astype(np.int64), ni - 1)
    r = rng.normal(0, 1, n).astype(np.float32)
    return u, i, r, nu, ni


class TestDeviceBlocking:
    @pytest.mark.parametrize("skew", [None, 2.0])
    @pytest.mark.parametrize("k", [2, 4])
    def test_layout_invariants(self, k, skew):
        u, i, r, nu, ni = _toy(skew=skew)
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=k, minibatch_multiple=64)

        su = np.asarray(p.su)
        si = np.asarray(p.si)
        sv = np.asarray(p.sv)
        sw = np.asarray(p.sw)
        # every real entry appears exactly once, with its value
        assert int(sw.sum()) == len(u)
        assert p.nnz == len(u)
        # stratum-major contract: block [s, pb] holds ratings with
        # user-block pb and item-block (pb+s) mod k
        for s in range(k):
            for pb in range(k):
                m = sw[s, pb] > 0
                if not m.any():
                    continue
                assert (su[s, pb][m] // p.rows_per_block_u == pb).all()
                assert (si[s, pb][m] // p.rows_per_block_v
                        == (pb + s) % k).all()
        # the multiset of (urow, irow, value) matches the input through the
        # id→row maps
        row_u = np.asarray(p.row_of_user)
        row_i = np.asarray(p.row_of_item)
        exp = sorted(zip(row_u[u].tolist(), row_i[i].tolist(),
                         np.float32(r).tolist()))
        got = sorted(zip(su[sw > 0].tolist(), si[sw > 0].tolist(),
                         sv[sw > 0].tolist()))
        assert exp == got

    def test_row_maps_and_omegas(self):
        u, i, r, nu, ni = _toy(skew=2.0)
        k = 4
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=k, minibatch_multiple=32)
        row_u = np.asarray(p.row_of_user)
        # bijective over ids: every id gets a distinct row
        assert len(set(row_u.tolist())) == nu
        # id_of_row inverts row_of_id
        id_of = np.asarray(p.id_of_user_row)
        assert (id_of[row_u] == np.arange(nu)).all()
        # omegas are the occurrence counts, indexed by row
        cnt = np.bincount(u, minlength=nu)
        assert (np.asarray(p.omega_u)[row_u] == cnt).all()
        # blocks are balanced: per-block id counts differ by at most 1 row
        blk = row_u // p.rows_per_block_u
        sizes = np.bincount(blk, minlength=k)
        assert sizes.max() - sizes.min() <= 1

    def test_load_balance_on_skewed_data(self):
        """The serpentine deal keeps per-block nnz near-equal even with
        power-law ids (same property the host pass guarantees)."""
        u, i, r, nu, ni = _toy(n=20_000, skew=2.0)
        k = 4
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=k, minibatch_multiple=1)
        blk = np.asarray(p.row_of_user)[u] // p.rows_per_block_u
        per_block = np.bincount(blk, minlength=k)
        assert per_block.max() / per_block.min() < 1.5

    def test_inv_counts_match_numpy_recomputation(self):
        u, i, r, nu, ni = _toy(n=3000, nu=40, ni=30, skew=2.0)  # many dups
        mb = 128
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=mb)
        su = np.asarray(p.su).reshape(-1)
        sw = np.asarray(p.sw).reshape(-1)
        icu = np.asarray(p.icu).reshape(-1)
        # recompute per-minibatch weighted counts in numpy on the SAME layout
        for m0 in range(0, len(su), mb):
            rows = su[m0:m0 + mb]
            w = sw[m0:m0 + mb]
            inv = icu[m0:m0 + mb]
            for j in range(mb):
                cnt = w[rows == rows[j]].sum()
                if w[j] > 0:
                    assert inv[j] == pytest.approx(1.0 / max(cnt, 1.0))

    def test_weight_zero_padding_entries_are_noops(self):
        """The weights channel: padded entries (w=0, id 0) occupy layout
        slots but contribute nothing — counts, omegas, real-entry multiset
        and training all match the unpadded problem (the per-host
        equal-shard padding contract for multi-host ingest)."""
        u, i, r, nu, ni = _toy(n=2000, seed=6, skew=2.0)
        n_pad = 137
        up = np.concatenate([u, np.zeros(n_pad, np.int64)])
        ip = np.concatenate([i, np.zeros(n_pad, np.int64)])
        rp = np.concatenate([r, np.zeros(n_pad, np.float32)])
        wp = np.concatenate([np.ones(len(u), np.float32),
                             np.zeros(n_pad, np.float32)])
        plain = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=64, seed=4)
        padded = device_blocking.device_block_problem(
            up, ip, rp, nu, ni, num_blocks=2, minibatch_multiple=64,
            seed=4, weights=wp)
        assert padded.nnz == plain.nnz == len(u)
        # identical weighted counts → identical row maps and omegas
        np.testing.assert_array_equal(np.asarray(plain.row_of_user),
                                      np.asarray(padded.row_of_user))
        np.testing.assert_array_equal(np.asarray(plain.omega_u),
                                      np.asarray(padded.omega_u))
        # same real-entry multiset through the layout
        def real(p):
            sw = np.asarray(p.sw) > 0
            return sorted(zip(np.asarray(p.su)[sw].tolist(),
                              np.asarray(p.si)[sw].tolist(),
                              np.asarray(p.sv)[sw].tolist()))
        assert real(plain) == real(padded)
        # collision scales ignore the w=0 slots: every real row-0 entry's
        # scale reflects only real occurrences (recomputed in numpy)
        su = np.asarray(padded.su).reshape(-1)
        sw = np.asarray(padded.sw).reshape(-1)
        icu = np.asarray(padded.icu).reshape(-1)
        for m0 in range(0, len(su), 64):
            rows, ws, inv = su[m0:m0 + 64], sw[m0:m0 + 64], icu[m0:m0 + 64]
            for j in range(0, 64, 13):
                if ws[j] > 0:
                    cnt = ws[rows == rows[j]].sum()
                    assert inv[j] == pytest.approx(1.0 / max(cnt, 1.0))

    def test_recompute_inv_counts_other_minibatch(self):
        """recompute_inv_counts(p, mb') on the same layout must equal the
        per-minibatch weighted-count definition at mb' (the bench autotune
        contract: one blocking pass, several kernel minibatches)."""
        u, i, r, nu, ni = _toy(n=3000, nu=40, ni=30, skew=2.0)
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=256)
        for mb in (64, 128):
            icu, _ = device_blocking.recompute_inv_counts(p, mb)
            su = np.asarray(p.su).reshape(-1)
            sw = np.asarray(p.sw).reshape(-1)
            icu = np.asarray(icu).reshape(-1)
            rng = np.random.default_rng(0)
            for m0 in rng.choice(len(su) // mb, 8, replace=False) * mb:
                rows = su[m0:m0 + mb]
                w = sw[m0:m0 + mb]
                for j in range(0, mb, 17):
                    if w[j] > 0:
                        cnt = w[rows == rows[j]].sum()
                        assert icu[m0 + j] == pytest.approx(
                            1.0 / max(cnt, 1.0))
        with pytest.raises(ValueError, match="divide"):
            device_blocking.recompute_inv_counts(p, p.su.shape[-1] * 2)

    def test_collision_scale_semantics_match_host(self):
        """Same definition as blocking.minibatch_inv_counts: a real entry's
        scale is 1/(weight-sum of its row in its minibatch)."""
        u = np.array([0, 0, 0, 1, 1, 2, 3, 3], np.int64)
        i = np.array([0, 1, 2, 0, 1, 0, 0, 1], np.int64)
        r = np.ones(8, np.float32)
        p = device_blocking.device_block_problem(
            u, i, r, 4, 3, num_blocks=1, minibatch_multiple=8, seed=3)
        su = np.asarray(p.su).reshape(-1)[:8]
        icu = np.asarray(p.icu).reshape(-1)[:8]
        cnt = {row: (su == row).sum() for row in set(su.tolist())}
        for j in range(8):
            assert icu[j] == pytest.approx(1.0 / cnt[su[j]])

    def test_truncated_exp_matches_host_distribution(self):
        """Device inverse-CDF draw ≈ host rejection draw (same truncated
        exponential): compare decile masses."""
        from large_scale_recommendation_tpu.core.generators import (
            _next_exp_discrete,
        )

        n_ids, lam, n = 1000, 2.0, 200_000
        host = _next_exp_discrete(np.random.default_rng(0), lam, n_ids, n)
        dev = np.asarray(device_blocking.truncated_exp_ids(
            jax.random.PRNGKey(0), lam, n_ids, n))
        assert dev.min() >= 0 and dev.max() < n_ids
        hh = np.bincount(host // 100, minlength=10) / n
        hd = np.bincount(dev // 100, minlength=10) / n
        np.testing.assert_allclose(hh, hd, atol=0.01)

    def test_synthetic_like_device_stats(self):
        (u, i, r), (hu, hi, hr), (nu, ni) = \
            device_blocking.synthetic_like_device(
                "ml-100k", nnz=50_000, rank=16, noise=0.1, seed=0)
        assert nu == 943 and ni == 1682
        assert u.shape[0] == 47_500 and hu.shape[0] == 2_500
        r = np.asarray(r)
        # planted signal std ≈ 1/sqrt(rank)=0.25, noise 0.1 → total ≈ 0.27
        assert 0.2 < r.std() < 0.35
        assert abs(r.mean()) < 0.02

    def test_end_to_end_convergence_through_dsgd_kernel(self):
        """Device pipeline → dsgd_train recovers planted structure (the
        shape of the bench's DSGD path, miniature)."""
        (u, i, r), (hu, hi, hr), (nu, ni) = \
            device_blocking.synthetic_like_device(
                "ml-100k", nnz=60_000, rank=4, noise=0.05, seed=1)
        k, mb, rank = 2, 512, 8
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=k, minibatch_multiple=mb, seed=1)
        U, V = device_blocking.init_factors_device(p, rank, scale=0.1)
        upd = RegularizedSGDUpdater(learning_rate=0.2, lambda_=0.05,
                                    schedule=constant_lr)
        hur, hir, hmask = p.holdout_rows(hu, hi)

        def rmse(U, V):
            sse = sgd_ops.sse_rows(U, V, hur, hir, hr, hmask)
            return float(np.sqrt(float(sse) / float(hmask.sum())))

        before = rmse(U, V)
        for t in range(12):
            U, V = sgd_ops.dsgd_train(
                U, V, p.su, p.si, p.sv, p.sw, p.omega_u, p.omega_v,
                p.icu, p.icv, updater=upd, minibatch=mb, num_blocks=k,
                iterations=1, collision="mean", t0=t)
        after = rmse(U, V)
        # measured (CPU and TPU agree): 0.5 → ~0.076 by sweep 12 (noise
        # floor 0.05); the bilinear bootstrap spends ~3 sweeps flat first
        assert after < before * 0.3
        assert after < 0.12

    def test_minibatch_sort_preserves_membership_and_math(self):
        u, i, r, nu, ni = _toy(n=2000, seed=5)
        mb = 64
        ps = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=mb, seed=2,
            minibatch_sort="item")
        pn = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=mb, seed=2)
        # same minibatch membership: each mb-chunk holds the same multiset
        for a, b in ((ps.su, pn.su), (ps.sv, pn.sv)):
            a2 = np.asarray(a).reshape(-1, mb)
            b2 = np.asarray(b).reshape(-1, mb)
            for row_a, row_b in zip(a2, b2):
                assert sorted(row_a.tolist()) == sorted(row_b.tolist())
        # sorted variant is item-ordered within chunks
        si2 = np.asarray(ps.si).reshape(-1, mb)
        assert all((np.diff(row) >= 0).all() for row in si2)

    def test_fit_device_full_model_surface(self):
        """DSGD.fit_device: device pipeline → standard MFModel (predict,
        rmse, risk, unseen-id semantics) at host-path quality."""
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )
        from large_scale_recommendation_tpu.core.types import Ratings

        (u, i, r), (hu, hi, hr), (nu, ni) = \
            device_blocking.synthetic_like_device(
                "ml-100k", nnz=60_000, rank=4, noise=0.05, seed=1)
        cfg = DSGDConfig(num_factors=8, lambda_=0.05, iterations=12,
                         learning_rate=0.2, lr_schedule="constant",
                         minibatch_size=512, seed=1, init_scale=0.1)
        m = DSGD(cfg).fit_device(u, i, r, nu, ni, num_blocks=2)
        test = Ratings.from_arrays(np.asarray(hu).astype(np.int64),
                                   np.asarray(hi).astype(np.int64),
                                   np.asarray(hr))
        assert m.rmse(test) < 0.12  # same floor as the ops-level test
        # host-path comparison on identical arrays
        train = Ratings.from_arrays(np.asarray(u).astype(np.int64),
                                    np.asarray(i).astype(np.int64),
                                    np.asarray(r))
        mh = DSGD(cfg).fit(train, num_blocks=2)
        assert abs(m.rmse(test) - mh.rmse(test)) < 0.03
        # unseen ids score exactly 0 (host IdIndex semantics): synthesize a
        # guaranteed-unseen id by refitting with one user id held out
        held = int(np.asarray(u)[0])
        uh = np.asarray(u).astype(np.int64)
        keep = uh != held
        m3 = DSGD(cfg).fit_device(uh[keep], np.asarray(i)[keep].astype(np.int64),
                                  np.asarray(r)[keep], nu, ni, num_blocks=2)
        s = m3.predict(np.array([held]), np.array([0]))
        assert float(s[0]) == 0.0
        assert np.isfinite(m.empirical_risk(test))

    def test_fit_device_checkpoint_segments_equal_straight_run(self):
        from large_scale_recommendation_tpu.models.dsgd import (
            DSGD,
            DSGDConfig,
        )
        from large_scale_recommendation_tpu.utils.checkpoint import (
            CheckpointManager,
        )
        import tempfile

        import dataclasses as dc

        u, i, r, nu, ni = _toy(n=5000, seed=9)
        cfg = DSGDConfig(num_factors=4, lambda_=0.1, iterations=6,
                         learning_rate=0.1, minibatch_size=256, seed=0,
                         init_scale=0.1)
        straight = DSGD(cfg).fit_device(u, i, r, nu, ni, num_blocks=2)
        with tempfile.TemporaryDirectory() as d:
            # run only 4 of the 6 iterations, snapshotting every 2 …
            cm = CheckpointManager(d)
            DSGD(dc.replace(cfg, iterations=4)).fit_device(
                u, i, r, nu, ni, num_blocks=2,
                checkpoint_manager=cm, checkpoint_every=2)
            # … then resume MID-RUN (restores step 4, trains 2 more with
            # t0=4) and require bitwise-path equality with the straight run
            resumed = DSGD(cfg).fit_device(u, i, r, nu, ni, num_blocks=2,
                                           checkpoint_manager=CheckpointManager(d),
                                           checkpoint_every=2, resume=True)
            # cross-path resume is refused: the host-blocked layout is
            # row-incompatible with these snapshots
            with pytest.raises(ValueError, match="kind"):
                from large_scale_recommendation_tpu.core.types import Ratings
                DSGD(cfg).fit(
                    Ratings.from_arrays(u, i, r), num_blocks=2,
                    checkpoint_manager=CheckpointManager(d), resume=True)
        np.testing.assert_allclose(np.asarray(straight.U),
                                   np.asarray(resumed.U), rtol=1e-5)

    def test_validate_dense_ids_mixed_host_device_no_int32_wrap(self):
        """A wild int64 id in a HOST array must fail validation even when
        the other side is a device array — the mixed path must not route
        the host array through a device cast (int64→int32 wrap would turn
        2^32+5 into a plausible small id that passes the range check)."""
        import jax.numpy as jnp
        wild = np.array([0, 2**32 + 5], np.int64)  # wraps to 5 in int32
        dev_ok = jnp.array([0, 1], jnp.int32)
        with pytest.raises(ValueError, match="dense ids"):
            device_blocking.validate_dense_ids(dev_ok, wild, 100, 100, "t")
        with pytest.raises(ValueError, match="dense ids"):
            device_blocking.validate_dense_ids(wild, dev_ok, 100, 100, "t")
        # all-device path: fused single-readback check still rejects
        with pytest.raises(ValueError, match="dense ids"):
            device_blocking.validate_dense_ids(
                dev_ok, jnp.array([0, 100], jnp.int32), 100, 100, "t")
        # and accepts in-range input in every combination
        device_blocking.validate_dense_ids(dev_ok, dev_ok, 100, 100, "t")
        device_blocking.validate_dense_ids(
            np.array([0, 1]), dev_ok, 100, 100, "t")

    @pytest.mark.slow
    def test_fuzz_layout_invariants(self):
        """Randomized shapes/skews/weights: the layout contract must hold
        for every draw (multiset preservation, stratum property, weighted
        collision scales)."""
        rng = np.random.default_rng(2026)
        for trial in range(20):
            nu = int(rng.integers(3, 400))
            ni = int(rng.integers(3, 300))
            n = int(rng.integers(10, 5000))
            k = int(rng.choice([1, 2, 3, 4, 8]))
            mb = int(rng.choice([1, 16, 64, 256]))
            skew = rng.choice([None, 1.0, 3.0])
            u = (rng.integers(0, nu, n) if skew is None else np.minimum(
                (-np.log1p(-rng.random(n) * (1 - np.exp(-skew))) / skew
                 * nu).astype(np.int64), nu - 1))
            i = rng.integers(0, ni, n)
            r = rng.normal(0, 1, n).astype(np.float32)
            w = (rng.random(n) > 0.2).astype(np.float32) \
                if trial % 3 == 0 else None
            p = device_blocking.device_block_problem(
                u, i, r, nu, ni, num_blocks=k, minibatch_multiple=mb,
                seed=trial, weights=w)
            wreal = np.ones(n) if w is None else w
            assert p.nnz == int((wreal > 0).sum()), (trial, p.nnz)
            su = np.asarray(p.su)
            si = np.asarray(p.si)
            sw = np.asarray(p.sw)
            m = sw > 0
            assert int(m.sum()) == p.nnz
            # stratum property on every real entry
            ub = su[m] // p.rows_per_block_u
            ib = si[m] // p.rows_per_block_v
            s_idx, p_idx, _ = np.nonzero(m)
            assert (ub == p_idx).all(), trial
            assert (ib == (p_idx + s_idx) % k).all(), trial
            # real multiset through the row maps
            keep = wreal > 0
            row_u = np.asarray(p.row_of_user)
            row_i = np.asarray(p.row_of_item)
            exp = sorted(zip(row_u[u[keep]].tolist(),
                             row_i[i[keep]].tolist(),
                             np.float32(r[keep]).tolist()))
            got = sorted(zip(su[m].tolist(), si[m].tolist(),
                             np.asarray(p.sv)[m].tolist()))
            assert exp == got, trial

    def test_init_factors_device_matches_host_initializer(self):
        from large_scale_recommendation_tpu.core.initializers import (
            PseudoRandomFactorInitializer,
        )

        u, i, r, nu, ni = _toy(n=500, nu=50, ni=40)
        p = device_blocking.device_block_problem(
            u, i, r, nu, ni, num_blocks=2, minibatch_multiple=16)
        U, _ = device_blocking.init_factors_device(p, rank=6, scale=0.08)
        init = PseudoRandomFactorInitializer(6, scale=0.08)
        ids = np.asarray(p.id_of_user_row)
        np.testing.assert_allclose(np.asarray(U), np.asarray(init(ids)),
                                   rtol=1e-6)


class TestInvCountsPresorted:
    def test_presorted_path_is_bit_equal(self):
        """The minibatch_sort side's collision scales skip the inner
        argsort (r5 layout optimization) — identical runs on sorted
        input, so the fast path must be bit-equal to the general one."""
        from large_scale_recommendation_tpu.data.device_blocking import (
            _inv_counts_2d,
        )

        rng = np.random.default_rng(0)
        rows = np.sort(rng.integers(0, 30, (16, 64)), axis=-1)
        w = (rng.random((16, 64)) > 0.2).astype(np.float32)
        a = _inv_counts_2d(jnp.asarray(rows), jnp.asarray(w))
        b = _inv_counts_2d(jnp.asarray(rows), jnp.asarray(w),
                           presorted=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
